#!/usr/bin/env python
"""TPU-hostile-pattern linter CLI (bigdl_tpu.analysis).

    tools/tpu_lint.py bigdl_tpu/ examples/ benchmarks/ \
        --baseline tools/tpu_lint_baseline.json

Exit codes: 0 clean (or every finding baselined/suppressed), 1 new
findings, 2 configuration error (unknown rule, hot-path finding in the
baseline — those rules guard live perf bugs and may never be
grandfathered).

The baseline stores line-number-free fingerprints so refactors that
merely move code don't churn it; changing the offending line itself
invalidates the entry and forces a re-look.  `--write-baseline`
refuses to record hot-path rules (host-sync / tracer-leak / donation):
fix those or suppress them inline with an explanation.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bigdl_tpu.analysis.linter import (  # noqa: E402
    HOT_PATH_RULES, RULES)

DEFAULT_PATHS = ["bigdl_tpu/"]


def load_baseline(path):
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        data = json.load(fh)
    out = {}
    for entry in data.get("suppressions", []):
        out[entry["fingerprint"]] = entry
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: bigdl_tpu/)")
    ap.add_argument("--baseline", default=None,
                    help="JSON baseline of accepted findings")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to --baseline and exit")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to report")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--hot-root", action="append", default=[],
                    help="extra hot-root qualname regex (repeatable)")
    ap.add_argument("--lock-graph", default=None, metavar="OUT",
                    help="dump the static acquired-before lock graph "
                         "(.dot for graphviz, .json for "
                         "tools/lockdep_reconcile.py)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            tag = " (hot-path: not baselinable)" if r in HOT_PATH_RULES \
                else ""
            print(f"{r}{tag}")
        return 0

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES)
        if unknown:
            print(f"tpu_lint: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    paths = args.paths or DEFAULT_PATHS
    from bigdl_tpu.analysis.linter import (DEFAULT_HOT_ROOTS,
                                           project_for_paths)
    hot_roots = list(DEFAULT_HOT_ROOTS) + args.hot_root
    proj = project_for_paths(paths, hot_roots=hot_roots)
    findings = proj.findings
    if rules is not None:
        findings = [f for f in findings if f.rule in rules]

    if args.lock_graph:
        graph = proj.lock_graph
        out = args.lock_graph
        with open(out, "w") as fh:
            if out.endswith(".json"):
                json.dump(graph.to_json(), fh, indent=1, sort_keys=True)
                fh.write("\n")
            else:
                fh.write(graph.to_dot())
        print(f"tpu_lint: wrote lock graph ({len(graph.nodes)} locks, "
              f"{len(graph.edges)} edges) to {out}")

    if args.write_baseline:
        if not args.baseline:
            print("tpu_lint: --write-baseline needs --baseline",
                  file=sys.stderr)
            return 2
        hot = [f for f in findings if f.rule in HOT_PATH_RULES]
        if hot:
            print("tpu_lint: refusing to baseline hot-path findings "
                  "(fix or suppress inline with a reason):",
                  file=sys.stderr)
            for f in hot:
                print("  " + f.render(), file=sys.stderr)
            return 2
        payload = {
            "version": 1,
            "comment": "accepted non-hot-path findings; hot-path rules "
                       "(host-sync/tracer-leak/donation) may never "
                       "appear here — tools/tpu_lint.py enforces",
            "suppressions": [
                {"fingerprint": f.fingerprint(), "rule": f.rule,
                 "path": f.path, "func": f.func, "message": f.message}
                for f in findings],
        }
        with open(args.baseline, "w") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"tpu_lint: wrote {len(findings)} suppression(s) to "
              f"{args.baseline}")
        return 0

    baseline = {}
    if args.baseline:
        baseline = load_baseline(args.baseline)
        bad = [e for e in baseline.values()
               if e.get("rule") in HOT_PATH_RULES]
        if bad:
            print("tpu_lint: baseline contains hot-path rule entries — "
                  "these guard live perf bugs and may never be "
                  "grandfathered:", file=sys.stderr)
            for e in bad:
                print(f"  {e['rule']} {e['path']} [{e.get('func', '?')}]",
                      file=sys.stderr)
            return 2

    fresh = [f for f in findings if f.fingerprint() not in baseline]
    for f in fresh:
        print(f.render())
    n_base = len(findings) - len(fresh)
    if fresh:
        print(f"tpu_lint: {len(fresh)} finding(s) "
              f"({n_base} baselined)", file=sys.stderr)
        return 1
    suffix = f" ({n_base} baselined)" if n_base else ""
    print(f"tpu_lint: clean{suffix}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
