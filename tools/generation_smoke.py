"""CI generation lane: the prefill/decode engine, validated end to end.

Runs — in ONE process under JAX_PLATFORMS=cpu — the properties
docs/serving.md promises for `bigdl_tpu.generation` (ISSUE 10
acceptance):

  * bucket discipline: 32 concurrent prompts of mixed lengths across two
    length buckets compile AT MOST len(buckets) x 2 executables, with
    ZERO steady-state recompile alarms from CompileMonitor;
  * greedy correctness: the engine's continuous-batched greedy output is
    token-identical to a full re-forward argmax loop;
  * hot-swap: a same-shaped params swap under traffic reuses every
    compiled executable (no re-trace) and the next request reports the
    new version;
  * observability: gen.prefill / gen.decode_step spans land in the trace
    ring carrying request cids, and the metrics snapshot exports ttft /
    ms-per-token percentiles;
  * paged + int8 KV lane (ISSUE 12): the same burst through a shared
    block pool (oversubscribed below ring worst case) with int8 K/V
    holds the SAME executable budget with zero steady alarms, a paged
    fp32 engine reproduces the ring engine's greedy tokens exactly, and
    the pool releases every block and reservation when traffic drains;
  * chunked prefill + spec decode lane (ISSUE 15): a 4k-token prompt is
    admitted MID-BURST into an oversubscribed paged pool with chunked
    prefill on — short requests keep completing while it folds — and
    the spec-on engine (1-layer draft, k=3) emits tokens identical to
    spec-off greedy, at the documented 5-per-bucket executable budget,
    zero steady alarms, zero leaked blocks;
  * prefix cache lane (ISSUE 18): N requests sharing a 1k-token system
    prompt ride an oversubscribed pool — warm admissions map the shared
    head read-only and fold only their cold tail, so prefix_hits > 0,
    prefill chunk count collapses vs the cold run, greedy output stays
    IDENTICAL to the prefix-off engine, zero steady alarms, and every
    block drains (free + store == allocatable; clear() returns the rest).

Usage: python tools/generation_smoke.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# lockdep must wrap locks AT CREATION, and importing any bigdl_tpu module
# creates module-level locks — so load the (stdlib-only) sanitizer by file
# path and instrument before the first bigdl_tpu import below
import importlib.util  # noqa: E402

_ld_spec = importlib.util.spec_from_file_location(
    "bigdl_tpu.analysis.lockdep",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "bigdl_tpu", "analysis", "lockdep.py"))
lockdep = importlib.util.module_from_spec(_ld_spec)
sys.modules[_ld_spec.name] = lockdep
_ld_spec.loader.exec_module(lockdep)
lockdep.install_if_enabled()

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax.extend.backend as _jeb

    _jeb.clear_backends()
except Exception:  # pragma: no cover - fallback for older jax
    import jax._src.xla_bridge as _xb

    _xb._clear_backends()

from bigdl_tpu import obs  # noqa: E402
from bigdl_tpu.generation import GenerationConfig, GenerationEngine  # noqa: E402
from bigdl_tpu.models.transformer import TransformerLM  # noqa: E402

BUCKETS = (16, 64)
SLOTS = 4
N_REQUESTS = 32


def main() -> int:
    obs.set_observability(metrics=True, tracing=True, compile_monitor=True)
    mon = obs.compile_monitor()

    model = TransformerLM(vocab_size=61, hidden_size=32, n_layer=2,
                          n_head=4, max_len=128, use_flash=False)
    params, _ = model.init((1, 16), rng=jax.random.PRNGKey(0))
    cfg = GenerationConfig(buckets=BUCKETS, slots=SLOTS,
                           capacity=N_REQUESTS + 8, max_new_tokens=6)
    eng = GenerationEngine(model, params, config=cfg)
    budget = 2 * len(BUCKETS)
    try:
        n_warm = eng.compile_count()
        assert n_warm <= budget, \
            f"warmup compiled {n_warm} executables, budget {budget}"

        # -- concurrent burst: mixed prompt lengths over both buckets ----
        rng = np.random.RandomState(0)
        t0 = time.perf_counter()
        futs = [eng.submit(rng.randint(0, 61, size=int(rng.randint(1, 14))),
                           max_new_tokens=int(rng.randint(1, 7)))
                for _ in range(N_REQUESTS)]
        results = [f.result(timeout=240) for f in futs]
        wall = time.perf_counter() - t0
        assert len(results) == N_REQUESTS
        n_exec = eng.compile_count()
        assert n_exec <= budget, \
            f"burst grew the executable set to {n_exec} (budget {budget})"
        n_re = mon.recompiles("generation/")
        assert n_re == 0, \
            f"{n_re} steady-state recompiles under generation/: " \
            f"{mon.snapshot()}"

        # -- greedy parity vs the full re-forward argmax loop ------------
        prompt = [7, 3, 19]
        got = eng.generate(prompt, max_new_tokens=5).tokens
        ctx = list(prompt)
        for want_i in range(5):
            logp, _ = model.apply(params, {}, jnp.asarray([ctx], jnp.int32),
                                  training=False)
            tok = int(jnp.argmax(logp[0, -1]))
            assert int(got[want_i]) == tok, (got, ctx, tok)
            ctx.append(tok)

        # -- same-shaped hot swap reuses every executable ----------------
        eng.swap("v1", jax.tree_util.tree_map(lambda a: a * 1.01, params))
        r = eng.generate(prompt, max_new_tokens=2)
        assert r.meta["version"] == "v1", r.meta
        assert eng.compile_count() == n_exec, \
            f"swap re-traced: {eng.compile_count()} != {n_exec}"
        assert mon.recompiles("generation/") == 0

        # -- spans + metrics surface -------------------------------------
        events = obs.tracer().events()  # (kind, name, cat, ..., args)
        by_name = {}
        for ev in events:
            by_name.setdefault(ev[1], []).append(ev[7])
        for needed in ("gen.prefill", "gen.decode_step"):
            assert needed in by_name, f"missing span {needed!r}"
        # spans carry request cids for cross-referencing with results
        assert any(a and "cid" in a for a in by_name["gen.prefill"])
        assert any(a and a.get("cids") for a in by_name["gen.decode_step"])
        snap = eng.metrics.snapshot()
        assert snap["requests_completed"] == N_REQUESTS + 2, snap
        assert snap["tokens_generated"] >= N_REQUESTS
        assert snap["ms_per_token"]["p99"] >= snap["ms_per_token"]["p50"] > 0
        assert snap["ttft_ms"]["p50"] > 0

        toks = snap["tokens_generated"]
        print(f"OK: generation lane green — {N_REQUESTS} concurrent "
              f"requests, {toks} tokens in {wall:.2f}s, "
              f"{n_exec}/{budget} executables, 0 steady recompiles, "
              f"ms/token p50={snap['ms_per_token']['p50']}")
    finally:
        eng.close()

    # -- paged + int8 KV lane (ISSUE 12) ---------------------------------
    # fresh CompileMonitor: the ring engine above marked generation/
    # steady, so this engine's own warmup would read as false alarms
    obs.set_observability(metrics=True, tracing=True, compile_monitor=True)
    mon = obs.compile_monitor()
    reg = obs.registry()
    # pool oversubscribed below ring worst case — buckets 16/64 x 4
    # slots at block 8 would need 2*4 + 8*4 + 1 = 41 blocks; give 24 so
    # admission backpressure and block recycling are on the tested path
    cfg8 = GenerationConfig(buckets=BUCKETS, slots=SLOTS,
                            capacity=N_REQUESTS + 8, max_new_tokens=6,
                            paged=True, kv_block_size=8, kv_pool_blocks=24,
                            cache_dtype=jnp.int8)
    eng8 = GenerationEngine(model, params, config=cfg8)
    try:
        rng = np.random.RandomState(0)
        futs = [eng8.submit(rng.randint(0, 61, size=int(rng.randint(1, 14))),
                            max_new_tokens=int(rng.randint(1, 7)))
                for _ in range(N_REQUESTS)]
        for f in futs:
            f.result(timeout=240)
        n_exec8 = eng8.compile_count()
        assert n_exec8 <= budget, \
            f"paged+int8 burst grew the executable set to {n_exec8} " \
            f"(budget {budget})"
        n_re8 = mon.recompiles("generation/")
        assert n_re8 == 0, \
            f"{n_re8} steady-state recompiles under generation/ with " \
            f"paged+int8: {mon.snapshot()}"
        pool = eng8._pool
        assert pool.blocks_free == pool.n_allocatable, \
            f"leaked blocks: {pool.blocks_free}/{pool.n_allocatable} free"
        assert pool.blocks_reserved == 0, "leaked reservations"
        assert reg.get("generation/kv_hbm_bytes|lane=pool") == \
            eng8.kv_nbytes() > 0
    finally:
        eng8.close()

    # paged fp32 must reproduce the ring engine's greedy tokens EXACTLY
    # (bitwise cache parity); int8 above holds its own tolerance bar in
    # tests/test_pagedkv.py, so here the fp32 lane carries the equality
    obs.set_observability(metrics=True, tracing=True, compile_monitor=True)
    cfgp = GenerationConfig(buckets=BUCKETS, slots=SLOTS, capacity=8,
                            max_new_tokens=6, paged=True, kv_block_size=8)
    with GenerationEngine(model, params, config=cfgp) as engp:
        prompt = [7, 3, 19]
        got = engp.generate(prompt, max_new_tokens=5).tokens
        ctx = list(prompt)
        for want_i in range(5):
            logp, _ = model.apply(params, {}, jnp.asarray([ctx], jnp.int32),
                                  training=False)
            tok = int(jnp.argmax(logp[0, -1]))
            assert int(got[want_i]) == tok, (got, ctx, tok)
            ctx.append(tok)

    print(f"OK: paged+int8 lane green — {N_REQUESTS} requests through a "
          f"24-block pool, {n_exec8}/{budget} executables, 0 steady "
          f"recompiles, pool leak-free, paged fp32 greedy == ring greedy")

    # -- chunked prefill + speculative decoding lane (ISSUE 15) ----------
    draft = TransformerLM(vocab_size=61, hidden_size=32, n_layer=1,
                          n_head=4, max_len=128, use_flash=False)
    dparams, _ = draft.init((1, 16), rng=jax.random.PRNGKey(1))
    rng = np.random.RandomState(2)
    shorts = [rng.randint(0, 61, size=int(rng.randint(2, 14))).tolist()
              for _ in range(12)]
    long_prompt = rng.randint(0, 61, size=4096).tolist()

    def spec_burst(**kw):
        obs.set_observability(metrics=True, tracing=True,
                              compile_monitor=True)
        m = obs.compile_monitor()
        e = GenerationEngine(
            model, params, buckets=BUCKETS, slots=SLOTS,
            capacity=N_REQUESTS, max_new_tokens=6, temperature=0.0,
            paged=True, kv_block_size=8, kv_pool_blocks=24,
            prefill_chunk=32, **kw)
        try:
            futs = [e.submit(p) for p in shorts[:6]]
            f_long = e.submit(long_prompt)        # 4k prompt mid-burst
            futs += [e.submit(p) for p in shorts[6:]]
            toks = [list(f.result(timeout=240).tokens) for f in futs]
            toks.append(list(f_long.result(timeout=240).tokens))
            return (toks, e.compile_count(), m.recompiles("generation/"),
                    e.metrics.snapshot(), e._pool)
        finally:
            e.close()

    base_toks, _, _, snap0, _ = spec_burst()
    spec_toks, n_spec, n_re_s, snap_s, pool = spec_burst(
        spec_decode=True, spec_k=3, draft_model=draft,
        draft_params=dparams)
    assert spec_toks == base_toks, \
        "spec-on greedy diverged from spec-off greedy"
    spec_budget = 5 * len(BUCKETS)
    assert n_spec <= spec_budget, \
        f"spec burst grew the executable set to {n_spec} " \
        f"(budget {spec_budget})"
    assert n_re_s == 0, \
        f"{n_re_s} steady-state recompiles with chunk+spec on"
    assert pool.blocks_free == pool.n_allocatable, \
        f"leaked blocks: {pool.blocks_free}/{pool.n_allocatable} free"
    assert pool.blocks_reserved == 0, "leaked reservations"
    for snap_i in (snap0, snap_s):
        assert snap_i["prefill_chunks"] >= 4096 // 32, snap_i
        assert snap_i["ttft_under_long_prefill_ms"]["count"] >= 1, snap_i
    assert snap_s["spec_rounds"] > 0 and \
        0.0 <= snap_s["spec_accept_rate"] <= 1.0, snap_s

    print(f"OK: chunk+spec lane green — 4k prompt chunked mid-burst "
          f"({snap_s['prefill_chunks']} chunks, contended ttft p99="
          f"{snap_s['ttft_under_long_prefill_ms']['p99']}ms), spec-on "
          f"greedy == spec-off greedy, accept rate "
          f"{snap_s['spec_accept_rate']}, {n_spec}/{spec_budget} "
          f"executables, 0 steady recompiles, pool leak-free")

    # -- prefix cache lane (ISSUE 18) ------------------------------------
    # a taller model (max_len 2048) so a 1k system prompt fits the
    # no-wrap bucket; pool of 100 blocks is oversubscribed (two cold
    # 66-block requests would need 132) so warm admissions must ride
    # the shared head to run concurrently
    big = TransformerLM(vocab_size=61, hidden_size=32, n_layer=2,
                        n_head=4, max_len=2048, use_flash=False)
    bparams, _ = big.init((1, 16), rng=jax.random.PRNGKey(0))
    rng = np.random.RandomState(4)
    head = rng.randint(1, 61, size=1024).tolist()
    prompts = [head + rng.randint(1, 61, size=int(k)).tolist()
               for k in rng.randint(4, 17, size=6)]

    def prefix_burst(on):
        obs.set_observability(metrics=True, tracing=True,
                              compile_monitor=True)
        m = obs.compile_monitor()
        e = GenerationEngine(
            big, bparams, buckets=(1152,), slots=2, capacity=8,
            max_new_tokens=8, temperature=0.0, paged=True,
            kv_block_size=16, kv_pool_blocks=100, prefill_chunk=64,
            prefix_cache=on)
        try:
            futs = [e.submit(p) for p in prompts]
            toks = [list(f.result(timeout=240).tokens) for f in futs]
            e.drain()
            pool, store = e._pool, e.prefix_store
            held = len(store) if on else 0
            assert pool.blocks_free + held == pool.n_allocatable, \
                f"leaked blocks: {pool.blocks_free} free + {held} " \
                f"store-held != {pool.n_allocatable}"
            assert pool.blocks_reserved == 0, "leaked reservations"
            assert pool.blocks_shared == 0, "shared refs outlived slots"
            if on:
                store.clear()
                assert pool.blocks_free == pool.n_allocatable, \
                    "store.clear() leaked blocks"
            return (toks, e.compile_count(),
                    m.recompiles("generation/"), e.metrics.snapshot())
        finally:
            e.close()

    cold_toks, _, _, snap_c = prefix_burst(False)
    warm_toks, n_px, n_re_p, snap_w = prefix_burst(True)
    assert warm_toks == cold_toks, \
        "prefix-cache greedy diverged from the cold engine"
    assert snap_w["prefix_hits"] >= len(prompts) - 1, snap_w
    assert snap_w["prefix_tokens_reused"] >= (len(prompts) - 1) * 960, \
        snap_w
    assert snap_w["prefill_chunks"] * 2 < snap_c["prefill_chunks"], \
        (snap_w["prefill_chunks"], snap_c["prefill_chunks"])
    assert n_px <= 2, \
        f"prefix burst grew the executable set to {n_px} (budget 2)"
    assert n_re_p == 0, \
        f"{n_re_p} steady-state recompiles with prefix cache on"

    print(f"OK: prefix cache lane green — {len(prompts)} requests on a "
          f"1k shared head, {snap_w['prefix_hits']} hits, "
          f"{snap_w['prefix_tokens_reused']} tokens reused, chunks "
          f"{snap_c['prefill_chunks']} cold -> {snap_w['prefill_chunks']} "
          f"warm, greedy identical, {n_px}/2 executables, 0 steady "
          f"recompiles, pool leak-free")
    return 0


if __name__ == "__main__":
    sys.exit(main())
