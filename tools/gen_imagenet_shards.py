"""Write ImageNet-shaped TFRecord shards for input-pipeline benchmarking.

Records carry REAL JPEG bytes (`image/encoded` + `image/class/label`
Example features — the standard ImageNet-TFRecord schema) so the host
pipeline pays the true decode cost.  Pixels are synthetic but with
natural-image statistics (smooth low-frequency fields + blobs + grain,
~street-scene JPEG entropy) so per-image decode time and file size are
ImageNet-like (~tens of KB at 500x375, the ImageNet-train average frame).

A pool of --pool distinct JPEGs is generated once and cycled with fresh
labels to reach the target size: encode cost is paid per POOL image,
decode cost downstream is identical for every record, and the byte
stream is exactly what the reference's production path consumes
(dataset/DataSet.scala:482-560 SeqFile ImageNet -> here TFRecord shards +
native/src/prefetch.cc).

    python tools/gen_imagenet_shards.py --out data/imagenet_tfr --gb 20
"""

from __future__ import annotations

import argparse
import io
import os

import numpy as np
from scipy import ndimage


def make_jpeg(rs: np.random.RandomState, h: int = 375, w: int = 500) -> bytes:
    from PIL import Image

    # low-frequency color field (the "scene")
    base = rs.rand(3, h // 25 + 2, w // 25 + 2).astype(np.float32)
    img = np.stack([ndimage.zoom(c, 25, order=3)[:h, :w] for c in base], -1)
    # mid-frequency blobs (objects/texture)
    blobs = rs.rand(3, h // 5 + 2, w // 5 + 2).astype(np.float32)
    img += 0.35 * np.stack([ndimage.zoom(c, 5, order=1)[:h, :w]
                            for c in blobs], -1)
    img += 0.05 * rs.rand(h, w, 3).astype(np.float32)  # grain
    img = (255 * (img - img.min()) / (np.ptp(img) + 1e-6)).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, "JPEG", quality=88)
    return buf.getvalue()


def main(argv=None) -> None:
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bigdl_tpu.dataset.tfrecord import TFRecordWriter
    from bigdl_tpu.nn.tf_ops import build_example_proto

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="data/imagenet_tfr")
    ap.add_argument("--gb", type=float, default=20.0)
    ap.add_argument("--pool", type=int, default=1024,
                    help="distinct JPEGs; cycled with fresh labels")
    ap.add_argument("--shard-mb", type=int, default=256)
    args = ap.parse_args(argv)

    rs = np.random.RandomState(7)
    pool = [make_jpeg(rs) for _ in range(args.pool)]
    mean = sum(map(len, pool)) / len(pool)
    print(f"pool: {args.pool} jpegs, mean {mean/1e3:.1f} KB")

    os.makedirs(args.out, exist_ok=True)
    target = int(args.gb * 1e9)
    shard_target = args.shard_mb * 1_000_000
    written = shard_idx = n_rec = 0
    w = None
    lab_rs = np.random.RandomState(11)
    while written < target:
        if w is None:
            path = os.path.join(args.out,
                                f"train-{shard_idx:05d}.tfrecord")
            w = TFRecordWriter(path)
            shard_written = 0
        rec = build_example_proto({
            "image/encoded": [pool[n_rec % args.pool]],
            "image/class/label": np.asarray(
                [lab_rs.randint(0, 1000)], np.int64),
        })
        w.write(rec)
        n_rec += 1
        written += len(rec) + 16
        shard_written += len(rec) + 16
        if shard_written >= shard_target:
            w.close()
            w = None
            shard_idx += 1
    if w is not None:
        w.close()
        shard_idx += 1
    print(f"{n_rec} records, {shard_idx} shards, "
          f"{written/1e9:.2f} GB -> {args.out}")


if __name__ == "__main__":
    main()
