#!/usr/bin/env python
"""Reconcile a runtime lockdep export against the static lock graph.

    tools/lockdep_reconcile.py /tmp/lockdep_fleet.json [paths...]

Loads the JSON written by `bigdl_tpu.analysis.lockdep.export_graph`
(site-keyed acquired-before edges observed while a smoke ran under
`BIGDL_TPU_LOCKDEP=1`), rebuilds the static graph from source, and
checks that EVERY runtime edge was statically predicted:

  * each runtime lock creation site must map to a lock the static pass
    registered (`LockGraph.site_index()` joins on `file:line`);
  * each observed src -> dst edge must exist in the static graph (weak
    edges count — prediction, not proof, is the bar).

An unpredicted edge means the static pass has a resolution blind spot
(or new code took locks through a callback the linter cannot see) —
either teach `bigdl_tpu.analysis.concurrency` the pattern or
restructure the code so the order is visible, as `BlockPool.claim`
does by invoking the reclaim hook outside the pool lock.

Exit codes: 0 reconciled, 1 unpredicted edges / unknown sites, 2 usage
error.  Runtime violations recorded in the export always fail (the CI
lane asserts zero separately, but belt and braces).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bigdl_tpu.analysis.linter import project_for_paths  # noqa: E402

DEFAULT_PATHS = ["bigdl_tpu/"]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("export", help="JSON from lockdep.export_graph")
    ap.add_argument("paths", nargs="*",
                    help="source paths for the static pass "
                         "(default: bigdl_tpu/)")
    ap.add_argument("--require-edges", type=int, default=0, metavar="N",
                    help="fail unless the export holds >= N edges "
                         "(guards against a smoke that never nested)")
    args = ap.parse_args(argv)

    if not os.path.exists(args.export):
        print(f"lockdep_reconcile: no export at {args.export}",
              file=sys.stderr)
        return 2
    with open(args.export) as fh:
        snap = json.load(fh)

    proj = project_for_paths(args.paths or DEFAULT_PATHS)
    graph = proj.lock_graph
    sites = graph.site_index()

    runtime_edges = [e for e in snap.get("edges", [])
                     if not e.get("same_site")]
    problems = []

    if snap.get("violations"):
        for v in snap["violations"]:
            problems.append("runtime violation: %s (%s)"
                            % (" -> ".join(v.get("cycle", [])),
                               v.get("kind", "?")))

    n_checked = 0
    for e in runtime_edges:
        src_key = sites.get(e["src"])
        dst_key = sites.get(e["dst"])
        if src_key is None or dst_key is None:
            missing = [s for s, k in ((e["src"], src_key),
                                      (e["dst"], dst_key)) if k is None]
            problems.append("unknown lock site(s) %s for runtime edge "
                            "%s -> %s — static pass never registered a "
                            "lock created there"
                            % (", ".join(missing), e["src"], e["dst"]))
            continue
        if src_key == dst_key:
            continue  # cross-instance sibling order: static rule's job
        n_checked += 1
        if (src_key, dst_key) not in graph.edges:
            problems.append("unpredicted edge %s -> %s (observed %dx, "
                            "thread %s) — not in the static graph"
                            % (src_key, dst_key, e.get("count", 1),
                               e.get("thread", "?")))

    if len(runtime_edges) < args.require_edges:
        problems.append("export holds %d edge(s), need >= %d — did the "
                        "smoke actually run instrumented?"
                        % (len(runtime_edges), args.require_edges))

    if problems:
        print("lockdep_reconcile: FAILED (%d problem(s)):" % len(problems),
              file=sys.stderr)
        for p in problems:
            print("  " + p, file=sys.stderr)
        return 1

    print("lockdep_reconcile: %d runtime edge(s) over %d site(s), all "
          "statically predicted (static graph: %d locks, %d edges)"
          % (n_checked,
             len({s for e in runtime_edges for s in (e["src"], e["dst"])}),
             len(graph.nodes), len(graph.edges)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
