#!/usr/bin/env bash
# CI gate for the quick test tier (VERDICT r4 item 8).
#
# Runs `pytest -m "not slow"` under a HARD wall-clock budget and fails on
# breach — the budget keeps the quick tier honest: tests that grow past
# it must either get faster or move to the slow tier (the reference's
# integration-test tag split, spark/dl/pom.xml:327-341).
#
#   tools/ci_quick_tier.sh [budget_seconds]   # default 180
set -u
BUDGET="${1:-180}"
cd "$(dirname "$0")/.."

# docs must track the code: PARITY.md claims vs shipped evidence
python tools/parity_drift_guard.py || exit 1

# TPU-hostile-pattern lint (docs/analysis.md): hot-path findings are
# hard failures, non-hot-path ones must be in the committed baseline
python tools/tpu_lint.py bigdl_tpu/ examples/ benchmarks/ \
    --baseline tools/tpu_lint_baseline.json || exit 1

start=$(date +%s)
timeout --signal=TERM "$BUDGET" python -m pytest tests/ -m "not slow" -q
rc=$?
elapsed=$(( $(date +%s) - start ))

if [ "$rc" -eq 0 ]; then
    # chaos lane: the deterministic fault-injection tests get their own
    # visible pass/fail line (a broken recovery path must not hide in the
    # bulk tier's dots) and run inside the same wall-clock budget —
    # including the sharded-checkpoint faults (single-chunk bitflip must
    # fall back loudly) and the kill-under-mesh-A / resume-under-mesh-B
    # topology-change fixture
    remaining=$(( BUDGET - elapsed ))
    [ "$remaining" -lt 30 ] && remaining=30
    timeout --signal=TERM "$remaining" python -m pytest \
        tests/test_resilience.py tests/test_health.py \
        tests/test_sharded_ckpt.py tests/test_elastic_reshard.py \
        tests/test_failover.py \
        -m "chaos and not slow" -q
    rc=$?
    elapsed=$(( $(date +%s) - start ))
fi

if [ "$rc" -eq 0 ]; then
    # obs lane: a short traced train + serving burst in one process; the
    # exported Chrome trace must be valid JSON with the feed/dispatch/
    # ckpt/serving spans and >=1 compile event attributed to a bucket
    # signature, and the metrics snapshot must export cleanly
    remaining=$(( BUDGET - elapsed ))
    [ "$remaining" -lt 30 ] && remaining=30
    timeout --signal=TERM "$remaining" python tools/obs_smoke.py
    rc=$?
    elapsed=$(( $(date +%s) - start ))
fi

if [ "$rc" -eq 0 ]; then
    # aot-cache lane: the same tiny train twice in fresh processes against
    # one BIGDL_TPU_COMPILE_CACHE dir — run 1 must store executables, run 2
    # must load them (cache hits + a compile.cache_load span) with zero
    # steady-recompile alarms; a silent cold restart fails here, not in prod
    remaining=$(( BUDGET - elapsed ))
    [ "$remaining" -lt 30 ] && remaining=30
    timeout --signal=TERM "$remaining" python tools/obs_smoke.py --aot-cache
    rc=$?
    elapsed=$(( $(date +%s) - start ))
fi

if [ "$rc" -eq 0 ]; then
    # readers lane: the disaggregated input plane under JAX_PLATFORMS=cpu
    # — a procs=2 pool must be bitwise-equal to the inline path (epoch
    # sequence AND trainer losses) and leak zero children; order bugs in
    # the reorder stage fail here, not as silent training-data skew
    remaining=$(( BUDGET - elapsed ))
    [ "$remaining" -lt 30 ] && remaining=30
    timeout --signal=TERM "$remaining" python tools/readers_smoke.py
    rc=$?
    elapsed=$(( $(date +%s) - start ))
fi

if [ "$rc" -eq 0 ]; then
    # generation lane: 32 concurrent prompts through the prefill/decode
    # engine — the executable set must stay <= buckets x 2 with zero
    # steady-state recompile alarms, greedy output must match a full
    # re-forward loop, and a hot-swap under traffic must not re-trace;
    # plus the paged+int8, chunk+spec, and prefix-cache lanes (a 1k
    # shared system prompt through an oversubscribed pool: hits > 0,
    # greedy identical to the cold run, zero leaked blocks)
    remaining=$(( BUDGET - elapsed ))
    [ "$remaining" -lt 30 ] && remaining=30
    timeout --signal=TERM "$remaining" python tools/generation_smoke.py
    rc=$?
    elapsed=$(( $(date +%s) - start ))
fi

if [ "$rc" -eq 0 ]; then
    # fleet lane: 2 tenants x 2 replicas through the multi-tenant front
    # door with a replica SIGKILL mid-burst — the interactive tenant's
    # SLO must hold under the batch flood, zero accepted requests may be
    # silently dropped, and the replacement replica must warm from the
    # compilecache (warmup_reused > 0, zero steady-recompile alarms)
    remaining=$(( BUDGET - elapsed ))
    [ "$remaining" -lt 30 ] && remaining=30
    timeout --signal=TERM "$remaining" python tools/fleet_smoke.py
    rc=$?
    elapsed=$(( $(date +%s) - start ))
fi

if [ "$rc" -eq 0 ]; then
    # failover lane: a 2-replica GENERATION fleet over an oversubscribed
    # paged pool — one request killed mid-decode must settle token-for-
    # token identical to the unkilled run through a prefix-warm resume
    # on the survivor, one killed mid-prefill-chunk must recompute cold
    # with zero loss, and the incident must leave exactly one flight
    # bundle, leak-free survivor pools, and zero steady-recompile alarms
    remaining=$(( BUDGET - elapsed ))
    [ "$remaining" -lt 30 ] && remaining=30
    timeout --signal=TERM "$remaining" python tools/fleet_smoke.py --failover
    rc=$?
    elapsed=$(( $(date +%s) - start ))
fi

if [ "$rc" -eq 0 ]; then
    # flight-recorder lane: the same 2x2 fleet with the black box armed
    # and a replica killed mid-burst — the incident must leave exactly
    # ONE postmortem bundle naming the trigger, the stitched fleet trace
    # must link the bounced request's admit -> dispatch -> redispatch ->
    # complete chain across lanes, and the SloMonitor must page a
    # burn-rate alert for the affected tenant
    remaining=$(( BUDGET - elapsed ))
    [ "$remaining" -lt 30 ] && remaining=30
    timeout --signal=TERM "$remaining" python tools/obs_smoke.py --fleet
    rc=$?
    elapsed=$(( $(date +%s) - start ))
fi

if [ "$rc" -eq 0 ]; then
    # lockdep lane: the fleet + generation + readers smokes again, this
    # time with the runtime lock-order sanitizer armed — any acquisition
    # that closes a cycle in the acquired-before graph raises inside the
    # smoke (rc != 0), each exported graph must be non-empty, and every
    # runtime edge must be predicted by the static lock-discipline pass
    # (tools/lockdep_reconcile.py: runtime ⊆ static, see docs/analysis.md)
    ld_dir=$(mktemp -d)
    for smoke in fleet generation readers; do
        [ "$rc" -ne 0 ] && break
        remaining=$(( BUDGET - elapsed ))
        [ "$remaining" -lt 30 ] && remaining=30
        BIGDL_TPU_LOCKDEP=1 \
        BIGDL_TPU_LOCKDEP_EXPORT="$ld_dir/${smoke}.json" \
            timeout --signal=TERM "$remaining" \
            python "tools/${smoke}_smoke.py"
        rc=$?
        elapsed=$(( $(date +%s) - start ))
        if [ "$rc" -eq 0 ]; then
            python tools/lockdep_reconcile.py "$ld_dir/${smoke}.json" \
                --require-edges 1
            rc=$?
        fi
    done
    rm -rf "$ld_dir"
fi

if [ "$rc" -eq 124 ]; then
    echo "FAIL: quick tier exceeded the ${BUDGET}s budget (killed)" >&2
    exit 1
fi
if [ "$rc" -ne 0 ]; then
    echo "FAIL: quick tier red (pytest rc=$rc, ${elapsed}s)" >&2
    exit "$rc"
fi
echo "OK: quick tier green in ${elapsed}s (budget ${BUDGET}s)"
