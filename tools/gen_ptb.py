"""Deterministic PTB-format corpus builder from REAL local English text.

The Penn Treebank corpus is licensed/undownloadable here (zero egress), so
this builds a corpus in the exact PTB distribution format — lowercase
tokenized text, numbers collapsed to `N`, rare words to `<unk>`, one
sentence per line, files named ptb.{train,valid,test}.txt — from the ~30 MB
of genuine human-written English prose already on this machine: the
docstrings of the installed numpy/scipy/jax/sklearn/pandas/torch/
transformers/matplotlib packages.  This is real natural language (written
by thousands of open-source contributors), not a synthetic token stream,
so a held-out perplexity on it is a meaningful measure of language-model
learning.  It is NOT the Penn Treebank; perplexities are comparable only
within this corpus, and every reported number says so.

Deterministic: files are walked in sorted order, the train/valid/test
split is a hash of the source path (so it is stable under re-runs and
package-version noise only moves individual files between splits), and
the output sha256s are printed.

    python tools/gen_ptb.py --out data/ptb

Stands in for: example/languagemodel/PTBWordLM.scala reading
ptb.train.txt via SequencePreprocess (models/rnn/Train.scala:48-59).
"""

from __future__ import annotations

import argparse
import ast
import collections
import glob
import hashlib
import os
import re

PKGS = ("numpy", "scipy", "jax", "sklearn", "pandas", "torch",
        "transformers", "matplotlib")


def _site() -> str:
    import numpy
    return os.path.dirname(os.path.dirname(numpy.__file__))

# lines that are rst/doctest/table noise, not prose
_SKIP = re.compile(
    r"^\s*(>>>|\.\.\.(\s|$)|\.\.\s|:\w+[^:]*:|-{3,}|={3,}|~{3,}|\*{3,}"
    r"|\||\+[-=+]|#|@|def |class |import |from |return |raise )")
_REF = re.compile(r"(:\w+:`[^`]*`|``[^`]*``|`[^`]*`_?|\[[0-9R]+\]_?)")
_NUM = re.compile(r"^[+-]?(\d+([.,]\d+)*|\.\d+)(e[+-]?\d+)?$", re.I)
_TOKEN = re.compile(r"[a-z0-9_.+-]+|[^\sa-z0-9]")
_SENT_SPLIT = re.compile(r"(?<=[.!?])\s+(?=[A-Z`\"'(])")


def _docstrings(path: str):
    try:
        tree = ast.parse(open(path, encoding="utf-8", errors="ignore").read())
    except (SyntaxError, ValueError, OSError):
        return
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            d = ast.get_docstring(node)
            if d:
                yield d


def _prose_sentences(doc: str):
    """Keep prose lines, drop code/markup; yield tokenized sentences."""
    para: list[str] = []
    for raw in doc.split("\n") + [""]:
        line = raw.strip()
        if not line or _SKIP.match(raw):
            if para:
                yield from _split_para(" ".join(para))
                para = []
            continue
        para.append(line)


def _split_para(text: str):
    text = _REF.sub(" ", text)
    for sent in _SENT_SPLIT.split(text):
        toks = _TOKEN.findall(sent.lower())
        toks = ["N" if _NUM.match(t) else t for t in toks]
        # prose filter: real sentences, not leftover signatures/paths
        if 4 <= len(toks) <= 60 and sum(t.isalpha() for t in toks) >= 3:
            yield toks


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="data/ptb")
    ap.add_argument("--vocab-size", type=int, default=10_000,
                    help="PTB convention: top vocab-1 words + <unk>")
    ap.add_argument("--max-train-tokens", type=int, default=950_000,
                    help="cap near real-PTB scale (929k train tokens)")
    ap.add_argument("--pkgs", default=None,
                    help="comma-separated package subset (default: all)")
    args = ap.parse_args(argv)
    pkgs = tuple(args.pkgs.split(",")) if args.pkgs else PKGS

    splits: dict[str, list[list[str]]] = {"train": [], "valid": [], "test": []}
    site = _site()
    files = []
    for pkg in pkgs:
        files += sorted(glob.glob(os.path.join(site, pkg, "**/*.py"),
                                  recursive=True))
    for path in files:
        rel = os.path.relpath(path, site)
        h = int(hashlib.sha256(rel.encode()).hexdigest(), 16) % 20
        split = "valid" if h == 0 else ("test" if h == 1 else "train")
        for doc in _docstrings(path):
            splits[split].extend(_prose_sentences(doc))

    # PTB-exact proportions: cap train, scale valid/test to ~7.5%/8.8% of it
    budgets = {"train": args.max_train_tokens,
               "valid": int(args.max_train_tokens * 0.079),
               "test": int(args.max_train_tokens * 0.089)}
    for name, sents in splits.items():
        kept, tok = [], 0
        for s in sents:
            if tok >= budgets[name]:
                break
            kept.append(s)
            tok += len(s) + 1  # +1: the <eos> the loader appends per line
        splits[name] = kept

    counts = collections.Counter(
        t for s in splits["train"] for t in s)
    vocab = {w for w, _ in counts.most_common(args.vocab_size - 1)}

    os.makedirs(args.out, exist_ok=True)
    for name, sents in splits.items():
        path = os.path.join(args.out, f"ptb.{name}.txt")
        with open(path, "w", encoding="utf-8") as f:
            for s in sents:
                f.write(" " + " ".join(
                    t if t in vocab else "<unk>" for t in s) + " \n")
        n_tok = sum(len(s) for s in sents)
        h = hashlib.sha256(open(path, "rb").read()).hexdigest()[:16]
        print(f"{path}  {len(sents)} sentences  {n_tok} tokens  sha256:{h}")
    print(f"vocab: {min(len(counts), args.vocab_size - 1) + 1} types "
          f"(incl <unk>); corpus: real docstring prose from {pkgs}")


if __name__ == "__main__":
    main()
