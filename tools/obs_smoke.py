"""CI obs lane: one traced train + serving burst, validated end to end.

Runs a short traced training run and a concurrent serving burst in ONE
process with the full observability plane on, exports the Chrome trace
and a metrics snapshot, and exits nonzero unless:

  * the trace file parses as VALID Chrome-trace JSON (json.load, not
    json-ish) and every event carries ph/name/pid/tid (+ts for X/i,
    +dur for X);
  * the trace contains the trainer phase spans (feed_next,
    step_dispatch), a checkpoint span (ckpt_save + the writer lane's
    ckpt.write), and the serving lifecycle (serve.admit, serve.dispatch,
    serve.complete);
  * at least one xla_compile event is attributed to a
    serving/bucket=N signature (the acceptance criterion) and one to
    train/step/bs=N;
  * zero steady-state recompiles were flagged across the whole run;
  * the metrics snapshot carries the expected train/serving/ckpt
    counters and exports to JSONL + Prometheus textfile formats.

Usage: python tools/obs_smoke.py [outdir]   (default: a temp dir)

`--aot-cache` runs the executable-cache lane instead (ISSUE 7 CI
acceptance): the same tiny train TWICE in separate processes against one
`BIGDL_TPU_COMPILE_CACHE` dir, asserting the first run stores executables
(cache misses > 0), the second run loads them (cache hits > 0, a
compile.cache_load span in its trace) and raises zero steady-recompile
alarms.  `--aot-cache-child` is one such process.
"""

import json
import os
import sys
import tempfile
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax.extend.backend as _jeb

    _jeb.clear_backends()
except Exception:  # pragma: no cover - fallback for older jax
    import jax._src.xla_bridge as _xb

    _xb._clear_backends()

import bigdl_tpu.nn as nn  # noqa: E402
from bigdl_tpu import obs, optim  # noqa: E402
from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch  # noqa: E402
from bigdl_tpu.optim import SGD, Trigger  # noqa: E402
from bigdl_tpu.serving import ServingRuntime  # noqa: E402

REQUIRED_SPANS = ("feed_next", "step_dispatch", "ckpt_save", "ckpt.write",
                  "serve.dispatch")
REQUIRED_INSTANTS = ("serve.admit", "serve.complete", "ckpt.commit")


def fail(msg):
    print(f"FAIL(obs_smoke): {msg}", file=sys.stderr)
    sys.exit(1)


def run_traced_train(ckpt_dir):
    rs = np.random.RandomState(7)
    samples = [Sample.from_ndarray(rs.randn(8).astype(np.float32),
                                   rs.randn(4).astype(np.float32))
               for _ in range(64)]
    ds = ArrayDataSet(samples).transform(SampleToMiniBatch(16))
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    o = optim.LocalOptimizer(model, ds, nn.MSECriterion(),
                             optim_method=SGD(learning_rate=0.05),
                             end_trigger=Trigger.max_epoch(2))
    o.set_checkpoint(ckpt_dir, Trigger.several_iteration(3))
    o.set_strict_transfers(True)  # the tracer must add zero device syncs
    o.optimize()


def run_serving_burst():
    model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4),
                          nn.LogSoftMax())
    params, state, _ = model.build(jax.random.PRNGKey(0), (8, 6))
    rs = np.random.RandomState(0)
    xs = [rs.randn(1, 6).astype(np.float32) for _ in range(32)]
    with ServingRuntime(model, params, state, buckets=(1, 8, 32),
                        example_input=np.zeros((1, 6), np.float32),
                        max_wait_ms=5.0) as rt:
        with ThreadPoolExecutor(max_workers=32) as pool:
            futures = list(pool.map(rt.submit, xs))
        outs = [f.result(30.0) for f in futures]
    cids = [f.meta["cid"] for f in futures]
    if len(set(cids)) != len(xs):
        fail(f"correlation ids not unique: {len(set(cids))}/{len(xs)}")
    if not all(o.shape == (1, 4) for o in outs):
        fail("serving outputs have wrong shapes")


def validate_trace(path):
    try:
        with open(path) as f:
            doc = json.load(f)
    except Exception as e:
        fail(f"trace is not valid JSON: {e}")
    evs = doc.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        fail("traceEvents missing or empty")
    for ev in evs:
        for field in ("ph", "name", "pid", "tid"):
            if field not in ev:
                fail(f"event missing {field!r}: {ev}")
        if ev["ph"] in ("X", "i") and "ts" not in ev:
            fail(f"timed event missing ts: {ev}")
        if ev["ph"] == "X" and "dur" not in ev:
            fail(f"complete event missing dur: {ev}")
    names = {e["name"] for e in evs}
    for req in REQUIRED_SPANS + REQUIRED_INSTANTS:
        if req not in names:
            fail(f"span/instant {req!r} absent from trace "
                 f"(have: {sorted(names)})")
    compiles = [e for e in evs if e["name"] == "xla_compile"]
    sigs = {e["args"]["signature"] for e in compiles}
    if not any(s.startswith("serving/bucket=") for s in sigs):
        fail(f"no compile event attributed to a bucket signature: {sigs}")
    if not any(s.startswith("train/step/bs=") for s in sigs):
        fail(f"no compile event attributed to a train step: {sigs}")
    if any(e["args"]["steady_recompile"] for e in compiles):
        fail("steady-state recompile flagged during the smoke run")
    return len(evs), sorted(sigs)


def validate_metrics(outdir):
    reg = obs.registry()
    snap = reg.snapshot()
    for counter, at_least in (("train/steps", 8),
                              ("ckpt/committed", 2),
                              ("serving/requests_admitted", 32),
                              ("serving/requests_completed", 32),
                              ("compile/total", 2)):
        if snap["counters"].get(counter, 0) < at_least:
            fail(f"counter {counter} = {snap['counters'].get(counter, 0)} "
                 f"< {at_least}")
    if snap["counters"].get("compile/steady_recompiles", 0):
        fail("compile/steady_recompiles nonzero")
    if "train/loss" not in snap["gauges"]:
        fail("train/loss gauge missing")
    jsonl = os.path.join(outdir, "metrics.jsonl")
    prom = os.path.join(outdir, "metrics.prom")
    reg.export_jsonl(jsonl, step=int(snap["counters"]["train/steps"]))
    reg.export_prometheus(prom)
    with open(jsonl) as f:
        json.loads(f.readline())
    with open(prom) as f:
        if "bigdl_tpu_train_steps" not in f.read():
            fail("prometheus export missing bigdl_tpu_train_steps")
    return snap


def aot_cache_child(cache_dir):
    """One process of the aot-cache lane: tiny train with the executable
    cache on + full tracing, then report the cache counters and whether
    the trace carries a compile.cache_load span."""
    os.environ["BIGDL_TPU_COMPILE_CACHE"] = cache_dir
    obs.set_observability(metrics=True, tracing=True, compile_monitor=True)
    with tempfile.TemporaryDirectory() as ckpt:
        run_traced_train(os.path.join(ckpt, "ckpt"))
    reg = obs.registry()
    tr = obs.tracer()
    names = {e[1] for e in tr.events()} if tr is not None else set()
    print("AOT_CACHE_CHILD " + json.dumps({
        "cache_hits": int(reg.get("compile/cache_hits")),
        "cache_misses": int(reg.get("compile/cache_misses")),
        "persistent_cache_hits": int(reg.get(
            "compile/persistent_cache_hits")),
        "steady_recompiles": int(reg.get("compile/steady_recompiles")),
        "cache_load_span": "compile.cache_load" in names,
    }), flush=True)


def aot_cache_lane():
    """Parent: two fresh-process children against ONE cache dir."""
    import subprocess

    cache_dir = tempfile.mkdtemp(prefix="aotcache_smoke_")
    runs = []
    for i in range(2):
        env = dict(os.environ)
        env["BIGDL_TPU_COMPILE_CACHE"] = cache_dir
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--aot-cache-child", cache_dir],
            env=env, capture_output=True, text=True, timeout=600)
        row = None
        for line in proc.stdout.splitlines():
            if line.startswith("AOT_CACHE_CHILD "):
                row = json.loads(line[len("AOT_CACHE_CHILD "):])
        if row is None:
            fail(f"aot-cache child {i} produced no report "
                 f"(rc={proc.returncode}):\n{proc.stdout[-2000:]}\n"
                 f"{proc.stderr[-2000:]}")
        runs.append(row)
    if runs[0]["cache_misses"] < 1:
        fail(f"first run stored nothing: {runs[0]}")
    if runs[1]["cache_hits"] < 1:
        fail(f"second run loaded nothing from the warm cache: {runs[1]}")
    if not runs[1]["cache_load_span"]:
        fail(f"second run's trace has no compile.cache_load span: {runs[1]}")
    for i, row in enumerate(runs):
        if row["steady_recompiles"]:
            fail(f"run {i} raised steady-recompile alarms: {row}")
    print(json.dumps({"aot_cache_smoke": "ok", "run1": runs[0],
                      "run2": runs[1]}))


def fleet_lane():
    """Fleet observability lane (ISSUE 14 CI acceptance): 2 tenants x 2
    replicas with the flight recorder on, one replica killed mid-burst.
    Exits nonzero unless the incident leaves exactly ONE postmortem
    bundle naming the trigger, the stitched fleet trace links the
    bounced request's admit -> dispatch(A) -> redispatch -> dispatch(B)
    -> complete chain across lanes, and the SloMonitor pages a
    burn-rate alert for the affected tenant."""
    import bigdl_tpu.compilecache as cc
    from bigdl_tpu.fleet import FleetRouter, TenantConfig
    from bigdl_tpu.obs import SLOObjective, SloMonitor
    from bigdl_tpu.resilience import ReplicaKillFault

    outdir = tempfile.mkdtemp(prefix="obs_smoke_fleet_")
    flight_dir = os.path.join(outdir, "flight")
    obs.set_observability(metrics=True, tracing=True, compile_monitor=True,
                          flight=True, flight_dir=flight_dir)
    cc.set_cache_dir(os.path.join(outdir, "cc"))

    model = nn.Sequential(nn.Linear(6, 32), nn.ReLU(), nn.Linear(32, 4))
    params, state, _ = model.build(jax.random.PRNGKey(0), (8, 6))

    def factory(name):
        return ServingRuntime(model, params, state, buckets=(1, 8),
                              max_wait_ms=1.0,
                              example_input=np.zeros((1, 6), np.float32))

    router = FleetRouter(factory, n_replicas=2,
                         tenants=[TenantConfig("bulk", tier="batch",
                                               weight=2.0, capacity=256),
                                  TenantConfig("chat", tier="interactive",
                                               capacity=64)])
    # a p99 target below any real CPU round-trip: every completion burns
    # budget, so the alert MUST page once the burst lands
    slo = SloMonitor([SLOObjective("chat", p99_ms=0.01),
                      SLOObjective("bulk", p99_ms=0.01)],
                     source=router.tenant_metrics, registry_fn=obs.registry)
    fault = ReplicaKillFault(at_dispatch=8)
    router.set_chaos(fault)
    rs = np.random.RandomState(3)
    try:
        slo.tick(now=0.0)  # pre-burst baseline row
        futs = []
        for i in range(52):
            tenant = "chat" if i % 4 == 0 else "bulk"
            futs.append(router.submit(
                tenant, rs.rand(1, 6).astype(np.float32),
                deadline_ms=60_000))
        outs = [f.result(60) for f in futs]
        if not all(o.shape == (1, 4) for o in outs):
            fail("fleet outputs have wrong shapes")
        if len(fault.fired) != 1:
            fail(f"chaos kill fired {len(fault.fired)} times, want 1")
        verdicts = slo.tick(now=10.0)
        bounced = [f for f in futs if f.meta["attempts"] > 1]
        if not bounced:
            fail("no request bounced through the redispatch path")
        cids = [f.meta["cid"] for f in futs]
        if len(set(cids)) != len(futs):
            fail("correlation ids not unique across the fleet burst")
        trace_path = os.path.join(outdir, "fleet_trace.json")
        obs.export_fleet_trace(trace_path)
    finally:
        router.close()

    # -- stitched trace: valid JSON, every event field-complete ---------
    try:
        with open(trace_path) as f:
            doc = json.load(f)
    except Exception as e:
        fail(f"fleet trace is not valid JSON: {e}")
    evs = doc["traceEvents"]
    for ev in evs:
        for field in ("ph", "name", "pid", "tid"):
            if field not in ev:
                fail(f"fleet-trace event missing {field!r}: {ev}")
        if ev["ph"] in ("X", "i", "s", "t", "f") and "ts" not in ev:
            fail(f"timed event missing ts: {ev}")
        if ev["ph"] == "X" and "dur" not in ev:
            fail(f"complete event missing dur: {ev}")
    lanes = doc["otherData"]["replica_lanes"]
    if sum(1 for n in lanes.values() if n.startswith("replica:")) != 2:
        fail(f"expected 2 replica lanes, got {lanes}")
    # the bounced cid's flow chain crosses lanes, s -> t... -> f
    cid = bounced[0].meta["cid"]
    flow = [e for e in evs
            if e.get("id") == cid and e["name"] == "fleet.request"]
    phs = [e["ph"] for e in flow]
    if phs != ["s"] + ["t"] * (len(flow) - 2) + ["f"] or len(flow) < 4:
        fail(f"bounced cid {cid} flow chain malformed: {phs}")
    if len({e["pid"] for e in flow}) < 2:
        fail(f"flow chain for {cid} never crossed a lane boundary")
    tl = obs.request_timeline(cid)
    if tl["redispatches"] < 1 or len(set(tl["replicas"])) != 2:
        fail(f"timeline for {cid} missing the redispatch hop: {tl}")

    # -- exactly ONE postmortem bundle naming the trigger ---------------
    bundles = sorted(d for d in os.listdir(flight_dir)
                     if "fleet_replica_death" in d)
    if len(bundles) != 1:
        fail(f"want exactly 1 replica-death bundle, got {bundles}")
    with open(os.path.join(flight_dir, bundles[0], "MANIFEST.json")) as f:
        manifest = json.load(f)
    if manifest["reason"] != "fleet.replica_death":
        fail(f"bundle names the wrong trigger: {manifest['reason']}")
    for name in ("fingerprint.json", "events.json", "log_tail.txt",
                 "metrics.json", "trace.json"):
        if not os.path.exists(os.path.join(flight_dir, bundles[0], name)):
            fail(f"bundle incomplete: {name} missing")

    # -- burn-rate alert for the affected tenant ------------------------
    reg = obs.registry()
    if reg.get("slo/alerts_total") < 1 or not slo.alerts:
        fail(f"no SLO burn-rate alert paged: {verdicts}")
    alert_tenants = {a["tenant"] for a in slo.alerts}
    if not alert_tenants & {"bulk", "chat"}:
        fail(f"alert names no fleet tenant: {slo.alerts}")
    n_redis = sum(reg.get(f"fleet/redispatches|tenant={t}")
                  for t in ("bulk", "chat"))
    if not n_redis or n_redis != reg.get("fleet/redispatched"):
        fail(f"per-tenant redispatch count wrong: {n_redis} vs "
             f"{reg.get('fleet/redispatched')}")
    print(json.dumps({
        "obs_smoke_fleet": "ok", "requests": len(futs),
        "bounced": len(bounced), "bounced_cid": cid,
        "redispatches": int(n_redis),
        "alert_tenants": sorted(alert_tenants),
        "bundle": bundles[0], "artifacts": outdir}))


def main():
    if "--fleet" in sys.argv:
        fleet_lane()
        return
    if "--aot-cache-child" in sys.argv:
        aot_cache_child(sys.argv[sys.argv.index("--aot-cache-child") + 1])
        return
    if "--aot-cache" in sys.argv:
        aot_cache_lane()
        return
    outdir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="obs_smoke_")
    os.makedirs(outdir, exist_ok=True)
    obs.set_observability(metrics=True, tracing=True, compile_monitor=True)
    run_traced_train(os.path.join(outdir, "ckpt"))
    run_serving_burst()
    trace_path = os.path.join(outdir, "trace.json")
    obs.export_trace(trace_path)
    n_events, sigs = validate_trace(trace_path)
    snap = validate_metrics(outdir)
    print(json.dumps({
        "obs_smoke": "ok", "trace_events": n_events,
        "compile_signatures": sigs,
        "train_steps": snap["counters"]["train/steps"],
        "serving_completed": snap["counters"]["serving/requests_completed"],
        "artifacts": outdir}))


if __name__ == "__main__":
    main()
