"""CI readers lane: the disaggregated input plane, validated end to end.

Writes a small tfrecord corpus to a temp dir, then asserts — in ONE
process under JAX_PLATFORMS=cpu — the properties docs/training.md
promises for `bigdl_tpu.dataset.readers` (ISSUE 9 acceptance):

  * pool-vs-inline parity: a procs=2 ReaderPool over the corpus yields a
    bitwise-identical epoch batch sequence to the single-process
    `dataset.data(train=True)` path (skip_corrupt=True pins the inline
    path to the deterministic sequential reader);
  * reshard parity: procs=1 and procs=2 sequences are bitwise-identical
    (order is owned by the reorder stage, not the worker:shard map);
  * trainer parity: a short training run with `set_feed(2,
    reader_procs=2)` produces bitwise-identical per-step losses to the
    reader-less run;
  * lifecycle: zero reader children survive the runs.

Usage: python tools/readers_smoke.py
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# lockdep must wrap locks AT CREATION, and importing any bigdl_tpu module
# creates module-level locks — so load the (stdlib-only) sanitizer by file
# path and instrument before the first bigdl_tpu import below
import importlib.util  # noqa: E402

_ld_spec = importlib.util.spec_from_file_location(
    "bigdl_tpu.analysis.lockdep",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "bigdl_tpu", "analysis", "lockdep.py"))
lockdep = importlib.util.module_from_spec(_ld_spec)
sys.modules[_ld_spec.name] = lockdep
_ld_spec.loader.exec_module(lockdep)
lockdep.install_if_enabled()

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax.extend.backend as _jeb

    _jeb.clear_backends()
except Exception:  # pragma: no cover - fallback for older jax
    import jax._src.xla_bridge as _xb

    _xb._clear_backends()

import bigdl_tpu.nn as nn  # noqa: E402
from bigdl_tpu import optim  # noqa: E402
from bigdl_tpu.core.random import RandomGenerator  # noqa: E402
from bigdl_tpu.dataset import (ArrayDataSet, Sample,  # noqa: E402
                               SampleToMiniBatch)
from bigdl_tpu.dataset.readers import ReaderPool  # noqa: E402
from bigdl_tpu.dataset.tfrecord import (ParsedExampleDataSet,  # noqa: E402
                                        TFRecordWriter)
from bigdl_tpu.nn.tf_ops import build_example_proto  # noqa: E402
from bigdl_tpu.optim import SGD, Trigger  # noqa: E402

DIM, BATCH = 4, 8


def write_corpus(root, n_shards=3, per_shard=32):
    rs = np.random.RandomState(0)
    paths = []
    for s in range(n_shards):
        p = os.path.join(root, f"shard{s}.tfrecord")
        with TFRecordWriter(p) as w:
            for i in range(per_shard):
                w.write(build_example_proto(
                    {"x": rs.randn(DIM).astype(np.float32),
                     "y": np.asarray([s * per_shard + i], np.int64)}))
        paths.append(p)
    return paths


def parsed_ds(paths):
    return ParsedExampleDataSet(paths, batch_size=BATCH,
                                dense_keys=["x", "y"],
                                dense_shapes=[(DIM,), ()], label_key="y",
                                skip_corrupt=True)


def epoch_batches(paths, procs):
    RandomGenerator.set_seed(42)
    ds = parsed_ds(paths)
    if procs == 0:
        it = ds.data(train=True)
        return [(np.asarray(b.get_input()), np.asarray(b.get_target()))
                for b in it]
    with ReaderPool(ds.reader_work(train=True), procs=procs,
                    on_corrupt=ds._count_corrupt) as pool:
        return [(np.asarray(b.get_input()), np.asarray(b.get_target()))
                for b in pool]


def assert_seq_equal(a, b, what):
    assert len(a) == len(b), f"{what}: {len(a)} vs {len(b)} batches"
    for i, ((xa, ya), (xb, yb)) in enumerate(zip(a, b)):
        assert xa.dtype == xb.dtype and ya.dtype == yb.dtype, \
            f"{what}: batch {i} dtype drift"
        if not (np.array_equal(xa, xb) and np.array_equal(ya, yb)):
            raise AssertionError(f"{what}: batch {i} differs")


def train_losses(procs, root, tag):
    from bigdl_tpu.utils.summary import TrainSummary

    centers = np.random.RandomState(99).randn(3, 6).astype(np.float32) * 3
    rs = np.random.RandomState(0)
    samples = [Sample.from_ndarray(
        centers[i % 3] + rs.randn(6).astype(np.float32) * 0.3,
        np.int32(i % 3)) for i in range(96)]
    ds = ArrayDataSet(samples).transform(SampleToMiniBatch(16))
    RandomGenerator.set_seed(7)
    model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 3),
                          nn.LogSoftMax())
    o = optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                             optim_method=SGD(learning_rate=0.3),
                             end_trigger=Trigger.max_epoch(2))
    o.set_feed(2, reader_procs=procs)
    o.set_train_summary(TrainSummary(root, tag))
    o.optimize()
    return [v for _, v in o.train_summary.read_scalar("Loss")]


def main():
    with tempfile.TemporaryDirectory() as root:
        paths = write_corpus(root)

        inline = epoch_batches(paths, 0)
        one = epoch_batches(paths, 1)
        two = epoch_batches(paths, 2)
        assert inline, "corpus produced no batches"
        assert_seq_equal(inline, one, "pool(1) vs inline")
        assert_seq_equal(one, two, "pool(2) vs pool(1)")
        print(f"readers_smoke: parity ok ({len(inline)} batches, "
              "inline == procs=1 == procs=2)")

        l0 = train_losses(0, root, "off")
        l2 = train_losses(2, root, "on")
        assert l0 and l0 == l2, (
            f"trainer loss drift with readers on: {l0[:3]} vs {l2[:3]}")
        print(f"readers_smoke: trainer parity ok ({len(l0)} steps "
              "bitwise-equal)")

        time.sleep(0.3)
        import multiprocessing
        orphans = [p for p in multiprocessing.active_children()
                   if p.is_alive()]
        assert not orphans, f"leaked reader children: {orphans}"
        print("readers_smoke: no leaked reader processes")
    print("readers_smoke: OK")


if __name__ == "__main__":
    main()
