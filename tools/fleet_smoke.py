"""CI fleet lane: the multi-tenant front door, validated end to end.

Runs — in ONE process under JAX_PLATFORMS=cpu — the ISSUE 11 acceptance
scenario: 2 tenants x 2 replicas with the compile cache on, a batch-tier
flood against an interactive tenant, a SIGKILL-analog replica drop
mid-burst, and the assertions that make the fleet layer trustworthy:

  * SLO isolation: the flooding batch tenant does not starve the
    interactive tenant — every interactive request completes within its
    deadline class, zero deadline rejections for it;
  * zero silent drops: every ACCEPTED request settles with a result or
    a loud error (killing one replica mid-burst loses nothing);
  * warm scale-out: the replacement replica warms from the process-
    scoped compilecache live layer — `fleet/warmup_reused` > 0 and ZERO
    steady-state recompile alarms;
  * per-tenant metrics: the Prometheus textfile export carries
    `{tenant="..."}` labeled series for both tenants.

Usage: python tools/fleet_smoke.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# lockdep must wrap locks AT CREATION, and importing any bigdl_tpu module
# creates module-level locks — so load the (stdlib-only) sanitizer by file
# path and instrument before the first bigdl_tpu import below
import importlib.util  # noqa: E402

_ld_spec = importlib.util.spec_from_file_location(
    "bigdl_tpu.analysis.lockdep",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "bigdl_tpu", "analysis", "lockdep.py"))
lockdep = importlib.util.module_from_spec(_ld_spec)
sys.modules[_ld_spec.name] = lockdep
_ld_spec.loader.exec_module(lockdep)
lockdep.install_if_enabled()

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax.extend.backend as _jeb

    _jeb.clear_backends()
except Exception:  # pragma: no cover - fallback for older jax
    import jax._src.xla_bridge as _xb

    _xb._clear_backends()

import bigdl_tpu.compilecache as cc  # noqa: E402
import bigdl_tpu.nn as nn  # noqa: E402
from bigdl_tpu import obs  # noqa: E402
from bigdl_tpu.fleet import FleetRouter, TenantConfig  # noqa: E402
from bigdl_tpu.resilience import ReplicaKillFault  # noqa: E402
from bigdl_tpu.serving import ServingRuntime  # noqa: E402

N_BULK = 40
N_CHAT = 12
CHAT_DEADLINE_MS = 10_000.0  # generous for a shared-CPU CI box; the SLO
#                              bar is "completed in deadline", not a
#                              wall-clock latency claim


def main() -> int:
    obs.set_observability(metrics=True, tracing=True, compile_monitor=True)
    reg = obs.registry()
    cache_dir = tempfile.mkdtemp(prefix="fleet_smoke_cc_")
    cc.set_cache_dir(cache_dir)

    model = nn.Sequential(nn.Linear(6, 32), nn.ReLU(), nn.Linear(32, 4))
    params, state, _ = model.build(jax.random.PRNGKey(0), (8, 6))

    def factory(name):
        return ServingRuntime(model, params, state, buckets=(1, 8),
                              max_wait_ms=1.0,
                              example_input=np.zeros((1, 6), np.float32))

    router = FleetRouter(
        factory, n_replicas=2,
        tenants=[TenantConfig("bulk", tier="batch", weight=2.0,
                              capacity=256),
                 TenantConfig("chat", tier="interactive", capacity=64)])
    fault = ReplicaKillFault(at_dispatch=8)
    router.set_chaos(fault)

    rng = np.random.RandomState(0)
    futs = []
    for i in range(N_BULK + N_CHAT):
        if i % ((N_BULK + N_CHAT) // N_CHAT) == 0 and \
                sum(1 for t, _ in futs if t == "chat") < N_CHAT:
            futs.append(("chat", router.submit(
                "chat", rng.rand(1, 6).astype(np.float32),
                deadline_ms=CHAT_DEADLINE_MS)))
        else:
            futs.append(("bulk", router.submit(
                "bulk", rng.rand(4, 6).astype(np.float32),
                deadline_ms=60_000)))

    # scale back out while the burst drains (the replacement must warm
    # from the live layer, not recompile)
    router.add_replica()

    lost = 0
    for tenant, fut in futs:
        try:
            out = fut.result(60)
            assert np.all(np.isfinite(np.asarray(out)))
        except Exception as e:  # noqa: BLE001 — loud errors are allowed…
            print(f"  loud failure ({tenant}): {type(e).__name__}: {e}")
            if tenant == "chat":
                lost += 1  # …but not for the interactive SLO tenant

    snap = router.snapshot()
    chat, bulk = snap["tenants"]["chat"], snap["tenants"]["bulk"]
    prom_path = os.path.join(cache_dir, "metrics.prom")
    reg.export_prometheus(prom_path)
    prom = open(prom_path).read()
    router.close()
    cc.reset()

    n_chat = sum(1 for t, _ in futs if t == "chat")
    n_bulk = len(futs) - n_chat
    print(f"fleet_smoke: {n_bulk} bulk + {n_chat} chat requests, "
          f"kill at dispatch #{fault.at_dispatch}")
    print(f"  killed replica: {fault.fired}")
    print(f"  chat:  completed={chat['requests_completed']} "
          f"deadline_rejected={chat['rejected_deadline']} "
          f"p99={chat['latency_ms']['p99']:.1f}ms")
    print(f"  bulk:  completed={bulk['requests_completed']} "
          f"deadline_rejected={bulk['rejected_deadline']}")
    print(f"  redispatched={snap['redispatched']} "
          f"warmup_reused={snap['warmup_reused']} "
          f"steady_recompiles={reg.get('compile/steady_recompiles')}")

    failures = []
    if len(fault.fired) != 1:
        failures.append(f"chaos fault fired {len(fault.fired)} times, want 1")
    if chat["requests_completed"] != n_chat or lost:
        failures.append(
            f"interactive SLO breach: {chat['requests_completed']}/{n_chat} "
            f"chat requests completed ({lost} failed loudly)")
    total_settled = (chat["requests_completed"] + chat["rejected_deadline"]
                     + bulk["requests_completed"] + bulk["rejected_deadline"])
    if total_settled < len(futs):
        failures.append(
            f"silent drop: {len(futs)} accepted, only {total_settled} "
            "settled with a result or a loud deadline rejection")
    if snap["warmup_reused"] <= 0:
        failures.append("scale-out warmed nothing from the compilecache "
                        "(fleet/warmup_reused == 0)")
    if reg.get("compile/steady_recompiles") > 0:
        failures.append(
            f"{int(reg.get('compile/steady_recompiles'))} steady-state "
            "recompile alarm(s): warm scale-out recompiled")
    for tenant in ("chat", "bulk"):
        needle = f'{{tenant="{tenant}"}}'
        if needle not in prom:
            failures.append(f"Prometheus export missing {needle} series")

    if failures:
        for f in failures:
            print(f"FAIL: {f}")
        return 1
    print("OK: fleet lane green (SLO isolation, zero silent drops, "
          "warm scale-out, per-tenant metrics)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
