"""CI fleet lane: the multi-tenant front door, validated end to end.

Runs — in ONE process under JAX_PLATFORMS=cpu — the ISSUE 11 acceptance
scenario: 2 tenants x 2 replicas with the compile cache on, a batch-tier
flood against an interactive tenant, a SIGKILL-analog replica drop
mid-burst, and the assertions that make the fleet layer trustworthy:

  * SLO isolation: the flooding batch tenant does not starve the
    interactive tenant — every interactive request completes within its
    deadline class, zero deadline rejections for it;
  * zero silent drops: every ACCEPTED request settles with a result or
    a loud error (killing one replica mid-burst loses nothing);
  * warm scale-out: the replacement replica warms from the process-
    scoped compilecache live layer — `fleet/warmup_reused` > 0 and ZERO
    steady-state recompile alarms;
  * per-tenant metrics: the Prometheus textfile export carries
    `{tenant="..."}` labeled series for both tenants.

`--failover` runs the ISSUE 20 acceptance scenario instead: a
2-replica GENERATION fleet over an oversubscribed paged pool, one
request killed mid-decode (token-for-token greedy parity with the
unkilled run, resumed through the survivor's prefix-warm store) and a
second killed mid-prefill-chunk (cold recompute, still zero loss),
with exactly one flight bundle, a leak-free survivor pool, and zero
steady-state recompile alarms.

Usage: python tools/fleet_smoke.py [--failover]
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# lockdep must wrap locks AT CREATION, and importing any bigdl_tpu module
# creates module-level locks — so load the (stdlib-only) sanitizer by file
# path and instrument before the first bigdl_tpu import below
import importlib.util  # noqa: E402

_ld_spec = importlib.util.spec_from_file_location(
    "bigdl_tpu.analysis.lockdep",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "bigdl_tpu", "analysis", "lockdep.py"))
lockdep = importlib.util.module_from_spec(_ld_spec)
sys.modules[_ld_spec.name] = lockdep
_ld_spec.loader.exec_module(lockdep)
lockdep.install_if_enabled()

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax.extend.backend as _jeb

    _jeb.clear_backends()
except Exception:  # pragma: no cover - fallback for older jax
    import jax._src.xla_bridge as _xb

    _xb._clear_backends()

import bigdl_tpu.compilecache as cc  # noqa: E402
import bigdl_tpu.nn as nn  # noqa: E402
from bigdl_tpu import obs  # noqa: E402
from bigdl_tpu.fleet import FleetRouter, TenantConfig  # noqa: E402
from bigdl_tpu.resilience import ReplicaKillFault  # noqa: E402
from bigdl_tpu.serving import ServingRuntime  # noqa: E402

N_BULK = 40
N_CHAT = 12
CHAT_DEADLINE_MS = 10_000.0  # generous for a shared-CPU CI box; the SLO
#                              bar is "completed in deadline", not a
#                              wall-clock latency claim


def main() -> int:
    obs.set_observability(metrics=True, tracing=True, compile_monitor=True)
    reg = obs.registry()
    cache_dir = tempfile.mkdtemp(prefix="fleet_smoke_cc_")
    cc.set_cache_dir(cache_dir)

    model = nn.Sequential(nn.Linear(6, 32), nn.ReLU(), nn.Linear(32, 4))
    params, state, _ = model.build(jax.random.PRNGKey(0), (8, 6))

    def factory(name):
        return ServingRuntime(model, params, state, buckets=(1, 8),
                              max_wait_ms=1.0,
                              example_input=np.zeros((1, 6), np.float32))

    router = FleetRouter(
        factory, n_replicas=2,
        tenants=[TenantConfig("bulk", tier="batch", weight=2.0,
                              capacity=256),
                 TenantConfig("chat", tier="interactive", capacity=64)])
    fault = ReplicaKillFault(at_dispatch=8)
    router.set_chaos(fault)

    rng = np.random.RandomState(0)
    futs = []
    for i in range(N_BULK + N_CHAT):
        if i % ((N_BULK + N_CHAT) // N_CHAT) == 0 and \
                sum(1 for t, _ in futs if t == "chat") < N_CHAT:
            futs.append(("chat", router.submit(
                "chat", rng.rand(1, 6).astype(np.float32),
                deadline_ms=CHAT_DEADLINE_MS)))
        else:
            futs.append(("bulk", router.submit(
                "bulk", rng.rand(4, 6).astype(np.float32),
                deadline_ms=60_000)))

    # scale back out while the burst drains (the replacement must warm
    # from the live layer, not recompile)
    router.add_replica()

    lost = 0
    for tenant, fut in futs:
        try:
            out = fut.result(60)
            assert np.all(np.isfinite(np.asarray(out)))
        except Exception as e:  # noqa: BLE001 — loud errors are allowed…
            print(f"  loud failure ({tenant}): {type(e).__name__}: {e}")
            if tenant == "chat":
                lost += 1  # …but not for the interactive SLO tenant

    snap = router.snapshot()
    chat, bulk = snap["tenants"]["chat"], snap["tenants"]["bulk"]
    prom_path = os.path.join(cache_dir, "metrics.prom")
    reg.export_prometheus(prom_path)
    prom = open(prom_path).read()
    router.close()
    cc.reset()

    n_chat = sum(1 for t, _ in futs if t == "chat")
    n_bulk = len(futs) - n_chat
    print(f"fleet_smoke: {n_bulk} bulk + {n_chat} chat requests, "
          f"kill at dispatch #{fault.at_dispatch}")
    print(f"  killed replica: {fault.fired}")
    print(f"  chat:  completed={chat['requests_completed']} "
          f"deadline_rejected={chat['rejected_deadline']} "
          f"p99={chat['latency_ms']['p99']:.1f}ms")
    print(f"  bulk:  completed={bulk['requests_completed']} "
          f"deadline_rejected={bulk['rejected_deadline']}")
    print(f"  redispatched={snap['redispatched']} "
          f"warmup_reused={snap['warmup_reused']} "
          f"steady_recompiles={reg.get('compile/steady_recompiles')}")

    failures = []
    if len(fault.fired) != 1:
        failures.append(f"chaos fault fired {len(fault.fired)} times, want 1")
    if chat["requests_completed"] != n_chat or lost:
        failures.append(
            f"interactive SLO breach: {chat['requests_completed']}/{n_chat} "
            f"chat requests completed ({lost} failed loudly)")
    total_settled = (chat["requests_completed"] + chat["rejected_deadline"]
                     + bulk["requests_completed"] + bulk["rejected_deadline"])
    if total_settled < len(futs):
        failures.append(
            f"silent drop: {len(futs)} accepted, only {total_settled} "
            "settled with a result or a loud deadline rejection")
    if snap["warmup_reused"] <= 0:
        failures.append("scale-out warmed nothing from the compilecache "
                        "(fleet/warmup_reused == 0)")
    if reg.get("compile/steady_recompiles") > 0:
        failures.append(
            f"{int(reg.get('compile/steady_recompiles'))} steady-state "
            "recompile alarm(s): warm scale-out recompiled")
    for tenant in ("chat", "bulk"):
        needle = f'{{tenant="{tenant}"}}'
        if needle not in prom:
            failures.append(f"Prometheus export missing {needle} series")

    if failures:
        for f in failures:
            print(f"FAIL: {f}")
        return 1
    print("OK: fleet lane green (SLO isolation, zero silent drops, "
          "warm scale-out, per-tenant metrics)")
    return 0


def failover_main() -> int:
    """Zero-loss mid-stream failover lane (ISSUE 20 acceptance)."""
    from bigdl_tpu.fleet import GenerationAdapter
    from bigdl_tpu.generation import GenerationConfig, GenerationEngine
    from bigdl_tpu.models.transformer import TransformerLM

    outdir = tempfile.mkdtemp(prefix="fleet_failover_")
    flight_dir = os.path.join(outdir, "flight")
    obs.set_observability(metrics=True, tracing=True, compile_monitor=True,
                          flight=True, flight_dir=flight_dir)
    reg = obs.registry()
    cc.set_cache_dir(os.path.join(outdir, "cc"))

    model = TransformerLM(vocab_size=61, hidden_size=32, n_layer=2,
                          n_head=4, max_len=256, use_flash=False)
    params, _ = model.init((1, 16), rng=jax.random.PRNGKey(0))

    max_new = 16
    engines = {}

    def factory(name):
        # oversubscribed: 24 allocatable blocks < 2 slots x 16
        # worst-case resident — recovery must ride the reservation
        # accounting, not pool headroom
        eng = GenerationEngine(
            model, params,
            config=GenerationConfig(
                buckets=(64,), slots=2, max_new_tokens=max_new,
                temperature=0.0, paged=True, kv_block_size=4,
                kv_pool_blocks=25, prefill_chunk=16,
                spec_decode=False, prefix_cache=True))
        engines[name] = eng
        return GenerationAdapter(eng)

    router = FleetRouter(
        factory, n_replicas=2, name="fo",
        tenants=[TenantConfig("t", tier="batch", deadline_ms=120_000.0)])

    rng = np.random.RandomState(11)
    prompt = rng.randint(1, 61, size=40).astype(np.int32)  # 3 chunk folds

    failures = []
    try:
        # warm both replicas' prefix stores with the prompt head, and
        # take the unkilled greedy baseline off the first run
        want = [int(t)
                for t in engines["fo-r1"].generate(prompt, timeout=120).tokens]
        warm2 = [int(t)
                 for t in engines["fo-r2"].generate(prompt, timeout=120).tokens]
        if warm2 != want:
            failures.append("replicas disagree before any fault was injected")

        # -- scenario A: kill the serving replica mid-decode ------------
        fault_a = ReplicaKillFault(
            at_decode_step=engines["fo-r1"]._steps + 6)
        fault_a.bind_engine(engines["fo-r1"], router, "fo-r1")
        fut = router.submit("t", prompt)
        res = fut.result(120)
        got = [int(t) for t in res.tokens]
        if not fault_a.fired:
            failures.append("mid-decode kill never fired")
        if got != want:
            failures.append(f"mid-decode failover diverged: want {want}, "
                            f"got {got}")
        if fut.meta.get("attempts") != 2:
            failures.append(f"want 2 dispatch attempts, got "
                            f"{fut.meta.get('attempts')}")
        resumed = int(res.meta.get("resumed_tokens", 0))
        if not res.meta.get("recovered") or resumed < 1:
            failures.append(f"survivor did not resume mid-stream "
                            f"(resumed_tokens={resumed})")
        if int(res.meta.get("recovery_prefix_tokens", 0)) < 16:
            failures.append(
                "recovery prefill was cold: recovery_prefix_tokens="
                f"{res.meta.get('recovery_prefix_tokens')} (store was warm)")
        surv = engines["fo-r2"].metrics.snapshot()
        if surv["recoveries"] < 1 or surv["recovery_ttft_ms"]["count"] < 1:
            failures.append(f"survivor engine recorded no recovery: {surv}")

        # -- scenario B: kill during a prefill chunk fold ----------------
        router.add_replica()  # fo-r3, warmed from the compilecache
        # drop r2's warm store so the next prefill folds cold through
        # all three chunks — the kill must land MID-prefill, not on the
        # single fold a chunk-skipping warm prefill needs
        engines["fo-r2"].prefix_store.clear()
        fault_b = ReplicaKillFault(
            at_prefill_chunk=engines["fo-r2"]._chunk_folds + 2)
        fault_b.bind_engine(engines["fo-r2"], router, "fo-r2")
        fut_b = router.submit("t", prompt)
        res_b = fut_b.result(120)
        got_b = [int(t) for t in res_b.tokens]
        if not fault_b.fired:
            failures.append("mid-prefill kill never fired")
        if got_b != want:
            failures.append(f"mid-prefill failover diverged: want {want}, "
                            f"got {got_b}")
        if fut_b.meta.get("attempts") != 2:
            failures.append(f"prefill-kill want 2 attempts, got "
                            f"{fut_b.meta.get('attempts')}")

        # -- fleet counters ---------------------------------------------
        if reg.get("fleet/failovers|tenant=t") != 2:
            failures.append(
                f"want 2 tenant-labeled failovers, got "
                f"{reg.get('fleet/failovers|tenant=t')}")
        if reg.get("fleet/resumed_tokens|tenant=t") < 1:
            failures.append("fleet/resumed_tokens never incremented")
        if reg.get("generation/recovery_prefix_hits|tenant=t") < 1:
            failures.append("no tenant-labeled recovery prefix hit")
        if snapshotted := router.snapshot():
            if snapshotted["warmup_reused"] <= 0:
                failures.append("scale-out replica warmed nothing from "
                                "the compilecache")

        # -- leak-free survivor pools -----------------------------------
        for name in router.replicas():
            eng = engines[name]
            eng.drain()
            pool, store = eng._pool, eng.prefix_store
            if pool.blocks_free + len(store) != pool.n_allocatable \
                    or pool.blocks_reserved != 0:
                failures.append(
                    f"{name} pool leaked: free={pool.blocks_free} "
                    f"store={len(store)} reserved={pool.blocks_reserved} "
                    f"allocatable={pool.n_allocatable}")
            store.clear()
            if pool.blocks_free != pool.n_allocatable:
                failures.append(f"{name} store clear() left blocks behind")
    finally:
        router.close(drain=False)

    # -- exactly one flight bundle (two kills inside the per-reason
    # cooldown collapse into one incident) ------------------------------
    bundles = sorted(d for d in os.listdir(flight_dir)
                     if "fleet_replica_death" in d) \
        if os.path.isdir(flight_dir) else []
    if len(bundles) != 1:
        failures.append(f"want exactly 1 replica-death flight bundle, "
                        f"got {bundles}")

    steady = int(reg.get("compile/steady_recompiles"))
    if steady:
        failures.append(f"{steady} steady-state recompile alarm(s): the "
                        "resume path changed the pinned executable set")

    print(f"fleet_smoke --failover: kills={fault_a.fired + fault_b.fired} "
          f"resumed_tokens={resumed} "
          f"prefix_warm={res.meta.get('recovery_prefix_tokens')} "
          f"failovers={int(reg.get('fleet/failovers'))} "
          f"bundles={len(bundles)} steady_recompiles={steady}")
    cc.reset()
    if failures:
        for f in failures:
            print(f"FAIL: {f}")
        return 1
    print("OK: failover lane green (mid-decode parity, mid-prefill "
          "parity, prefix-warm recovery, leak-free pools, one bundle, "
          "zero steady recompiles)")
    return 0


if __name__ == "__main__":
    sys.exit(failover_main() if "--failover" in sys.argv else main())
