#!/usr/bin/env python
"""PARITY.md drift guard (wired into tools/ci_quick_tier.sh).

PARITY.md's "Known remaining gaps" rots in one direction: a gap gets
closed in code but the doc keeps claiming it's missing (this happened to
the multi-output-metrics and partitioned-checkpoint-write gaps — both
shipped with tests while the doc still said "unsupported").  This guard
encodes the closed gaps as (stale-claim pattern, evidence) pairs and
fails when:

  1. a stale claim pattern reappears in PARITY.md while its evidence
     files still exist (the doc regressed), or
  2. an evidence file named by a CLOSED rule disappears (the doc now
     overclaims — the feature was removed without reopening the gap).

Add a rule when you close a gap; the pattern should match the OLD
gap wording tightly enough not to trip on the new CLOSED note.

  python tools/parity_drift_guard.py        # exit 0 clean, 1 on drift
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Gap wordings that must NOT appear while their closing evidence exists
# (the doc regressed to claiming a shipped feature is missing).
STALE_GAP_RULES = [
    (
        "multi-output per-tensor validation metrics",
        r"multi-output Models support only loss-type validation metrics",
        ["tests/test_keras_multi_metrics.py"],
    ),
    (
        "DT_STRING checkpoint write",
        r"DT_STRING[^.]*unsupported on write",
        ["bigdl_tpu/utils/tf_checkpoint.py",
         "tests/test_tf_variables.py"],
    ),
    (
        "partitioned checkpoint write",
        r"writing partitioned checkpoints is unsupported",
        ["bigdl_tpu/utils/tf_checkpoint.py",
         "tests/test_tf_variables.py"],
    ),
]

# Shipped-capability wordings whose evidence must EXIST while the claim
# is in the doc (the doc overclaims a feature that was removed).
CLOSED_CLAIM_RULES = [
    (
        "per-output metrics CLOSED note",
        r"per-output\s+validation metrics",
        ["tests/test_keras_multi_metrics.py"],
    ),
    (
        "partitioned/DT_STRING write CLOSED note",
        r"partitioned checkpoints write",
        ["bigdl_tpu/utils/tf_checkpoint.py", "tests/test_tf_variables.py"],
    ),
    (
        "serving runtime behind PredictionService",
        r"facade over the `bigdl_tpu\.serving`",
        ["bigdl_tpu/serving/runtime.py", "docs/serving.md",
         "tests/test_serving.py"],
    ),
]


def main() -> int:
    parity = os.path.join(REPO, "PARITY.md")
    with open(parity, encoding="utf-8") as f:
        text = f.read()

    def line_of(match: "re.Match") -> int:
        return text.count("\n", 0, match.start()) + 1

    failures = []
    for name, pattern, evidence in STALE_GAP_RULES:
        missing = [p for p in evidence
                   if not os.path.exists(os.path.join(REPO, p))]
        stale = re.search(pattern, text)
        if stale and not missing:
            failures.append(
                f"PARITY.md:{line_of(stale)} still claims '{name}' is a gap, "
                f"but the evidence shipped: {', '.join(evidence)}")

    for name, pattern, evidence in CLOSED_CLAIM_RULES:
        missing = [p for p in evidence
                   if not os.path.exists(os.path.join(REPO, p))]
        claim = re.search(pattern, text)
        if claim and missing:
            failures.append(
                f"PARITY.md:{line_of(claim)} claims '{name}' but its "
                f"evidence is gone: {', '.join(missing)} "
                "(reopen the gap or fix the paths)")

    if failures:
        for msg in failures:
            print(f"DRIFT: {msg}", file=sys.stderr)
        return 1
    n = len(STALE_GAP_RULES) + len(CLOSED_CLAIM_RULES)
    print(f"parity drift guard: {n} rules clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
