"""Deterministic MNIST-format dataset generator (zero-egress stand-in).

This environment has no network egress, so the real MNIST idx files cannot
be downloaded (VERDICT r4 item 1 sanctions exactly this fallback: commit a
deterministic generator that writes the real file FORMATS and say so).

What this writes is byte-for-byte the MNIST distribution format —
idx3-ubyte/idx1-ubyte with magics 2051/2049, gzip members named
{train,t10k}-{images-idx3,labels-idx1}-ubyte.gz — so the repo's production
loader (`bigdl_tpu/dataset/datasets.py:load_mnist`, which mirrors
pyspark/bigdl/dataset/mnist.py) parses it unmodified, exactly as it would
parse the real thing.

The pixels are NOT random blobs: the source glyphs are the 1,797 REAL
handwritten digits bundled with scikit-learn (the UCI optical-digits set —
genuine human handwriting, shipped inside the package, no download).  The
generator

  1. splits the SOURCE images into disjoint train/test pools
     (stratified, so no test digit image ever seeds a train sample —
     test accuracy measures generalization to unseen handwriting);
  2. upsamples each 8x8 glyph to a ~20x20 box (the MNIST convention:
     digit centered by center-of-mass in a 28x28 field);
  3. applies per-sample random affine distortions (rotation, scale,
     shear, translation) + Gaussian smoothing + pixel noise, seeded by
     a fixed RandomState, to expand the pools to 60,000 train /
     10,000 test — MNIST's exact cardinalities.

Everything is deterministic: same seed -> bit-identical files (sha256s
are printed so a skeptic can verify reproduction).

    python tools/gen_mnist.py --out data/mnist

Reference being stood in for: models/lenet/Train.scala reads the real
idx files via DataSet.array(load(trainData), ...).
"""

from __future__ import annotations

import argparse
import gzip
import hashlib
import os
import struct

import numpy as np
from scipy import ndimage

SEED = 20260731


def _expand_pool(pool_x: np.ndarray, pool_y: np.ndarray, n_out: int,
                 rs: np.random.RandomState) -> tuple[np.ndarray, np.ndarray]:
    """Expand a pool of real 8x8 glyphs to n_out distorted 28x28 images."""
    n_src = len(pool_x)
    out = np.zeros((n_out, 28, 28), np.uint8)
    labels = np.zeros(n_out, np.uint8)
    # upsample each source glyph once to 20x20 float [0,1]
    up = np.stack([
        ndimage.zoom(g / 16.0, 20 / 8, order=3).clip(0, 1) for g in pool_x
    ])
    for i in range(n_out):
        j = i % n_src  # cycle the pool so every class/source is covered
        g = up[j]
        ang = rs.uniform(-11, 11) * np.pi / 180
        sc = rs.uniform(0.9, 1.1)
        sh = rs.uniform(-0.08, 0.08)
        ca, sa = np.cos(ang), np.sin(ang)
        # affine about the glyph center
        m = np.array([[ca, -sa], [sa, ca]]) @ np.array([[1, sh], [0, 1]]) / sc
        c = np.array([9.5, 9.5])
        g = ndimage.affine_transform(g, m, offset=c - m @ c, order=3).clip(0, 1)
        g = ndimage.gaussian_filter(g, rs.uniform(0.25, 0.6))
        g = g + rs.normal(0, 0.012, g.shape)
        g = np.clip(g * rs.uniform(0.95, 1.2), 0, 1)
        # center by center-of-mass in the 28x28 field (MNIST convention)
        total = g.sum()
        cy, cx = (ndimage.center_of_mass(g) if total > 0 else (9.5, 9.5))
        ty = int(round(13.5 - cy)) + rs.randint(-1, 2)
        tx = int(round(13.5 - cx)) + rs.randint(-1, 2)
        field = np.zeros((28, 28), np.float32)
        ys, xs = np.mgrid[0:20, 0:20]
        yy = np.clip(ys + ty, 0, 27)
        xx = np.clip(xs + tx, 0, 27)
        np.maximum.at(field, (yy.ravel(), xx.ravel()), g.ravel())
        out[i] = (field * 255).astype(np.uint8)
        labels[i] = pool_y[j]
    return out, labels


def write_idx3(path: str, images: np.ndarray) -> None:
    n, r, c = images.shape
    payload = struct.pack(">iiii", 2051, n, r, c) + images.tobytes()
    with gzip.GzipFile(path, "wb", mtime=0) as f:  # mtime=0: deterministic gz
        f.write(payload)


def write_idx1(path: str, labels: np.ndarray) -> None:
    payload = struct.pack(">ii", 2049, len(labels)) + labels.tobytes()
    with gzip.GzipFile(path, "wb", mtime=0) as f:
        f.write(payload)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="data/mnist")
    ap.add_argument("--n-train", type=int, default=60_000)
    ap.add_argument("--n-test", type=int, default=10_000)
    args = ap.parse_args(argv)

    from sklearn.datasets import load_digits
    d = load_digits()
    x, y = d.images.astype(np.float32), d.target.astype(np.uint8)

    # curation: drop source glyphs that 10-fold k-NN cross-validation
    # misclassifies (~2.3% of the set) — at 8x8 these are genuinely
    # ambiguous handwriting, and every distorted copy of one lands in the
    # output as an unlearnable label.  MNIST itself was a curated subset
    # of NIST; this is the same step, made explicit and deterministic.
    from sklearn.model_selection import cross_val_predict
    from sklearn.neighbors import KNeighborsClassifier
    pred = cross_val_predict(KNeighborsClassifier(3),
                             x.reshape(len(y), -1), y, cv=10)
    keep = pred == y
    print(f"curation: dropping {int((~keep).sum())} ambiguous source "
          f"glyphs of {len(y)}")
    x, y = x[keep], y[keep]

    # stratified disjoint source split: last 2 of every 10 per class -> test
    rs = np.random.RandomState(SEED)
    test_mask = np.zeros(len(y), bool)
    for cls in range(10):
        idx = np.where(y == cls)[0]
        rs.shuffle(idx)
        test_mask[idx[: len(idx) // 5]] = True
    print(f"source: {len(y)} real glyphs -> "
          f"{int((~test_mask).sum())} train-pool / {int(test_mask.sum())} test-pool")

    os.makedirs(args.out, exist_ok=True)
    jobs = [
        ("train", x[~test_mask], y[~test_mask], args.n_train,
         np.random.RandomState(SEED + 1)),
        ("t10k", x[test_mask], y[test_mask], args.n_test,
         np.random.RandomState(SEED + 2)),
    ]
    for prefix, px, py, n, prs in jobs:
        imgs, labels = _expand_pool(px, py, n, prs)
        ip = os.path.join(args.out, f"{prefix}-images-idx3-ubyte.gz")
        lp = os.path.join(args.out, f"{prefix}-labels-idx1-ubyte.gz")
        write_idx3(ip, imgs)
        write_idx1(lp, labels)
        for p in (ip, lp):
            h = hashlib.sha256(open(p, "rb").read()).hexdigest()[:16]
            print(f"{p}  {os.path.getsize(p)/1e6:.1f} MB  sha256:{h}")


if __name__ == "__main__":
    main()
