#!/bin/bash
# One-shot on-chip measurement capture (run when the axon tunnel is up):
#   bash benchmarks/run_all_tpu.sh [outdir]
# Each stage is bounded by `timeout` so a dead tunnel cannot wedge the
# process holding the device grant (never kill -9 a TPU holder).
set -u
OUT=${1:-/root/repo/benchmarks/results}
mkdir -p "$OUT"
export PYTHONPATH=/root/repo:/root/.axon_site

run() {  # name, timeout_s, cmd...
  local name=$1 tmo=$2; shift 2
  echo "=== $name ==="
  timeout "$tmo" "$@" 2>&1 | tee "$OUT/$name.log"
  local rc=${PIPESTATUS[0]}  # the benchmark's status, not tee's
  echo "rc=$rc ($name)"
}

run bench          600 python /root/repo/bench.py
run bench_fusebn   600 env BENCH_FUSE_BN=1 python /root/repo/bench.py
run int8          1800 python /root/repo/benchmarks/bench_int8.py
run appendix_fuse 1500 python /root/repo/benchmarks/bench_appendix.py --fuse-bn
# round-5 additions.  bench_input_pipeline is host-only (forces the CPU
# backend) but still run it SEQUENTIALLY: one process per tunnel.
# Real-data stages need shards: python tools/gen_imagenet_shards.py --gb 20
run transformer   2400 python /root/repo/benchmarks/bench_transformer.py --iters 40
run bf16_state    1500 python /root/repo/benchmarks/bench_bf16_state.py
if [ -d /root/repo/data/imagenet_tfr ]; then
  run input_pipeline 600 python /root/repo/benchmarks/bench_input_pipeline.py
  run bench_realdata 600 python /root/repo/bench.py --real-data
fi
echo "all done -> $OUT"
