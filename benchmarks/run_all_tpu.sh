#!/bin/bash
# One-shot on-chip measurement capture (run when the axon tunnel is up):
#   bash benchmarks/run_all_tpu.sh [outdir]
# Each stage is bounded by `timeout` so a dead tunnel cannot wedge the
# process holding the device grant (never kill -9 a TPU holder).
set -u
OUT=${1:-/root/repo/benchmarks/results}
mkdir -p "$OUT"
export PYTHONPATH=/root/repo:/root/.axon_site

run() {  # name, timeout_s, cmd...
  local name=$1 tmo=$2; shift 2
  echo "=== $name ==="
  timeout "$tmo" "$@" 2>&1 | tee "$OUT/$name.log"
  local rc=${PIPESTATUS[0]}  # the benchmark's status, not tee's
  echo "rc=$rc ($name)"
}

run bench          600 python /root/repo/bench.py
run bench_fusebn   600 env BENCH_FUSE_BN=1 python /root/repo/bench.py
run int8           900 python /root/repo/benchmarks/bench_int8.py
run appendix_fuse 1500 python /root/repo/benchmarks/bench_appendix.py --fuse-bn
echo "all done -> $OUT"
