"""Fleet front-door overhead and scale-out latency (ISSUE 11).

Two questions a serving operator asks before putting `FleetRouter` in
front of a runtime:

  1. **What does the front door cost?**  Interleaved A/B: the SAME burst
     of requests is pushed through a bare `ServingRuntime` (direct) and
     through a 1-tenant/1-replica `FleetRouter` (routed), alternating
     trials so drift (thermal, page cache, GC) hits both arms equally.
     The bar: routed wall-clock within 2% of direct at the median.
  2. **What does warm scale-out buy?**  Cold boot (empty disk + live
     compile cache) vs `add_replica()` against the process-scoped live
     layer — the warm path must reuse executables (`warmup_reused` > 0)
     instead of recompiling.

`--failover-quick` (ISSUE 20) answers two more and writes
benchmarks/results/failover_quick.json: prefix-warm vs cold recovery
TTFT for a >=1k-token in-flight resume (bar: warm >= 2x faster,
token parity, leak-free pool) and the no-fault cost of the per-step
progress snapshots that make resume possible (interleaved A/B,
bar: <= 1% at the median of pairwise ratios).

Emits one JSON row per phase and writes
benchmarks/results/fleet_quick.json under --quick.

    python benchmarks/bench_fleet.py            # TPU-sized
    python benchmarks/bench_fleet.py --quick    # CPU-sized (CI)
    python benchmarks/bench_fleet.py --failover-quick
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BUCKETS = (8, 32)
MAX_WAIT_MS = 1.0


def build_model(quick: bool):
    import jax

    import bigdl_tpu.nn as nn

    width = 2048
    model = nn.Sequential(nn.Linear(128, width), nn.ReLU(),
                          nn.Linear(width, width), nn.ReLU(),
                          nn.Linear(width, 64))
    params, state, _ = model.build(jax.random.PRNGKey(0), (BUCKETS[-1], 128))
    return model, params, state


def make_runtime(model, params, state):
    from bigdl_tpu.serving import ServingConfig, ServingRuntime

    return ServingRuntime(
        model, params, state,
        example_input=np.zeros((1, 128), np.float32),
        config=ServingConfig(buckets=BUCKETS, max_wait_ms=MAX_WAIT_MS,
                             capacity=512))


def burst(requests, submit):
    """Submit every request, then wait for all — wall-clock seconds."""
    t0 = time.perf_counter()
    futs = [submit(x) for x in requests]
    for f in futs:
        f.result(120)
    return time.perf_counter() - t0


def run_ab(model, params, state, n_requests: int, trials: int):
    """Interleaved direct-vs-routed trials over identical request sets."""
    from bigdl_tpu.fleet import FleetRouter, TenantConfig

    # full-bucket requests: the bar compares front-door cost against a
    # serving-sized unit of work, not an empty forward — the router's
    # per-request cost is fixed, so a toy payload would overstate it
    rs = np.random.RandomState(1)
    requests = [rs.rand(BUCKETS[-1], 128).astype(np.float32)
                for _ in range(n_requests)]

    rt = make_runtime(model, params, state)
    router = FleetRouter(
        lambda name: make_runtime(model, params, state),
        n_replicas=1,
        tenants=[TenantConfig("bench", tier="batch", capacity=1024)])
    try:
        # one untimed lap per arm: page in code paths, settle compiles
        burst(requests, lambda x: rt.submit(x, deadline_ms=None))
        burst(requests, lambda x: router.submit("bench", x))
        direct, routed = [], []
        for _ in range(trials):
            direct.append(burst(requests,
                                lambda x: rt.submit(x, deadline_ms=None)))
            routed.append(burst(requests, lambda x: router.submit("bench", x)))
    finally:
        router.close()
        rt.close()

    d_med = statistics.median(direct)
    r_med = statistics.median(routed)
    # overhead from PAIRWISE per-trial ratios: the arms alternate, so a
    # load spike or thermal drift hits trial k's direct and routed runs
    # alike and cancels in the ratio — medians of the raw walls do not
    # have that property on a shared CI box
    ratios = [r / d for d, r in zip(direct, routed)]
    overhead_pct = (statistics.median(ratios) - 1.0) * 100.0
    return [
        {"phase": "direct_burst", "requests": n_requests, "trials": trials,
         "wall_ms_median": round(d_med * 1e3, 2),
         "wall_ms_all": [round(t * 1e3, 2) for t in direct]},
        {"phase": "routed_burst", "requests": n_requests, "trials": trials,
         "wall_ms_median": round(r_med * 1e3, 2),
         "wall_ms_all": [round(t * 1e3, 2) for t in routed]},
        {"phase": "router_overhead", "overhead_pct": round(overhead_pct, 2),
         "bar_pct": 2.0, "pass": bool(overhead_pct < 2.0)},
    ]


def run_scaleout(model, params, state):
    """Cold boot vs warm `add_replica()` off the live compile cache."""
    import bigdl_tpu.compilecache as cc
    from bigdl_tpu import obs
    from bigdl_tpu.fleet import FleetRouter, TenantConfig

    cc.reset()
    cc.set_cache_dir(tempfile.mkdtemp(prefix="bench_fleet_cc_"))
    # fresh CompileMonitor: the A/B phase already settled these
    # signatures, and a cold boot legitimately recompiles them — only a
    # recompile during the WARM add is an alarm worth reporting
    obs.set_observability(compile_monitor=True)
    try:
        t0 = time.perf_counter()
        router = FleetRouter(
            lambda name: make_runtime(model, params, state),
            n_replicas=1,
            tenants=[TenantConfig("bench", tier="batch", capacity=1024)])
        cold_ms = (time.perf_counter() - t0) * 1e3
        try:
            alarms0 = obs.registry().get("compile/steady_recompiles")
            t0 = time.perf_counter()
            router.add_replica()
            warm_ms = (time.perf_counter() - t0) * 1e3
            snap = router.snapshot()
            warm_alarms = (obs.registry().get("compile/steady_recompiles")
                           - alarms0)
        finally:
            router.close()
        return {
            "phase": "scaleout",
            "cold_boot_ms": round(cold_ms, 1),
            "warm_add_replica_ms": round(warm_ms, 1),
            "speedup": round(cold_ms / warm_ms, 1) if warm_ms else None,
            "warmup_reused": int(snap["warmup_reused"]),
            "steady_recompiles_during_warm_add": int(warm_alarms),
        }
    finally:
        cc.reset()


def run_failover_recovery(quick: bool):
    """Prefix-warm vs cold recovery TTFT for a >=1k-token in-flight
    request (ISSUE 20 acceptance).  One engine, interleaved trials: a
    `prefix_store.clear()` forces the cold arm to re-fold the whole
    1k-token effective prompt; the cold run itself republishes it, so
    the warm arm that follows rides the chunk-skipping path.  Both arms
    must stay token-for-token identical to the unkilled baseline."""
    import jax

    from bigdl_tpu.generation import GenerationConfig, GenerationEngine
    from bigdl_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab_size=61, hidden_size=32, n_layer=2,
                          n_head=4, max_len=2048, use_flash=False)
    params, _ = model.init((1, 16), rng=jax.random.PRNGKey(0))
    rs = np.random.RandomState(5)
    prompt = rs.randint(1, 61, size=1024).astype(np.int32)
    max_new = 32
    trials = 5 if quick else 9
    eng = GenerationEngine(model, params, config=GenerationConfig(
        buckets=(1280,), slots=2, max_new_tokens=max_new, temperature=0.0,
        paged=True, kv_block_size=16, prefill_chunk=128,
        spec_decode=False, prefix_cache=True))
    try:
        base = eng.generate(prompt, timeout=600, cid="fo-bench")
        want = [int(t) for t in base.tokens]
        resume = want[:max_new // 2]  # the victim died mid-decode
        cold, warm = [], []
        parity = True
        prefix_tokens = 0
        for _ in range(trials):
            eng.prefix_store.clear()
            r_cold = eng.generate(prompt, timeout=600, cid="fo-bench",
                                  resume_tokens=resume)
            r_warm = eng.generate(prompt, timeout=600, cid="fo-bench",
                                  resume_tokens=resume)
            cold.append(float(r_cold.meta["ttft_ms"]))
            warm.append(float(r_warm.meta["ttft_ms"]))
            parity = parity and [int(t) for t in r_cold.tokens] == want \
                and [int(t) for t in r_warm.tokens] == want
            prefix_tokens = int(r_warm.meta.get("recovery_prefix_tokens", 0))
        eng.drain()
        pool, store = eng._pool, eng.prefix_store
        leak_free = bool(
            pool.blocks_free + len(store) == pool.n_allocatable
            and pool.blocks_reserved == 0)
    finally:
        eng.close()
    c_med, w_med = statistics.median(cold), statistics.median(warm)
    speedup = c_med / w_med if w_med else None
    return {
        "phase": "failover_recovery_ttft",
        "prompt_tokens": int(prompt.size), "resumed_tokens": len(resume),
        "trials": trials,
        "cold_recovery_ttft_ms_median": round(c_med, 2),
        "warm_recovery_ttft_ms_median": round(w_med, 2),
        "cold_ttft_ms_all": [round(t, 2) for t in cold],
        "warm_ttft_ms_all": [round(t, 2) for t in warm],
        "warm_speedup": round(speedup, 2) if speedup else None,
        "recovery_prefix_tokens": prefix_tokens,
        "token_parity": bool(parity), "pool_leak_free": leak_free,
        "bar_speedup": 2.0,
        "pass": bool(parity and leak_free and speedup and speedup >= 2.0),
    }


def run_progress_overhead(quick: bool):
    """Failover-on-no-faults cost: the progress snapshots published at
    every decode step, measured as an interleaved A/B of the SAME decode
    burst with `progress_meta` on vs off.  Pairwise per-trial ratios
    (the run_ab discipline) — the bar is <= 1% at the median."""
    import jax

    from bigdl_tpu.generation import GenerationConfig, GenerationEngine
    from bigdl_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab_size=61, hidden_size=32, n_layer=2,
                          n_head=4, max_len=128, use_flash=False)
    params, _ = model.init((1, 16), rng=jax.random.PRNGKey(0))
    rs = np.random.RandomState(7)
    prompts = [rs.randint(1, 61, size=24).astype(np.int32)
               for _ in range(8)]
    trials = 7 if quick else 11

    def mk(progress):
        return GenerationEngine(model, params, config=GenerationConfig(
            buckets=(64,), slots=4, max_new_tokens=32, temperature=0.0,
            paged=False, prefill_chunk=0, spec_decode=False,
            prefix_cache=False, progress_meta=progress))

    def lap(eng):
        t0 = time.perf_counter()
        futs = [eng.submit(p) for p in prompts]
        for f in futs:
            f.result(120)
        return time.perf_counter() - t0

    eng_on, eng_off = mk(True), mk(False)
    try:
        lap(eng_on), lap(eng_off)  # untimed: settle compiles per arm
        on, off = [], []
        for _ in range(trials):
            off.append(lap(eng_off))
            on.append(lap(eng_on))
    finally:
        eng_on.close()
        eng_off.close()
    ratios = [a / b for a, b in zip(on, off)]
    overhead_pct = (statistics.median(ratios) - 1.0) * 100.0
    return {
        "phase": "progress_meta_overhead",
        "requests": len(prompts), "max_new_tokens": 32, "trials": trials,
        "wall_ms_median_on": round(statistics.median(on) * 1e3, 2),
        "wall_ms_median_off": round(statistics.median(off) * 1e3, 2),
        "overhead_pct": round(overhead_pct, 2),
        "bar_pct": 1.0, "pass": bool(overhead_pct < 1.0),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small MLP, fewer trials (CPU-sized)")
    ap.add_argument("--failover-quick", action="store_true",
                    help="ISSUE 20 failover bars only: warm-vs-cold "
                         "recovery TTFT + progress-meta overhead A/B")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--trials", type=int, default=None)
    args = ap.parse_args(argv)

    import jax

    platform = jax.devices()[0].platform
    n_requests = args.requests or (64 if args.quick else 256)
    trials = args.trials or (7 if args.quick else 11)

    import bigdl_tpu.compilecache as cc
    from bigdl_tpu import obs

    obs.set_observability(metrics=True, compile_monitor=True)

    if args.failover_quick:
        cc.set_cache_dir(tempfile.mkdtemp(prefix="bench_failover_"))
        meta = {"platform": platform, "model": "transformer-lm-tiny"}
        rows = []
        for row in (run_failover_recovery(quick=True),
                    run_progress_overhead(quick=True)):
            rows.append({**meta, **row})
            print(json.dumps(rows[-1]), flush=True)
        out = os.path.join(os.path.dirname(__file__), "results",
                           "failover_quick.json")
        with open(out, "w") as f:
            json.dump(rows, f, indent=1)
        print(f"# wrote {out}")
        return 0 if all(r["pass"] for r in rows) else 1

    # cache on for the A/B phase too: the routed arm's replica warms
    # from the live layer instead of re-tracing what the direct arm's
    # runtime already compiled (fleets run with the cache on)
    cc.set_cache_dir(tempfile.mkdtemp(prefix="bench_fleet_ab_"))
    model, params, state = build_model(args.quick)

    meta = {"platform": platform, "buckets": list(BUCKETS),
            "max_wait_ms": MAX_WAIT_MS,
            "model": "mlp2048"}
    rows = []
    for row in run_ab(model, params, state, n_requests, trials):
        rows.append({**meta, **row})
        print(json.dumps(rows[-1]), flush=True)
    rows.append({**meta, **run_scaleout(model, params, state)})
    print(json.dumps(rows[-1]), flush=True)

    if args.quick:
        out = os.path.join(os.path.dirname(__file__), "results",
                           "fleet_quick.json")
        with open(out, "w") as f:
            json.dump(rows, f, indent=1)
        print(f"# wrote {out}")

    bar = next(r for r in rows if r["phase"] == "router_overhead")
    return 0 if bar["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
