"""Fleet front-door overhead and scale-out latency (ISSUE 11).

Two questions a serving operator asks before putting `FleetRouter` in
front of a runtime:

  1. **What does the front door cost?**  Interleaved A/B: the SAME burst
     of requests is pushed through a bare `ServingRuntime` (direct) and
     through a 1-tenant/1-replica `FleetRouter` (routed), alternating
     trials so drift (thermal, page cache, GC) hits both arms equally.
     The bar: routed wall-clock within 2% of direct at the median.
  2. **What does warm scale-out buy?**  Cold boot (empty disk + live
     compile cache) vs `add_replica()` against the process-scoped live
     layer — the warm path must reuse executables (`warmup_reused` > 0)
     instead of recompiling.

Emits one JSON row per phase and writes
benchmarks/results/fleet_quick.json under --quick.

    python benchmarks/bench_fleet.py            # TPU-sized
    python benchmarks/bench_fleet.py --quick    # CPU-sized (CI)
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BUCKETS = (8, 32)
MAX_WAIT_MS = 1.0


def build_model(quick: bool):
    import jax

    import bigdl_tpu.nn as nn

    width = 2048
    model = nn.Sequential(nn.Linear(128, width), nn.ReLU(),
                          nn.Linear(width, width), nn.ReLU(),
                          nn.Linear(width, 64))
    params, state, _ = model.build(jax.random.PRNGKey(0), (BUCKETS[-1], 128))
    return model, params, state


def make_runtime(model, params, state):
    from bigdl_tpu.serving import ServingConfig, ServingRuntime

    return ServingRuntime(
        model, params, state,
        example_input=np.zeros((1, 128), np.float32),
        config=ServingConfig(buckets=BUCKETS, max_wait_ms=MAX_WAIT_MS,
                             capacity=512))


def burst(requests, submit):
    """Submit every request, then wait for all — wall-clock seconds."""
    t0 = time.perf_counter()
    futs = [submit(x) for x in requests]
    for f in futs:
        f.result(120)
    return time.perf_counter() - t0


def run_ab(model, params, state, n_requests: int, trials: int):
    """Interleaved direct-vs-routed trials over identical request sets."""
    from bigdl_tpu.fleet import FleetRouter, TenantConfig

    # full-bucket requests: the bar compares front-door cost against a
    # serving-sized unit of work, not an empty forward — the router's
    # per-request cost is fixed, so a toy payload would overstate it
    rs = np.random.RandomState(1)
    requests = [rs.rand(BUCKETS[-1], 128).astype(np.float32)
                for _ in range(n_requests)]

    rt = make_runtime(model, params, state)
    router = FleetRouter(
        lambda name: make_runtime(model, params, state),
        n_replicas=1,
        tenants=[TenantConfig("bench", tier="batch", capacity=1024)])
    try:
        # one untimed lap per arm: page in code paths, settle compiles
        burst(requests, lambda x: rt.submit(x, deadline_ms=None))
        burst(requests, lambda x: router.submit("bench", x))
        direct, routed = [], []
        for _ in range(trials):
            direct.append(burst(requests,
                                lambda x: rt.submit(x, deadline_ms=None)))
            routed.append(burst(requests, lambda x: router.submit("bench", x)))
    finally:
        router.close()
        rt.close()

    d_med = statistics.median(direct)
    r_med = statistics.median(routed)
    # overhead from PAIRWISE per-trial ratios: the arms alternate, so a
    # load spike or thermal drift hits trial k's direct and routed runs
    # alike and cancels in the ratio — medians of the raw walls do not
    # have that property on a shared CI box
    ratios = [r / d for d, r in zip(direct, routed)]
    overhead_pct = (statistics.median(ratios) - 1.0) * 100.0
    return [
        {"phase": "direct_burst", "requests": n_requests, "trials": trials,
         "wall_ms_median": round(d_med * 1e3, 2),
         "wall_ms_all": [round(t * 1e3, 2) for t in direct]},
        {"phase": "routed_burst", "requests": n_requests, "trials": trials,
         "wall_ms_median": round(r_med * 1e3, 2),
         "wall_ms_all": [round(t * 1e3, 2) for t in routed]},
        {"phase": "router_overhead", "overhead_pct": round(overhead_pct, 2),
         "bar_pct": 2.0, "pass": bool(overhead_pct < 2.0)},
    ]


def run_scaleout(model, params, state):
    """Cold boot vs warm `add_replica()` off the live compile cache."""
    import bigdl_tpu.compilecache as cc
    from bigdl_tpu import obs
    from bigdl_tpu.fleet import FleetRouter, TenantConfig

    cc.reset()
    cc.set_cache_dir(tempfile.mkdtemp(prefix="bench_fleet_cc_"))
    # fresh CompileMonitor: the A/B phase already settled these
    # signatures, and a cold boot legitimately recompiles them — only a
    # recompile during the WARM add is an alarm worth reporting
    obs.set_observability(compile_monitor=True)
    try:
        t0 = time.perf_counter()
        router = FleetRouter(
            lambda name: make_runtime(model, params, state),
            n_replicas=1,
            tenants=[TenantConfig("bench", tier="batch", capacity=1024)])
        cold_ms = (time.perf_counter() - t0) * 1e3
        try:
            alarms0 = obs.registry().get("compile/steady_recompiles")
            t0 = time.perf_counter()
            router.add_replica()
            warm_ms = (time.perf_counter() - t0) * 1e3
            snap = router.snapshot()
            warm_alarms = (obs.registry().get("compile/steady_recompiles")
                           - alarms0)
        finally:
            router.close()
        return {
            "phase": "scaleout",
            "cold_boot_ms": round(cold_ms, 1),
            "warm_add_replica_ms": round(warm_ms, 1),
            "speedup": round(cold_ms / warm_ms, 1) if warm_ms else None,
            "warmup_reused": int(snap["warmup_reused"]),
            "steady_recompiles_during_warm_add": int(warm_alarms),
        }
    finally:
        cc.reset()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small MLP, fewer trials (CPU-sized)")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--trials", type=int, default=None)
    args = ap.parse_args(argv)

    import jax

    platform = jax.devices()[0].platform
    n_requests = args.requests or (64 if args.quick else 256)
    trials = args.trials or (7 if args.quick else 11)

    import bigdl_tpu.compilecache as cc
    from bigdl_tpu import obs

    obs.set_observability(metrics=True, compile_monitor=True)
    # cache on for the A/B phase too: the routed arm's replica warms
    # from the live layer instead of re-tracing what the direct arm's
    # runtime already compiled (fleets run with the cache on)
    cc.set_cache_dir(tempfile.mkdtemp(prefix="bench_fleet_ab_"))
    model, params, state = build_model(args.quick)

    meta = {"platform": platform, "buckets": list(BUCKETS),
            "max_wait_ms": MAX_WAIT_MS,
            "model": "mlp2048"}
    rows = []
    for row in run_ab(model, params, state, n_requests, trials):
        rows.append({**meta, **row})
        print(json.dumps(rows[-1]), flush=True)
    rows.append({**meta, **run_scaleout(model, params, state)})
    print(json.dumps(rows[-1]), flush=True)

    if args.quick:
        out = os.path.join(os.path.dirname(__file__), "results",
                           "fleet_quick.json")
        with open(out, "w") as f:
            json.dump(rows, f, indent=1)
        print(f"# wrote {out}")

    bar = next(r for r in rows if r["phase"] == "router_overhead")
    return 0 if bar["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
