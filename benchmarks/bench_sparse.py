"""Microbench: device-sparse SparseLinear bags vs dense multi-hot matmul.

VERDICT r3 item 2 evidence: at wide vocabs the dense multi-hot path
materializes a (B, vocab) activation and runs a (B, vocab) x (vocab, out)
matmul every step — HBM traffic scales with vocab.  The bag path gathers
nnz rows per record; work scales with nnz.  Reference capability:
tensor/SparseTensorMath.scala sparse gemm.

Run: PYTHONPATH=. python benchmarks/bench_sparse.py [--vocab 1000000]
Prints a json line per path with steps/s and the speedup ratio.
"""

import argparse
import json
import time

import numpy as np

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.table import Table


def _time_step(fn, args, iters=30, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    # host readback on a dependent value — true sync through the axon tunnel
    float(jnp.sum(out["weight"]))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(jnp.sum(out["weight"]))
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=1_000_000)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--nnz", type=int, default=64)
    ap.add_argument("--out", type=int, default=64)
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    m = nn.SparseLinear(args.vocab, args.out)
    params, state, _ = m.build(jax.random.PRNGKey(0),
                               Table((args.batch, args.nnz),
                                     (args.batch, args.nnz)))
    ids = rs.randint(0, args.vocab,
                     size=(args.batch, args.nnz)).astype(np.int32)
    vals = rs.rand(args.batch, args.nnz).astype(np.float32)
    dense = np.zeros((args.batch, args.vocab), np.float32)
    dense[np.arange(args.batch)[:, None], ids] = vals

    tgt = rs.randn(args.batch, args.out).astype(np.float32)

    @jax.jit
    def grad_bag(p, ids, vals):
        def loss(p):
            y, _ = m.apply(p, state, Table(ids, vals))
            return jnp.mean((y - tgt) ** 2)
        return jax.grad(loss)(p)

    @jax.jit
    def grad_dense(p, x):
        def loss(p):
            y, _ = m.apply(p, state, x)
            return jnp.mean((y - tgt) ** 2)
        return jax.grad(loss)(p)

    # the e2e training step moves the host batch to the device every
    # iteration (DistriOptimizer._put_batch) — the dense multi-hot batch
    # is (B, vocab) floats (1 GB at B=256, vocab=1e6) while the bag pair
    # is (B, nnz) ids + values; that transfer is part of the step
    def step_bag(p):
        return grad_bag(p, jnp.asarray(ids), jnp.asarray(vals))

    def step_dense(p):
        return grad_dense(p, jnp.asarray(dense))

    t_bag = _time_step(step_bag, (params,), args.iters)
    t_dense = _time_step(step_dense, (params,), max(3, args.iters // 3))

    # device-only portion (batch already resident), for attribution
    ids_d, vals_d, dense_d = (jnp.asarray(ids), jnp.asarray(vals),
                              jnp.asarray(dense))
    t_bag_dev = _time_step(grad_bag, (params, ids_d, vals_d), args.iters)
    t_dense_dev = _time_step(grad_dense, (params, dense_d),
                             max(3, args.iters // 3))

    print(json.dumps({"path": "bag", "ms_per_step": t_bag * 1e3,
                      "ms_device_only": t_bag_dev * 1e3,
                      "vocab": args.vocab, "batch": args.batch,
                      "nnz": args.nnz}))
    print(json.dumps({"path": "dense_multi_hot",
                      "ms_per_step": t_dense * 1e3,
                      "ms_device_only": t_dense_dev * 1e3}))
    print(json.dumps({"metric": "sparse_bag_speedup",
                      "value": t_dense / t_bag, "unit": "x",
                      "note": "full step incl. host->device batch"}))


if __name__ == "__main__":
    main()
