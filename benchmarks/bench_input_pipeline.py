"""Host input-pipeline benchmark (VERDICT r4 item 2).

Measures the production real-data path stage by stage on this host, then
end to end:

  stage 1  C++ prefetcher raw record read (native/src/prefetch.cc)
  stage 2  + Example proto parse (nn/tf_ops.parse_example_proto)
  stage 3  + JPEG decode (PIL, in the MT pool)
  stage 4  full: + ImageNet-train augmentation (RandomResize ->
           RandomCropper(224) -> Flip -> ChannelNormalize) +
           MTImageFeatureToBatch assembly -> b256 batches
  stage 5  + DeviceFeed end to end: the stage-4 pipeline behind the
           async feed (assembly + device staging in the worker), a
           consumer draining staged batches — reports delivered
           throughput plus the consumer's residual stall per batch

Reference analogue: dataset/image/MTLabeledBGRImgToBatch.scala over
SeqFile ImageNet shards (dataset/DataSet.scala:482-560).

    python benchmarks/bench_input_pipeline.py --data data/imagenet_tfr \
        [--seconds 30] [--threads N]

Prints one JSON line per stage plus a worker-count extrapolation against
the synthetic-input chip rate from the latest BENCH artifact.
"""

from __future__ import annotations

import argparse
import glob
import io
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# host-only benchmark — keep jax off the TPU tunnel (sitecustomize
# initializes the real backend at import; a second process on the tunnel
# breaks concurrent chip benches)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax.extend.backend as _jeb

    _jeb.clear_backends()
except Exception:
    pass

import numpy as np  # noqa: E402


def _records(paths):
    from bigdl_tpu.dataset.tfrecord import PrefetchRecordReader

    return PrefetchRecordReader(paths, n_threads=2, capacity=512)


def _timed(it, seconds, cost_fn=len):
    """Drain `it` for ~`seconds`; returns (n_items, total_bytes, dt).
    The budget is checked EVERY item: batch iterators can take tens of
    seconds per item on a 2-core host."""
    n = tot = 0
    t0 = time.perf_counter()
    for item in it:
        n += 1
        tot += cost_fn(item)
        if time.perf_counter() - t0 > seconds:
            break
    return n, tot, time.perf_counter() - t0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default="data/imagenet_tfr")
    ap.add_argument("--seconds", type=float, default=30.0)
    ap.add_argument("--threads", type=int, default=os.cpu_count())
    ap.add_argument("--batch-size", type=int, default=256)
    args = ap.parse_args(argv)

    from bigdl_tpu.nn.tf_ops import parse_example_proto
    from bigdl_tpu.vision.pipelines import (
        DecodeJPEGFeature, imagenet_record_features, imagenet_train_chain,
        shard_paths)
    from bigdl_tpu.vision.image import MTImageFeatureToBatch

    paths = shard_paths(args.data)
    results = {}

    # stage 1: raw framed-record read through the C++ prefetcher
    n, tot, dt = _timed(iter(_records(paths)), args.seconds)
    results["1_raw_read"] = {"rec_per_s": n / dt, "GB_per_s": tot / dt / 1e9}

    # stage 2: + proto parse
    def parsed():
        for rec in _records(paths):
            yield parse_example_proto(rec)

    n, _, dt = _timed(parsed(), args.seconds, cost_fn=lambda _: 0)
    results["2_parse"] = {"rec_per_s": n / dt}

    # stage 3: + JPEG decode only (single thread, to isolate decode cost)
    from PIL import Image

    def decoded():
        for rec in itertools.islice(_records(paths), 4096):
            f = parse_example_proto(rec)
            img = Image.open(io.BytesIO(f["image/encoded"][0]))
            yield np.asarray(img.convert("RGB"))

    n, tot, dt = _timed(decoded(), args.seconds, cost_fn=lambda a: a.nbytes)
    results["3_decode_1thread"] = {"img_per_s": n / dt,
                                   "decoded_GB_per_s": tot / dt / 1e9}

    # stage 4: the full pipeline as a trainer would run it — the SAME
    # builder bench.py --real-data uses (bigdl_tpu/vision/pipelines.py)
    mt = MTImageFeatureToBatch(224, 224, args.batch_size,
                               DecodeJPEGFeature(imagenet_train_chain(224)),
                               num_threads=args.threads)
    n, tot, dt = _timed(mt(imagenet_record_features(paths)), args.seconds,
                        cost_fn=lambda b: b[0].nbytes)
    img_s = n * args.batch_size / dt
    results["4_full_pipeline"] = {
        "img_per_s": img_s, "batch_per_s": n / dt,
        "threads": args.threads, "decoded_GB_per_s": tot / dt / 1e9}

    # stage 5: DeviceFeed end to end — same pipeline, but assembly AND
    # device staging run in the feed worker while the consumer (standing
    # in for the step loop) only drains.  stall_ms is what a training
    # step would still wait on input per batch; ~0 means full overlap.
    from bigdl_tpu.dataset.feed import DeviceFeed

    mt5 = MTImageFeatureToBatch(224, 224, args.batch_size,
                                DecodeJPEGFeature(imagenet_train_chain(224)),
                                num_threads=args.threads)

    def _stage(b):
        return tuple(jax.device_put(a) for a in b)

    stalls = []

    def fed():
        with DeviceFeed(mt5(imagenet_record_features(paths)), _stage,
                        prefetch_depth=2, name="DeviceFeed-bench") as feed:
            for item in feed:
                stalls.append(item.stall_s)
                yield item

    n, tot, dt = _timed(fed(), args.seconds,
                        cost_fn=lambda it: it.batch[0].nbytes)
    results["5_device_feed_e2e"] = {
        "img_per_s": n * args.batch_size / dt, "batch_per_s": n / dt,
        "prefetch_depth": 2, "staged_GB_per_s": tot / dt / 1e9,
        "mean_stall_ms": 1e3 * float(np.mean(stalls)) if stalls else 0.0}

    # worker math vs the chip's synthetic-input ceiling
    chip = None
    for path in sorted(glob.glob(os.path.join(
            os.path.dirname(__file__), "..", "BENCH_r*.json")), reverse=True):
        try:
            parsed = json.load(open(path))["parsed"]
            # synthetic-input chip rate ONLY — a --real-data capture
            # shares the unit but is host-bound, not a chip ceiling
            if parsed["metric"] == "resnet50_imagenet_train_throughput":
                chip = parsed["value"]
                break
        except Exception:
            continue
    cores = os.cpu_count()
    if chip:
        results["worker_math"] = {
            "chip_img_per_s_synthetic": chip,
            "host_img_per_s_measured": round(img_s, 1),
            "host_cores": cores,
            "cores_needed_1chip": round(chip / (img_s / cores), 1),
            "note": "linear-in-cores extrapolation; decode+augment are "
                    "embarrassingly parallel across images"}
    for k, v in results.items():
        print(json.dumps({k: {kk: (round(vv, 3) if isinstance(vv, float)
                                   else vv) for kk, vv in v.items()}}))
    return results


if __name__ == "__main__":
    main()
