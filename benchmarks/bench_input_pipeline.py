"""Host input-pipeline benchmark (VERDICT r4 item 2).

Measures the production real-data path stage by stage on this host, then
end to end:

  stage 1  C++ prefetcher raw record read (native/src/prefetch.cc)
  stage 2  + Example proto parse (nn/tf_ops.parse_example_proto)
  stage 3  + JPEG decode (PIL, in the MT pool)
  stage 4  full: + ImageNet-train augmentation (RandomResize ->
           RandomCropper(224) -> Flip -> ChannelNormalize) +
           MTImageFeatureToBatch assembly -> b256 batches
  stage 5  + DeviceFeed end to end: the stage-4 pipeline behind the
           async feed (assembly + device staging in the worker), a
           consumer draining staged batches — reports delivered
           throughput plus the consumer's residual stall per batch
  stage 6  reader-pool e2e: the same parse+decode+resize assembly
           offloaded to `dataset.readers.ReaderPool` child PROCESSES
           (procs in {1,2,4}), interleaved against the in-thread
           assembler — the measured multi-process scaling curve that
           replaces the old linear-in-cores extrapolation

Reference analogue: dataset/image/MTLabeledBGRImgToBatch.scala over
SeqFile ImageNet shards (dataset/DataSet.scala:482-560).

    python benchmarks/bench_input_pipeline.py --data data/imagenet_tfr \
        [--seconds 30] [--threads N]

Prints one JSON line per stage plus the measured reader-pool scaling
against the synthetic-input chip rate from the latest BENCH artifact.

`--readers-quick [out.json]` skips the corpus stages and runs the
self-contained reader-pool A-B (synthetic in-memory JPEG corpus + a
latency-bound proxy), writing the committed
benchmarks/results/readers_quick.json artifact.
"""

from __future__ import annotations

import argparse
import glob
import io
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# host-only benchmark — keep jax off the TPU tunnel (sitecustomize
# initializes the real backend at import; a second process on the tunnel
# breaks concurrent chip benches)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax.extend.backend as _jeb

    _jeb.clear_backends()
except Exception:
    pass

import numpy as np  # noqa: E402


def _records(paths):
    from bigdl_tpu.dataset.tfrecord import PrefetchRecordReader

    return PrefetchRecordReader(paths, n_threads=2, capacity=512)


def _timed(it, seconds, cost_fn=len):
    """Drain `it` for ~`seconds`; returns (n_items, total_bytes, dt).
    The budget is checked EVERY item: batch iterators can take tens of
    seconds per item on a 2-core host."""
    n = tot = 0
    t0 = time.perf_counter()
    for item in it:
        n += 1
        tot += cost_fn(item)
        if time.perf_counter() - t0 > seconds:
            break
    return n, tot, time.perf_counter() - t0


def _drain_batches(work, procs):
    """Assemble every chunk of `work`; returns (n_batches, seconds).
    procs=0 is the in-thread assembler (the single-process baseline the
    acceptance criterion compares against); procs>=1 offloads assembly to
    that many reader child processes behind the reorder stage."""
    from bigdl_tpu.dataset.readers import ReaderPool

    t0 = time.perf_counter()
    if procs == 0:
        n = 0
        for item in work.item_stream(0):
            work.assemble(item)
            n += 1
    else:
        with ReaderPool(work, procs=procs) as pool:
            n = sum(1 for _ in pool)
    return n, time.perf_counter() - t0


def _reader_ab(make_work, procs_list=(0, 1, 2, 4), rounds=3):
    """Interleaved A-B: each round runs every leg once (0=in-thread first)
    so background-load drift hits all legs alike; per-leg best-of-rounds
    throughput is reported, mirroring bench_trainer_overhead's
    interleaving discipline."""
    best = {p: 0.0 for p in procs_list}
    batches = None
    for _ in range(rounds):
        for p in procs_list:
            n, dt = _drain_batches(make_work(), p)
            batches = n
            best[p] = max(best[p], n / dt)
    return best, batches


def _synthetic_jpeg_corpus(n=384, side=64):
    """In-memory JPEG bytes (no corpus on disk needed): decode+augment
    cost is real PIL work, just on small images so the quick bench stays
    quick."""
    from PIL import Image

    rs = np.random.RandomState(0)
    blobs = []
    for _ in range(n):
        img = Image.fromarray(rs.randint(0, 255, (side, side, 3), np.uint8))
        buf = io.BytesIO()
        img.save(buf, format="JPEG", quality=80)
        blobs.append(buf.getvalue())
    return blobs


def _decode_assemble(chunk):
    from PIL import Image

    out = []
    for blob in chunk:
        img = Image.open(io.BytesIO(blob)).convert("RGB").resize((32, 32))
        out.append(np.asarray(img, np.float32) / 255.0)
    return np.stack(out)


def _decode_assemble_latency(chunk, io_ms=30.0):
    # latency-bound proxy: models remote-storage reads (GCS shard gets)
    # where the wall clock is dominated by I/O WAITS, not CPU — the
    # regime reader processes exist for, and the only one a 1-core CI
    # host can demonstrate overlap in honestly
    time.sleep(io_ms / 1e3)
    return _decode_assemble(chunk)


def readers_quick(out_path=None):
    """The committed readers_quick.json: reader-pool vs in-thread A-B on
    (a) a real-decode corpus — honest CPU-bound rows, which on an N-core
    host cannot beat in-thread by more than ~N — and (b) a latency-bound
    proxy whose speedup transfers to storage-bound production input."""
    from bigdl_tpu.dataset.readers import ChunkWork

    blobs = _synthetic_jpeg_corpus()
    cores = os.cpu_count()
    rows = []

    cpu_best, nb = _reader_ab(
        lambda: ChunkWork(blobs, 16, _decode_assemble))
    for p in sorted(cpu_best):
        rows.append({"path": "readers_ab_decode_cpu_bound",
                     "procs": p, "host_cores": cores,
                     "batch_per_s": round(cpu_best[p], 2),
                     "batches": nb})

    lat_best, nb = _reader_ab(
        lambda: ChunkWork(blobs, 16, _decode_assemble_latency))
    for p in sorted(lat_best):
        rows.append({"path": "readers_ab_latency_bound_proxy",
                     "procs": p, "host_cores": cores, "io_ms_per_batch": 30.0,
                     "batch_per_s": round(lat_best[p], 2),
                     "batches": nb})

    speedup = lat_best[4] / lat_best[0] if lat_best[0] else 0.0
    rows.append({"metric": "readers_pool_speedup",
                 "value": round(speedup, 2),
                 "procs": 4, "vs": "in-thread assembler",
                 "workload": "latency_bound_proxy",
                 "ok": bool(speedup >= 2.5)})
    artifact = {
        "bench": "PYTHONPATH=. JAX_PLATFORMS=cpu python "
                 "benchmarks/bench_input_pipeline.py --readers-quick",
        "date": time.strftime("%Y-%m-%d"),
        "platform": f"cpu backend, {cores}-core host. Legs are interleaved "
                    "(in-thread, procs=1, 2, 4 per round; best-of-3 rounds). "
                    "The cpu_bound rows are the honest ceiling for THIS "
                    "host: decode is pure CPU, so a 1-core box cannot beat "
                    "in-thread no matter how many reader processes it "
                    "forks (expect <=1x there). The headline speedup comes "
                    "from the latency_bound_proxy rows, where each batch "
                    "carries a 30 ms simulated storage wait — the regime "
                    "the pool targets in production (remote-shard reads): "
                    "waits overlap across processes even on one core, so "
                    "the scaling transfers while the CPU rows do not.",
        "rows": rows,
    }
    out = json.dumps(artifact, indent=2)
    print(out)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(out + "\n")
    return artifact


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default="data/imagenet_tfr")
    ap.add_argument("--seconds", type=float, default=30.0)
    ap.add_argument("--threads", type=int, default=os.cpu_count())
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--readers-quick", nargs="?", const="-", default=None,
                    metavar="OUT_JSON",
                    help="run the self-contained reader-pool A-B and exit "
                         "(writes the JSON artifact to OUT_JSON if given)")
    args = ap.parse_args(argv)

    if args.readers_quick is not None:
        return readers_quick(None if args.readers_quick == "-"
                             else args.readers_quick)

    from bigdl_tpu.nn.tf_ops import parse_example_proto
    from bigdl_tpu.vision.pipelines import (
        DecodeJPEGFeature, imagenet_record_features, imagenet_train_chain,
        shard_paths)
    from bigdl_tpu.vision.image import MTImageFeatureToBatch

    paths = shard_paths(args.data)
    results = {}

    # stage 1: raw framed-record read through the C++ prefetcher
    n, tot, dt = _timed(iter(_records(paths)), args.seconds)
    results["1_raw_read"] = {"rec_per_s": n / dt, "GB_per_s": tot / dt / 1e9}

    # stage 2: + proto parse
    def parsed():
        for rec in _records(paths):
            yield parse_example_proto(rec)

    n, _, dt = _timed(parsed(), args.seconds, cost_fn=lambda _: 0)
    results["2_parse"] = {"rec_per_s": n / dt}

    # stage 3: + JPEG decode only (single thread, to isolate decode cost)
    from PIL import Image

    def decoded():
        for rec in itertools.islice(_records(paths), 4096):
            f = parse_example_proto(rec)
            img = Image.open(io.BytesIO(f["image/encoded"][0]))
            yield np.asarray(img.convert("RGB"))

    n, tot, dt = _timed(decoded(), args.seconds, cost_fn=lambda a: a.nbytes)
    results["3_decode_1thread"] = {"img_per_s": n / dt,
                                   "decoded_GB_per_s": tot / dt / 1e9}

    # stage 4: the full pipeline as a trainer would run it — the SAME
    # builder bench.py --real-data uses (bigdl_tpu/vision/pipelines.py)
    mt = MTImageFeatureToBatch(224, 224, args.batch_size,
                               DecodeJPEGFeature(imagenet_train_chain(224)),
                               num_threads=args.threads)
    n, tot, dt = _timed(mt(imagenet_record_features(paths)), args.seconds,
                        cost_fn=lambda b: b[0].nbytes)
    img_s = n * args.batch_size / dt
    results["4_full_pipeline"] = {
        "img_per_s": img_s, "batch_per_s": n / dt,
        "threads": args.threads, "decoded_GB_per_s": tot / dt / 1e9}

    # stage 5: DeviceFeed end to end — same pipeline, but assembly AND
    # device staging run in the feed worker while the consumer (standing
    # in for the step loop) only drains.  stall_ms is what a training
    # step would still wait on input per batch; ~0 means full overlap.
    from bigdl_tpu.dataset.feed import DeviceFeed

    mt5 = MTImageFeatureToBatch(224, 224, args.batch_size,
                                DecodeJPEGFeature(imagenet_train_chain(224)),
                                num_threads=args.threads)

    def _stage(b):
        return tuple(jax.device_put(a) for a in b)

    stalls = []

    def fed():
        with DeviceFeed(mt5(imagenet_record_features(paths)), _stage,
                        prefetch_depth=2, name="DeviceFeed-bench") as feed:
            for item in feed:
                stalls.append(item.stall_s)
                yield item

    n, tot, dt = _timed(fed(), args.seconds,
                        cost_fn=lambda it: it.batch[0].nbytes)
    results["5_device_feed_e2e"] = {
        "img_per_s": n * args.batch_size / dt, "batch_per_s": n / dt,
        "prefetch_depth": 2, "staged_GB_per_s": tot / dt / 1e9,
        "mean_stall_ms": 1e3 * float(np.mean(stalls)) if stalls else 0.0}

    # stage 6: reader-pool e2e — the stage-2/3 assembly (parse + decode +
    # resize to the crop size) offloaded to child processes, procs in
    # {1,2,4}, interleaved against the in-thread assembler.  Unlike the
    # stage-4 thread pool this also parallelizes the GIL-bound parts
    # (proto parse, numpy conversion), so its scaling curve is the one
    # worker_math may extrapolate from.
    from bigdl_tpu.dataset.readers import ChunkWork

    raw = list(itertools.islice(iter(_records(paths)), 2048))
    crop = 224

    def _assemble_imagenet(chunk):
        from PIL import Image

        out = []
        for rec in chunk:
            f = parse_example_proto(rec)
            img = Image.open(io.BytesIO(f["image/encoded"][0]))
            out.append(np.asarray(img.convert("RGB").resize((crop, crop)),
                                  np.float32))
        return np.stack(out)

    pool_best, nb = _reader_ab(
        lambda: ChunkWork(raw, 32, _assemble_imagenet), rounds=2)
    results["6_reader_pool_e2e"] = {
        "batches": nb, "chunk": 32,
        **{f"batch_per_s_procs{p}" if p else "batch_per_s_inthread":
           round(v, 3) for p, v in sorted(pool_best.items())},
        "scaling_p4_vs_inthread": round(
            pool_best[4] / pool_best[0], 2) if pool_best[0] else 0.0}

    # worker math vs the chip's synthetic-input ceiling
    chip = None
    for path in sorted(glob.glob(os.path.join(
            os.path.dirname(__file__), "..", "BENCH_r*.json")), reverse=True):
        try:
            parsed = json.load(open(path))["parsed"]
            # synthetic-input chip rate ONLY — a --real-data capture
            # shares the unit but is host-bound, not a chip ceiling
            if parsed["metric"] == "resnet50_imagenet_train_throughput":
                chip = parsed["value"]
                break
        except Exception:
            continue
    cores = os.cpu_count()
    if chip:
        # measured reader-pool scaling replaces the old linear-in-cores
        # assumption: procs=4 vs in-thread from stage 6, per-process rate
        # from the procs=1 leg
        s6 = results["6_reader_pool_e2e"]
        per_proc_img_s = s6["batch_per_s_procs1"] * 32
        results["worker_math"] = {
            "chip_img_per_s_synthetic": chip,
            "host_img_per_s_measured": round(img_s, 1),
            "host_cores": cores,
            "reader_scaling_p4_measured": s6["scaling_p4_vs_inthread"],
            "reader_procs_needed_1chip": round(chip / per_proc_img_s, 1)
            if per_proc_img_s else None,
            "note": "from the measured stage-6 reader-pool curve (procs=1 "
                    "leg sets the per-process rate, the p4/in-thread ratio "
                    "shows how far this host is from linear); hosts with "
                    "more cores re-measure rather than assume linearity"}
    for k, v in results.items():
        print(json.dumps({k: {kk: (round(vv, 3) if isinstance(vv, float)
                                   else vv) for kk, vv in v.items()}}))
    return results


if __name__ == "__main__":
    main()
