"""Serving latency through the micro-batching runtime (VERDICT r5 item 6).

The PredictionService path had never been latency-measured; this harness
times it END-TO-END through `bigdl_tpu.serving.ServingRuntime` — admission
queue, bucket coalescing, pad-to-bucket, jitted forward, readback — not
just the bare forward.  Three serving variants of the same weights:

  * fp32        — the model as built
  * int8        — calibrated static int8 (`nn.quantize(mode="static")`)
  * bn_folded   — inference conv+BN fold (`utils/fusion.fold_batchnorm`)

and three request phases per variant:

  * b1   — sequential single-row requests (pure latency; includes the
           max-wait coalescing window, which is part of the honest number)
  * b8   — sequential 8-row requests
  * burst64_b1 — 64 concurrent single-row requests (the coalescing smoke:
           occupancy/batches show the scheduler folding them into few
           fixed-shape forwards)
  * swap — params-only hot-swap under traffic: swap_ms and
           swap-to-first-request ms (the registry reuses every live
           compiled executable, so neither includes a re-trace)

Emits one JSON row per (variant, phase) with p50/p99/mean latency, batch
occupancy, device-batch count and compiled-shape count, and writes the
table to benchmarks/results/serving.json.

    python benchmarks/bench_serving.py            # ResNet-50 @224 (TPU)
    python benchmarks/bench_serving.py --quick    # ResNet-20/CIFAR @32 (CPU-sized)
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BUCKETS = (1, 8, 32)
MAX_WAIT_MS = 2.0


def build_variants(model_name: str):
    """Returns (image, [(variant, module, params, state), ...])."""
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet50
    from bigdl_tpu.models.resnet import resnet_cifar
    from bigdl_tpu.utils.fusion import fold_batchnorm

    if model_name == "resnet50":
        model, image, classes = resnet50(1000), 224, 1000
    else:
        model, image, classes = resnet_cifar(20, 10), 32, 10
    params, state, _ = model.build(jax.random.PRNGKey(0),
                                   (BUCKETS[-1], image, image, 3))
    rs = np.random.RandomState(0)
    calib = [jnp.asarray(rs.rand(8, image, image, 3), jnp.float32)]

    variants = [("fp32", model, params, state)]

    qm, qp = nn.quantize(model, params, mode="static")
    qp = nn.calibrate(qm, qp, state, calib)
    variants.append(("int8", qm, qp, state))

    fmodel, fparams, fstate = fold_batchnorm(model, params, state)
    variants.append(("bn_folded", fmodel, fparams, fstate))
    return image, variants


def run_phase(module, params, state, image: int, phase: str, n: int):
    from bigdl_tpu.serving import ServingConfig, ServingRuntime

    rs = np.random.RandomState(1)
    example = rs.rand(1, image, image, 3).astype(np.float32)
    rt = ServingRuntime(
        module, params, state, example_input=example,
        config=ServingConfig(buckets=BUCKETS, max_wait_ms=MAX_WAIT_MS,
                             capacity=256))
    try:
        t0 = time.perf_counter()
        if phase == "burst64_b1":
            reqs = [rs.rand(1, image, image, 3).astype(np.float32)
                    for _ in range(n)]
            with concurrent.futures.ThreadPoolExecutor(16) as pool:
                list(pool.map(rt.predict, reqs))
        else:
            rows = 1 if phase == "b1" else 8
            for _ in range(n):
                rt.predict(rs.rand(rows, image, image, 3).astype(np.float32))
        wall = time.perf_counter() - t0
        snap = rt.metrics.snapshot()
        return {
            "phase": phase, "requests": n,
            "p50_ms": snap["latency_ms"]["p50"],
            "p99_ms": snap["latency_ms"]["p99"],
            "mean_ms": snap["latency_ms"]["mean"],
            "device_batch_p50_ms": snap["device_batch_ms"]["p50"],
            "batch_occupancy": snap["batch_occupancy"],
            "batches": snap["batches"],
            "compiled_shapes": rt.compile_count(),
            "wall_s": round(wall, 2),
        }
    finally:
        rt.close()


def run_swap_phase(module, params, state, image: int):
    """Hot-swap cost: under steady traffic, register a same-shaped second
    version (a params-only swap — the registry reuses every live compiled
    executable) and time both the swap itself and swap-to-first-request."""
    import jax

    from bigdl_tpu import obs
    from bigdl_tpu.serving import ServingConfig, ServingRuntime

    rs = np.random.RandomState(1)
    example = rs.rand(1, image, image, 3).astype(np.float32)
    rt = ServingRuntime(
        module, params, state, example_input=example,
        config=ServingConfig(buckets=BUCKETS, max_wait_ms=MAX_WAIT_MS,
                             capacity=256))
    try:
        x = rs.rand(1, image, image, 3).astype(np.float32)
        rt.predict(x)  # steady traffic before the swap
        reused0 = obs.registry().get("serving/warmup_reused")
        t0 = time.perf_counter()
        rt.swap("v1", jax.tree_util.tree_map(lambda l: l, params), state)
        swap_s = time.perf_counter() - t0
        rt.predict(x)
        first_s = time.perf_counter() - t0
        return {
            "phase": "swap", "requests": 1,
            "swap_ms": round(swap_s * 1e3, 2),
            "swap_to_first_request_ms": round(first_s * 1e3, 2),
            "warmup_reused": int(obs.registry().get("serving/warmup_reused")
                                 - reused0),
            "compiled_shapes": rt.compile_count(),
        }
    finally:
        rt.close()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="ResNet-20/CIFAR @32x32, fewer requests (CPU-sized)")
    ap.add_argument("--model", choices=("resnet50", "resnet20_cifar"),
                    default=None)
    ap.add_argument("--requests", type=int, default=None)
    args = ap.parse_args(argv)
    model_name = args.model or ("resnet20_cifar" if args.quick else "resnet50")
    n_seq = args.requests or (24 if args.quick else 50)

    import jax

    platform = jax.devices()[0].platform
    image, variants = build_variants(model_name)

    rows = []
    for variant, module, params, state in variants:
        for phase, n in (("b1", n_seq), ("b8", max(8, n_seq // 2)),
                         ("burst64_b1", 64)):
            row = {"model": model_name, "variant": variant,
                   "platform": platform, "max_wait_ms": MAX_WAIT_MS,
                   "buckets": list(BUCKETS),
                   **run_phase(module, params, state, image, phase, n)}
            rows.append(row)
            print(json.dumps(row), flush=True)
        row = {"model": model_name, "variant": variant,
               "platform": platform, "max_wait_ms": MAX_WAIT_MS,
               "buckets": list(BUCKETS),
               **run_swap_phase(module, params, state, image)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    out = os.path.join(os.path.dirname(__file__), "results", "serving.json")
    with open(out, "w") as f:
        json.dump(rows, f, indent=1)
    print(f"# wrote {out}")


if __name__ == "__main__":
    main()
