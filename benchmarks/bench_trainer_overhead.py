"""Trainer-loop overhead attribution (VERDICT r3 weak #1 / item 5).

Round 3 measured `DistriOptimizer.optimize()` 10-13% under the raw jitted
step on the tunneled TPU and ATTRIBUTED the gap to the ~100 ms tunnel
round trip without proof.  This experiment settles the attribution and
measures each component on the local CPU backend:

  1. environment readback latency: reading back even ONE trivial
     completed step costs a fixed ~110 ms in this environment (local CPU
     backend, no tunnel!), while re-reading an already-materialized value
     is ~0.06 ms — so "microsecond readback" does not exist here and the
     round-3 gap arithmetic (readback_latency / (depth/2) per step) is
     the controlling model everywhere in this image;
  2. raw dispatch throughput: the optimizer's own compiled step in a
     tight loop, ONE final sync (bench.py's denominator);
  3. pure host-python driver cost: optimize() with the drain pushed out
     of the window (depth >> iters) minus row 2 — dataset iteration,
     dispatch, metrics, logging, triggers;
  4. optimize() at the standard async depth, plus an injected-latency
     sweep (+0/1/10/100 ms per readback) checked against the
     amortization model ms/step ~= raw + (readback + injected)/(depth/2).

While building this, four real loop defects were found and fixed (each
reproduced here before the fix):
  - the drain's eager `jnp.stack` compiled a FRESH concat executable for
    every distinct burst length (seconds of XLA compiles per epoch) and
    paid ~2 eager dispatches per scalar; worse, ANY packing program run
    at drain time enqueues BEHIND the in-flight steps on the in-order
    device, stalling each drain for queue_depth x step_time (measured
    1.3 s/drain at depth 32 on the tunnel) -> a device-side telemetry
    ring written by a tiny per-step jit; the drain reads the ring
    SNAPSHOT of an already-executed step (one transfer, no queue wait);
  - `jax.random.fold_in` dispatched ~5 eager ops per step -> jitted;
  - the host-lr path device_put a fresh scalar every step (a put can
    serialize the in-flight pipeline) -> cached until the lr changes.

A fifth experiment A-Bs the DeviceFeed input pipeline (ISSUE 2): the same
loop over HOST-resident batches (so per-step assembly + H2D staging work
exists) with the feed off (inline staging, prefetch_depth=0) vs on
(depth 2, staging overlapped in the worker), plus the device-resident
path where the feed's residual stall must be ~0.

A sixth experiment A-Bs checkpoint saving (ISSUE 3): trigger-driven saves
with `async_save=False` (the loop pays serialize+fsync+rename inline) vs
the AsyncCheckpointer default (the loop pays only the on-device snapshot
dispatch; IO overlaps in the bounded writer thread).

A seventh experiment A-Bs cold-start (ISSUE 7): `--restart` runs fresh
subprocesses against a cold vs prewarmed `BIGDL_TPU_COMPILE_CACHE` dir and
compares pre-first-step compile time (plus an in-process hot-swap
warm-reuse A-B); the capture commits as results/aotcache_quick.json.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python benchmarks/bench_trainer_overhead.py
     [--feed-only | --ckpt | --restart]
Prints one json line per row.
"""

import argparse
import json
import os
import statistics
import sys
import time
from collections import deque

# the --ckpt reshard A-B shards a training mesh over virtual devices;
# the 8-device host platform must be forced BEFORE jax initializes
# (same pattern as tools/obs_smoke.py).  Other modes leave the
# environment untouched so their committed captures stay comparable.
if "--ckpt" in sys.argv:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim_mod
from bigdl_tpu.core.random import RandomGenerator
from bigdl_tpu.dataset.dataset import ArrayDataSet
from bigdl_tpu.dataset.minibatch import MiniBatch
from bigdl_tpu.optim import SGD, Trigger

BATCH, HW, CIN, NCLS = 32, 32, 3, 10
ITERS = 60


def _model():
    return nn.Sequential(
        nn.SpatialConvolution(CIN, 32, 3, 3, 1, 1, -1, -1), nn.ReLU(),
        nn.SpatialConvolution(32, 32, 3, 3, 1, 1, -1, -1), nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.SpatialConvolution(32, 64, 3, 3, 1, 1, -1, -1), nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Flatten(), nn.Linear(64 * (HW // 4) ** 2, NCLS),
        nn.LogSoftMax())


class _RepeatDataSet(ArrayDataSet):
    """Cycles one prebuilt DEVICE-RESIDENT MiniBatch — the bench.py
    methodology (device-resident batches isolate the loop; the raw-step
    denominator reuses one device batch, so the loop must too)."""

    def __init__(self, batch, n):
        self.batch = batch
        self.n = n

    def size(self):
        return self.batch.size() * self.n

    def data(self, train):
        return iter([self.batch] * self.n)


def _build(iters=ITERS):
    RandomGenerator.set_seed(7)
    rs = np.random.RandomState(0)
    x = rs.randn(BATCH, HW, HW, CIN).astype(np.float32)
    y = (np.arange(BATCH) % NCLS).astype(np.int32)
    ds = _RepeatDataSet(MiniBatch(jnp.asarray(x), jnp.asarray(y)), iters)
    o = optim_mod.DistriOptimizer(
        _model(), ds, nn.ClassNLLCriterion(),
        optim_method=SGD(learning_rate=0.01),
        end_trigger=Trigger.max_iteration(iters))
    return o, x, y


class _HostDataSet(ArrayDataSet):
    """Cycles prebuilt HOST-resident MiniBatches: unlike _RepeatDataSet,
    every step pays batch staging (numpy -> sharded device arrays), so the
    feed has real work to pull off the hot loop."""

    def __init__(self, batches, n):
        self.batches = list(batches)
        self.n = n

    def size(self):
        return self.batches[0].size() * self.n

    def data(self, train):
        return iter([self.batches[i % len(self.batches)]
                     for i in range(self.n)])


def _inject_latency(latency_s):
    """Patch the optimizer module's numpy binding so every drain readback
    (np.asarray of a device array) pays extra round-trip latency."""
    import bigdl_tpu.optim.optimizer as om

    real_np = om.np

    class _SlowNp:
        def __getattr__(self, name):
            return getattr(real_np, name)

        @staticmethod
        def asarray(a, *args, **kw):
            if isinstance(a, jax.Array):
                time.sleep(latency_s)
            return real_np.asarray(a, *args, **kw)

    om.np = _SlowNp()
    return lambda: setattr(om, "np", real_np)


def measure_readback_latency():
    """Fixed cost of reading back ONE freshly-dispatched trivial step vs
    re-reading a materialized value."""

    @jax.jit
    def stepish(p):
        return p * 0.999, jnp.sum(p)

    p = jnp.ones((8, 2))
    p, l = stepish(p)
    float(l)
    fresh = []
    for _ in range(15):
        p, l = stepish(p)
        t0 = time.perf_counter()
        float(l)
        fresh.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    float(l)
    rere = time.perf_counter() - t0
    return float(np.median(fresh)), rere


def measure_raw():
    """Tight dispatch loop over the optimizer's own compiled step, one
    final sync (bench.py style)."""
    o, x, y = _build()
    first = next(iter(o.dataset.data(train=False)))
    o._init_model(first)
    step = o._build_step()
    params, mstate, ostate = o.params, o.model_state, o.opt_state
    xd, yd = jnp.asarray(x), jnp.asarray(y)
    rng = jax.random.PRNGKey(0)
    lr = jnp.asarray(0.01, jnp.float32)
    for _ in range(3):
        params, mstate, ostate, loss, lru = step(params, mstate, ostate,
                                                 xd, yd, rng, lr)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        params, mstate, ostate, loss, lru = step(params, mstate, ostate,
                                                 xd, yd, rng, lr)
    float(loss)
    return (time.perf_counter() - t0) / ITERS


def measure_loop(latency_ms=0.0, no_drain=False):
    o, _, _ = _build()
    if no_drain:
        # push every readback out of the measured window: the loop's only
        # sync is the final flush -> ms/step isolates host python cost
        o._async_depth = lambda: 4 * ITERS
    restore = _inject_latency(latency_ms / 1e3) if latency_ms else None
    try:
        o.optimize()  # warm: compiles the step + telemetry-ring write
        o.end_when = Trigger.max_iteration(2 * ITERS)
        t0 = time.perf_counter()
        o.optimize()
        return (time.perf_counter() - t0) / ITERS
    finally:
        if restore:
            restore()


def measure_feed(prefetch_depth, host_batches=True, iters=ITERS):
    """optimize() ms/step with the input feed at `prefetch_depth`.

    host_batches=True uses numpy batches (staging work exists each step);
    False uses the device-resident batch (staging is a sharding check, so
    the feed's residual stall must be ~0).
    """
    RandomGenerator.set_seed(7)
    rs = np.random.RandomState(0)
    if host_batches:
        batches = [MiniBatch(rs.randn(BATCH, HW, HW, CIN).astype(np.float32),
                             (np.arange(BATCH) % NCLS).astype(np.int32))
                   for _ in range(8)]
        ds = _HostDataSet(batches, iters)
    else:
        x = rs.randn(BATCH, HW, HW, CIN).astype(np.float32)
        y = (np.arange(BATCH) % NCLS).astype(np.int32)
        ds = _RepeatDataSet(MiniBatch(jnp.asarray(x), jnp.asarray(y)), iters)
    o = optim_mod.DistriOptimizer(
        _model(), ds, nn.ClassNLLCriterion(),
        optim_method=SGD(learning_rate=0.01),
        end_trigger=Trigger.max_iteration(iters))
    o.set_feed(prefetch_depth)
    o.optimize()  # warm: compiles the step + telemetry-ring write
    o.end_when = Trigger.max_iteration(2 * iters)
    t0 = time.perf_counter()
    o.optimize()
    per = (time.perf_counter() - t0) / iters
    return per, o.metrics.get("feed stall")


def feed_ab(iters=ITERS):
    """Feed off/on A-B (ISSUE 2 acceptance): same work, staging inline vs
    overlapped.  Returns the two host-batch ms/step numbers."""
    rows = {}
    for depth in (0, 2):
        per, stall = min((measure_feed(depth, iters=iters)
                          for _ in range(3)), key=lambda r: r[0])
        rows[depth] = per
        print(json.dumps({
            "path": "feed_ab_host_batches", "prefetch_depth": depth,
            "ms_per_step": round(per * 1e3, 2),
            "feed_stall_ms_per_step": round(stall * 1e3, 3)}))
    # device-resident batches: staging is a no-op put, stall must vanish
    per, stall = measure_feed(2, host_batches=False, iters=iters)
    print(json.dumps({
        "path": "feed_device_resident", "prefetch_depth": 2,
        "ms_per_step": round(per * 1e3, 2),
        "feed_stall_ms_per_step": round(stall * 1e3, 3)}))
    assert stall < 2e-3, f"device-resident feed stall {stall*1e3:.2f} ms"
    print(json.dumps({
        "metric": "feed_overlap_ok",
        "value": bool(rows[2] <= rows[0] * 1.10),
        "speedup_on_vs_off": round(rows[0] / rows[2], 3)}))
    return rows


def measure_ckpt(async_save, every=5, iters=ITERS):
    """optimize() with trigger-driven checkpoints in sync vs async mode.

    Returns (ms_per_step, stall_s_per_save, n_saves): `checkpoint stall`
    is what the step loop PAID at each trigger — the full
    serialize+fsync+rename for sync, only the on-device snapshot dispatch
    (+ any writer backpressure) for async.
    """
    import tempfile

    from bigdl_tpu.resilience import committed_steps

    with tempfile.TemporaryDirectory() as tmp:
        o, _, _ = _build(iters)
        o.optimize()  # warm: compiles the step + telemetry-ring write
        o.set_checkpoint(tmp, Trigger.several_iteration(every),
                         async_save=async_save, keep_last=3)
        o.end_when = Trigger.max_iteration(2 * iters)
        t0 = time.perf_counter()
        o.optimize()
        per = (time.perf_counter() - t0) / iters
        n_saves = len(committed_steps(tmp))
    return per, o.metrics.get("checkpoint stall"), n_saves


def ckpt_ab(iters=ITERS):
    """Sync/async checkpoint A-B (ISSUE 3 acceptance): same saves, the
    write either stalls the loop or overlaps it in the writer thread."""
    rows = {}
    for mode in ("sync", "async"):
        per, stall, n = min((measure_ckpt(mode == "async", iters=iters)
                             for _ in range(3)), key=lambda r: r[0])
        rows[mode] = (per, stall)
        print(json.dumps({
            "path": "ckpt_ab", "mode": mode, "n_saves": n,
            "ms_per_step": round(per * 1e3, 2),
            "ckpt_stall_ms_per_save": round(stall * 1e3, 3)}))
    sync_stall, async_stall = rows["sync"][1], rows["async"][1]
    assert async_stall < sync_stall, (
        f"async save stall {async_stall*1e3:.2f} ms/save not below sync "
        f"{sync_stall*1e3:.2f} ms/save")
    print(json.dumps({
        "metric": "ckpt_async_overlap_ok", "value": True,
        "stall_ratio_sync_over_async":
            round(sync_stall / max(async_stall, 1e-9), 1)}))
    return rows


def _reshard_build(layout, root, iters, every, mesh_b=False):
    """A tp-sharded MLP under dp(2)xtp(2) writing `layout` checkpoints —
    or, with mesh_b, the RESTORE-side twin: dp(4)xtp(2) with a different
    tp rule set, so loading a mesh-A save re-cuts every sharded leaf."""
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.core.engine import AXIS_DATA, AXIS_MODEL, Engine
    from bigdl_tpu.parallel import ShardingRules

    RandomGenerator.set_seed(7)
    rs = np.random.RandomState(0)
    feat, hidden, ncls = 256, 1024, 10
    x = rs.randn(BATCH, feat).astype(np.float32)
    y = (np.arange(BATCH) % ncls).astype(np.int32)
    ds = _RepeatDataSet(MiniBatch(jnp.asarray(x), jnp.asarray(y)), iters)
    model = nn.Sequential(nn.Linear(feat, hidden), nn.ReLU(),
                          nn.Linear(hidden, ncls), nn.LogSoftMax())
    if mesh_b:
        mesh = Engine.build_mesh(**{AXIS_DATA: 4, AXIS_MODEL: 2})
        rules = ShardingRules().add(r"^0/weight$", P(AXIS_MODEL, None))
    else:
        mesh = Engine.build_mesh(devices=jax.devices()[:4],
                                 **{AXIS_DATA: 2, AXIS_MODEL: 2})
        rules = (ShardingRules()
                 .add(r"^0/weight$", P(None, AXIS_MODEL))
                 .add(r"^0/bias$", P(AXIS_MODEL))
                 .add(r"^2/weight$", P(AXIS_MODEL, None)))
    o = optim_mod.DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                                  optim_method=SGD(learning_rate=0.05),
                                  mesh=mesh, sharding_rules=rules,
                                  end_trigger=Trigger.max_iteration(iters))
    if root is not None:
        o.set_checkpoint(root, Trigger.several_iteration(every),
                         async_save=True, keep_last=2, layout=layout)
    return o


def _leaf_nbytes(leaf):
    return int(leaf.nbytes) if hasattr(leaf, "nbytes") \
        else int(np.asarray(leaf).nbytes)


def measure_reshard(layout, iters=8, every=4, restore_rounds=3):
    """One leg of the chunked-vs-monolithic A-B: train under dp(2)xtp(2)
    with trigger-driven async saves in `layout`, then time restoring the
    committed checkpoint onto a DIFFERENT topology (dp(4)xtp(2), changed
    tp rules).  Returns (stall_s_per_save, n_saves, peak_host_bytes,
    tree_bytes, max_chunk_bytes, restore_s)."""
    import tempfile

    from bigdl_tpu.resilience import committed_steps
    from bigdl_tpu.utils.checkpoint import latest_checkpoint, load_checkpoint
    from bigdl_tpu.utils.ckpt_chunked import plan_chunks

    with tempfile.TemporaryDirectory() as tmp:
        o = _reshard_build(layout, tmp, iters, every)
        # keep a handle on the writer: optimize() closes and drops it in
        # its finally block, but peak_host_bytes survives on the object
        writer = o._ensure_ckpt_writer()
        o.optimize()
        stall = o.metrics.get("checkpoint stall")
        n_saves = len(committed_steps(tmp))
        peak = int(writer.peak_host_bytes)
        trees = [t for t in (o.params, o.model_state, o.opt_state)
                 if t is not None]
        leaves = [l for t in trees for l in jax.tree_util.tree_leaves(t)]
        total = sum(_leaf_nbytes(l) for l in leaves)
        # the writer's contract: peak host memory == the largest single
        # chunk (one shard of one leaf), never the gathered tree
        max_chunk = 0
        for leaf in leaves:
            item = np.dtype(getattr(leaf, "dtype", None)
                            or np.asarray(leaf).dtype).itemsize
            for _start, cshape, _fetch in plan_chunks(leaf):
                max_chunk = max(
                    max_chunk, int(np.prod(cshape, dtype=np.int64)) * item)
        ckpt = latest_checkpoint(tmp)
        o_b = _reshard_build(layout, None, 1, 1, mesh_b=True)
        o_b.optimize()  # builds + shards the restore-side templates
        restore = float("inf")
        for _ in range(restore_rounds):
            t0 = time.perf_counter()
            loaded = load_checkpoint(
                ckpt, o_b.params,
                o_b.model_state if o_b.model_state else None,
                o_b.opt_state)
            jax.block_until_ready(
                [l for tree in loaded[:3] if tree is not None
                 for l in jax.tree_util.tree_leaves(tree)])
            restore = min(restore, time.perf_counter() - t0)
    return stall, n_saves, peak, total, max_chunk, restore


def reshard_ab(iters=8, out_path=None):
    """Chunked-vs-monolithic checkpoint A-B (elastic-reshard acceptance):
    same mesh, same saves — the layouts differ in save stall, writer peak
    host bytes, and restore-onto-a-different-mesh wall time.  Asserts the
    chunked writer's bounded-host contract: peak == largest chunk, never
    the gathered tree."""
    out_rows = []
    legs = {}
    for layout in ("monolithic", "chunked"):
        stall, n, peak, total, max_chunk, restore = \
            measure_reshard(layout, iters=max(iters, 8))
        legs[layout] = (peak, total, max_chunk)
        out_rows.append({
            "path": "reshard_ab", "layout": layout, "n_saves": n,
            "ckpt_stall_ms_per_save": round(stall * 1e3, 3),
            "peak_host_bytes": peak, "tree_bytes": total,
            "restore_onto_new_mesh_ms": round(restore * 1e3, 2)})
        print(json.dumps(out_rows[-1]), flush=True)
    c_peak, total, max_chunk = legs["chunked"]
    m_peak = legs["monolithic"][0]
    assert c_peak <= max_chunk, (
        f"chunked writer peak {c_peak} B exceeds its largest chunk "
        f"{max_chunk} B — a full gather leaked into the save path")
    assert c_peak < m_peak, (
        f"chunked peak {c_peak} B not below monolithic {m_peak} B")
    out_rows.append({
        "metric": "reshard_bounded_host_ok", "value": True,
        "max_chunk_bytes": max_chunk,
        "host_bytes_ratio_monolithic_over_chunked":
            round(m_peak / max(c_peak, 1), 1)})
    print(json.dumps(out_rows[-1]))
    if out_path:
        artifact = {
            "bench": "PYTHONPATH=. JAX_PLATFORMS=cpu python "
                     "benchmarks/bench_trainer_overhead.py --ckpt "
                     f"--iters {iters}",
            "date": time.strftime("%Y-%m-%d"),
            "platform": f"cpu backend, {os.cpu_count()}-core host forced "
                        "to 8 virtual devices. Both legs train the same "
                        "tp-sharded MLP under dp(2)xtp(2) with async "
                        "saves every 4 steps; restore is timed onto a "
                        "dp(4)xtp(2) mesh with a DIFFERENT tp rule set "
                        "(reshard-on-load), min over 3 rounds. "
                        "Monolithic restore returns host trees (the v1 "
                        "reader contract); chunked assembles each target "
                        "shard on device from intersecting chunks.",
            "rows": out_rows,
        }
        with open(out_path, "w") as fh:
            json.dump(artifact, fh, indent=2)
        print(f"wrote {out_path}")
    return out_rows


def measure_watchdog(enabled, iters=ITERS):
    """optimize() ms/step with the divergence watchdog off vs on (ISSUE 5
    acceptance: the health fold-in — finite-check on loss + grad global
    norm, the 3-column telemetry ring, the gated update — must cost <1%).
    Both legs run at the SAME async depth (the watchdog caps depth at
    `max_lag`; the A-B must not conflate that cadence change with the
    in-step arithmetic)."""
    o, _, _ = _build(iters)
    depth = min(o._async_depth(), 8)
    o._async_depth = lambda: depth
    if enabled:
        from bigdl_tpu.health import WatchdogConfig

        o.set_watchdog(WatchdogConfig(max_lag=depth))
    o.optimize()  # warm: compiles the step + telemetry-ring write
    o.end_when = Trigger.max_iteration(2 * iters)
    t0 = time.perf_counter()
    o.optimize()
    return (time.perf_counter() - t0) / iters


def watchdog_ab(iters=ITERS, rounds=4):
    """Watchdog off/on A-B; prints one row per leg + the overhead verdict.

    The legs are INTERLEAVED (off, on, off, on, ...) and each leg takes
    its min across rounds: on a shared host the background load drifts by
    more than the effect under test, and back-to-back blocks would charge
    that drift to whichever leg ran second."""
    rows = {False: float("inf"), True: float("inf")}
    for _ in range(rounds):
        for enabled in (False, True):
            rows[enabled] = min(rows[enabled],
                                measure_watchdog(enabled, iters))
    for enabled in (False, True):
        print(json.dumps({
            "path": "watchdog_ab", "watchdog": enabled,
            "ms_per_step": round(rows[enabled] * 1e3, 2)}))
    overhead = rows[True] / rows[False] - 1.0
    print(json.dumps({
        "metric": "watchdog_overhead_ok",
        "value": bool(overhead < 0.01),
        "overhead_pct": round(overhead * 100, 2)}))
    return rows


def measure_readers(autoscale, iters=ITERS):
    """optimize() ms/step with the reader pool on in BOTH legs and only
    the stall-driven autoscaler toggled (ISSUE 9 acceptance: its EMA
    bookkeeping + scale decisions must cost <1% when the device is the
    bottleneck).  Assembly is real work (per-sample numpy stacking of
    32x32x3 images) but the conv step dominates, so the loop is
    device-bound — the regime the autoscaler idles in."""
    from bigdl_tpu.dataset import Sample, SampleToMiniBatch

    RandomGenerator.set_seed(7)
    rs = np.random.RandomState(0)
    samples = [Sample.from_ndarray(rs.randn(HW, HW, CIN).astype(np.float32),
                                   np.int32(i % NCLS))
               for i in range(BATCH * iters)]
    ds = ArrayDataSet(samples).transform(SampleToMiniBatch(BATCH))
    o = optim_mod.DistriOptimizer(
        _model(), ds, nn.ClassNLLCriterion(),
        optim_method=SGD(learning_rate=0.01),
        end_trigger=Trigger.max_iteration(iters))
    o.set_feed(2, reader_procs=2, reader_autoscale=autoscale)
    o.optimize()  # warm: compiles the step, forks the first pool
    o.end_when = Trigger.max_iteration(2 * iters)
    t0 = time.perf_counter()
    o.optimize()
    return (time.perf_counter() - t0) / iters


def readers_ab(iters=ITERS, rounds=3, out_path=None):
    """Reader-autoscaler off/on A-B, interleaved with per-leg min across
    rounds (same discipline as watchdog_ab: shared-host load drifts by
    more than the effect under test)."""
    rows = {False: float("inf"), True: float("inf")}
    for _ in range(rounds):
        for autoscale in (False, True):
            rows[autoscale] = min(rows[autoscale],
                                  measure_readers(autoscale, iters))
    out_rows = []
    for autoscale in (False, True):
        out_rows.append({
            "path": "readers_ab", "reader_procs": 2,
            "autoscale": autoscale,
            "ms_per_step": round(rows[autoscale] * 1e3, 2)})
        print(json.dumps(out_rows[-1]))
    overhead = rows[True] / rows[False] - 1.0
    out_rows.append({
        "metric": "readers_overhead_ok",
        "value": bool(overhead < 0.01),
        "overhead_pct": round(overhead * 100, 2)})
    print(json.dumps(out_rows[-1]))
    if out_path:
        artifact = {
            "bench": "PYTHONPATH=. JAX_PLATFORMS=cpu python "
                     f"benchmarks/bench_trainer_overhead.py --readers "
                     f"--iters {iters}",
            "date": time.strftime("%Y-%m-%d"),
            "platform": f"cpu backend, {os.cpu_count()}-core host. Both "
                        "legs run the procs=2 reader pool; only the "
                        "stall-driven autoscaler differs, so the A-B "
                        "isolates its EMA/note_feed bookkeeping from the "
                        "pool's own IPC. Interleaved legs, per-leg min "
                        f"over {rounds} rounds. The step is a conv net, "
                        "device-bound, so the autoscaler sees low stall "
                        "and holds (or shrinks) — the production idle "
                        "regime the <1% bound is about.",
            "rows": out_rows,
        }
        with open(out_path, "w") as fh:
            json.dump(artifact, fh, indent=2)
            fh.write("\n")
    return rows


def measure_obs(tracing, iters=ITERS):
    """optimize() ms/step with the obs plane at its default (metrics +
    compile monitor on) vs full span tracing on.  Returns (ms/step,
    events recorded) — the on leg must actually have traced the loop."""
    from bigdl_tpu import obs

    o, _, _ = _build(iters)
    obs.set_observability(tracing=tracing)
    try:
        o.optimize()  # warm: compiles the step + telemetry-ring write
        o.end_when = Trigger.max_iteration(2 * iters)
        t0 = time.perf_counter()
        o.optimize()
        per = (time.perf_counter() - t0) / iters
        tr = obs.tracer()
        return per, (len(tr.events()) if tr is not None else 0)
    finally:
        obs.set_observability(tracing=False)


def obs_ab(iters=ITERS, rounds=8):
    """Tracing off/on A-B (obs ISSUE acceptance): the span tracer on the
    trainer's phase seams (feed_next, step_dispatch, drain instants) must
    cost <1% of a step.  Same interleave-and-min discipline as
    watchdog_ab: background load drifts by more than the effect under
    test, so back-to-back blocks would charge that drift to whichever
    leg ran second."""
    rows = {False: float("inf"), True: float("inf")}
    events = 0
    for _ in range(rounds):
        for tracing in (False, True):
            per, n = measure_obs(tracing, iters)
            rows[tracing] = min(rows[tracing], per)
            if tracing:
                events = max(events, n)
    assert events >= iters, f"tracing-on leg recorded only {events} events"
    for tracing in (False, True):
        print(json.dumps({
            "path": "obs_ab", "tracing": tracing,
            "ms_per_step": round(rows[tracing] * 1e3, 2),
            **({"trace_events": events} if tracing else {})}))
    overhead = rows[True] / rows[False] - 1.0
    print(json.dumps({
        "metric": "obs_tracing_overhead_ok",
        "value": bool(overhead < 0.01),
        "overhead_pct": round(overhead * 100, 2)}))
    return rows


def flight_trainer_rows(iters, rounds, flight_dir):
    """Trainer leg of the flight A-B: the obs_ab traced leg with the
    flight recorder ADDITIONALLY armed (tracing + ring notes + log-tail
    handler; no trigger fires in the window, so the measured cost is the
    passive black box).  Unlike measure_obs, both legs share ONE built
    optimizer and alternate per SHORT timed window — the plane is
    re-read at each optimize() (the hot loop hoists `obs.tracer()` once
    per call), so toggling between calls is exact.  The verdict is the
    MEDIAN of per-pair on/off ratios: adjacent windows (~1.5 s apart)
    see the same background load, so each ratio cancels the minute-scale
    drift this shared host shows (±12% between runs — per-leg mins over
    long windows provably did not converge under it)."""
    from bigdl_tpu import obs

    o, _, _ = _build(iters)
    obs.set_observability(tracing=False, flight=False)
    o.optimize()  # warm: compiles the step + telemetry-ring write
    total = iters
    mins = {False: float("inf"), True: float("inf")}
    ratios = []
    events = 0
    try:
        for _ in range(rounds):
            pair = {}
            for on in (False, True):
                if on:
                    obs.set_observability(tracing=True, flight=True,
                                          flight_dir=flight_dir)
                    assert obs.flight_recorder() is not None
                else:
                    obs.set_observability(tracing=False, flight=False)
                total += iters
                o.end_when = Trigger.max_iteration(total)
                t0 = time.perf_counter()
                o.optimize()
                pair[on] = (time.perf_counter() - t0) / iters
                mins[on] = min(mins[on], pair[on])
                if on:
                    events = max(events, len(obs.tracer().events()))
            ratios.append(pair[True] / pair[False])
    finally:
        obs.set_observability(tracing=False, flight=False)
    assert events >= iters, f"armed leg recorded only {events} events"
    out_rows = []
    for on in (False, True):
        out_rows.append({
            "path": "flight_trainer_ab", "tracing": on, "flight_armed": on,
            "ms_per_step_min": round(mins[on] * 1e3, 2),
            **({"trace_events": events} if on else {})})
        print(json.dumps(out_rows[-1]), flush=True)
    overhead = statistics.median(ratios) - 1.0
    out_rows.append({
        "metric": "flight_trainer_overhead_ok",
        "value": bool(overhead < 0.01),
        "overhead_pct": round(overhead * 100, 2),
        "pairs": len(ratios)})
    print(json.dumps(out_rows[-1]))
    return out_rows


def fleet_flight_ab(n_requests=64, trials=11):
    """Routed-burst A-B with the flight recorder off vs armed — tracing
    OFF in both legs, the recommended incident posture (metrics +
    compile monitor + flight ON, tracing OFF; docs/observability.md).
    This isolates exactly what "always-on" costs the serving path: the
    log-tail handler plus the trigger check, nothing per request.  The
    armed leg must cost <1% wall on the same burst, and must still
    produce a complete on-demand bundle afterwards (proof the recorder
    was live, not a disarmed no-op)."""
    import tempfile

    import bigdl_tpu.compilecache as cc
    from bigdl_tpu import obs
    from bigdl_tpu.fleet import FleetRouter, TenantConfig

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import bench_fleet

    cc.set_cache_dir(tempfile.mkdtemp(prefix="flight_fleet_cc_"))
    flight_dir = tempfile.mkdtemp(prefix="flight_fleet_")
    model, params, state = bench_fleet.build_model(True)
    rs = np.random.RandomState(1)
    requests = [rs.rand(bench_fleet.BUCKETS[-1], 128).astype(np.float32)
                for _ in range(n_requests)]
    router = FleetRouter(
        lambda name: bench_fleet.make_runtime(model, params, state),
        n_replicas=2,
        tenants=[TenantConfig("bench", tier="batch", capacity=1024)])
    walls = {False: float("inf"), True: float("inf")}
    ratios = []
    try:
        for armed in (False, True):  # untimed: page in both postures
            obs.set_observability(flight=armed, flight_dir=flight_dir)
            bench_fleet.burst(requests, lambda x: router.submit("bench", x))
        for _ in range(trials):
            pair = {}
            for armed in (False, True):
                obs.set_observability(flight=armed, flight_dir=flight_dir)
                pair[armed] = bench_fleet.burst(
                    requests, lambda x: router.submit("bench", x))
                walls[armed] = min(walls[armed], pair[armed])
            ratios.append(pair[True] / pair[False])
        # still armed after the last leg: the recorder must be real
        bundle = obs.dump_flight("bench.capture")
        assert bundle is not None, "armed leg had no live flight recorder"
        with open(os.path.join(bundle, "trace.json")) as fh:
            json.load(fh)
    finally:
        obs.set_observability(flight=False)
        router.close()
        cc.reset()
    out_rows = []
    for armed in (False, True):
        out_rows.append({
            "path": "fleet_flight_ab", "flight_armed": armed,
            "requests": n_requests, "replicas": 2, "trials": trials,
            "burst_wall_ms_min": round(walls[armed] * 1e3, 2)})
        print(json.dumps(out_rows[-1]), flush=True)
    # median of per-trial pairwise ratios — adjacent bursts see the same
    # host load, so drift cancels (the bench_fleet router-overhead
    # discipline, needed even more at a 1% bar than at its 2%)
    overhead = statistics.median(ratios) - 1.0
    out_rows.append({
        "metric": "flight_fleet_overhead_ok",
        "value": bool(overhead < 0.01),
        "overhead_pct": round(overhead * 100, 2),
        "bundle_on_demand": True})
    print(json.dumps(out_rows[-1]))
    return out_rows


def flight_ab(iters=ITERS, rounds=24, out_path=None):
    """The flight-recorder A-B pair (obs ISSUE acceptance re-proven with
    the black box armed): trainer leg (tracing + armed recorder vs off)
    and fleet leg (armed recorder alone vs off on a routed burst), both
    interleaved with per-pair ratio medians.  Writes
    results/flight_quick.json."""
    import tempfile

    out_rows = flight_trainer_rows(iters, rounds,
                                   tempfile.mkdtemp(prefix="flight_bench_"))
    out_rows.extend(fleet_flight_ab())
    if out_path:
        artifact = {
            "bench": "PYTHONPATH=. JAX_PLATFORMS=cpu python "
                     "benchmarks/bench_trainer_overhead.py --obs --flight "
                     f"--iters {iters}",
            "date": time.strftime("%Y-%m-%d"),
            "platform": f"cpu backend, {os.cpu_count()}-core shared host "
                        "whose background load drifts by more than the "
                        "effect under test, so both legs take the MEDIAN "
                        "of per-pair on/off ratios over adjacent windows "
                        "(drift cancels in each ratio) rather than "
                        "per-leg aggregates. Trainer leg: ONE built "
                        f"optimizer, {rounds} alternating {iters}-iter "
                        "windows of off vs tracing+armed-recorder — no "
                        "trigger fires in the window, so the on leg pays "
                        "tracing plus the passive black box (log-tail "
                        "handler). Fleet leg: the same 64-request routed "
                        "burst through a 2-replica FleetRouter with the "
                        "flight recorder off vs armed, tracing off in "
                        "BOTH legs (the recommended incident posture); "
                        "the armed leg then dumps a bundle on demand to "
                        "prove the recorder was live. The <1% bars are "
                        "the ISSUE acceptance criterion.",
            "rows": out_rows,
        }
        with open(out_path, "w") as fh:
            json.dump(artifact, fh, indent=2)
            fh.write("\n")
        print(f"# wrote {out_path}")
    return out_rows


def lockdep_trainer_rows(iters, rounds):
    """Trainer leg of the lockdep A-B: one optimizer built with pristine
    locks, one built under `instrument_locks()` (its locks wrapped at
    creation), alternating short timed windows; the on-windows also keep
    the factory/sleep/queue patches installed so the measured cost is
    the full sanitizer posture.  Median of per-pair ratios (same drift
    discipline as flight_trainer_rows)."""
    import threading

    from bigdl_tpu.analysis import lockdep

    pristine_lock = threading.Lock
    assert not lockdep.instrumented()
    o_off, _, _ = _build(iters)
    assert lockdep.instrument_locks()
    o_on, _, _ = _build(iters)
    assert lockdep.uninstrument_locks()
    # the off switch is structurally free: with lockdep uninstalled the
    # original C lock factory is back and the off leg executes the exact
    # byte-identical path a no-lockdep process runs
    assert threading.Lock is pristine_lock
    for o in (o_off, o_on):
        o.optimize()  # warm: compiles the step
    totals = {False: iters, True: iters}
    mins = {False: float("inf"), True: float("inf")}
    ratios = []
    try:
        for _ in range(rounds):
            pair = {}
            for on, o in ((False, o_off), (True, o_on)):
                if on:
                    lockdep.instrument_locks()
                try:
                    totals[on] += iters
                    o.end_when = Trigger.max_iteration(totals[on])
                    t0 = time.perf_counter()
                    o.optimize()
                    pair[on] = (time.perf_counter() - t0) / iters
                finally:
                    if on:
                        lockdep.uninstrument_locks()
                mins[on] = min(mins[on], pair[on])
            ratios.append(pair[True] / pair[False])
    finally:
        lockdep.uninstrument_locks()
    out_rows = []
    for on in (False, True):
        out_rows.append({
            "path": "lockdep_trainer_ab", "lockdep": on,
            "ms_per_step_min": round(mins[on] * 1e3, 2)})
        print(json.dumps(out_rows[-1]), flush=True)
    overhead = statistics.median(ratios) - 1.0
    out_rows.append({
        "metric": "lockdep_trainer_overhead_ok",
        "value": bool(overhead < 0.05),
        "overhead_pct": round(overhead * 100, 2),
        "pairs": len(ratios)})
    print(json.dumps(out_rows[-1]))
    out_rows.append({
        "metric": "lockdep_off_overhead_ok", "value": True,
        "off_overhead_pct": 0.0,
        "proof": "uninstrumented legs run the pristine threading.Lock "
                 "factory (asserted by identity) — the off switch "
                 "executes byte-identical code to a no-lockdep process"})
    print(json.dumps(out_rows[-1]))
    return out_rows


def lockdep_fleet_ab(n_requests=64, trials=11):
    """Routed-burst A-B with the lock-order sanitizer off vs on: one
    router built pristine, one built instrumented (every router /
    replica / batcher / per-request future lock wrapped), on-windows
    keep the patches installed so new per-request locks pay the
    creation-site walk too.  The on leg must (a) cost <2% wall on the
    same burst, (b) record a non-empty acquired-before graph with ZERO
    violations — proof the sanitizer was live, not a disarmed no-op."""
    import tempfile

    import bigdl_tpu.compilecache as cc
    from bigdl_tpu.analysis import lockdep
    from bigdl_tpu.fleet import FleetRouter, TenantConfig

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import bench_fleet

    cc.set_cache_dir(tempfile.mkdtemp(prefix="lockdep_fleet_cc_"))
    model, params, state = bench_fleet.build_model(True)
    rs = np.random.RandomState(1)
    requests = [rs.rand(bench_fleet.BUCKETS[-1], 128).astype(np.float32)
                for _ in range(n_requests)]

    def mk_router():
        return FleetRouter(
            lambda name: bench_fleet.make_runtime(model, params, state),
            n_replicas=2,
            tenants=[TenantConfig("bench", tier="batch", capacity=1024)])

    lockdep.reset()
    router_off = mk_router()
    assert lockdep.instrument_locks()
    router_on = mk_router()
    assert lockdep.uninstrument_locks()
    walls = {False: float("inf"), True: float("inf")}
    ratios = []
    try:
        for r in (router_off, router_on):  # untimed: page both postures
            bench_fleet.burst(requests, lambda x: r.submit("bench", x))
        for _ in range(trials):
            pair = {}
            for on, r in ((False, router_off), (True, router_on)):
                if on:
                    lockdep.instrument_locks()
                try:
                    pair[on] = bench_fleet.burst(
                        requests, lambda x: r.submit("bench", x))
                finally:
                    if on:
                        lockdep.uninstrument_locks()
                walls[on] = min(walls[on], pair[on])
            ratios.append(pair[True] / pair[False])
        snap = lockdep.snapshot()
        assert snap["counters"]["violations"] == 0, snap["violations"]
        assert snap["counters"]["edges"] > 0, \
            "on leg recorded no edges — sanitizer was not live"
    finally:
        lockdep.uninstrument_locks()
        lockdep.reset()
        router_off.close()
        router_on.close()
        cc.reset()
    out_rows = []
    for on in (False, True):
        out_rows.append({
            "path": "lockdep_fleet_ab", "lockdep": on,
            "requests": n_requests, "replicas": 2, "trials": trials,
            "burst_wall_ms_min": round(walls[on] * 1e3, 2),
            **({"graph_edges": snap["counters"]["edges"],
                "violations": 0} if on else {})})
        print(json.dumps(out_rows[-1]), flush=True)
    # the ON leg's cost is RECORDED, not gated tight: every instrumented
    # acquire takes the process-global lockdep state lock, so a routed
    # burst pays single-digit % — acceptable for a CI/test posture (the
    # hard 0% requirement is on the OFF leg, proven by factory identity).
    # The loose bound only catches pathological regressions.
    overhead = statistics.median(ratios) - 1.0
    out_rows.append({
        "metric": "lockdep_fleet_overhead_ok",
        "value": bool(overhead < 0.15),
        "overhead_pct": round(overhead * 100, 2)})
    print(json.dumps(out_rows[-1]))
    return out_rows


def lockdep_ab(iters=ITERS, rounds=8, out_path=None):
    """The lockdep A-B pair (docs/analysis.md "Lock discipline"): trainer
    leg + routed fleet-burst leg, both off vs on with per-pair ratio
    medians.  Writes results/lockdep_quick.json."""
    out_rows = lockdep_trainer_rows(iters, rounds)
    out_rows.extend(lockdep_fleet_ab())
    if out_path:
        artifact = {
            "bench": "PYTHONPATH=. JAX_PLATFORMS=cpu python "
                     "benchmarks/bench_trainer_overhead.py --lockdep "
                     f"--iters {iters}",
            "date": time.strftime("%Y-%m-%d"),
            "platform": f"cpu backend, {os.cpu_count()}-core shared host; "
                        "both legs take the MEDIAN of per-pair off/on "
                        "ratios over adjacent windows (drift cancels in "
                        "each ratio). Trainer leg: two optimizers — one "
                        "built pristine, one with its locks wrapped by "
                        f"instrument_locks() — alternating {iters}-iter "
                        "windows; on-windows keep the factory/sleep/queue "
                        "patches installed. Fleet leg: the same "
                        "64-request burst through a pristine vs an "
                        "instrumented 2-replica FleetRouter; the on leg "
                        "must leave a non-empty acquired-before graph "
                        "with zero violations. The off switch is free by "
                        "construction (pristine factory identity "
                        "asserted), which is the hard acceptance bar — "
                        "lockdep is a TEST/CI posture, not a prod one.",
            "rows": out_rows,
        }
        with open(out_path, "w") as fh:
            json.dump(artifact, fh, indent=2)
            fh.write("\n")
        print(f"# wrote {out_path}")
    return out_rows


def lint_hotpath_ab(iters=ITERS):
    """A-B of the tpu_lint host-sync fixes (bigdl_tpu.analysis): each
    "before" leg re-injects the exact pattern the linter flagged, the
    "after" leg runs the shipped code path.

      * predict loop: pre-fix per-batch `np.asarray(y)` (one full device
        sync per batch) vs device slices + ONE `jax.device_get` epilogue;
      * trainer host-lr path: pre-fix per-step `float(self._current_lr())`
        device pull vs the Plateau host-side mirror (`host_value`), where
        the device scalar is put once per lr CHANGE.
    """
    from bigdl_tpu.optim.predictor import Predictor
    from bigdl_tpu.optim.schedules import Plateau

    DIM = 64
    rs = np.random.RandomState(0)
    model = nn.Sequential(nn.Linear(DIM, 128), nn.ReLU(),
                          nn.Linear(128, NCLS), nn.LogSoftMax())
    params, state, _ = model.build(jax.random.PRNGKey(0), (BATCH, DIM))
    pred = Predictor(model, params, state, batch_size=BATCH,
                     prefetch_depth=0)  # inline staging: fair vs `before`
    data = rs.randn(iters * BATCH, DIM).astype(np.float32)

    def predict_before():
        # the pre-fix Predictor.predict body: host sync EVERY batch
        outs = []
        for off in range(0, data.shape[0], BATCH):
            xd = pred._put(data[off:off + BATCH])
            y = pred._fwd(pred.params, pred.state, xd)
            outs.append(np.asarray(y))
        return np.concatenate(outs, axis=0)

    def predict_after():
        return pred.predict(data)

    predict_before(), predict_after()  # warm the compile
    t0 = time.perf_counter()
    a = predict_before()
    t_before = (time.perf_counter() - t0) / iters
    t0 = time.perf_counter()
    b = predict_after()
    t_after = (time.perf_counter() - t0) / iters
    np.testing.assert_allclose(a, b, rtol=1e-6)
    print(json.dumps({"path": "lint_predict_per_batch_sync", "fixed": False,
                      "ms_per_batch": round(t_before * 1e3, 3)}))
    print(json.dumps({"path": "lint_predict_device_accumulate", "fixed": True,
                      "ms_per_batch": round(t_after * 1e3, 3)}))

    import bigdl_tpu.optim.optimizer as om

    def lr_run(emulate_prefix):
        RandomGenerator.set_seed(7)
        rs2 = np.random.RandomState(0)
        x = rs2.randn(BATCH, HW, HW, CIN).astype(np.float32)
        y = (np.arange(BATCH) % NCLS).astype(np.int32)
        ds = _RepeatDataSet(MiniBatch(jnp.asarray(x), jnp.asarray(y)), iters)
        o = optim_mod.DistriOptimizer(
            _model(), ds, nn.ClassNLLCriterion(),
            optim_method=SGD(learning_rate=0.01, schedule=Plateau()),
            end_trigger=Trigger.max_iteration(iters))
        saved = om.Optimizer._current_lr_host
        if emulate_prefix:
            om.Optimizer._current_lr_host = \
                lambda self: float(self._current_lr())
        try:
            o.optimize()  # warm: compiles the step + telemetry-ring write
            o.end_when = Trigger.max_iteration(2 * iters)
            t0 = time.perf_counter()
            o.optimize()
            return (time.perf_counter() - t0) / iters
        finally:
            om.Optimizer._current_lr_host = saved

    for fixed in (False, True):
        per = min(lr_run(emulate_prefix=not fixed) for _ in range(2))
        print(json.dumps({"path": "lint_hostlr_device_pull" if not fixed
                          else "lint_hostlr_host_mirror", "fixed": fixed,
                          "ms_per_step": round(per * 1e3, 2)}))


def restart_child(iters):
    """Hidden leg of `--restart`: ONE fresh process, build + first step,
    then report what the start-up cost was made of.  The parent sets
    `BIGDL_TPU_COMPILE_CACHE` in this process's environment (a fresh dir
    for the cold leg, the shared prewarmed dir for the warm leg)."""
    from bigdl_tpu import obs

    o, _, _ = _build(iters)
    o.end_when = Trigger.max_iteration(1)
    t0 = time.perf_counter()
    o.optimize()  # model init + step executable + first dispatch
    first_step_s = time.perf_counter() - t0
    mon = obs.compile_monitor()
    reg = obs.registry()
    row = {
        "restart_to_first_step_s": round(first_step_s, 3),
        # every backend-compile second paid before the first step landed
        # — the quantity a warm executable cache exists to eliminate
        "pre_first_step_compile_s": round(mon.compile_secs(""), 3),
        "train_compile_s": round(mon.compile_secs("train/"), 3),
        "cache_hits": int(reg.get("compile/cache_hits")),
        "cache_misses": int(reg.get("compile/cache_misses")),
        "persistent_cache_hits": int(reg.get(
            "compile/persistent_cache_hits")),
        "cache_load_ms": round(float(reg.get("compile/cache_load_ms")), 2),
        "steady_recompiles": int(reg.get("compile/steady_recompiles")),
    }
    print("RESTART_CHILD " + json.dumps(row), flush=True)


def _run_restart_child(cache_dir, iters):
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["BIGDL_TPU_COMPILE_CACHE"] = cache_dir
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--restart-child",
         "--iters", str(iters)],
        env=env, capture_output=True, text=True, timeout=900)
    for line in proc.stdout.splitlines():
        if line.startswith("RESTART_CHILD "):
            return json.loads(line[len("RESTART_CHILD "):])
    raise RuntimeError(f"restart child produced no row (rc={proc.returncode})"
                       f":\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")


def restart_ab(iters=4, rounds=2, out_path=None):
    """Cold/warm executable-cache restart A-B (ISSUE 7 acceptance).

    Each leg is a REAL fresh process (subprocess): cold gets a brand-new
    cache dir every round, warm reuses one dir prewarmed by an unmeasured
    child before the rounds start.  Legs interleave (cold, warm, cold,
    warm) and each takes its min across rounds — same discipline as
    watchdog_ab: background load drifts by more than the effect under
    test.  The verdict requires the warm leg to pay <=50% of the cold
    leg's pre-first-step compile time, with cache hits > 0 and zero
    steady-recompile alarms.
    """
    import os
    import tempfile

    rows = []
    warm_dir = tempfile.mkdtemp(prefix="aotcache_warm_")
    prewarm = _run_restart_child(warm_dir, iters)  # unmeasured cache fill
    print(json.dumps({"path": "restart_prewarm", **prewarm}))
    legs = {"cold": [], "warm": []}
    for rnd in range(rounds):
        for leg in ("cold", "warm"):
            d = tempfile.mkdtemp(prefix="aotcache_cold_") \
                if leg == "cold" else warm_dir
            row = {"path": "restart_ab", "leg": leg, "round": rnd,
                   **_run_restart_child(d, iters)}
            legs[leg].append(row)
            rows.append(row)
            print(json.dumps(row), flush=True)
    cold = min(r["pre_first_step_compile_s"] for r in legs["cold"])
    warm = min(r["pre_first_step_compile_s"] for r in legs["warm"])
    warm_hits = max(r["cache_hits"] for r in legs["warm"])
    warm_alarms = max(r["steady_recompiles"] for r in legs["warm"])
    verdict = {
        "metric": "aotcache_restart_ok",
        "value": bool(warm <= 0.5 * cold and warm_hits > 0
                      and warm_alarms == 0),
        "cold_pre_first_step_compile_s": cold,
        "warm_pre_first_step_compile_s": warm,
        "compile_reduction_pct": round((1.0 - warm / max(cold, 1e-9)) * 100,
                                       1),
        "warm_cache_hits": warm_hits,
        "warm_steady_recompiles": warm_alarms,
    }
    rows.append(verdict)
    print(json.dumps(verdict))
    rows.extend(swap_warm_ab())
    if out_path:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(rows, f, indent=1)
        print(f"# wrote {out_path}")
    assert verdict["value"], verdict
    return rows


def swap_warm_ab():
    """Hot-swap-to-first-request A-B, in process: a params-only swap with
    the warmed-executable reuse shipped in this PR vs the pre-fix
    behaviour (every bucket re-runs a warmup forward), on the same
    runtime.  Complements bench_serving.py's `swap` phase with a direct
    before/after of the registry fix."""
    from bigdl_tpu import obs
    from bigdl_tpu.serving import ServingConfig, ServingRuntime

    model = nn.Sequential(nn.Linear(64, 256), nn.ReLU(),
                          nn.Linear(256, NCLS), nn.LogSoftMax())
    params, state, _ = model.build(jax.random.PRNGKey(0), (8, 64))
    rs = np.random.RandomState(3)
    example = rs.rand(1, 64).astype(np.float32)
    x = rs.rand(1, 64).astype(np.float32)
    rows = []
    with ServingRuntime(model, params, state, example_input=example,
                        config=ServingConfig(buckets=(1, 8, 32),
                                             max_wait_ms=1.0)) as rt:
        rt.predict(x)
        for fixed in (False, True):
            best = float("inf")
            for _ in range(5):
                if not fixed:
                    # pre-fix behaviour: no live-executable table, every
                    # registration re-runs one forward per bucket
                    rt._warmed.clear()
                    rt._warmed_psig = None
                t0 = time.perf_counter()
                rt.swap("v-%s-%d" % (fixed, time.perf_counter_ns()),
                        params, state)
                rt.predict(x)
                best = min(best, time.perf_counter() - t0)
            rows.append({
                "path": "swap_warm_ab", "warm_reuse": fixed,
                "swap_to_first_request_ms": round(best * 1e3, 3)})
            print(json.dumps(rows[-1]), flush=True)
    reused = int(obs.registry().get("serving/warmup_reused"))
    rows.append({"metric": "swap_warm_reuse_ok",
                 "value": bool(reused >= 3),
                 "warmup_reused": reused})
    print(json.dumps(rows[-1]))
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--feed-only", action="store_true",
                    help="run just the DeviceFeed A-B (quick capture mode)")
    ap.add_argument("--ckpt", action="store_true",
                    help="run the sync/async checkpoint A-B plus the "
                         "chunked-vs-monolithic reshard A-B (writes "
                         "results/reshard_quick.json)")
    ap.add_argument("--lint-hotpath", action="store_true",
                    help="A-B the tpu_lint host-sync fixes (quick capture)")
    ap.add_argument("--watchdog", action="store_true",
                    help="run just the divergence-watchdog off/on A-B")
    ap.add_argument("--readers", action="store_true",
                    help="run just the reader-autoscaler off/on A-B "
                         "(procs=2 pool in both legs)")
    ap.add_argument("--obs", action="store_true",
                    help="run just the obs span-tracing off/on A-B")
    ap.add_argument("--flight", action="store_true",
                    help="with --obs: arm the flight recorder on the "
                         "traced leg and add the routed-fleet black-box "
                         "A-B (writes results/flight_quick.json)")
    ap.add_argument("--lockdep", action="store_true",
                    help="run the lock-order-sanitizer off/on A-B "
                         "(trainer + routed fleet burst; writes "
                         "results/lockdep_quick.json)")
    ap.add_argument("--restart", action="store_true",
                    help="cold/warm executable-cache restart A-B "
                         "(subprocess legs; writes --out)")
    ap.add_argument("--restart-child", action="store_true",
                    help=argparse.SUPPRESS)  # one leg of --restart
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--out", default=None,
                    help="json capture path for --restart (default: "
                         "benchmarks/results/aotcache_quick.json)")
    ap.add_argument("--iters", type=int, default=ITERS)
    args = ap.parse_args(argv)
    if args.restart_child:
        restart_child(max(2, min(args.iters, 8)))
        return
    if args.restart:
        out = args.out or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "results",
            "aotcache_quick.json")
        restart_ab(iters=max(2, min(args.iters, 8)), rounds=args.rounds,
                   out_path=out)
        return
    if args.feed_only:
        feed_ab(args.iters)
        return
    if args.ckpt:
        ckpt_ab(args.iters)
        out = args.out or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "results",
            "reshard_quick.json")
        reshard_ab(iters=max(2, min(args.iters, 12)), out_path=out)
        return
    if args.lint_hotpath:
        lint_hotpath_ab(args.iters)
        return
    if args.lockdep:
        out = args.out or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "results",
            "lockdep_quick.json")
        lockdep_ab(args.iters, rounds=max(args.rounds, 8), out_path=out)
        return
    if args.watchdog:
        watchdog_ab(args.iters)
        return
    if args.readers:
        readers_ab(args.iters, rounds=max(args.rounds, 3),
                   out_path=args.out)
        return
    if args.obs:
        if args.flight:
            out = args.out or os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "results",
                "flight_quick.json")
            flight_ab(args.iters, out_path=out)
        else:
            obs_ab(args.iters)
        return
    lat, rere = measure_readback_latency()
    print(json.dumps({"metric": "env_readback_latency_ms",
                      "fresh_result": round(lat * 1e3, 2),
                      "materialized_rere": round(rere * 1e3, 3)}))
    raw = min(measure_raw() for _ in range(3))
    print(json.dumps({"path": "raw_step_one_sync",
                      "ms_per_step": round(raw * 1e3, 2)}))

    nodrain = min(measure_loop(no_drain=True) for _ in range(3))
    host_cost = nodrain - raw
    print(json.dumps({"path": "optimize_no_drain",
                      "ms_per_step": round(nodrain * 1e3, 2),
                      "host_python_ms_per_step": round(host_cost * 1e3, 3)}))

    o, _, _ = _build()
    depth = o._async_depth()
    flush = max(1, depth // 2)
    for inj in (0.0, 1.0, 10.0, 100.0):
        per = measure_loop(inj)
        model = nodrain + (lat + inj / 1e3) / flush
        print(json.dumps({"path": "optimize_loop",
                          "injected_readback_ms": inj,
                          "ms_per_step": round(per * 1e3, 2),
                          "amortization_model_ms": round(model * 1e3, 2)}))
        if inj == 0.0:
            base = per

    # the defensible claims, asserted:
    # 1. the driver's own host cost is small in absolute terms (measured
    #    ~3.5 ms/step here: ~0.35 ms pjit dispatch + ~0.24 ms batch
    #    asarray + ~0.47 ms fold_in dispatch + loop body — <5% of a real
    #    100 ms TPU step);
    assert host_cost < 6e-3, f"host python {host_cost*1e3:.2f} ms/step"
    # 2. the standard-depth loop sits within the amortization model of
    #    the measured environment readback latency (no unexplained gap)
    bound = nodrain + 2.0 * lat / flush + 2e-3
    assert base <= bound, (base, bound)
    print(json.dumps({"metric": "loop_overhead_explained", "value": True,
                      "host_python_ms": round(host_cost * 1e3, 3),
                      "readback_amortized_ms": round(lat / flush * 1e3, 2)}))

    feed_ab(args.iters)


if __name__ == "__main__":
    main()
