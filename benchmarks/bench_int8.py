"""Int8 inference benchmark: where quantization PAYS on TPU.

Reference premise: int8 exists to be fast (nn/quantized/Quantizer.scala:
27-32, BigQuant MixPrecisionGEMM).  Round-1 finding: dynamic int8 was ~8%
SLOWER than fp32 on ResNet-50 (per-layer activation abs-max reduces on an
HBM-bound model).  This harness measures all modes on the two headline
workloads:

  * ResNet-50 batch-256 inference: bf16 vs int8 dynamic vs int8 static
    (calibrated scales — no runtime reduce) vs weight-only.
  * TransformerLM single-token decode step (batch 8): bf16 vs weight-only
    int8 — bandwidth-bound, weights dominate HBM traffic, int8 halves it.

Run on the TPU:
  PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/bench_int8.py

Prints one json line per (workload, mode) with ms/step and speedup vs the
bf16 baseline of that workload.
"""

import json
import time

import numpy as np


def _sync(v):
    # through the remote-TPU tunnel block_until_ready returns early; a
    # host readback on a value depending on the computation is the sync
    import jax.numpy as jnp

    return float(jnp.sum(v.astype(jnp.float32)))


def _time_fn(fn, *args, warmup=3, iters=20):
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def bench_resnet():
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet50

    batch, image, classes = 256, 224, 1000
    model = resnet50(classes)
    shape = (batch, image, image, 3)
    params, state, _ = model.build(jax.random.PRNGKey(0), shape)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(*shape), jnp.bfloat16)

    results = {}

    p16 = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16), params)
    fwd16 = jax.jit(lambda p, s, x: model.apply(p, s, x, training=False)[0])
    results["bf16"] = _time_fn(fwd16, p16, state, x)

    # conv+BN folded serving graph (utils/fusion.py): deletes the BN
    # elementwise passes the compiler must otherwise keep live
    from bigdl_tpu.utils.fusion import fold_batchnorm

    fmodel, fparams, fstate = fold_batchnorm(model, params, state)
    fp16 = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16), fparams)
    ffwd = jax.jit(lambda p, s, x, m=fmodel: m.apply(p, s, x,
                                                     training=False)[0])
    results["bf16_bnfold"] = _time_fn(ffwd, fp16, fstate, x)

    for mode in ("dynamic", "static", "weight_only"):
        qm, qp = nn.quantize(model, params, mode=mode)
        if mode == "static":
            t0 = time.perf_counter()
            qp = nn.calibrate(qm, qp, state,
                              [jnp.asarray(rs.rand(8, image, image, 3),
                                           jnp.float32)])
            print(f"# calibration took {time.perf_counter() - t0:.1f}s",
                  flush=True)
        qfwd = jax.jit(lambda p, s, x, qm=qm: qm.apply(p, s, x,
                                                       training=False)[0])
        results[mode] = _time_fn(qfwd, qp, state, x)

    # the composed serving stack: fold conv+BN FIRST, then quantize —
    # quantizing the unfolded model leaves f32 BN normalize passes
    # between every dequant and the next quant (tested compose:
    # tests/test_quantized.py round-3; this is the deployment path)
    for mode in ("static", "weight_only"):
        qm, qp = nn.quantize(fmodel, fparams, mode=mode)
        if mode == "static":
            qp = nn.calibrate(qm, qp, fstate,
                              [jnp.asarray(rs.rand(8, image, image, 3),
                                           jnp.float32)])
        qfwd = jax.jit(lambda p, s, x, qm=qm: qm.apply(p, s, x,
                                                       training=False)[0])
        results[f"{mode}_bnfold"] = _time_fn(qfwd, qp, fstate, x)

    # auto mode: quantize() measures float+all modes itself and keeps the
    # winner — the row must match the best of the measured modes (VERDICT
    # r3 item 6: no mode may ship a silent slowdown vs bf16)
    # bench_iters=30: the r5 capture showed the default 10-iter microbench
    # has enough tunnel noise (~±15%) to mispick bf16 over a static mode
    # that the 20-iter table measured 1.245x faster
    am, ap = nn.quantize(
        model, params, mode="auto",
        sample_input=np.asarray(rs.rand(*shape), np.float32), state=state,
        calib_batches=[jnp.asarray(rs.rand(8, image, image, 3),
                                   jnp.float32)], bench_iters=30)
    afwd = jax.jit(lambda p, s, x, am=am: am.apply(p, s, x,
                                                   training=False)[0])
    results["auto"] = _time_fn(afwd, ap, state, x)
    print(json.dumps({"auto_picked": am._quant_auto_report["picked"],
                      "auto_table_ms": {
                          k: round(v, 2) for k, v in
                          am._quant_auto_report["ms_per_batch"].items()}}),
          flush=True)

    # repeat the baseline last: the spread between the two bf16 runs is
    # the run-to-run noise floor of the tunnel, printed for honesty
    results["bf16_rep"] = _time_fn(fwd16, p16, state, x)

    for mode, ms in results.items():
        print(json.dumps({
            "workload": "resnet50_b256_infer", "mode": mode,
            "ms_per_step": round(ms, 2),
            "speedup_vs_bf16": round(results["bf16"] / ms, 3)}), flush=True)
    return results


def bench_decode():
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.nn.quantized import WeightOnlyInt8

    vocab, hidden, layers, heads, batch = 32000, 1024, 12, 16, 8
    model = TransformerLM(vocab_size=vocab, hidden_size=hidden,
                          n_layer=layers, n_head=heads, use_flash=False,
                          scan_layers=True)
    params, state, _ = model.build(jax.random.PRNGKey(0), (batch, 1))
    toks = jnp.asarray(np.random.RandomState(0).randint(0, vocab, (batch, 1)))

    results = {}
    p16 = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16), params)
    fwd16 = jax.jit(lambda p, s, x: model.apply(p, s, x, training=False)[0])
    results["bf16"] = _time_fn(fwd16, p16, state, toks, iters=50)

    qm, qp = WeightOnlyInt8.from_float(model, params,
                                       compute_dtype=jnp.bfloat16)
    qfwd = jax.jit(lambda p, s, x: qm.apply(p, s, x, training=False)[0])
    results["weight_only"] = _time_fn(qfwd, qp, state, toks, iters=50)

    # auto row (VERDICT r4 item 6): quantize(mode='auto') must govern the
    # decode workload class too — on a non-walkable custom Module it
    # microbenches {float, bf16, weight_only_wrap} and keeps the winner
    from bigdl_tpu.nn.quantized import quantize

    am, ap = quantize(model, params, mode="auto", sample_input=toks,
                      state=state, bench_iters=20)
    afwd = jax.jit(lambda p, s, x, am=am: am.apply(p, s, x,
                                                   training=False)[0])
    results["auto"] = _time_fn(afwd, ap, state, toks, iters=50)
    print(json.dumps({"decode_auto_picked": am._quant_auto_report["picked"],
                      "decode_auto_table_ms": {
                          k: round(v, 3) for k, v in
                          am._quant_auto_report["ms_per_batch"].items()}}),
          flush=True)

    for mode, ms in results.items():
        print(json.dumps({
            "workload": "transformer_lm_decode_b8", "mode": mode,
            "ms_per_step": round(ms, 3),
            "speedup_vs_bf16": round(results["bf16"] / ms, 3)}), flush=True)
    return results


if __name__ == "__main__":
    bench_decode()
    bench_resnet()
