"""Evidence-grade ResNet-50 training-throughput appendix.

Produces the artifacts BENCH_APPENDIX.md records: a batch-size sweep with
measured ms/step, XLA cost-analysis FLOPs and HBM bytes per step, and the
derived roofline (v5e: ~197 TFLOP/s bf16, ~819 GB/s HBM), following the
reference's measurement methodology (records / iteration wall time,
models/utils/DistriOptimizerPerf.scala:32-86).

Run on the TPU:
  PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/bench_appendix.py
"""

import json
import time

import numpy as np

V5E_BF16_FLOPS = 197e12
V5E_HBM_BYTES_S = 819e9
WARMUP, ITERS = 3, 20


def build_step(model, optim, criterion):
    import jax
    import jax.numpy as jnp

    def train_step(params, model_state, opt_state, x, y):
        def loss_fn(p):
            p16 = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16), p)
            out, new_state = model.apply(p16, model_state, x, training=True,
                                         rng=None)
            return criterion.forward(out.astype(jnp.float32), y), new_state

        (loss, new_model_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt_state = optim.step(grads, params, opt_state)
        return new_params, new_model_state, new_opt_state, loss

    return train_step


def sweep(batches=(128, 192, 256, 320, 384), remat=False,
          fuse_bn=False):
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet50
    from bigdl_tpu.optim import SGD

    rows = []
    for batch in batches:
        model = resnet50(1000, remat=remat, fuse_bn=fuse_bn)
        shape = (batch, 224, 224, 3)
        params, state, _ = model.build(jax.random.PRNGKey(0), shape)
        optim = SGD(learning_rate=0.1, momentum=0.9, dampening=0.0)
        opt_state = optim.init(params)
        step = build_step(model, optim, nn.ClassNLLCriterion())
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.rand(*shape), jnp.bfloat16)
        y = jnp.asarray(rs.randint(0, 1000, batch))

        lowered = jax.jit(step, donate_argnums=(0, 1, 2)).lower(
            params, state, opt_state, x, y)
        compiled = lowered.compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        bytes_ = float(cost.get("bytes accessed", 0.0))

        def sync(tree):
            leaf = jax.tree_util.tree_leaves(tree)[0]
            return float(jnp.sum(leaf.astype(jnp.float32)))

        p, s, o = params, state, opt_state
        for _ in range(WARMUP):
            p, s, o, loss = compiled(p, s, o, x, y)
        sync(p)
        t0 = time.perf_counter()
        for _ in range(ITERS):
            p, s, o, loss = compiled(p, s, o, x, y)
        sync(p)
        dt = (time.perf_counter() - t0) / ITERS

        flop_floor = flops / V5E_BF16_FLOPS
        hbm_floor = bytes_ / V5E_HBM_BYTES_S
        roofline = max(flop_floor, hbm_floor)
        rows.append({
            "remat": remat,
            "fuse_bn": fuse_bn,
            "batch": batch,
            "ms_per_step": round(dt * 1e3, 2),
            "img_per_s": round(batch / dt, 1),
            "tflops_per_step": round(flops / 1e12, 2),
            "hbm_gb_per_step": round(bytes_ / 1e9, 2),
            "flop_floor_ms": round(flop_floor * 1e3, 2),
            "hbm_floor_ms": round(hbm_floor * 1e3, 2),
            "roofline_ms": round(roofline * 1e3, 2),
            "roofline_frac": round(roofline / dt, 3),
            "bound": "HBM" if hbm_floor > flop_floor else "FLOP",
        })
        print(json.dumps(rows[-1]), flush=True)
        del p, s, o, compiled, lowered
    return rows


if __name__ == "__main__":
    import sys

    if "--remat" in sys.argv:
        rows = sweep(batches=(256, 384, 512), remat=True)
    elif "--fuse-bn" in sys.argv:
        # the conv+BN-stats pallas epilogue variant (nn.SpatialConvolutionBN)
        # vs the standard step at the operating point and one larger batch
        rows = sweep(batches=(256, 384), fuse_bn=True)
        rows += sweep(batches=(256,), fuse_bn=False)
    else:
        rows = sweep()
    print(json.dumps({"sweep": rows}))
