"""Modeled multi-chip scaling table (the honest single-chip substitute).

No multi-chip hardware exists in this environment, so the scaling
evidence is assembled from what CAN be measured here:

1. the per-step COLLECTIVE bytes of the real dp-sharded train step —
   counted from the compiled HLO of the 8-virtual-device DistriOptimizer
   program (every all-reduce/all-gather/reduce-scatter/collective-permute
   operand, the same program multi-chip hardware would run), and
2. the measured single-chip step time (BENCH_APPENDIX.md batch sweep),

combined with a bandwidth model whose assumptions are printed with the
table.  Reference anchor: the whitepaper's scaling claim is ~"close to
linear" data-parallel scaling on its cluster (docs/docs/whitepaper.md:
160-164, axes-free curves); the north star here is >=70% efficiency at
256 chips.

Model:
  per-chip ring all-reduce moves 2*(N-1)/N * G bytes over the slowest
  link; ICI all-reduce effective bandwidth B_ici per chip within a slice
  (v5e public figure ~45 GB/s/link x 4 links, derated to an effective
  ALGORITHM bandwidth); one v5e slice only (no DCN modeling).  Gradient
  all-reduce OVERLAPS backward (ParallelOptimizer's per-leaf collectives;
  XLA latency-hiding scheduler): exposed comm = max(0, t_comm -
  overlap_window).  Weak scaling (fixed per-chip batch 256).

Run (CPU, 8 virtual devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PYTHONPATH=/root/repo python benchmarks/bench_scaling_model.py
"""

import json
import re

import numpy as np

# ---- measured inputs (single v5e chip, batch 256) ----


def _measured_step_ms(default: float = 103.1) -> float:
    """Read the operating point from the LATEST bench artifact
    (BENCH_r*.json img/s at b256) so a re-capture automatically updates
    the model instead of silently diverging from the measurement."""
    import glob
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                       reverse=True):
        try:
            parsed = json.load(open(path)).get("parsed") or {}
            # the SYNTHETIC-input metric only: --real-data captures share
            # the unit but are host-input-bound, not the chip's step time
            if parsed.get("metric") == "resnet50_imagenet_train_throughput" \
                    and parsed.get("value"):
                return 256.0 / float(parsed["value"]) * 1e3
        except Exception:
            continue
    return default


STEP_MS_1CHIP = _measured_step_ms()  # ms/step at b256, from BENCH_r*.json
BACKWARD_FRACTION = 0.6        # bwd ~2/3 of fwd+bwd FLOPs; overlap window

# ---- bandwidth assumptions (printed with the table) ----
ICI_ALGO_BW = 90e9   # bytes/s effective all-reduce bandwidth per chip
#   (v5e: 4 ICI links x ~45 GB/s raw; ring algorithm efficiency + framing
#    derate to ~90 GB/s usable — conservative vs the scaling-book figures)
CHIPS_PER_SLICE = 256  # v5e slice ceiling: ICI-only up to 256 chips
DCN_ALGO_BW = 6.25e9  # bytes/s per chip cross-slice (50 Gbps) — a STATED
#   ASSUMPTION, not a measurement (this environment has no second slice);
#   conservative vs public v5e multislice figures.  The multislice rows
#   model the hierarchical all-reduce Engine.build_multislice_mesh's
#   layout produces: within-slice reduce-scatter + all-gather over ICI
#   (the full 2(n-1)/n ring), plus a cross-slice all-reduce of each
#   chip's G/n_slice_chips gradient shard over DCN
#   (2(S-1)/S * G/chips_per_slice wire bytes per chip).
DCN_HOP_LATENCY_S = 10e-6  # per cross-slice hop (assumption, printed)


_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")
_SHAPE_RE = re.compile(r"(f32|bf16|f16|s32|u32|s8|u8|pred)\[([0-9,]*)\]")
_BYTES = {"f32": 4, "s32": 4, "u32": 4, "bf16": 2, "f16": 2, "s8": 1,
          "u8": 1, "pred": 1}


def collective_bytes_from_hlo(hlo_text: str):
    """Sum output bytes of every collective op in the compiled HLO."""
    total = 0
    per_op = {}
    for line in hlo_text.splitlines():
        stripped = line.strip()
        m = re.match(r"%?\S+\s*=\s*(\(?[^)]*\)?)\s*(" +
                     "|".join(_COLLECTIVES) + r")\(", stripped)
        if not m:
            continue
        op = m.group(2)
        nbytes = 0
        for dt, dims in _SHAPE_RE.findall(m.group(1)):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _BYTES[dt]
        total += nbytes
        per_op[op] = per_op.get(op, 0) + nbytes
    return total, per_op


def measure_collectives(batch_per_chip=32, n_devices=8):
    """Compile the REAL dp train step over the virtual mesh and count its
    collective bytes.  (Per-chip gradient all-reduce bytes are invariant
    to the dp degree up to the 2*(N-1)/N ring factor, which the model
    applies per N.)"""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import bigdl_tpu.nn as nn
    from bigdl_tpu.core.engine import AXIS_DATA, Engine
    from bigdl_tpu.models import resnet50
    from bigdl_tpu.optim import SGD

    mesh = Engine.build_mesh(devices=jax.devices()[:n_devices],
                             **{AXIS_DATA: n_devices})
    model = resnet50(1000)
    batch = batch_per_chip * n_devices
    shape = (batch, 64, 64, 3)  # smaller spatial dims: same param/grad
    # collectives, CPU-compilable in minutes
    params, state, _ = model.build(jax.random.PRNGKey(0), shape)
    optim = SGD(learning_rate=0.1, momentum=0.9, dampening=0.0)
    opt_state = optim.init(params)
    crit = nn.ClassNLLCriterion()

    def train_step(params, model_state, opt_state, x, y):
        def loss_fn(p):
            p16 = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16), p)
            out, new_state = model.apply(p16, model_state, x, training=True)
            return crit.forward(out.astype(jnp.float32), y), new_state

        (loss, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt = optim.step(grads, params, opt_state)
        return new_params, new_state, new_opt, loss

    rep = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(AXIS_DATA))
    put = lambda t, s: jax.tree_util.tree_map(  # noqa: E731
        lambda a: jax.device_put(a, s), t)
    params = put(params, rep)
    state = put(state, rep)
    opt_state = put(opt_state, rep)
    rs = np.random.RandomState(0)
    x = jax.device_put(jnp.asarray(rs.rand(*shape), jnp.bfloat16), data)
    y = jax.device_put(jnp.asarray(rs.randint(0, 1000, batch)), data)

    lowered = jax.jit(train_step, donate_argnums=(0, 1, 2)).lower(
        params, state, opt_state, x, y)
    compiled = lowered.compile()
    hlo = compiled.as_text()
    total, per_op = collective_bytes_from_hlo(hlo)
    n_params = sum(int(np.prod(v.shape))
                   for v in jax.tree_util.tree_leaves(params))
    return total, per_op, n_params


HOP_LATENCY_S = 2e-6  # per ring hop (conservative ICI latency)


def model_scaling(grad_bytes_per_chip, chips=(8, 16, 32, 64, 128, 256),
                  ici_bw=ICI_ALGO_BW, overlap_frac=BACKWARD_FRACTION,
                  label="overlap"):
    """Weak-scaling table: fixed per-chip batch, time(N) = compute +
    exposed all-reduce (+ 2(N-1) hop latencies)."""
    rows = []
    t_step = STEP_MS_1CHIP / 1e3
    overlap = t_step * overlap_frac
    for n in chips:
        # grad_bytes_per_chip is the all-reduce OUTPUT size G from the
        # compiled HLO (validated: exactly 4 bytes x n_params — no ring
        # factor baked in); a ring all-reduce moves 2*(N-1)/N * G of wire
        # traffic per chip
        ring = 2 * (n - 1) / n
        moved = grad_bytes_per_chip * ring
        t_comm = moved / ici_bw + 2 * (n - 1) * HOP_LATENCY_S
        exposed = max(0.0, t_comm - overlap)
        t_n = t_step + exposed
        rows.append({
            "model": label,
            "chips": n,
            "per_chip_allreduce_MB": round(moved / 1e6, 1),
            "t_comm_ms": round(t_comm * 1e3, 2),
            "exposed_ms": round(exposed * 1e3, 2),
            "ms_per_step": round(t_n * 1e3, 1),
            "img_s_total": round(256 * n / t_n),
            "efficiency_vs_8": None,  # filled below
        })
    base = rows[0]["img_s_total"] / rows[0]["chips"]
    for r in rows:
        r["efficiency_vs_8"] = round(r["img_s_total"] / r["chips"] / base, 3)
    return rows


def model_scaling_multislice(grad_bytes_per_chip, slices=(2, 4, 8),
                             chips_per_slice=CHIPS_PER_SLICE,
                             ici_bw=ICI_ALGO_BW, dcn_bw=DCN_ALGO_BW,
                             overlap_frac=BACKWARD_FRACTION):
    """Pod-scale rows past the single-slice ceiling: hierarchical
    all-reduce = full within-slice ring over ICI + cross-slice all-reduce
    of the per-chip gradient SHARD over DCN (the layout
    Engine.build_multislice_mesh encodes: data axis outermost, crossing
    slices)."""
    rows = []
    t_step = STEP_MS_1CHIP / 1e3
    overlap = t_step * overlap_frac
    n = chips_per_slice
    for s in slices:
        chips = s * n
        ici_moved = grad_bytes_per_chip * 2 * (n - 1) / n
        dcn_moved = (grad_bytes_per_chip / n) * 2 * (s - 1) / s
        t_comm = (ici_moved / ici_bw + 2 * (n - 1) * HOP_LATENCY_S
                  + dcn_moved / dcn_bw + 2 * (s - 1) * DCN_HOP_LATENCY_S)
        exposed = max(0.0, t_comm - overlap)
        t_n = t_step + exposed
        rows.append({
            "model": "multislice",
            "slices": s,
            "chips": chips,
            "per_chip_ici_MB": round(ici_moved / 1e6, 1),
            "per_chip_dcn_MB": round(dcn_moved / 1e6, 2),
            "t_comm_ms": round(t_comm * 1e3, 2),
            "exposed_ms": round(exposed * 1e3, 2),
            "ms_per_step": round(t_n * 1e3, 1),
            "img_s_total": round(256 * chips / t_n),
            "efficiency_vs_1slice": None,
        })
    return rows


def main():
    # the axon sitecustomize registers/initializes the TPU plugin at
    # interpreter startup; force the 8-virtual-device CPU platform the
    # same way the graft entry's dryrun does
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import __graft_entry__ as ge

    ge._force_virtual_cpu(8)
    total, per_op, n_params = measure_collectives()
    print(json.dumps({"hlo_collective_bytes_8dev": total,
                      "per_op": per_op,
                      "n_params": n_params}), flush=True)
    rows = model_scaling(total)
    # pessimistic bound: ICI derated to one link's raw rate, ZERO
    # backward overlap — every collective byte is exposed
    worst = model_scaling(total, ici_bw=45e9, overlap_frac=0.0,
                          label="no-overlap/45GBs")
    multi = model_scaling_multislice(total)
    base = rows[-1]["img_s_total"] / rows[-1]["chips"]  # 256-chip slice
    for r in multi:
        r["efficiency_vs_1slice"] = round(
            r["img_s_total"] / r["chips"] / base, 3)
    for r in rows + worst + multi:
        print(json.dumps(r), flush=True)
    print(json.dumps({"assumptions": {
        "step_ms_1chip_b256": STEP_MS_1CHIP,
        "ici_algo_bw_GBs": ICI_ALGO_BW / 1e9,
        "ici_pessimistic_GBs": 45.0,
        "hop_latency_us": HOP_LATENCY_S * 1e6,
        "overlap_window_fraction": BACKWARD_FRACTION,
        "weak_scaling_batch_per_chip": 256,
        "chips_per_slice": CHIPS_PER_SLICE,
        "dcn_algo_bw_GBs_ASSUMED": DCN_ALGO_BW / 1e9,
        "dcn_hop_latency_us_ASSUMED": DCN_HOP_LATENCY_S * 1e6,
    }, "table": rows, "pessimistic": worst, "multislice": multi}))


if __name__ == "__main__":
    main()
