"""MEASURED CPU-framework baseline for the headline vs_baseline anchor.

The reference (BigDL) publishes no absolute throughput numbers; its
premise is ResNet-class training on dual-socket Xeon nodes with a
mainstream CPU DL stack (whitepaper Fig 7; README "orders of magnitude
faster than out-of-box ... Torch" on Xeon).  The reference itself cannot
run in this image (Scala/Spark, no JVM), so the closest MEASURABLE
stand-in is PyTorch CPU — a mainstream CPU framework with MKL-class
kernels — training the same ResNet-50 ImageNet-shape step on THIS host's
Xeon-class CPUs, all cores.

This replaces the round-1..3 anchor (a ~16 img/s order-of-magnitude
ESTIMATE for a 2017 Broadwell node): the number below is measured on the
actual host, which is a far larger machine than the whitepaper's nodes —
i.e. the resulting vs_baseline is CONSERVATIVE.

Run: python benchmarks/bench_cpu_torch_baseline.py [--batch 32] [--iters 8]
Prints one json line.
"""

import argparse
import json
import os
import time

import torch
import torch.nn as nn


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, planes, stride=1, down=None):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * 4)
        self.relu = nn.ReLU(inplace=True)
        self.down = down

    def forward(self, x):
        idt = x if self.down is None else self.down(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return self.relu(out + idt)


class ResNet50(nn.Module):
    def __init__(self, classes=1000):
        super().__init__()
        self.cin = 64
        self.stem = nn.Sequential(
            nn.Conv2d(3, 64, 7, 2, 3, bias=False), nn.BatchNorm2d(64),
            nn.ReLU(inplace=True), nn.MaxPool2d(3, 2, 1))
        self.layer1 = self._layer(64, 3, 1)
        self.layer2 = self._layer(128, 4, 2)
        self.layer3 = self._layer(256, 6, 2)
        self.layer4 = self._layer(512, 3, 2)
        self.pool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(2048, classes)

    def _layer(self, planes, blocks, stride):
        down = None
        if stride != 1 or self.cin != planes * 4:
            down = nn.Sequential(
                nn.Conv2d(self.cin, planes * 4, 1, stride, bias=False),
                nn.BatchNorm2d(planes * 4))
        layers = [Bottleneck(self.cin, planes, stride, down)]
        self.cin = planes * 4
        layers += [Bottleneck(self.cin, planes) for _ in range(blocks - 1)]
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.stem(x)
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        return self.fc(torch.flatten(self.pool(x), 1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=2)
    args = ap.parse_args()

    torch.set_num_threads(os.cpu_count() or 1)
    torch.manual_seed(0)
    model = ResNet50()
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    crit = nn.CrossEntropyLoss()
    x = torch.randn(args.batch, 3, 224, 224)
    y = torch.randint(0, 1000, (args.batch,))

    def step():
        opt.zero_grad(set_to_none=True)
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
        return float(loss)

    for _ in range(args.warmup):
        step()
    t0 = time.perf_counter()
    for _ in range(args.iters):
        step()
    dt = (time.perf_counter() - t0) / args.iters
    print(json.dumps({
        "metric": "torch_cpu_resnet50_train_throughput",
        "value": round(args.batch / dt, 2), "unit": "images/sec",
        "ms_per_step": round(dt * 1e3, 1), "batch": args.batch,
        "threads": torch.get_num_threads()}))


if __name__ == "__main__":
    main()
