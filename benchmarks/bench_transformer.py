"""Transformer stack on the chip (VERDICT r4 item 3).

Three measurements, all on the real TPU, all synced via dependent host
readback (block_until_ready does not truly block through the tunnel):

1. TransformerLM (GPT-2-small shape: 768h/12L/12H, vocab 32k, seq 1024)
   full train step — tokens/s and MFU vs the v5e bf16 roofline.
2. flash-attention pallas kernel (ops/flash_attention.py) vs XLA's native
   dense attention (ops/attention.dense_attention), fwd and fwd+bwd,
   seq 1024..8192, bf16 — the measured keep/lose evidence for the kernel.
3. PTB LSTM (reference 'medium': 650h x 2 layers, the lax.scan
   recurrence) train-step throughput.

    python benchmarks/bench_transformer.py [--quick]

Emits BENCH-style JSON rows and writes benchmarks/results/transformer.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

V5E_BF16_TFLOPS = 197.0  # per-chip peak (pallas_guide / public v5e spec)


def sync(x):
    import jax
    import jax.numpy as jnp

    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def timeit(fn, *args, iters=20, warmup=3):
    out = None
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / iters


def bench_lm(batch: int, seq: int, iters: int):
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.optim import SGD

    d, n_layer, n_head, vocab = 768, 12, 12, 32_000
    model = TransformerLM(vocab_size=vocab, hidden_size=d, n_layer=n_layer,
                          n_head=n_head, max_len=seq)
    params, state, _ = model.build(jax.random.PRNGKey(0), (batch, seq))
    optim = SGD(learning_rate=0.01, momentum=0.9, dampening=0.0)
    opt_state = optim.init(params)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)

    def train_step(params, opt_state, x, y):
        def loss_fn(p):
            p16 = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16), p)
            out, _ = model.apply(p16, {}, x, training=True, rng=None)
            # keep the (B,S,V) log-probs in bf16: an fp32 cast here
            # materializes 4 GB at b32 and made b16 HBM-bound (measured);
            # the criterion's gather+mean is loss-value-only
            return crit.forward(out, y).astype(jnp.float32)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params, new_opt = optim.step(grads, params, opt_state)
        return new_params, new_opt, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randint(0, vocab, (batch, seq)), jnp.int32)
    y = jnp.asarray(rs.randint(0, vocab, (batch, seq)), jnp.int32)

    st = [params, opt_state]

    def run(x, y):
        st[0], st[1], loss = step(st[0], st[1], x, y)
        return loss

    dt = timeit(run, x, y, iters=iters)
    tok_s = batch * seq / dt

    # analytic train FLOPs/token: 6*N on the matmul params (weights seen
    # fwd+bwd+grad) + attention scores/values 12*L*d*S_causal (6*L*d*S)
    n_param = sum(int(np.prod(np.shape(a)))
                  for a in jax.tree_util.tree_leaves(params))
    n_emb = vocab * d
    # tied embeddings: the head matmul IS the embedding matrix -> its
    # FLOPs count once as a matmul (6*n_emb), lookup-side is gather
    flops_tok = 6 * (n_param - n_emb) + 6 * n_emb + 6 * n_layer * d * seq
    mfu = flops_tok * tok_s / (V5E_BF16_TFLOPS * 1e12)
    return {"metric": "transformer_lm_train", "batch": batch, "seq": seq,
            "tok_per_s": round(tok_s, 0), "ms_per_step": round(dt * 1e3, 2),
            "params_M": round(n_param / 1e6, 1),
            "mfu_vs_197TFLOPs": round(mfu, 3)}


def bench_attention(seq: int, train: bool, iters: int, heads=12, hd=64,
                    batch=4):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.attention import dense_attention
    from bigdl_tpu.ops.flash_attention import flash_attention

    rs = np.random.RandomState(0)
    # (B, S, H, D) — BOTH cores take batch-major sequence layout (dense
    # einsum 'bqhd,bkhd->bhqk'; flash unpacks b, sq, h, d = q.shape).  The
    # round-5 sweep built (B, H, S, D) here and therefore measured
    # attention over an actual sequence length of `hd` with `seq` heads —
    # every round-5 attention row is invalid (ADVICE.md high, r5).
    shape = (batch, seq, heads, hd)
    q = jnp.asarray(rs.randn(*shape), jnp.bfloat16)
    k = jnp.asarray(rs.randn(*shape), jnp.bfloat16)
    v = jnp.asarray(rs.randn(*shape), jnp.bfloat16)

    def mk(fn):
        if train:
            def loss(q, k, v):
                return jnp.sum(fn(q, k, v, causal=True)
                               .astype(jnp.float32))
            return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        return jax.jit(lambda q, k, v: fn(q, k, v, causal=True))

    out = {}
    for name, fn in (("xla_dense", dense_attention),
                     ("flash_pallas", flash_attention)):
        try:
            dt = timeit(mk(fn), q, k, v, iters=iters)
            out[name] = round(dt * 1e3, 3)
        except Exception as e:  # OOM at long seq is a result, not a crash
            out[name] = f"failed: {type(e).__name__}"
    if all(isinstance(v, float) for v in out.values()):
        out["flash_speedup"] = round(out["xla_dense"] / out["flash_pallas"], 3)
    return {"metric": "attention_fwd" if not train else "attention_train",
            "seq": seq, "batch": batch, "heads": heads, "head_dim": hd,
            **out}


def bench_ptb(iters: int):
    from bigdl_tpu.models.perf import run_perf

    rec_s, ms = run_perf("ptb_lstm", batch_size=20, iterations=iters,
                         warmup=3, dtype="bfloat16")
    return {"metric": "ptb_lstm_medium_train", "batch": 20, "num_steps": 35,
            "tok_per_s": round(rec_s * 35, 0), "ms_per_step": round(ms, 2)}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args(argv)
    iters = 5 if args.quick else args.iters

    rows = []

    def record(fn, *a, **kw):
        try:
            rows.append(fn(*a, **kw))
        except Exception as e:  # OOM at a size is a RESULT for the table
            rows.append({"metric": fn.__name__, "args": [a, kw],
                         "failed": f"{type(e).__name__}: {str(e)[:160]}"})
        print(json.dumps(rows[-1]), flush=True)

    for batch in ((8,) if args.quick else (8, 16, 32)):
        record(bench_lm, batch, 1024, iters)
    for seq in ((1024, 2048) if args.quick else (1024, 2048, 4096, 8192)):
        b = max(1, 8192 // seq // 2)
        for train in (False, True):
            record(bench_attention, seq, train, iters, batch=b)
    record(bench_ptb, iters)

    out = os.path.join(os.path.dirname(__file__), "results",
                       "transformer.json")
    with open(out, "w") as f:
        json.dump(rows, f, indent=1)
    print(f"# wrote {out}")


if __name__ == "__main__":
    main()
