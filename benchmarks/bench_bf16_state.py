"""Bytes-reduction experiment: bf16 gradients + bf16 momentum with fp32
master weights on the HBM-bound ResNet-50 train step (VERDICT r4 item 10).

The b256 step moves 77.1 GB (XLA cost analysis); params+grads+momentum
are the fixed ~0.4 GB/step term (25.6M params x 4 B x {param read, grad
write+read, slot read+write}).  Storing the SGD-momentum slot in bf16 and
keeping gradients bf16 through the update halves those streams; the fp32
master copy preserves update precision (the standard mixed-precision
recipe — and the analogue of the reference's fp16 wire compression,
parameters/FP16CompressedTensor.scala, applied to optimizer state).

Accept/reject is measured, appendix-style, like the remat and conv+BN
chapters: both variants on the real chip, XLA cost-analysis bytes for
each, plus an update-precision parity probe (fp32-slot vs bf16-slot
parameter drift after N steps).

    python benchmarks/bench_bf16_state.py [--iters 40]

Prints one JSON row per variant + a parity row.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=40)
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet50

    batch, image, classes = args.batch, 224, 1000
    model = resnet50(classes)
    shape = (batch, image, image, 3)
    params, state, _ = model.build(jax.random.PRNGKey(0), shape)
    criterion = nn.ClassNLLCriterion()
    lr, momentum = 0.1, 0.9

    def grads_of(params, model_state, x, y):
        def loss_fn(p):
            p16 = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16), p)
            out, new_state = model.apply(p16, model_state, x,
                                         training=True, rng=None)
            return criterion.forward(out.astype(jnp.float32), y), new_state

        (loss, new_state), g = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return loss, new_state, g

    def step_fp32(params, model_state, mom, x, y):
        """Baseline: fp32 grads (jax.grad of fp32 params), fp32 slots."""
        loss, new_state, g = grads_of(params, model_state, x, y)
        new_mom = jax.tree_util.tree_map(
            lambda m, gi: momentum * m + gi, mom, g)
        new_params = jax.tree_util.tree_map(
            lambda p, m: p - lr * m, params, new_mom)
        return new_params, new_state, new_mom, loss

    def step_bf16_state(params, model_state, mom, x, y):
        """Experiment: gradients cast bf16 at the boundary, momentum
        STORED bf16; update math in fp32 against the fp32 master."""
        loss, new_state, g = grads_of(params, model_state, x, y)
        g16 = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16), g)
        new_mom = jax.tree_util.tree_map(
            lambda m, gi: (momentum * m.astype(jnp.float32)
                           + gi.astype(jnp.float32)).astype(jnp.bfloat16),
            mom, g16)
        new_params = jax.tree_util.tree_map(
            lambda p, m: p - lr * m.astype(jnp.float32), params, new_mom)
        return new_params, new_state, new_mom, loss

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(*shape), jnp.bfloat16)
    y = jnp.asarray(rs.randint(0, classes, batch))

    def sync(tree):
        leaf = jax.tree_util.tree_leaves(tree)[0]
        return float(jnp.sum(leaf.astype(jnp.float32)))

    def run(step_fn, mom_dtype, tag):
        # fresh buffers per variant: the step donates its params/state,
        # which deletes the donated arrays — sharing the global trees
        # across variants would crash the second run on deleted Arrays
        p = jax.tree_util.tree_map(jnp.array, params)
        st = jax.tree_util.tree_map(jnp.array, state)
        mom = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, mom_dtype), params)
        step = jax.jit(step_fn, donate_argnums=(0, 1, 2))
        # XLA's own account of the bytes the compiled step accesses
        cost = step.lower(p, st, mom, x, y).compile().cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        gb = float(cost.get("bytes accessed", 0.0)) / 1e9
        for _ in range(3):
            p, st, mom, loss = step(p, st, mom, x, y)
        sync(p)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            p, st, mom, loss = step(p, st, mom, x, y)
        sync(p)
        dt = (time.perf_counter() - t0) / args.iters
        row = {"variant": tag, "ms_per_step": round(dt * 1e3, 2),
               "img_per_s": round(batch / dt, 1),
               "hbm_GB_per_step_xla": round(gb, 2)}
        print(json.dumps(row), flush=True)
        return row, p

    base_row, base_p = run(step_fp32, jnp.float32, "fp32_grads_slots")
    exp_row, exp_p = run(step_bf16_state, jnp.bfloat16, "bf16_grads_slots")

    # update-precision parity after iters steps (same data each step)
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                           - b.astype(jnp.float32)))),
        base_p, exp_p)
    scale = jax.tree_util.tree_map(
        lambda a: float(jnp.max(jnp.abs(a.astype(jnp.float32))) + 1e-12),
        base_p)
    rel = max(d / s for d, s in zip(jax.tree_util.tree_leaves(diffs),
                                    jax.tree_util.tree_leaves(scale)))
    print(json.dumps({
        "parity_max_rel_param_drift": round(rel, 5),
        "speedup": round(base_row["ms_per_step"] / exp_row["ms_per_step"], 3),
        "bytes_saved_GB": round(base_row["hbm_GB_per_step_xla"]
                                - exp_row["hbm_GB_per_step_xla"], 2)}),
        flush=True)


if __name__ == "__main__":
    main()
