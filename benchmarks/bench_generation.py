"""Autoregressive generation latency through the prefill/decode engine
(ISSUE 10 acceptance: per-token p50/p99 committed to results/).

Times the END-TO-END path — admission, prefill executable, continuous-
batched decode steps, per-step (slots,) token readback — through
`bigdl_tpu.generation.GenerationEngine`, not the bare cached forward.
Two weight variants of the same LM:

  * fp32        — the model as built (bf16 on TPU-sized runs)
  * weight_only — leaf-wise int8 weights (`WeightOnlyInt8.from_float`),
                  the decode-class quantization (bandwidth-bound)

and two load phases per variant:

  * seq1   — sequential single requests (interactive latency: TTFT plus
             ms/token with one active slot)
  * burstN — N concurrent requests over `slots` slots (throughput:
             ms/token is per decode STEP, every active slot advances one
             token per step, so tokens/s = active x 1000 / ms_per_token)

Emits one JSON row per (variant, phase) with TTFT and per-token p50/p99,
prefill ms, tokens/s, executable count (must stay <= buckets x 2), and
writes the table to benchmarks/results/generation_quick.json (--quick)
or generation.json.

    python benchmarks/bench_generation.py            # TPU-sized LM
    python benchmarks/bench_generation.py --quick    # CPU-sized LM
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_variants(quick: bool):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.nn.quantized import WeightOnlyInt8

    if quick:
        kw = dict(vocab_size=512, hidden_size=64, n_layer=2, n_head=4)
    else:
        kw = dict(vocab_size=32000, hidden_size=1024, n_layer=12, n_head=16)
    model = TransformerLM(max_len=1024, use_flash=False, **kw)
    params, _ = model.init((1, 16), rng=jax.random.PRNGKey(0))
    variants = [("fp32", model, params)]
    qm, qp = WeightOnlyInt8.from_float(
        model, params, compute_dtype=None if quick else jnp.bfloat16)
    variants.append(("weight_only", qm, qp))
    return kw["vocab_size"], variants


def run_phase(engine, vocab: int, phase: str, n: int, max_new: int) -> dict:
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, vocab, size=int(rng.randint(4, 14)))
               for _ in range(n)]
    t0 = time.perf_counter()
    if phase == "seq1":
        results = [engine.generate(p, max_new_tokens=max_new)
                   for p in prompts]
    else:
        futs = [engine.submit(p, max_new_tokens=max_new) for p in prompts]
        results = [f.result(timeout=600) for f in futs]
    wall = time.perf_counter() - t0
    snap = engine.metrics.snapshot()
    tokens = sum(r.meta["tokens"] for r in results)
    ttft = sorted(r.meta["ttft_ms"] for r in results)
    per_tok = sorted(r.meta["ms_per_token"] for r in results
                     if r.meta["ms_per_token"] is not None)

    def pct(xs, q):
        return round(xs[min(len(xs) - 1, int(q / 100 * len(xs)))], 3)

    return {
        "phase": phase, "requests": n, "max_new_tokens": max_new,
        "tokens": tokens,
        "ttft_p50_ms": pct(ttft, 50), "ttft_p99_ms": pct(ttft, 99),
        "ms_per_token_p50": pct(per_tok, 50),
        "ms_per_token_p99": pct(per_tok, 99),
        "prefill_p50_ms": snap["prefill_ms"]["p50"],
        "tokens_per_s": round(tokens / wall, 1),
        "compiled_executables": engine.compile_count(),
        "wall_s": round(wall, 2),
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="2-layer hidden-64 LM, fewer requests (CPU-sized)")
    ap.add_argument("--requests", type=int, default=None)
    args = ap.parse_args(argv)

    import jax

    from bigdl_tpu.generation import GenerationConfig, GenerationEngine

    platform = jax.devices()[0].platform
    n_seq = args.requests or (12 if args.quick else 32)
    max_new = 16 if args.quick else 64
    buckets = (32, 128) if args.quick else (128, 512)
    slots = 4 if args.quick else 8
    vocab, variants = build_variants(args.quick)

    rows = []
    for variant, module, params in variants:
        cfg = GenerationConfig(buckets=buckets, slots=slots,
                               capacity=256, max_new_tokens=max_new)
        engine = GenerationEngine(module, params, config=cfg)
        budget = 2 * len(buckets)
        try:
            for phase, n in (("seq1", n_seq), (f"burst{4 * slots}",
                                               4 * slots)):
                row = {"variant": variant, "platform": platform,
                       "buckets": list(buckets), "slots": slots,
                       **run_phase(engine, vocab, phase, n, max_new)}
                assert row["compiled_executables"] <= budget, row
                rows.append(row)
                print(json.dumps(row), flush=True)
        finally:
            engine.close()

    name = "generation_quick.json" if args.quick else "generation.json"
    out = os.path.join(os.path.dirname(__file__), "results", name)
    with open(out, "w") as f:
        json.dump(rows, f, indent=1)
    print(f"# wrote {out}")


if __name__ == "__main__":
    main()
