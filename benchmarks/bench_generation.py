"""Autoregressive generation latency through the prefill/decode engine
(ISSUE 10 acceptance: per-token p50/p99 committed to results/).

Times the END-TO-END path — admission, prefill executable, continuous-
batched decode steps, per-step (slots,) token readback — through
`bigdl_tpu.generation.GenerationEngine`, not the bare cached forward.
Two weight variants of the same LM:

  * fp32        — the model as built (bf16 on TPU-sized runs)
  * weight_only — leaf-wise int8 weights (`WeightOnlyInt8.from_float`),
                  the decode-class quantization (bandwidth-bound)

and two load phases per variant:

  * seq1   — sequential single requests (interactive latency: TTFT plus
             ms/token with one active slot)
  * burstN — N concurrent requests over `slots` slots (throughput:
             ms/token is per decode STEP, every active slot advances one
             token per step, so tokens/s = active x 1000 / ms_per_token)

Emits one JSON row per (variant, phase) with TTFT and per-token p50/p99,
prefill ms, tokens/s, executable count (must stay <= buckets x 2), and
writes the table to benchmarks/results/generation_quick.json (--quick)
or generation.json.

    python benchmarks/bench_generation.py            # TPU-sized LM
    python benchmarks/bench_generation.py --quick    # CPU-sized LM

`--decode-quick` instead runs the ISSUE 12 decode-path evidence and
writes results/decode_quick.json:

  * interleaved A/B of the decode-attention lowerings (dense ring-mask
    path vs the length-1-query `decode_attention_ref`) per KV capacity,
    including a long-context frontier — the measurements backing
    `_MEASURED_DEFAULTS` in bigdl_tpu/ops/decode_attention.py (the
    shipping table must agree with this file's winners);
  * KV bytes-per-resident-token for fp32 vs int8 pools (the >= 1.9x
    resident-tokens-per-byte acceptance bar);
  * an engine-level ring vs paged vs paged+int8 A/B on a mixed-length
    workload: same greedy tokens, executable budget, and the HBM bytes
    actually resident (paged pool oversubscribed below ring worst case).

It ALSO writes results/spec_quick.json (ISSUE 15 evidence):

  * chunked-on/off interleaved A/B: p99 TTFT of short requests admitted
    while a largest-bucket prompt prefills — the >= 2x acceptance bar —
    plus the long-context frontier (4k prompt, admittable ONLY with
    chunking, short TTFT while it folds);
  * spec-on/off interleaved A/B per cache lane: greedy ms/token, token
    equality, and the measured acceptance rate — the win/loss table
    behind `_MEASURED_SPEC_DEFAULTS` / `_MEASURED_CHUNK_DEFAULTS` in
    bigdl_tpu/generation/engine.py (the shipping defaults must agree
    with this file's verdicts).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_variants(quick: bool):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.nn.quantized import WeightOnlyInt8

    if quick:
        kw = dict(vocab_size=512, hidden_size=64, n_layer=2, n_head=4)
    else:
        kw = dict(vocab_size=32000, hidden_size=1024, n_layer=12, n_head=16)
    model = TransformerLM(max_len=1024, use_flash=False, **kw)
    params, _ = model.init((1, 16), rng=jax.random.PRNGKey(0))
    variants = [("fp32", model, params)]
    qm, qp = WeightOnlyInt8.from_float(
        model, params, compute_dtype=None if quick else jnp.bfloat16)
    variants.append(("weight_only", qm, qp))
    return kw["vocab_size"], variants


def run_phase(engine, vocab: int, phase: str, n: int, max_new: int) -> dict:
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, vocab, size=int(rng.randint(4, 14)))
               for _ in range(n)]
    t0 = time.perf_counter()
    if phase == "seq1":
        results = [engine.generate(p, max_new_tokens=max_new)
                   for p in prompts]
    else:
        futs = [engine.submit(p, max_new_tokens=max_new) for p in prompts]
        results = [f.result(timeout=600) for f in futs]
    wall = time.perf_counter() - t0
    snap = engine.metrics.snapshot()
    tokens = sum(r.meta["tokens"] for r in results)
    ttft = sorted(r.meta["ttft_ms"] for r in results)
    per_tok = sorted(r.meta["ms_per_token"] for r in results
                     if r.meta["ms_per_token"] is not None)

    def pct(xs, q):
        return round(xs[min(len(xs) - 1, int(q / 100 * len(xs)))], 3)

    return {
        "phase": phase, "requests": n, "max_new_tokens": max_new,
        "tokens": tokens,
        "ttft_p50_ms": pct(ttft, 50), "ttft_p99_ms": pct(ttft, 99),
        "ms_per_token_p50": pct(per_tok, 50),
        "ms_per_token_p99": pct(per_tok, 99),
        "prefill_p50_ms": snap["prefill_ms"]["p50"],
        "tokens_per_s": round(tokens / wall, 1),
        "compiled_executables": engine.compile_count(),
        "wall_s": round(wall, 2),
    }


def _bench_decode_impls(capacities, b=4, h=4, d=16, iters=200, rounds=7):
    """Interleaved A/B of the S=1 decode-attention lowerings at each KV
    capacity.  Alternating dense/ref inside every round cancels thermal
    and allocator drift; the per-round medians are what decides the
    `_MEASURED_DEFAULTS` shipping table."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn.attention import causal_mask
    from bigdl_tpu.ops.attention import dense_attention
    from bigdl_tpu.ops.decode_attention import decode_attention_ref

    rows = []
    for cap in capacities:
        rng = np.random.default_rng(cap)
        q = jnp.asarray(rng.normal(size=(b, h, d)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(b, cap, h, d)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(b, cap, h, d)).astype(np.float32))
        lengths = jnp.asarray(
            rng.integers(cap // 2, cap, size=(b,)).astype(np.int32))

        @jax.jit
        def dense(q, k, v, lengths):
            mask = jax.vmap(
                lambda off: causal_mask(1, k.shape[1], q_offset=off))(lengths)
            return dense_attention(q[:, None], k, v, mask=mask[:, None])

        @jax.jit
        def ref(q, k, v, lengths):
            return decode_attention_ref(q, k, v, lengths=lengths)

        fns = {"dense": dense, "ref": ref}
        for f in fns.values():  # warm outside the timed region
            jax.block_until_ready(f(q, k, v, lengths))
        samples = {name: [] for name in fns}
        for _ in range(rounds):
            for name, f in fns.items():  # interleave A/B every round
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = f(q, k, v, lengths)
                jax.block_until_ready(out)
                samples[name].append((time.perf_counter() - t0) / iters)
        med = {name: float(np.median(ts) * 1e6)
               for name, ts in samples.items()}
        winner = min(med, key=med.get)
        rows.append({
            "capacity": int(cap), "batch": b, "n_head": h, "head_dim": d,
            "dense_us": round(med["dense"], 2), "ref_us": round(med["ref"], 2),
            "winner": winner,
            "speedup_vs_dense": round(med["dense"] / med[winner], 3),
        })
        print(json.dumps(rows[-1]), flush=True)
    return rows


def _bench_kv_bytes():
    """Bytes per resident KV token, fp32 vs int8(+fp32 scales)."""
    import jax.numpy as jnp

    from bigdl_tpu.generation import BlockPool

    rows = []
    for tag, n_layer, n_head, head_dim in (("quick", 2, 4, 16),
                                           ("7b-ish", 32, 32, 128)):
        fp = BlockPool(n_layer, 2, 16, n_head, head_dim, jnp.float32)
        q8 = BlockPool(n_layer, 2, 16, n_head, head_dim, jnp.int8)
        ratio = fp.bytes_per_token() / q8.bytes_per_token()
        rows.append({
            "model": tag, "n_layer": n_layer, "n_head": n_head,
            "head_dim": head_dim,
            "fp32_bytes_per_token": fp.bytes_per_token(),
            "int8_bytes_per_token": q8.bytes_per_token(),
            "resident_tokens_per_byte_ratio": round(ratio, 3),
        })
        print(json.dumps(rows[-1]), flush=True)
    return rows


def _bench_engine_paged(vocab, variants):
    """Ring fp32 vs paged fp32 vs paged int8 through the REAL engine on a
    mixed-length workload.  The paged pool is sized BELOW ring worst case
    (oversubscribed) so admission backpressure and block recycling are in
    the measured path; fp32 paged tokens must equal ring bitwise."""
    import jax.numpy as jnp

    from bigdl_tpu.generation import GenerationConfig, GenerationEngine

    _, model, params = variants[0]
    buckets, slots, max_new = (32, 128), 4, 16
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, vocab, size=int(s))
               for s in rng.choice([5, 9, 24, 60, 100], size=16)]

    def run(label, **kw):
        # fresh CompileMonitor per engine: the previous engine already
        # marked generation/ steady, so this engine's own warmup would
        # otherwise read as false steady-state alarms
        from bigdl_tpu import obs
        obs.set_observability(metrics=True, compile_monitor=True)
        cfg = GenerationConfig(buckets=buckets, slots=slots, capacity=64,
                               max_new_tokens=max_new, **kw)
        eng = GenerationEngine(model, params, config=cfg)
        try:
            t0 = time.perf_counter()
            futs = [eng.submit(p) for p in prompts]
            toks = [f.result(timeout=600).tokens.tolist() for f in futs]
            wall = time.perf_counter() - t0
            row = {"engine": label, "kv_hbm_bytes": eng.kv_nbytes(),
                   "wall_s": round(wall, 2),
                   "compiled_executables": eng.compile_count(),
                   "tokens": sum(len(t) for t in toks)}
            if eng._pool is not None:
                assert eng._pool.blocks_free == eng._pool.n_allocatable
            return row, toks
        finally:
            eng.close()

    # worst case would be 2*4 + 8*4 + 1 = 41 blocks of 16; give 24 so the
    # pool is ~0.56x ring worst case and admission has to recycle
    rows = []
    ring_row, ring_toks = run("ring_fp32")
    rows.append(ring_row)
    for label, kw in (
            ("paged_fp32", dict(paged=True, kv_pool_blocks=24)),
            ("paged_int8", dict(paged=True, kv_pool_blocks=24,
                                cache_dtype=jnp.int8))):
        row, toks = run(label, **kw)
        row["hbm_vs_ring"] = round(row["kv_hbm_bytes"]
                                   / ring_row["kv_hbm_bytes"], 3)
        row["tokens_equal_ring"] = toks == ring_toks
        if label == "paged_fp32":
            assert toks == ring_toks, "paged fp32 lost bitwise parity"
        rows.append(row)
    for r in rows:
        print(json.dumps(r), flush=True)
    return rows


def _bench_chunked_ttft(vocab, variants, rounds=5, shorts_per_round=6):
    """Chunked-on/off interleaved A/B: admit a largest-bucket prompt,
    then a volley of short requests; their TTFT is the stall the
    one-shot prefill imposes.  Alternating engines inside every round
    cancels drift.  The frontier row folds a 4k prompt (admittable only
    with chunking on) and measures short TTFT while it chunks.

    The LM here is sized so the largest-bucket prefill costs ~100ms on
    the CPU backend — the regime chunked prefill targets; on the quick
    LM (hidden 64) a 512-token prefill is ~15ms, below the per-chunk
    scheduling overhead, and the A/B would measure loop overhead, not
    the admission stall."""
    import jax

    from bigdl_tpu import obs
    from bigdl_tpu.generation import GenerationEngine
    from bigdl_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab_size=vocab, hidden_size=256, n_layer=4,
                          n_head=8, max_len=1024, use_flash=False)
    params, _ = model.init((1, 16), rng=jax.random.PRNGKey(0))
    buckets, chunk = (32, 512), 32
    rng = np.random.RandomState(13)
    long_prompt = rng.randint(0, vocab, size=buckets[-1] - 16)
    shorts = [rng.randint(0, vocab, size=8) for _ in range(shorts_per_round)]

    def mk(ch):
        obs.set_observability(metrics=True, compile_monitor=True)
        return GenerationEngine(model, params, buckets=buckets, slots=4,
                                capacity=64, max_new_tokens=8,
                                temperature=0.0, prefill_chunk=ch)

    engines = {"chunk_off": mk(0), "chunk_on": mk(chunk)}
    ttfts = {name: [] for name in engines}
    try:
        for name, eng in engines.items():  # warm outside the timed region
            eng.generate(shorts[0], max_new_tokens=2)
        for _ in range(rounds):
            for name, eng in engines.items():  # interleave A/B every round
                f_long = eng.submit(long_prompt, max_new_tokens=8)
                futs = [eng.submit(p, max_new_tokens=2) for p in shorts]
                ttfts[name] += [f.result(timeout=600).meta["ttft_ms"]
                                for f in futs]
                f_long.result(timeout=600)

        def pct(xs, q):
            xs = sorted(xs)
            return round(xs[min(len(xs) - 1, int(q / 100 * len(xs)))], 3)

        off_p99, on_p99 = pct(ttfts["chunk_off"], 99), pct(ttfts["chunk_on"], 99)
        row = {
            "workload": f"{len(long_prompt)}-token prompt + "
                        f"{shorts_per_round} short requests x {rounds}",
            "prefill_chunk": chunk,
            "short_ttft_p50_ms": {n: pct(t, 50) for n, t in ttfts.items()},
            "short_ttft_p99_ms": {"chunk_off": off_p99, "chunk_on": on_p99},
            "p99_stall_cut": round(off_p99 / max(on_p99, 1e-9), 2),
            "winner": "chunk_on" if on_p99 < off_p99 else "chunk_off",
        }
        print(json.dumps(row), flush=True)

        # long-context frontier: 4k prompt, no unchunked baseline EXISTS
        eng = engines["chunk_on"]
        frontier = rng.randint(0, vocab, size=4096)
        try:
            engines["chunk_off"].submit(frontier)
            baseline = "admitted (unexpected)"
        except ValueError:
            baseline = "rejected at submit (prompt > largest bucket)"
        f_long = eng.submit(frontier, max_new_tokens=8)
        futs = [eng.submit(p, max_new_tokens=2) for p in shorts]
        fr_ttft = [f.result(timeout=600).meta["ttft_ms"] for f in futs]
        f_long.result(timeout=600)
        snap = eng.metrics.snapshot()
        frontier_row = {
            "frontier_prompt_tokens": 4096, "prefill_chunk": chunk,
            "chunk_off_baseline": baseline,
            "short_ttft_p50_ms": pct(fr_ttft, 50),
            "short_ttft_p99_ms": pct(fr_ttft, 99),
            "prefill_chunks": snap["prefill_chunks"],
            "ttft_under_long_prefill_p99_ms":
                snap["ttft_under_long_prefill_ms"]["p99"],
        }
        print(json.dumps(frontier_row), flush=True)
        return row, frontier_row
    finally:
        for eng in engines.values():
            eng.close()


def _bench_spec_ab(vocab, variants, n_requests=8, rounds=5):
    """Spec-on/off interleaved A/B per cache lane: greedy ms/token with
    and without the draft-verify lane, token equality (the distribution
    bar), and the measured acceptance rate.  The verdict — ship only
    where spec-on wins — is what `_MEASURED_SPEC_DEFAULTS` encodes."""
    import jax

    from bigdl_tpu import obs
    from bigdl_tpu.generation import GenerationEngine
    from bigdl_tpu.models.transformer import TransformerLM

    _, model, params = variants[0]
    draft = TransformerLM(vocab_size=vocab, hidden_size=64, n_layer=1,
                          n_head=4, max_len=1024, use_flash=False)
    dparams, _ = draft.init((1, 16), rng=jax.random.PRNGKey(1))
    rng = np.random.RandomState(17)
    prompts = [rng.randint(0, vocab, size=int(rng.randint(4, 24)))
               for _ in range(n_requests)]

    rows = []
    for lane, lane_kw in (("ring", {}),
                          ("paged", dict(paged=True, kv_block_size=16))):
        def mk(spec):
            obs.set_observability(metrics=True, compile_monitor=True)
            kw = dict(lane_kw)
            if spec:
                kw.update(spec_decode=True, spec_k=4, draft_model=draft,
                          draft_params=dparams)
            # 24-token prompts + 24 new tokens fit the 64 bucket: no
            # ring wrap, so spec-on/off equality is exact greedy parity
            return GenerationEngine(model, params, buckets=(64, 128),
                                    slots=4, capacity=64,
                                    max_new_tokens=24, temperature=0.0,
                                    **kw)

        engines = {"spec_off": mk(False), "spec_on": mk(True)}
        samples = {name: [] for name in engines}
        toks = {}
        try:
            for name, eng in engines.items():  # warm outside timed region
                eng.generate(prompts[0], max_new_tokens=2)
            for _ in range(rounds):
                for name, eng in engines.items():  # interleave every round
                    t0 = time.perf_counter()
                    futs = [eng.submit(p) for p in prompts]
                    out = [f.result(timeout=600).tokens.tolist()
                           for f in futs]
                    wall = time.perf_counter() - t0
                    toks[name] = out
                    n_tok = sum(len(t) for t in out)
                    samples[name].append(wall * 1e3 / n_tok)
            med = {n: float(np.median(s)) for n, s in samples.items()}
            snap = engines["spec_on"].metrics.snapshot()
            winner = min(med, key=med.get)
            rows.append({
                "lane": lane,
                "spec_off_ms_per_token": round(med["spec_off"], 3),
                "spec_on_ms_per_token": round(med["spec_on"], 3),
                "speedup_spec_on": round(med["spec_off"] / med["spec_on"], 3),
                "accept_rate": snap["spec_accept_rate"],
                "spec_rounds": snap["spec_rounds"],
                "draft_steps": snap["draft_steps"],
                "tokens_equal": toks["spec_on"] == toks["spec_off"],
                "winner": winner,
            })
            assert rows[-1]["tokens_equal"], \
                f"{lane}: spec-on greedy diverged from spec-off"
            print(json.dumps(rows[-1]), flush=True)
        finally:
            for eng in engines.values():
                eng.close()
    return rows


def _bench_prefix_ab(n_requests=8, rounds=5, prefix_len=1024):
    """Interleaved shared-prefix A/B (ISSUE 18): N concurrent requests
    share a 1k-token system prompt through an oversubscribed paged pool
    with chunked prefill, prefix cache ON vs OFF.  Reports p50 TTFT,
    prefill chunk count, cold prefill tokens (prompt tokens actually
    folded), peak resident-tokens-per-HBM-byte from `kv_sharing()`, and
    the fp32 greedy parity bit.  The verdict feeds the
    `_MEASURED_PREFIX_DEFAULTS` comment in generation/engine.py."""
    import jax

    from bigdl_tpu import obs
    from bigdl_tpu.generation import GenerationEngine
    from bigdl_tpu.models.transformer import TransformerLM

    vocab = 512
    model = TransformerLM(vocab_size=vocab, hidden_size=64, n_layer=2,
                          n_head=4, max_len=2048, use_flash=False)
    params, _ = model.init((1, 16), rng=jax.random.PRNGKey(0))
    rng = np.random.RandomState(23)
    head = rng.randint(0, vocab, size=prefix_len).tolist()
    prompts = [head + rng.randint(0, vocab, size=int(k)).tolist()
               for k in rng.randint(4, 17, size=n_requests)]
    prompt_tokens = sum(len(p) for p in prompts)

    def mk(on):
        obs.set_observability(metrics=True, compile_monitor=False)
        # pool of 160 blocks is oversubscribed: a cold request needs 66,
        # so at most 2 of the 4 slots can fold cold concurrently — warm
        # admissions reserve only their ~6 cold-suffix blocks and all 4
        # slots run, which is the sharing effect the A/B measures
        return GenerationEngine(
            model, params, buckets=(1152,), slots=4,
            capacity=n_requests + 4, max_new_tokens=16, temperature=0.0,
            paged=True, kv_block_size=16, kv_pool_blocks=160,
            prefill_chunk=64, prefix_cache=on)

    engines = {"off": mk(False), "on": mk(True)}
    ttft = {k: [] for k in engines}
    cold_tokens = {k: [] for k in engines}
    chunks = {k: [] for k in engines}
    density = {k: 0.0 for k in engines}
    toks = {}
    try:
        for eng in engines.values():  # warm: compile + populate store
            for f in [eng.submit(p) for p in prompts]:
                f.result(timeout=600)
        for _ in range(rounds):
            for name, eng in engines.items():  # interleave every round
                pre = eng.metrics.snapshot()
                futs = [eng.submit(p) for p in prompts]
                while not all(f.done() for f in futs):
                    sh = eng.kv_sharing()
                    if sh and sh["unique_bytes"]:
                        density[name] = max(
                            density[name],
                            sh["resident_tokens"] / sh["unique_bytes"])
                    time.sleep(0.001)
                res = [f.result(timeout=600) for f in futs]
                toks[name] = [r.tokens.tolist() for r in res]
                post = eng.metrics.snapshot()
                ttft[name].append(float(np.median(
                    [r.meta["ttft_ms"] for r in res])))
                chunks[name].append(
                    post["prefill_chunks"] - pre["prefill_chunks"])
                cold_tokens[name].append(
                    prompt_tokens - (post["prefix_tokens_reused"]
                                     - pre["prefix_tokens_reused"]))
        snap_on = engines["on"].metrics.snapshot()
        med = lambda xs: float(np.median(xs))  # noqa: E731
        row = {
            "requests": n_requests, "prefix_len": prefix_len,
            "rounds": rounds, "buckets": [1152], "slots": 4,
            "prefill_chunk": 64, "kv_block_size": 16,
            "kv_pool_blocks": 160,
            "ttft_p50_ms_off": round(med(ttft["off"]), 3),
            "ttft_p50_ms_on": round(med(ttft["on"]), 3),
            "ttft_p50_cut": round(med(ttft["off"]) / med(ttft["on"]), 3),
            "prefill_chunks_off": med(chunks["off"]),
            "prefill_chunks_on": med(chunks["on"]),
            "cold_prefill_tokens_off": med(cold_tokens["off"]),
            "cold_prefill_tokens_on": med(cold_tokens["on"]),
            "cold_token_cut": round(
                med(cold_tokens["off"]) / max(1.0, med(cold_tokens["on"])),
                3),
            "resident_tokens_per_hbm_byte_off": density["off"],
            "resident_tokens_per_hbm_byte_on": density["on"],
            "density_gain": round(
                density["on"] / max(1e-12, density["off"]), 3),
            "prefix_hits": snap_on["prefix_hits"],
            "prefix_tokens_reused": snap_on["prefix_tokens_reused"],
            "tokens_equal_fp32": toks["on"] == toks["off"],
        }
        assert row["tokens_equal_fp32"], \
            "prefix-cache greedy diverged from the cold engine"
        assert row["cold_token_cut"] >= 2.0, \
            f"acceptance bar: cold prefill tokens cut only " \
            f"{row['cold_token_cut']}x (< 2x)"
        print(json.dumps(row), flush=True)
        return row
    finally:
        for eng in engines.values():
            eng.close()


def run_prefix_quick() -> None:
    import jax

    platform = jax.devices()[0].platform
    row = _bench_prefix_ab()
    wins = (row["ttft_p50_ms_on"] < row["ttft_p50_ms_off"]
            and row["cold_token_cut"] >= 2.0)
    out = {
        "platform": platform,
        "prefix_ab": row,
        "verdict": {
            # prefix caching rides chunked prefill, which ships as an
            # opt-in admission policy (_MEASURED_CHUNK_DEFAULTS == 0) —
            # so even a winning A/B keeps _MEASURED_PREFIX_DEFAULTS
            # off; the row above is the evidence for enabling it per
            # deployment (BIGDL_TPU_PREFIX_CACHE=1 with prefill_chunk
            # set)
            "prefix_default_on": False,
            "prefix_wins": wins,
            "note": ("shared-prefix traffic wins on TTFT, cold tokens "
                     "and HBM density; ships behind the chunked-prefill "
                     "opt-in (BIGDL_TPU_PREFIX_CACHE)" if wins else
                     "no win on this backend; ships off"),
        },
    }
    path = os.path.join(os.path.dirname(__file__), "results",
                        "prefix_quick.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"# wrote {path}")


def run_spec_quick(platform: str) -> None:
    vocab, variants = build_variants(True)
    chunk_row, frontier_row = _bench_chunked_ttft(vocab, variants)
    spec_rows = _bench_spec_ab(vocab, variants)
    spec_wins = all(r["winner"] == "spec_on" for r in spec_rows)
    out = {
        "platform": platform,
        "chunked_ttft_ab": chunk_row,
        "long_context_frontier": frontier_row,
        "spec_ab": spec_rows,
        "verdict": {
            # chunking is an admission-POLICY change (prompts beyond the
            # largest bucket become admittable), so even a winning A/B
            # ships opt-in: _MEASURED_CHUNK_DEFAULTS stays 0 and the p99
            # cut above is the evidence for turning it on per deployment
            "chunk_default": 0,
            "chunk_p99_stall_cut": chunk_row["p99_stall_cut"],
            "spec_default_on": spec_wins,
            "spec_note": ("spec-on wins; flip _MEASURED_SPEC_DEFAULTS"
                          if spec_wins else
                          "spec-on loses on this backend (draft cost + "
                          "acceptance too low); ships off by default"),
        },
    }
    path = os.path.join(os.path.dirname(__file__), "results",
                        "spec_quick.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"# wrote {path}")


def run_decode_quick() -> None:
    import jax

    platform = jax.devices()[0].platform
    out = {
        "platform": platform,
        "decode_attention_us": _bench_decode_impls((32, 128, 512)),
        "long_context_frontier_us": _bench_decode_impls(
            (1024, 4096), iters=50, rounds=5),
        "kv_bytes_per_token": _bench_kv_bytes(),
        "engine_paged_ab": _bench_engine_paged(*build_variants(True)),
    }
    path = os.path.join(os.path.dirname(__file__), "results",
                        "decode_quick.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"# wrote {path}")
    run_spec_quick(platform)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="2-layer hidden-64 LM, fewer requests (CPU-sized)")
    ap.add_argument("--decode-quick", action="store_true",
                    help="decode-attention A/B + paged/int8 KV evidence "
                         "(writes results/decode_quick.json)")
    ap.add_argument("--prefix-quick", action="store_true",
                    help="shared-prefix cache interleaved A/B "
                         "(writes results/prefix_quick.json)")
    ap.add_argument("--requests", type=int, default=None)
    args = ap.parse_args(argv)

    if args.decode_quick:
        run_decode_quick()
        return
    if args.prefix_quick:
        run_prefix_quick()
        return

    import jax

    from bigdl_tpu.generation import GenerationConfig, GenerationEngine

    platform = jax.devices()[0].platform
    n_seq = args.requests or (12 if args.quick else 32)
    max_new = 16 if args.quick else 64
    buckets = (32, 128) if args.quick else (128, 512)
    slots = 4 if args.quick else 8
    vocab, variants = build_variants(args.quick)

    rows = []
    for variant, module, params in variants:
        cfg = GenerationConfig(buckets=buckets, slots=slots,
                               capacity=256, max_new_tokens=max_new)
        engine = GenerationEngine(module, params, config=cfg)
        budget = 2 * len(buckets)
        try:
            for phase, n in (("seq1", n_seq), (f"burst{4 * slots}",
                                               4 * slots)):
                row = {"variant": variant, "platform": platform,
                       "buckets": list(buckets), "slots": slots,
                       **run_phase(engine, vocab, phase, n, max_new)}
                assert row["compiled_executables"] <= budget, row
                rows.append(row)
                print(json.dumps(row), flush=True)
        finally:
            engine.close()

    name = "generation_quick.json" if args.quick else "generation.json"
    out = os.path.join(os.path.dirname(__file__), "results", name)
    with open(out, "w") as f:
        json.dump(rows, f, indent=1)
    print(f"# wrote {out}")


if __name__ == "__main__":
    main()
