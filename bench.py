"""Benchmark: ResNet-50 ImageNet-shape training throughput (images/sec/chip).

The reference's measurement harness is DistriOptimizerPerf
(models/utils/DistriOptimizerPerf.scala:32-86): synthetic ImageNet-shaped
input, throughput = records / iteration wall time
(optim/DistriOptimizer.scala:402-407).  This is the same measurement on one
TPU chip: full train step (fwd+bwd+SGD-momentum update+BN stats), bf16
compute / fp32 params.

vs_baseline: BigDL publishes no absolute throughput numbers
(BASELINE.json published: {}) and cannot run in this image (Scala/Spark,
no JVM), so the anchor is a MEASUREMENT-DERIVED stand-in: PyTorch CPU
(the mainstream MKL-kernel CPU framework) trains this exact ResNet-50
step at 0.865 img/s/core on THIS host's modern cores
(benchmarks/bench_cpu_torch_baseline.py: 1.73 img/s on the 2 cores this
cgroup exposes); scaled LINEARLY — generous to the baseline, intra-node
MKL scaling is sublinear — to a 44-core dual-socket node, the hardware
class of the whitepaper's scaling study (docs/docs/whitepaper.md:
160-164), that is ~38 img/s/node.  The older ~16 img/s Broadwell-era
estimate is consistent with it (2017 cores were ~half as fast).  Full
derivation + caveats: BENCH_APPENDIX.md "Baseline anchor".

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np

# 0.865 img/s/core measured (torch CPU, this host) x 44 cores, linear
XEON_NODE_BASELINE_IMG_S = 38.0

# Batch 256 is the measured throughput sweet spot on v5e (sweep table in
# BENCH_APPENDIX.md); the step is HBM-bandwidth-bound (XLA cost analysis:
# 77.1 GB/step -> 94.1 ms roofline at 819 GB/s; measured 103.1 ms = 91% of
# roofline) and remat was measured to INCREASE bytes (appendix), so the
# standard step is the shipped configuration.
BATCH = 256
IMAGE = 224
CLASSES = 1000
WARMUP = 3
ITERS = 40  # ±4% run-to-run variance through the device tunnel; more
# iterations tighten the estimate at ~10s extra wall time


def _watchdog(seconds: float):
    """A dead device tunnel hangs backend init forever; fail FAST with a
    parseable artifact instead (the r02 bench failure mode was a silent
    hang until the driver's own timeout)."""
    import os
    import threading

    def _fire():
        print(json.dumps({
            "metric": "resnet50_imagenet_train_throughput",
            "value": 0.0,
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,
            "error": f"device unreachable: no progress within {seconds:.0f}s "
                     f"(TPU tunnel down?)"}), flush=True)
        os._exit(3)

    t = threading.Timer(seconds, _fire)
    t.daemon = True
    t.start()
    return t


def run_real_data(data_dir: str):
    """b256 train step fed by the real host pipeline with upload overlap
    (device_put of batch i+1 is issued before batch i's step is awaited)."""
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet50
    from bigdl_tpu.optim import SGD

    model = resnet50(CLASSES)
    shape = (BATCH, IMAGE, IMAGE, 3)
    params, state, _ = model.build(jax.random.PRNGKey(0), shape)
    optim = SGD(learning_rate=0.1, momentum=0.9, dampening=0.0)
    opt_state = optim.init(params)
    criterion = nn.ClassNLLCriterion()

    def train_step(params, model_state, opt_state, x, y):
        def loss_fn(p):
            p16 = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16), p)
            out, new_state = model.apply(p16, model_state, x, training=True,
                                         rng=None)
            return criterion.forward(out.astype(jnp.float32), y), new_state

        (loss, new_model_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt_state = optim.step(grads, params, opt_state)
        return new_params, new_model_state, new_opt_state, loss

    from bigdl_tpu.vision.pipelines import imagenet_train_batches

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    batches = imagenet_train_batches(data_dir, BATCH, image=IMAGE,
                                     loop=True)

    def put(b):
        imgs, labels = b
        return (jax.device_put(jnp.asarray(imgs, jnp.bfloat16)),
                jax.device_put(jnp.asarray(labels, jnp.int32)))

    # compile + warmup on the first real batch
    x, y = put(next(batches))
    for _ in range(2):
        params, state, opt_state, loss = step(params, state, opt_state, x, y)
    float(jnp.sum(jax.tree_util.tree_leaves(params)[0].astype(jnp.float32)))

    iters = 12  # ~15 s of host pipeline at the measured 2-core rate
    nxt = put(next(batches))
    t0 = time.perf_counter()
    for _ in range(iters):
        x, y = nxt
        params, state, opt_state, loss = step(params, state, opt_state, x, y)
        # overlap: assemble+upload the next batch while the step runs
        nxt = put(next(batches))
    float(jnp.sum(jax.tree_util.tree_leaves(params)[0].astype(jnp.float32)))
    dt = time.perf_counter() - t0
    img_s = BATCH * iters / dt
    print(json.dumps({
        "metric": "resnet50_real_data_train_throughput",
        "value": round(img_s, 2),
        "unit": "images/sec/chip",
        "host_cores": __import__("os").cpu_count(),
        "note": "host-input-bound on this 2-core cgroup; see "
                "BENCH_APPENDIX input-pipeline section for the "
                "cores-per-chip math",
    }))


def main():
    watchdog = _watchdog(600.0)
    import sys

    if "--real-data" in sys.argv:
        data_dir = "data/imagenet_tfr"
        for i, a in enumerate(sys.argv):
            if a == "--real-data" and i + 1 < len(sys.argv) \
                    and not sys.argv[i + 1].startswith("-"):
                data_dir = sys.argv[i + 1]
        run_real_data(data_dir)
        watchdog.cancel()
        return
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet50
    from bigdl_tpu.optim import SGD

    import os

    # BENCH_FUSE_BN=1 measures the pallas conv+BN-stats variant
    # (nn.SpatialConvolutionBN; BENCH_APPENDIX.md's named lever)
    model = resnet50(CLASSES, fuse_bn=os.environ.get("BENCH_FUSE_BN") == "1")
    shape = (BATCH, IMAGE, IMAGE, 3)
    params, state, _ = model.build(jax.random.PRNGKey(0), shape)
    optim = SGD(learning_rate=0.1, momentum=0.9, dampening=0.0)
    opt_state = optim.init(params)
    criterion = nn.ClassNLLCriterion()

    def train_step(params, model_state, opt_state, x, y):
        def loss_fn(p):
            # bf16 compute, fp32 params/update (the MXU-native dtype policy;
            # replaces the reference's fp16 wire compression,
            # parameters/FP16CompressedTensor.scala).  BN running stats stay
            # fp32 end-to-end: activations are bf16 either way, and skipping
            # the per-step fp32<->bf16 state churn keeps the stats exact.
            p16 = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16), p)
            out, new_state = model.apply(p16, model_state, x, training=True,
                                         rng=None)
            return criterion.forward(out.astype(jnp.float32), y), new_state

        (loss, new_model_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt_state = optim.step(grads, params, opt_state)
        return new_params, new_model_state, new_opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rs = np.random.RandomState(0)
    # the host input pipeline delivers bf16 batches (the augmentation chain
    # ends in a cast); feeding fp32 would waste 2x input bandwidth
    x = jnp.asarray(rs.rand(*shape), jnp.bfloat16)
    y = jnp.asarray(rs.randint(0, CLASSES, BATCH))

    def sync(tree):
        # NOTE: through the remote-TPU tunnel block_until_ready returns
        # before execution finishes; a host readback is the only real sync
        leaf = jax.tree_util.tree_leaves(tree)[0]
        return float(jnp.sum(leaf.astype(jnp.float32)))

    for _ in range(WARMUP):
        params, state, opt_state, loss = step(params, state, opt_state, x, y)
    sync(params)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        params, state, opt_state, loss = step(params, state, opt_state, x, y)
    sync(params)  # depends on the final update: full chain executed
    dt = time.perf_counter() - t0

    watchdog.cancel()
    img_s = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "resnet50_imagenet_train_throughput",
        "value": round(img_s, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s / XEON_NODE_BASELINE_IMG_S, 2),
    }))


if __name__ == "__main__":
    main()
