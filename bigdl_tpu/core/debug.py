"""Numerics debugging switches.

Reference (survey §5.2): BigDL has NO race detection or sanitizers —
concurrency safety is by convention, and the survey's rebuild note is that
JAX's functional purity removes that bug class, with jax's nan/inf debug
checks as the analogue.  This module is that analogue: one switch for the
trace-level nan/inf checks plus an eager tree assertion for debugging.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def enable_nan_checks(enable: bool = True) -> None:
    """Re-run jitted computations de-optimized when a NaN appears and point
    at the producing primitive (jax_debug_nans)."""
    jax.config.update("jax_debug_nans", enable)


def enable_inf_checks(enable: bool = True) -> None:
    jax.config.update("jax_debug_infs", enable)


def assert_finite(tree: Any, name: str = "tree") -> None:
    """Host-side check that every leaf of a pytree is finite; raises
    FloatingPointError naming the offending path (eager debugging aid for
    params/grads between steps)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        if not np.isfinite(arr).all():
            keys = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                            for p in path)
            n_bad = int((~np.isfinite(arr)).sum())
            raise FloatingPointError(
                f"{name}/{keys}: {n_bad} non-finite value(s) "
                f"(shape {arr.shape})")


_callbacks_ok: bool = None  # probed lazily; some backends (tunneled TPU
# PJRT plugins) don't implement host send/recv callbacks


def _callbacks_supported() -> bool:
    global _callbacks_ok
    if _callbacks_ok is None:
        import threading

        # tap_finite is typically called while TRACING a jit function;
        # jit-under-trace inlines, so the probe must run with clean trace
        # state — trace state is thread-local, so probe on a fresh thread.
        def probe():
            global _callbacks_ok
            try:
                y = jax.jit(
                    lambda a: jax.debug.callback(lambda v: None, a) or a)(
                    jnp.zeros(()))
                float(np.asarray(y))  # host readback: surfaces async errors
                _callbacks_ok = True
            except Exception:
                _callbacks_ok = False

        t = threading.Thread(target=probe)
        t.start()
        t.join()
    return bool(_callbacks_ok)


def tap_finite(x: jnp.ndarray, name: str = "value") -> jnp.ndarray:
    """Identity usable INSIDE jit that host-prints a warning when the
    tensor contains non-finite values (jax.debug.callback — does not
    sync).  Degrades to a plain identity on backends without host
    callbacks (e.g. tunneled TPU plugins)."""
    if not _callbacks_supported():
        return x

    def cb(ok, count):
        if not ok:
            print(f"[bigdl_tpu.debug] {name}: {int(count)} non-finite value(s)")

    finite = jnp.isfinite(x)
    jax.debug.callback(cb, jnp.all(finite), jnp.sum(~finite))
    return x


def check_gradients(module, input_shape, *, rng=None, eps: float = 1e-3,
                    rtol: float = 1e-2, atol: float = 1e-4,
                    n_probe: int = 5, criterion=None, target=None,
                    seed: int = 0):
    """Numeric (central-difference) vs autodiff gradient check for a module
    — the analogue of the reference's test-side GradientChecker
    (spark/dl test utils, used across its nn specs).

    Checks d(loss)/d(param) on `n_probe` randomly chosen parameter scalars
    per leaf, where loss = criterion(module(x), target) (defaults to
    sum-of-squares of the output).  Returns the max relative error;
    raises AssertionError beyond (rtol, atol).  Perturbations keep each
    leaf's own dtype (enable jax_enable_x64 and tighten eps for fp64-grade
    checks); non-floating leaves are skipped.
    """
    if rng is None:
        rng = jax.random.PRNGKey(seed)
    k_build, k_x = jax.random.split(rng)
    params, state, _ = module.build(k_build, input_shape)
    x = jax.random.normal(k_x, input_shape)

    def loss_fn(p):
        # full-precision matmuls INSIDE the traced function: on TPU the
        # default fast (bf16-pass) precision injects noise larger than the
        # eps-sized central differences.  (A `with` block around jax.jit
        # would be inert — tracing happens lazily at the first call.)
        with jax.default_matmul_precision("highest"):
            y, _ = module.apply(p, state, x, training=False)
            if criterion is not None:
                return criterion.forward(y, target)
            leaves = jax.tree_util.tree_leaves(y)
            return sum(jnp.sum(jnp.square(leaf)) for leaf in leaves) * 0.5

    loss_jit = jax.jit(loss_fn)  # one compile; reused 2*n_probe*leaves times
    auto = jax.grad(loss_fn)(params)
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = jax.tree_util.tree_leaves(auto)
    rs = np.random.RandomState(seed)
    worst = 0.0
    for li, (leaf0, g) in enumerate(zip(flat_p, flat_g)):
        dtype = np.asarray(leaf0).dtype
        if leaf0.size == 0 or not np.issubdtype(dtype, np.floating):
            continue
        leaf = np.asarray(leaf0, np.float64)
        for idx in rs.choice(leaf.size, min(n_probe, leaf.size), replace=False):
            loc = np.unravel_index(idx, leaf.shape)

            def perturbed(delta):
                pl = leaf.copy()
                pl[loc] += delta
                flat2 = list(flat_p)
                flat2[li] = jnp.asarray(pl, dtype)
                return float(loss_jit(jax.tree_util.tree_unflatten(treedef, flat2)))

            numeric = (perturbed(eps) - perturbed(-eps)) / (2 * eps)
            analytic = float(np.asarray(g)[loc])
            err = abs(numeric - analytic) / max(abs(numeric), abs(analytic), atol / rtol)
            worst = max(worst, err)
            if err > rtol and abs(numeric - analytic) > atol:
                raise AssertionError(
                    f"gradient mismatch at leaf {li} {loc}: "
                    f"numeric {numeric:.6g} vs autodiff {analytic:.6g} "
                    f"(rel err {err:.3g})")
    return worst
