"""Typed runtime configuration.

BigDL scatters configuration across `bigdl.*` Java system properties,
SparkConf injection, and per-model scopt parsers (reference:
utils/Engine.scala:190-260, survey §5.6).  Here all runtime knobs live in one
typed dataclass populated from environment variables with a single prefix,
so every subsystem reads the same source of truth.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

_PREFIX = "BIGDL_TPU_"


def _env(name: str, default: str) -> str:
    return os.environ.get(_PREFIX + name, default)


def _env_int(name: str, default: int) -> int:
    return int(_env(name, str(default)))


def _env_bool(name: str, default: bool) -> bool:
    return _env(name, str(default)).lower() in ("1", "true", "yes", "on")


def _env_float(name: str, default: float) -> float:
    return float(_env(name, str(default)))


@dataclasses.dataclass
class EngineConfig:
    """Runtime knobs, analogous to the `bigdl.*` property namespace.

    reference: utils/Engine.scala:190-260 (localMode, engineType, coreNumber,
    check.singleton), optim/DistriOptimizer.scala:856-857 (failure.retryTimes).
    """

    # Execution platform: "tpu", "cpu", "auto". "auto" takes whatever
    # jax.devices() offers (the analogue of EngineType MklBlas|MklDnn
    # selection, utils/Engine.scala:37-38 — on TPU there is one engine: XLA).
    platform: str = "auto"
    # Default compute dtype policy: "float32" or "bfloat16" (replaces BigDL's
    # fp16 wire compression, parameters/FP16CompressedTensor.scala — on TPU
    # bf16 is native and the compression layer disappears into dtype choice).
    compute_dtype: str = "float32"
    # Failure-restart budget for the training loop: up to
    # `failure_retry_times` restarts from the latest committed checkpoint,
    # with exponential backoff `backoff_base_s * 2^attempt` capped at
    # `failure_retry_interval_s` (reference: the unbounded retry of
    # optim/DistriOptimizer.scala:855-935, now bounded — see
    # bigdl_tpu/resilience).
    failure_retry_times: int = 5
    failure_retry_interval_s: int = 120
    backoff_base_s: float = 2.0
    # Checkpoint saves default to the AsyncCheckpointer (snapshot on
    # device, bounded background writer, atomic tmp->rename commit);
    # 0/false restores the synchronous in-loop save.  Multi-process runs
    # force the synchronous collective path regardless.
    ckpt_async: bool = True
    # Path polled by the PreemptionGuard: the file's existence requests a
    # clean preemption exit (final sync checkpoint + resumable marker) —
    # the test/orchestrator channel equivalent of SIGTERM.
    preempt_file: Optional[str] = None
    # Multi-host coordination (replaces Spark driver/executor bring-up).
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    # Logging
    log_level: str = "INFO"
    # Seed for the global RandomGenerator (utils/RandomGenerator.scala:50-56).
    seed: int = 1
    # Default mesh layout, e.g. "data=8,model=2" (all devices on the data
    # axis when unset); the launcher's --mesh flag exports this.
    mesh_spec: Optional[str] = None
    # Async driver depth: in-flight steps before the driver reads a loss
    # back.  Per-step readback cost ~= readback_latency / (depth/2)
    # (BENCH_APPENDIX "Trainer-loop gap attribution"); raise it on
    # high-latency links (remote tunnels), at the price of driver logs
    # trailing up to `depth` steps.  Deterministic triggers only; loss-
    # reading triggers (min_loss/max_score) force synchronous mode.
    async_depth: int = 32
    # Input-feed prefetch depth: batches the DeviceFeed worker stages on
    # device ahead of the step loop (host collate + H2D transfer overlap
    # in-flight compute).  Host memory bound: at most `feed_depth + 1`
    # assembled batches exist at once.  0 = synchronous staging (the
    # pre-feed loop).  See docs/training.md "Input feed & overlap".
    feed_depth: int = 2
    # Disaggregated input plane (dataset/readers.py): reader PROCESSES
    # that own batch assembly (decode/augment/stack) outside the trainer
    # process, feeding DeviceFeed through a sequence-numbered reorder
    # stage (batch order — and losses — stay bitwise-equal to in-thread
    # assembly).  0 = off (in-thread).  reader_autoscale lets the
    # stall-driven autoscaler grow/shrink within [1, reader_procs].
    # See docs/training.md "Disaggregated readers & autoscaling".
    reader_procs: int = 0
    reader_autoscale: bool = True
    # Numeric-divergence watchdog (bigdl_tpu.health): a device-side finite
    # check on loss + grad norm folded into the jitted step, with the
    # skip -> lr_backoff -> rollback -> abort policy ladder.  Off by
    # default: it adds one f32 to the step output and caps async_depth at
    # the watchdog's max_lag.  See docs/training.md "Numeric health".
    watchdog: bool = False
    # Restore-time per-leaf CRC32C verification of checkpoint files
    # against meta.json's integrity block (on by default — integrity is
    # opt-out; pre-integrity checkpoints load unverified either way).
    ckpt_verify: bool = True
    # Checkpoint writer layout: "chunked" (v2 — per-shard chunk files,
    # mesh descriptor + per-chunk CRCs in meta.json, elastic restore onto
    # a different topology, host memory bounded by one chunk) or
    # "monolithic" (v1 — one .npz per tree).  The reader accepts both.
    ckpt_layout: str = "chunked"

    def parse_mesh(self) -> Optional[dict]:
        if not self.mesh_spec:
            return None
        out = {}
        for part in self.mesh_spec.split(","):
            axis, sep, n = part.partition("=")
            axis = axis.strip()
            n = n.strip()
            # -1 means "whatever is left" (Engine.build_mesh infers it)
            if not sep or not axis or not (n.isdigit() or n == "-1"):
                raise ValueError(
                    f"bad mesh spec {self.mesh_spec!r} (BIGDL_TPU_MESH / "
                    f"--mesh): expected 'axis=N[,axis=N...]' (N an int or "
                    f"-1 for remainder), e.g. 'data=8,model=2'; offending "
                    f"part: {part!r}")
            out[axis] = int(n)
        return out

    @staticmethod
    def from_env() -> "EngineConfig":
        cfg = EngineConfig(
            platform=_env("PLATFORM", "auto"),
            compute_dtype=_env("COMPUTE_DTYPE", "float32"),
            failure_retry_times=_env_int("FAILURE_RETRY_TIMES", 5),
            failure_retry_interval_s=_env_int("FAILURE_RETRY_INTERVAL_S", 120),
            backoff_base_s=_env_float("BACKOFF_BASE_S", 2.0),
            ckpt_async=_env_bool("CKPT_ASYNC", True),
            preempt_file=os.environ.get(_PREFIX + "PREEMPT_FILE"),
            log_level=_env("LOG_LEVEL", "INFO"),
            seed=_env_int("SEED", 1),
            mesh_spec=os.environ.get(_PREFIX + "MESH"),
            async_depth=_env_int("ASYNC_DEPTH", 32),
            feed_depth=_env_int("FEED_DEPTH", 2),
            reader_procs=_env_int("READER_PROCS", 0),
            reader_autoscale=_env_bool("READER_AUTOSCALE", True),
            watchdog=_env_bool("WATCHDOG", False),
            ckpt_verify=_env_bool("CKPT_VERIFY", True),
            ckpt_layout=_env("CKPT_LAYOUT", "chunked"),
        )
        if _PREFIX + "COORDINATOR_ADDRESS" in os.environ:
            cfg.coordinator_address = os.environ[_PREFIX + "COORDINATOR_ADDRESS"]
            cfg.num_processes = _env_int("NUM_PROCESSES", 1)
            cfg.process_id = _env_int("PROCESS_ID", 0)
        return cfg
