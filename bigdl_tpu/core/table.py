"""Table — the heterogeneous activity container.

BigDL's `Activity` union is `Tensor | Table` where `Table` is a lua-style
1-indexed int/any-keyed map built with `T(...)` (reference:
nn/abstractnn/Activity.scala, utils/Table.scala).  Here a Table is a jax
pytree node, so it flows through jit/grad/vmap transparently; layers that
take/return multiple activities (ConcatTable, CAddTable, LSTM hidden state)
use it exactly where the reference uses Table.

Indexing is 1-based via `table[1]` to preserve reference call-site semantics,
while iteration order is insertion order.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator

import jax


class Table:
    """Ordered, 1-indexed container of activities. Registered as a pytree."""

    def __init__(self, *items: Any, **named: Any):
        self._dict: Dict[Any, Any] = {}
        for i, item in enumerate(items):
            self._dict[i + 1] = item
        self._dict.update(named)

    # -- mapping interface ------------------------------------------------
    def __getitem__(self, key: Any) -> Any:
        return self._dict[key]

    def __setitem__(self, key: Any, value: Any) -> None:
        self._dict[key] = value

    def __contains__(self, key: Any) -> bool:
        return key in self._dict

    def __len__(self) -> int:
        return len(self._dict)

    def __iter__(self) -> Iterator[Any]:
        return iter(self._dict.values())

    def keys(self):
        return self._dict.keys()

    def values(self):
        return self._dict.values()

    def items(self):
        return self._dict.items()

    def insert(self, value: Any) -> "Table":
        """Append at the next integer slot (reference Table.insert)."""
        i = 1
        while i in self._dict:
            i += 1
        self._dict[i] = value
        return self

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}: {v!r}" for k, v in self._dict.items())
        return f"T({inner})"

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Table) and self._dict.keys() == other._dict.keys() and all(
            _eq(self._dict[k], other._dict[k]) for k in self._dict
        )

    def __hash__(self):  # pytree nodes must not rely on hashing contents
        return id(self)


def _eq(a: Any, b: Any) -> bool:
    try:
        import numpy as np

        if hasattr(a, "shape") or hasattr(b, "shape"):
            return bool(np.array_equal(a, b))
    except Exception:
        pass
    return bool(a == b)


def T(*items: Any, **named: Any) -> Table:
    """Constructor matching the reference's `T(...)` (utils/Table.scala)."""
    return Table(*items, **named)


def _table_flatten(t: Table):
    keys = tuple(t._dict.keys())
    return tuple(t._dict[k] for k in keys), keys


def _table_unflatten(keys, children) -> Table:
    t = Table()
    for k, c in zip(keys, children):
        t._dict[k] = c
    return t


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)
