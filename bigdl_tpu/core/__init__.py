from bigdl_tpu.core.engine import Engine
from bigdl_tpu.core.random import RandomGenerator
from bigdl_tpu.core.table import Table, T

__all__ = ["Engine", "RandomGenerator", "Table", "T"]
