from bigdl_tpu.core.engine import Engine
from bigdl_tpu.core.random import RandomGenerator
from bigdl_tpu.core.table import Table, T
from bigdl_tpu.core.debug import (assert_finite, enable_inf_checks,
                                  enable_nan_checks, tap_finite)

__all__ = ["Engine", "RandomGenerator", "Table", "T",
           "assert_finite", "enable_inf_checks", "enable_nan_checks",
           "tap_finite"]
