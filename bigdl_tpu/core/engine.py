"""Engine — process/mesh bring-up for the TPU runtime.

BigDL's `Engine` singleton (reference: utils/Engine.scala:41) discovers
executor/core topology from SparkConf, owns thread pools, and binds MKL/OMP
affinity.  On TPU none of that exists: XLA owns intra-chip parallelism, and
inter-chip parallelism is expressed as a `jax.sharding.Mesh` over which
jitted programs are partitioned.  So this Engine's job is:

  * device discovery (the analogue of `sparkExecutorAndCore`,
    utils/Engine.scala:446-465),
  * multi-host coordination (`jax.distributed.initialize` replaces one Spark
    executor per node, survey §5.8),
  * mesh construction with named axes (data/model/sequence/pipeline/expert)
    laid out so collectives ride ICI before DCN,
  * the global config + RNG seed plumbing.

There are no thread pools to manage — `Engine.default`/`Engine.model`
(utils/Engine.scala:324-334) have no TPU equivalent because replica fan-out
happens inside one compiled program, not across JVM threads.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from bigdl_tpu.core.config import EngineConfig

logger = logging.getLogger("bigdl_tpu")

# Canonical mesh axis names, in the order they should be laid out over the
# device topology.  Data-parallel is outermost (maps to DCN across slices),
# model/tensor axes innermost (maps to ICI neighbours).
AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQUENCE = "sequence"
AXIS_PIPELINE = "pipeline"
AXIS_EXPERT = "expert"


class Engine:
    """Singleton runtime. Call `Engine.init()` once per process before use."""

    _lock = threading.Lock()
    _initialized = False
    _config: Optional[EngineConfig] = None
    _mesh: Optional[Mesh] = None

    @classmethod
    def init(
        cls,
        config: Optional[EngineConfig] = None,
        mesh_shape: Optional[Dict[str, int]] = None,
    ) -> None:
        """Bring up the runtime.

        The analogue of `Engine.init` (utils/Engine.scala:105): resolves the
        device topology and (optionally) joins a multi-host cluster.  Unlike
        the reference there is no per-executor re-init inside tasks
        (optim/DistriOptimizer.scala:581) — every process runs this once.
        """
        with cls._lock:
            if cls._initialized:
                return
            cfg = config or EngineConfig.from_env()
            logging.basicConfig(level=getattr(logging, cfg.log_level, logging.INFO))
            if cfg.coordinator_address is not None:
                # Multi-host bring-up: the moral equivalent of Spark executor
                # registration (survey §5.8 "one JAX process per TPU host
                # replaces one Spark executor per node").  Must run before ANY
                # backend-initializing jax call (including process_count), so
                # the only guard is the config itself.
                jax.distributed.initialize(
                    coordinator_address=cfg.coordinator_address,
                    num_processes=cfg.num_processes,
                    process_id=cfg.process_id,
                )
            cls._config = cfg
            cls._mesh = cls._build_mesh(mesh_shape or cfg.parse_mesh())
            cls._initialized = True
            logger.info(
                "Engine initialized: %d device(s) on platform %s, mesh %s",
                jax.device_count(),
                jax.devices()[0].platform,
                dict(zip(cls._mesh.axis_names, cls._mesh.devices.shape)),
            )

    @classmethod
    def reset(cls) -> None:
        """Tear down (test helper)."""
        with cls._lock:
            cls._initialized = False
            cls._config = None
            cls._mesh = None

    # ------------------------------------------------------------------
    # Topology
    # ------------------------------------------------------------------

    @classmethod
    def config(cls) -> EngineConfig:
        cls.init()
        assert cls._config is not None
        return cls._config

    @classmethod
    def node_number(cls) -> int:
        """Number of host processes (BigDL: executor count)."""
        return jax.process_count()

    @classmethod
    def core_number(cls) -> int:
        """Number of accelerator chips (BigDL: total cores across executors,
        utils/Engine.scala:446-465 — on TPU the unit of data parallelism is
        the chip, not the CPU core)."""
        return jax.device_count()

    @classmethod
    def mesh(cls) -> Mesh:
        cls.init()
        assert cls._mesh is not None
        return cls._mesh

    @classmethod
    def set_mesh(cls, mesh: Mesh) -> None:
        cls.init()
        cls._mesh = mesh

    # ------------------------------------------------------------------
    # Mesh construction
    # ------------------------------------------------------------------

    @staticmethod
    def _build_mesh(mesh_shape: Optional[Dict[str, int]]) -> Mesh:
        if mesh_shape is None:
            mesh_shape = {AXIS_DATA: jax.device_count()}
        return Engine.build_mesh(**mesh_shape)

    @staticmethod
    def build_multislice_mesh(devices: Optional[Sequence] = None,
                              slice_of=None, **axes: int) -> Mesh:
        """Multislice mesh recipe: the OUTERMOST axis (put `data` first)
        crosses slice boundaries — its collectives ride DCN — while every
        inner axis (`model`/`sequence`/...) stays WITHIN one slice so its
        collectives ride ICI.  This is the pod-scale layout the gradient
        all-reduce wants: one DCN hop per step on the data axis, all
        tensor-parallel traffic on ICI (survey §5.8 TPU-native note).

        `slice_of(device)` maps a device to its slice id (defaults to the
        device's `slice_index`, 0 when absent — single-slice devices
        degrade to plain `build_mesh`).  Raises when an inner axis would
        straddle a slice boundary.
        """
        pool = list(devices) if devices is not None else jax.devices()
        if slice_of is None:
            slice_of = lambda d: getattr(d, "slice_index", 0) or 0
        groups: Dict[int, list] = {}
        for d in pool:
            groups.setdefault(int(slice_of(d)), []).append(d)
        slice_sizes = {len(v) for v in groups.values()}
        if len(slice_sizes) != 1:
            raise ValueError(f"uneven slices: "
                             f"{ {k: len(v) for k, v in groups.items()} }")
        slice_size = slice_sizes.pop()
        names = list(axes.keys())
        sizes = list(axes.values())
        if -1 in sizes:
            known = int(np.prod([s for s in sizes if s != -1]))
            sizes[sizes.index(-1)] = len(pool) // known
        inner = int(np.prod(sizes[1:])) if len(sizes) > 1 else 1
        if slice_size % inner != 0:
            raise ValueError(
                f"inner axes {dict(zip(names[1:], sizes[1:]))} "
                f"(size {inner}) would straddle a slice of {slice_size} "
                f"devices — keep model/sequence axes within one slice "
                f"(ICI) and put the slice-crossing dimension on "
                f"{names[0]!r}")
        # slice-major device order => slice boundaries land on the
        # outermost axis when the array is reshaped to the mesh shape
        ordered = [d for k in sorted(groups) for d in groups[k]]
        if int(np.prod(sizes)) != len(ordered):
            raise ValueError(f"mesh {dict(zip(names, sizes))} != device "
                             f"count {len(ordered)}")
        dev_array = np.array(ordered).reshape(tuple(sizes))
        return Mesh(dev_array, tuple(names))

    @staticmethod
    def build_mesh(devices: Optional[Sequence] = None, **axes: int) -> Mesh:
        """Build a named-axis device mesh.

        Axis sizes must multiply to the device count (all devices, or the
        given `devices` subset); `-1` means "whatever is left".  Uses
        `mesh_utils.create_device_mesh` so that the innermost (rightmost)
        axes land on ICI-adjacent devices — put `model`/`sequence` axes last
        and `data` first so gradient allreduce crosses DCN only on the data
        axis.
        """
        names = list(axes.keys())
        sizes = list(axes.values())
        pool = list(devices) if devices is not None else jax.devices()
        n = len(pool)
        if sizes.count(-1) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if -1 in sizes:
            known = int(np.prod([s for s in sizes if s != -1])) if len(sizes) > 1 else 1
            if n % known != 0:
                raise ValueError(f"device count {n} not divisible by {known}")
            sizes[sizes.index(-1)] = n // known
        if int(np.prod(sizes)) != n:
            raise ValueError(f"mesh {dict(zip(names, sizes))} != device count {n}")
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(tuple(sizes), devices=pool)
        except Exception:  # pragma: no cover - non-uniform topologies
            dev_array = np.array(pool).reshape(tuple(sizes))
        return Mesh(dev_array, tuple(names))

    # ------------------------------------------------------------------
    # Virtual-device helpers (testing the multi-chip path on one host —
    # the analogue of BigDL testing BlockManager allreduce with
    # SparkContext("local[N]"), survey §4)
    # ------------------------------------------------------------------

    @staticmethod
    def force_host_device_count(n: int) -> None:
        """Must be called before jax backends initialize (e.g. in conftest)."""
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
