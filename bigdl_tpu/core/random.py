"""Deterministic RNG plumbing.

BigDL's `RandomGenerator` is a per-thread mersenne twister with a settable
global seed (reference: utils/RandomGenerator.scala:50-56).  JAX uses
counter-based threefry keys; this module provides the same "set one seed,
everything downstream is reproducible" ergonomics by owning a root key and
handing out deterministically derived subkeys (fold_in by purpose/name), so
per-replica/per-layer streams are independent without any mutable state on
device.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax


class RandomGenerator:
    """Process-global seed registry (functional keys underneath)."""

    _lock = threading.Lock()
    _seed: int = 1
    _counter: int = 0

    @classmethod
    def set_seed(cls, seed: int) -> None:
        with cls._lock:
            cls._seed = seed
            cls._counter = 0

    @classmethod
    def get_seed(cls) -> int:
        return cls._seed

    @classmethod
    def next_key(cls) -> jax.Array:
        """A fresh key; successive calls yield independent streams."""
        with cls._lock:
            cls._counter += 1
            c = cls._counter
        return jax.random.fold_in(jax.random.PRNGKey(cls._seed), c)

    @classmethod
    def key_for(cls, name: str, step: Optional[int] = None) -> jax.Array:
        """Deterministic named stream (e.g. 'dropout', 'shuffle').  Uses a
        stable hash (crc32), NOT python's salted hash(), so every process of
        a multi-host job derives the same key for the same name."""
        import zlib

        tag = zlib.crc32(name.encode()) & 0x7FFFFFFF
        key = jax.random.fold_in(jax.random.PRNGKey(cls._seed), tag)
        if step is not None:
            key = jax.random.fold_in(key, step)
        return key
