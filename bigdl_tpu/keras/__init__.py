"""Keras-1.2.2-compatible high-level API (TPU-native).

Reference: nn/keras/ (Scala Keras API, 71 files) and
pyspark/bigdl/nn/keras/ (Python mirror).  The reference maintains this as a
separate layer zoo wrapping bigdl layers; here Keras layers are thin
lazily-shaped adapters over bigdl_tpu.nn and the topologies reuse the
Optimizer/Predictor/Evaluator machinery directly — Python IS the host
language on TPU, so there is no Py4J split.
"""

from bigdl_tpu.keras.layers import (
    KerasLayer,
    Dense,
    Activation,
    Dropout,
    Flatten,
    Reshape,
    Convolution2D,
    MaxPooling2D,
    AveragePooling2D,
    GlobalAveragePooling2D,
    BatchNormalization,
    Embedding,
    LSTM,
    GRU,
    SimpleRNN,
    TimeDistributed,
    Convolution1D,
    Convolution3D,
    AtrousConvolution1D,
    AtrousConvolution2D,
    Deconvolution2D,
    SeparableConvolution2D,
    ConvLSTM2D,
    Bidirectional,
    MaxoutDense,
    ThresholdedReLU,
    LeakyReLU,
    ELU,
    PReLU,
    SReLU,
    LocallyConnected1D,
    LocallyConnected2D,
    Merge,
    MaxPooling1D,
    GlobalMaxPooling1D,
    GlobalMaxPooling2D,
    GlobalAveragePooling1D,
    AveragePooling1D,
    MaxPooling3D,
    AveragePooling3D,
    GlobalMaxPooling3D,
    GlobalAveragePooling3D,
    ZeroPadding1D,
    ZeroPadding2D,
    ZeroPadding3D,
    Cropping2D,
    Cropping1D,
    Cropping3D,
    UpSampling1D,
    UpSampling2D,
    Permute,
    RepeatVector,
    Highway,
    SpatialDropout1D,
    SpatialDropout2D,
    SpatialDropout3D,
)
from bigdl_tpu.keras.topology import Sequential, Model
from bigdl_tpu.keras.objectives import (
    CategoricalCrossEntropy,
    resolve_loss,
    resolve_optimizer,
    resolve_metrics,
)

Conv2D = Convolution2D  # keras-2 alias

__all__ = [
    "KerasLayer", "Dense", "Activation", "Dropout", "Flatten", "Reshape",
    "Convolution2D", "Conv2D", "MaxPooling2D", "AveragePooling2D",
    "GlobalAveragePooling2D", "BatchNormalization", "Embedding", "LSTM",
    "GRU", "SimpleRNN", "TimeDistributed", "Sequential", "Model",
    "Convolution1D", "MaxPooling1D", "GlobalMaxPooling1D",
    "GlobalMaxPooling2D", "GlobalAveragePooling1D", "ZeroPadding1D",
    "ZeroPadding2D", "Cropping2D", "UpSampling1D", "UpSampling2D",
    "Permute", "RepeatVector", "Highway", "SpatialDropout1D",
    "SpatialDropout2D", "SpatialDropout3D", "Cropping1D", "Cropping3D",
    "ZeroPadding3D", "AveragePooling1D", "MaxPooling3D", "AveragePooling3D",
    "GlobalMaxPooling3D", "GlobalAveragePooling3D", "Convolution3D",
    "AtrousConvolution1D", "AtrousConvolution2D", "Deconvolution2D",
    "SeparableConvolution2D", "ConvLSTM2D", "Bidirectional", "MaxoutDense",
    "ThresholdedReLU", "LeakyReLU", "ELU", "PReLU", "SReLU", "LocallyConnected1D", "LocallyConnected2D", "Merge",
    "CategoricalCrossEntropy", "resolve_loss", "resolve_optimizer",
    "resolve_metrics",
]
