"""Keras-style Model/Sequential with compile/fit/evaluate/predict.

Reference: nn/keras/Topology.scala:55-158 (KerasModel.compile/fit/evaluate/
predict over DataSet or RDD) and the Python mirror
(pyspark/bigdl/nn/keras/topology.py:82-105).

fit() drives the same LocalOptimizer/DistriOptimizer machinery the
low-level API uses (reference fit does exactly this: it builds an
Optimizer internally), so mesh sharding, checkpointing, and summaries all
apply.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.minibatch import MiniBatch
from bigdl_tpu.keras.objectives import (
    resolve_loss,
    resolve_metrics,
    resolve_optimizer,
)
from bigdl_tpu.optim import Optimizer, Predictor, Evaluator, Trigger, Loss
from bigdl_tpu.utils import TrainSummary, ValidationSummary


def _rows(x) -> int:
    return x[0].shape[0] if isinstance(x, (list, tuple)) else x.shape[0]


def _take(x, idx):
    """Row-slice an array or a LIST of arrays (keras multi-input x /
    multi-output y)."""
    if isinstance(x, (list, tuple)):
        return tuple(np.asarray(c[idx]) for c in x)
    return np.asarray(x[idx])


def _to_minibatches(x, y, batch_size: int) -> List[MiniBatch]:
    n = _rows(x)
    out = []
    for off in range(0, n, batch_size):
        sl = slice(off, off + batch_size)
        yi = None if y is None else _take(y, sl)
        out.append(MiniBatch(_take(x, sl), yi))
    return out


class _ListDataSet(DataSet):
    """Fixed pre-built batches (evaluation path — order is irrelevant)."""

    def __init__(self, batches: List[MiniBatch]):
        self.batches = batches

    def size(self) -> int:
        return sum(b.size() for b in self.batches)

    def data(self, train: bool):
        return iter(self.batches)


class _ArrayTrainDataSet(DataSet):
    """Training batches with a fresh seeded row permutation each epoch
    (the reference's DistributedDataSet shuffles per epoch,
    dataset/DataSet.scala:167)."""

    def __init__(self, x, y: np.ndarray, batch_size: int,
                 seed: int = 1):
        self.x, self.y = x, y
        self.batch_size = batch_size
        self.seed = seed
        self._epoch = 0

    def size(self) -> int:
        return _rows(self.x)

    def data(self, train: bool):
        if not train:
            return iter(_to_minibatches(self.x, self.y, self.batch_size))
        perm = np.random.RandomState(self.seed + self._epoch).permutation(
            _rows(self.x))
        self._epoch += 1
        return iter(_to_minibatches(_take(self.x, perm), _take(self.y, perm),
                                    self.batch_size))


class KerasTopology:
    """compile/fit/evaluate/predict mixin (reference: Topology.scala:55-158)."""

    def compile(self, optimizer: Union[str, Any], loss: Union[str, Any],
                metrics: Optional[Sequence[Any]] = None) -> None:
        self.optim_method = resolve_optimizer(optimizer)
        # multi-output functional Models (keras semantics,
        # nn/keras/Topology.scala:55-158): a LIST of losses pairs one per
        # output head; a single loss repeats across heads; totals sum
        n_out = len(getattr(self, "output_nodes", ()) or ()) or 1
        if isinstance(loss, (list, tuple)) and len(loss) != n_out:
            raise ValueError(f"{len(loss)} losses for {n_out} outputs")
        if isinstance(loss, (list, tuple)) or n_out > 1:
            from bigdl_tpu.nn.criterion import ParallelCriterion
            items = (list(loss) if isinstance(loss, (list, tuple))
                     else [loss] * n_out)
            pc = ParallelCriterion()
            for item in items:
                pc.add(resolve_loss(item))
            self.criterion = pc
        else:
            self.criterion = resolve_loss(loss)
        # keras semantics: the GENERIC 'accuracy'/'acc' string under
        # binary_crossentropy means elementwise binary accuracy; explicit
        # Top1Accuracy instances (or 'top1') are honored as requested
        from bigdl_tpu.nn.criterion import BCECriterion
        from bigdl_tpu.optim.validation import BinaryAccuracy, Loss, PerOutput

        def resolve_one(m, crit):
            # generic 'accuracy' under a BCE head = elementwise binary acc
            if (isinstance(m, str) and m.lower() in ("accuracy", "acc")
                    and isinstance(crit, BCECriterion)):
                return BinaryAccuracy()
            return resolve_metrics([m])[0]

        resolved = []
        if n_out > 1:
            # per-tensor metrics on multi-output Models (reference:
            # nn/keras/Topology.scala:55-158).  Two spec shapes:
            #   metrics=["accuracy", None]      one entry PER OUTPUT
            #     (length == n_out, with None / nested-list entries);
            #   metrics=["accuracy"]            flat list, applied to
            #     EVERY output (keras-1 semantics).
            # "loss"/Loss entries stay whole-model (the summed multi-head
            # criterion), never routed per head.
            ms = list(metrics or [])
            crits = getattr(self.criterion, "criteria",
                            [None] * n_out)
            per_output_spec = len(ms) == n_out and any(
                m is None or isinstance(m, (list, tuple)) for m in ms)

            def add(m, head):
                if isinstance(m, Loss) or m == "loss":
                    resolved.append(m if isinstance(m, Loss)
                                    else Loss(self.criterion))
                else:
                    resolved.append(
                        PerOutput(resolve_one(m, crits[head]), head))

            if per_output_spec:
                for i, spec in enumerate(ms):
                    if spec is None:
                        continue
                    for m in (spec if isinstance(spec, (list, tuple))
                              else [spec]):
                        add(m, i)
            else:
                for m in ms:
                    if isinstance(m, Loss) or m == "loss":
                        add(m, 0)
                    else:
                        for i in range(n_out):
                            add(m, i)
        else:
            for m in (metrics or []):
                resolved.append(resolve_one(m, self.criterion))
        self.metrics = resolved
        # a re-compile changes loss/metrics: drop cached compiled programs
        self._evaluator = None
        self._eval_methods = None
        self._predictor = None
        # keep any set_checkpoint/set_tensorboard made before compile()
        self._ckpt = getattr(self, "_ckpt", None)
        self._tb = getattr(self, "_tb", None)

    def set_checkpoint(self, path: str, trigger: Optional[Trigger] = None) -> None:
        self._ckpt = (path, trigger or Trigger.every_epoch())

    def set_tensorboard(self, log_dir: str, app_name: str) -> None:
        self._tb = (log_dir, app_name)

    def _require_compiled(self):
        if not hasattr(self, "optim_method"):
            raise RuntimeError("call compile(optimizer, loss[, metrics]) first")

    def fit(self, x: Union[np.ndarray, DataSet], y: Optional[np.ndarray] = None,
            batch_size: int = 32, nb_epoch: int = 10,
            validation_data: Optional[Tuple[np.ndarray, np.ndarray]] = None,
            mesh=None, sharding_rules=None,
            batch_partition=None) -> "KerasTopology":
        self._require_compiled()
        if isinstance(x, DataSet):
            dataset = x
        else:
            if y is None:
                raise ValueError("fit(x, y) needs labels unless x is a DataSet")
            if isinstance(x, (list, tuple)):  # keras multi-input x
                x = tuple(np.asarray(c) for c in x)
            if isinstance(y, (list, tuple)):  # keras multi-output y
                y = tuple(np.asarray(c) for c in y)
            # drop-last so the jitted train step sees one static batch shape
            n_full = (_rows(x) // batch_size) * batch_size
            if n_full == 0:
                raise ValueError(
                    f"fewer samples ({_rows(x)}) than batch_size ({batch_size})")
            dataset = _ArrayTrainDataSet(_take(x, slice(0, n_full)),
                                         _take(y, slice(0, n_full)),
                                         batch_size)
        opt = Optimizer(model=self, dataset=dataset, criterion=self.criterion,
                        end_trigger=Trigger.max_epoch(nb_epoch), mesh=mesh,
                        sharding_rules=sharding_rules,
                        batch_partition=batch_partition)
        opt.set_optim_method(self.optim_method)
        if validation_data is not None:
            vx, vy = validation_data
            val_methods = list(self.metrics) or [Loss(self.criterion)]
            opt.set_validation(Trigger.every_epoch(),
                               _ListDataSet(_to_minibatches(vx, vy, batch_size)),
                               val_methods)
        if self._ckpt is not None:
            opt.set_checkpoint(*self._ckpt)
        if self._tb is not None:
            log_dir, app = self._tb
            opt.set_train_summary(TrainSummary(log_dir, app))
            opt.set_val_summary(ValidationSummary(log_dir, app))
        opt.optimize()
        return self

    def evaluate(self, x: np.ndarray, y: np.ndarray,
                 batch_size: int = 32) -> List[Tuple[str, float]]:
        """Returns [(name, value)]: loss first, then compiled metrics."""
        self._require_compiled()
        if self.params is None:
            raise RuntimeError("model has no parameters; fit() or init() first")
        # cache the Evaluator AND the methods list (the Evaluator's jitted
        # step is keyed on the method objects) so repeated evaluate() calls
        # reuse one compiled program
        if getattr(self, "_evaluator", None) is None:
            self._evaluator = Evaluator(self)
            self._eval_methods = [Loss(self.criterion)] + list(self.metrics)
        methods = self._eval_methods
        results = self._evaluator.test(self.params, self.state,
                                       _ListDataSet(_to_minibatches(x, y, batch_size)),
                                       methods, batch_size=batch_size)
        return [(r.name, r.result()[0]) for r in results]

    def predict(self, x: np.ndarray, batch_size: int = 32) -> np.ndarray:
        if self.params is None:
            raise RuntimeError("model has no parameters; fit() or init() first")
        # cache the Predictor (and so its jitted forward), invalidated when
        # params OR state change identity (stale BN running stats otherwise)
        cached = getattr(self, "_predictor", None)
        if (cached is None or cached[0] is not self.params
                or cached[1] is not self.state or cached[2] != batch_size):
            self._predictor = (self.params, self.state, batch_size,
                               Predictor(self, self.params, self.state,
                                         batch_size=batch_size))
        return self._predictor[3].predict(x)

    def predict_classes(self, x: np.ndarray, batch_size: int = 32):
        y = self.predict(x, batch_size)
        if isinstance(y, list):  # multi-output: one argmax per head
            return [np.argmax(h, axis=-1) for h in y]
        return np.argmax(y, axis=-1)


# KerasTopology is first in the MRO so its evaluate() (metric evaluation,
# Keras semantics) wins over Module.evaluate() (eval-mode switch).
class Sequential(KerasTopology, nn.Sequential):
    """Keras-style Sequential (reference: nn/keras/Topology.scala Sequential)."""

    _serial_name = "keras.Sequential"


class Model(KerasTopology, nn.Graph):
    """Keras-style functional Model over a node DAG
    (reference: nn/keras/Topology.scala Model)."""

    _serial_name = "keras.Model"
