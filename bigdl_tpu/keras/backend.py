"""Run keras-1 code with this framework as the training backend.

Reference: pyspark/bigdl/keras/backend.py — `with_bigdl_backend(kmodel)`
wraps a LIVE, compiled keras-1.2.2 model: the definition converts through
DefinitionLoader, the weights through WeightLoader, the compiled
optimizer/loss/metrics through OptimConverter, and fit/evaluate/predict
then run on the BigDL engine with keras signatures.

Here the wrapper is DUCK-TYPED (keras 1.2.2 is dead software and not in
the environment): anything exposing `to_json()`, `layers` (each with
`.name`/`.get_weights()`), and the compiled `loss`/`optimizer`/`metrics`
attributes converts — which is exactly the surface a real keras-1 Model
object exposes.  fit/evaluate/predict keep the keras-1 signatures
(`nb_epoch`, `validation_data`) and delegate to the Keras-API topology
(`keras/topology.py`), i.e. the standard Optimizer/Evaluator/Predictor
stack on the TPU path.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from bigdl_tpu.keras.converter import (load_keras_weights,
                                       model_from_json_config)


def _scalar(v, default=None):
    if v is None:
        return default
    try:
        return float(np.asarray(v))
    except Exception:
        get = getattr(v, "get_value", None)
        if get is not None:
            return float(np.asarray(get()))
        raise


def to_bigdl_optim_method(koptim_method) -> Any:
    """Map a keras-1 optimizer OBJECT (duck-typed by class name + hyper
    attrs) to an OptimMethod.  Reference:
    pyspark/bigdl/keras/optimization.py OptimConverter.to_bigdl_optim_method."""
    from bigdl_tpu import optim

    name = type(koptim_method).__name__.lower()
    o = koptim_method
    lr = _scalar(getattr(o, "lr", None), 0.01)
    decay = _scalar(getattr(o, "decay", None), 0.0)
    if name == "sgd":
        return optim.SGD(
            learning_rate=lr, learning_rate_decay=decay,
            momentum=_scalar(getattr(o, "momentum", None), 0.0),
            dampening=0.0,
            nesterov=bool(getattr(o, "nesterov", False)))
    if name == "rmsprop":
        return optim.RMSprop(learning_rate=lr, learning_rate_decay=decay,
                             decay_rate=_scalar(getattr(o, "rho", None), 0.9),
                             epsilon=_scalar(getattr(o, "epsilon", None), 1e-8))
    if name == "adagrad":
        return optim.Adagrad(learning_rate=lr, learning_rate_decay=decay)
    if name == "adadelta":
        return optim.Adadelta(decay_rate=_scalar(getattr(o, "rho", None), 0.95),
                              epsilon=_scalar(getattr(o, "epsilon", None), 1e-8))
    if name == "adam":
        return optim.Adam(learning_rate=lr, learning_rate_decay=decay,
                          beta1=_scalar(getattr(o, "beta_1", None), 0.9),
                          beta2=_scalar(getattr(o, "beta_2", None), 0.999),
                          epsilon=_scalar(getattr(o, "epsilon", None), 1e-8))
    if name == "adamax":
        return optim.Adamax(learning_rate=lr,
                            beta1=_scalar(getattr(o, "beta_1", None), 0.9),
                            beta2=_scalar(getattr(o, "beta_2", None), 0.999))
    raise ValueError(f"unsupported keras optimizer {type(koptim_method).__name__!r}")


class KerasModelWrapper:
    """reference: pyspark/bigdl/keras/backend.py:21."""

    def __init__(self, kmodel, input_shape=None, seed: int = 0):
        import jax

        from bigdl_tpu import nn
        from bigdl_tpu.core.table import Table

        self.model = model_from_json_config(kmodel.to_json())
        shape = input_shape
        if shape is None:
            declared = getattr(self.model, "keras_batch_input_shapes", None)
            if declared is not None:
                shapes = [(1,) + tuple(s[1:]) for s in declared]
                shape = shapes[0] if len(shapes) == 1 else shapes
            else:
                first = self.model.children[next(iter(self.model.children))]
                shape = (1,) + tuple(first.keras_input_shape)
        multi = (isinstance(shape, (list, tuple)) and shape
                 and isinstance(shape[0], (list, tuple)))
        build_shape = Table(*[tuple(s) for s in shape]) if multi \
            else tuple(shape)
        params, state, _ = self.model.build(jax.random.PRNGKey(seed),
                                            build_shape)
        # weights from the live model (reference: WeightLoader)
        if isinstance(self.model, nn.Graph):
            for layer in kmodel.layers:
                ws = layer.get_weights()
                if not ws:
                    continue
                child = self.model.children[layer.name]
                params[layer.name], state[layer.name] = load_keras_weights(
                    child, params[layer.name], state.get(layer.name, {}),
                    [ws])
        else:
            groups = [layer.get_weights() for layer in kmodel.layers
                      if layer.get_weights()]
            if groups:
                params, state = load_keras_weights(self.model, params,
                                                   state, groups)
        self.model.params, self.model.state = params, state
        # compiled training config (reference: OptimConverter)
        loss = getattr(kmodel, "loss", None)
        if loss is not None:
            optimizer = getattr(kmodel, "optimizer", None)
            self.model.compile(
                to_bigdl_optim_method(optimizer) if optimizer is not None
                and not isinstance(optimizer, str) else (optimizer or "sgd"),
                loss, list(getattr(kmodel, "metrics", None) or []))

    @property
    def params(self):
        return self.model.params

    @property
    def state(self):
        return self.model.state

    def fit(self, x, y=None, batch_size: int = 32, nb_epoch: int = 10,
            validation_data=None, **kwargs):
        self.model.fit(x, y, batch_size=batch_size, nb_epoch=nb_epoch,
                       validation_data=validation_data, **kwargs)
        return self

    def evaluate(self, x, y, batch_size: int = 32):
        return self.model.evaluate(x, y, batch_size=batch_size)

    def predict(self, x, batch_size: Optional[int] = None):
        return self.model.predict(x, batch_size=batch_size or 32)

    def predict_classes(self, x, batch_size: int = 32):
        return self.model.predict_classes(x, batch_size=batch_size)


def with_bigdl_backend(kmodel, input_shape=None) -> KerasModelWrapper:
    """reference: pyspark/bigdl/keras/backend.py:178."""
    return KerasModelWrapper(kmodel, input_shape=input_shape)
