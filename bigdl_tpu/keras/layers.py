"""Keras-1.2.2-style layers.

Reference: nn/keras/ (71 files) — each Keras layer wraps a bigdl layer
behind Keras argument names, with shape inference provided by the
`KerasLayer` adapter (nn/keras/KerasLayer.scala:165).

Same design here: a KerasLayer is a Module whose inner nn layer is created
lazily at `build` time when the input shape is known (Keras layers don't
take input sizes; bigdl_tpu.nn layers do).  Image layout is NHWC
("tf" dim ordering in Keras-1 terms — the TPU-native choice; the
reference's Scala Keras API uses NCHW "th" ordering).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import Module

_ACTIVATIONS = {
    "relu": nn.ReLU,
    "tanh": nn.Tanh,
    "sigmoid": nn.Sigmoid,
    "softmax": nn.SoftMax,
    "log_softmax": nn.LogSoftMax,
    "softplus": nn.SoftPlus,
    "softsign": nn.SoftSign,
    "hard_sigmoid": nn.HardSigmoid,
    "linear": None,
    None: None,
}


def activation_layer(name: Optional[str]) -> Optional[Module]:
    if name not in _ACTIVATIONS:
        raise ValueError(f"unknown activation {name!r}; "
                         f"one of {sorted(k for k in _ACTIVATIONS if k)}")
    cls = _ACTIVATIONS[name]
    return cls() if cls is not None else None


class KerasLayer(Module):
    """Adapter: lazily constructs the inner nn layer from the input shape
    (reference: nn/keras/KerasLayer.scala:165)."""

    _serial_name: Optional[str] = None

    def __init__(self, input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        # Keras input_shape excludes the batch dim
        self.keras_input_shape = tuple(input_shape) if input_shape else None
        self.inner: Optional[Module] = None

    def _make(self, input_shape: Tuple[int, ...]) -> Module:
        raise NotImplementedError

    def _inner_for(self, input_shape) -> Module:
        if self.keras_input_shape is not None:
            declared = self.keras_input_shape
            actual = tuple(input_shape)[1:]  # drop batch dim
            if len(declared) != len(actual) or any(
                    d is not None and d != a for d, a in zip(declared, actual)):
                raise ValueError(
                    f"{self.name}: declared input_shape {declared} does not "
                    f"match data shape {actual} (batch dim excluded)")
        if self.inner is None:
            self.inner = self._make(tuple(input_shape))
        return self.inner

    def build(self, rng, input_shape):
        inner = self._inner_for(input_shape)
        return inner.build(rng, input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        if self.inner is None:
            raise RuntimeError(f"{self.name}: build() must run before apply()")
        return self.inner.apply(params, state, x, training=training, rng=rng)

    def output_shape(self, input_shape):
        return self._inner_for(input_shape).output_shape(input_shape)


def _with_activation(core: Module, activation: Optional[str]) -> Module:
    act = activation_layer(activation)
    if act is None:
        return core
    return nn.Sequential(core, act)


class Dense(KerasLayer):
    """reference: nn/keras/Dense.scala."""

    def __init__(self, output_dim: int, activation: Optional[str] = None,
                 bias: bool = True, input_dim: Optional[int] = None,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        if input_dim is not None and input_shape is None:
            input_shape = (input_dim,)
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.activation = activation
        self.bias = bias

    def _make(self, input_shape):
        return _with_activation(
            nn.Linear(input_shape[-1], self.output_dim, with_bias=self.bias),
            self.activation)


class Activation(KerasLayer):
    def __init__(self, activation: str,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.activation = activation

    def _make(self, input_shape):
        layer = activation_layer(self.activation)
        return layer if layer is not None else nn.Identity()


class Dropout(KerasLayer):
    def __init__(self, p: float, input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.p = p

    def _make(self, input_shape):
        return nn.Dropout(self.p)


class Flatten(KerasLayer):
    def _make(self, input_shape):
        return nn.Flatten()


class Reshape(KerasLayer):
    def __init__(self, target_shape: Sequence[int],
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.target_shape = tuple(target_shape)

    def _make(self, input_shape):
        return nn.Reshape(self.target_shape, batch_mode=True)


class Convolution2D(KerasLayer):
    """NHWC conv (Keras-1 'tf' ordering). reference: nn/keras/Convolution2D.scala."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None, border_mode: str = "valid",
                 subsample: Tuple[int, int] = (1, 1), bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = tuple(subsample)
        self.bias = bias

    def _make(self, input_shape):
        cin = input_shape[-1]
        if self.border_mode == "same":
            pad_h = pad_w = -1  # TF-SAME: out = ceil(n/s), asymmetric pad
        elif self.border_mode == "valid":
            pad_h = pad_w = 0
        else:
            raise ValueError(f"border_mode must be 'valid' or 'same', "
                             f"got {self.border_mode!r}")
        core = nn.SpatialConvolution(
            cin, self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], pad_w, pad_h,
            with_bias=self.bias)
        return _with_activation(core, self.activation)


class _Pooling2D(KerasLayer):
    def __init__(self, pool_size: Tuple[int, int] = (2, 2),
                 strides: Optional[Tuple[int, int]] = None,
                 border_mode: str = "valid",
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.pool_size = tuple(pool_size)
        self.strides = tuple(strides) if strides is not None else self.pool_size
        self.border_mode = border_mode

    def _pads(self):
        if self.border_mode == "same":
            return -1, -1  # TF-SAME: out = ceil(n/s), asymmetric pad
        return 0, 0


class MaxPooling2D(_Pooling2D):
    def _make(self, input_shape):
        pw, ph = self._pads()
        return nn.SpatialMaxPooling(self.pool_size[1], self.pool_size[0],
                                    self.strides[1], self.strides[0], pw, ph)


class AveragePooling2D(_Pooling2D):
    def _make(self, input_shape):
        pw, ph = self._pads()
        # TF/Keras 'same' avg-pool divides by the count of valid elements
        return nn.SpatialAveragePooling(self.pool_size[1], self.pool_size[0],
                                        self.strides[1], self.strides[0], pw, ph,
                                        count_include_pad=(self.border_mode != "same"))


class GlobalAveragePooling2D(KerasLayer):
    def _make(self, input_shape):
        return nn.GlobalAveragePooling2D()


class BatchNormalization(KerasLayer):
    """Spatial for 4-D input, plain for 2-D — resolved at build time."""

    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.epsilon = epsilon
        self.momentum = momentum

    def _make(self, input_shape):
        n_out = input_shape[-1]
        # Keras momentum is the running-average retain factor; bigdl's is the
        # update factor.
        mom = 1.0 - self.momentum
        if len(input_shape) == 4:
            return nn.SpatialBatchNormalization(n_out, eps=self.epsilon,
                                                momentum=mom)
        if len(input_shape) == 3:
            return nn.TemporalBatchNormalization(n_out, eps=self.epsilon,
                                                 momentum=mom)
        return nn.BatchNormalization(n_out, eps=self.epsilon, momentum=mom)


class Embedding(KerasLayer):
    def __init__(self, input_dim: int, output_dim: int,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def _make(self, input_shape):
        return nn.LookupTable(self.input_dim, self.output_dim)


class _Rnn(KerasLayer):
    def __init__(self, output_dim: int, return_sequences: bool = False,
                 activation: str = "tanh",
                 inner_activation: str = "hard_sigmoid",
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.return_sequences = return_sequences
        self.activation = activation
        self.inner_activation = inner_activation

    def _cell(self, input_size: int):
        raise NotImplementedError

    def _make(self, input_shape):
        _, t, f = input_shape
        rec = nn.Recurrent(self._cell(f))
        if self.return_sequences:
            return rec
        return nn.Sequential(rec, nn.Select(1, t - 1))


class LSTM(_Rnn):
    def _cell(self, input_size):
        return nn.LSTMCell(input_size, self.output_dim,
                           gate_activation=self.inner_activation,
                           activation=self.activation)


class GRU(_Rnn):
    """keras-1 GRU.  `reset_after` is an explicit constructor arg so the
    cell convention travels in the serialized spec: False (default) is the
    keras-1 semantics — reset gate applies BEFORE the hidden matmul
    (keras/layers/recurrent.py), so keras-1 GRU weights import bit-exactly;
    True is the torch/fused convention.  NOTE: specs saved before this arg
    existed were built reset_after=True and rebuild as False — reload those
    checkpoints with GRU(..., reset_after=True)."""

    def __init__(self, output_dim: int, return_sequences: bool = False,
                 activation: str = "tanh",
                 inner_activation: str = "hard_sigmoid",
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None, *,
                 reset_after: bool = False):
        super().__init__(output_dim, return_sequences, activation,
                         inner_activation, input_shape, name)
        self.reset_after = reset_after

    def _cell(self, input_size):
        return nn.GRUCell(input_size, self.output_dim,
                          reset_after=self.reset_after)


class SimpleRNN(_Rnn):
    def _cell(self, input_size):
        return nn.RnnCell(input_size, self.output_dim,
                          activation=self.activation)


class TimeDistributed(KerasLayer):
    """Wrap a Keras layer to apply per timestep."""

    def __init__(self, layer: KerasLayer,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.layer = layer

    def _make(self, input_shape):
        n, t = input_shape[0], input_shape[1]
        inner = self.layer._inner_for((n * t,) + tuple(input_shape[2:]))
        return nn.TimeDistributed(inner)


# serializer registration happens in bigdl_tpu/keras/__init__.py


class Convolution1D(KerasLayer):
    """1-D conv over (N, T, C). reference: nn/keras/Convolution1D.scala."""

    def __init__(self, nb_filter: int, filter_length: int,
                 activation: Optional[str] = None, subsample_length: int = 1,
                 bias: bool = True, input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.subsample_length = subsample_length
        self.bias = bias

    def _make(self, input_shape):
        core = nn.TemporalConvolution(input_shape[-1], self.nb_filter,
                                      self.filter_length, self.subsample_length,
                                      with_bias=self.bias)
        return _with_activation(core, self.activation)


class MaxPooling1D(KerasLayer):
    """reference: nn/keras/MaxPooling1D.scala."""

    def __init__(self, pool_length: int = 2, stride: Optional[int] = None,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.pool_length = pool_length
        self.stride = stride or pool_length

    def _make(self, input_shape):
        return nn.TemporalMaxPooling(self.pool_length, self.stride)


class GlobalMaxPooling1D(KerasLayer):
    """reference: nn/keras/GlobalMaxPooling1D.scala."""

    def _make(self, input_shape):
        return nn.Max(dimension=1)


class GlobalMaxPooling2D(KerasLayer):
    """reference: nn/keras/GlobalMaxPooling2D.scala."""

    def _make(self, input_shape):
        return nn.GlobalMaxPooling2D()


class GlobalAveragePooling1D(KerasLayer):
    """reference: nn/keras/GlobalAveragePooling1D.scala."""

    def _make(self, input_shape):
        return nn.Mean(dimension=1)


class ZeroPadding1D(KerasLayer):
    """reference: nn/keras/ZeroPadding1D.scala."""

    def __init__(self, padding: int = 1,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.padding = padding

    def _make(self, input_shape):
        return nn.Sequential(nn.Padding(1, -self.padding),
                             nn.Padding(1, self.padding))


class ZeroPadding2D(KerasLayer):
    """reference: nn/keras/ZeroPadding2D.scala ((top, bottom), (left, right))."""

    def __init__(self, padding: Sequence[int] = (1, 1),
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        p = tuple(padding)
        if len(p) == 2 and all(isinstance(v, (tuple, list)) for v in p):
            p = (p[0][0], p[0][1], p[1][0], p[1][1])  # ((t, b), (l, r))
        elif len(p) == 2:  # symmetric keras-1 form (pad_h, pad_w)
            p = (p[0], p[0], p[1], p[1])
        self.padding = p  # (top, bottom, left, right)

    def _make(self, input_shape):
        t, b, l, r = self.padding
        return nn.SpatialZeroPadding(l, r, t, b)


class Cropping2D(KerasLayer):
    """reference: nn/keras/Cropping2D.scala."""

    def __init__(self, cropping: Sequence[Sequence[int]] = ((0, 0), (0, 0)),
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.cropping = tuple(tuple(c) for c in cropping)

    def _make(self, input_shape):
        return nn.Cropping2D(self.cropping[0], self.cropping[1])


class UpSampling1D(KerasLayer):
    """reference: nn/keras/UpSampling1D.scala."""

    def __init__(self, length: int = 2,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.length = length

    def _make(self, input_shape):
        return nn.UpSampling1D(self.length)


class UpSampling2D(KerasLayer):
    """reference: nn/keras/UpSampling2D.scala."""

    def __init__(self, size: Sequence[int] = (2, 2),
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.size = tuple(size)

    def _make(self, input_shape):
        return nn.UpSampling2D(self.size)


class Permute(KerasLayer):
    """Permute non-batch dims; 1-based keras dims.
    reference: nn/keras/Permute.scala."""

    def __init__(self, dims: Sequence[int],
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.dims = tuple(dims)

    def _make(self, input_shape):
        perm = (0,) + self.dims  # keras dims are 1-based over non-batch
        swaps = []
        order = list(range(len(perm)))
        for i, want in enumerate(perm):
            j = order.index(want)
            if i != j:
                order[i], order[j] = order[j], order[i]
                swaps.append((i, j))
        return nn.Transpose(swaps)


class RepeatVector(KerasLayer):
    """(N, C) -> (N, n, C). reference: nn/keras/RepeatVector.scala."""

    def __init__(self, n: int, input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.n = n

    def _make(self, input_shape):
        return nn.Replicate(self.n, dim=1)


class Highway(KerasLayer):
    """reference: nn/keras/Highway.scala."""

    def __init__(self, activation: Optional[str] = "tanh",
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.activation = activation

    def _make(self, input_shape):
        return nn.Highway(input_shape[-1],
                          activation=activation_layer(self.activation))


class SpatialDropout1D(KerasLayer):
    """reference: nn/keras/SpatialDropout1D.scala."""

    def __init__(self, p: float = 0.5,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.p = p

    def _make(self, input_shape):
        return nn.SpatialDropout1D(self.p)


class SpatialDropout2D(SpatialDropout1D):
    """reference: nn/keras/SpatialDropout2D.scala."""

    def _make(self, input_shape):
        return nn.SpatialDropout2D(self.p)


class SpatialDropout3D(SpatialDropout1D):
    """reference: nn/keras/SpatialDropout3D.scala."""

    def _make(self, input_shape):
        return nn.SpatialDropout3D(self.p)


class MaxPooling3D(KerasLayer):
    """NDHWC volumetric max pool. reference: nn/keras/MaxPooling3D.scala."""

    def __init__(self, pool_size=(2, 2, 2), strides=None,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.pool_size = tuple(pool_size)
        self.strides = tuple(strides) if strides else self.pool_size

    def _make(self, input_shape):
        (kt, kh, kw), (dt, dh, dw) = self.pool_size, self.strides
        return nn.VolumetricMaxPooling(kt, kw, kh, dt, dw, dh)


class AveragePooling3D(MaxPooling3D):
    """reference: nn/keras/AveragePooling3D.scala."""

    def _make(self, input_shape):
        (kt, kh, kw), (dt, dh, dw) = self.pool_size, self.strides
        return nn.VolumetricAveragePooling(kt, kw, kh, dt, dw, dh)


class AveragePooling1D(KerasLayer):
    """reference: nn/keras/AveragePooling1D.scala.  Composed as a width-1
    2-D avg pool over (N, T, 1, C)."""

    def __init__(self, pool_length: int = 2, stride: Optional[int] = None,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.pool_length = pool_length
        self.stride = stride or pool_length

    def _make(self, input_shape):
        return nn.Sequential(
            nn.Unsqueeze(2),
            nn.SpatialAveragePooling(1, self.pool_length, 1, self.stride),
            nn.Squeeze(2))


class GlobalMaxPooling3D(KerasLayer):
    """reference: nn/keras/GlobalMaxPooling3D.scala."""

    def _make(self, input_shape):
        return nn.Sequential(nn.Max(1), nn.Max(1), nn.Max(1))


class GlobalAveragePooling3D(KerasLayer):
    """reference: nn/keras/GlobalAveragePooling3D.scala."""

    def _make(self, input_shape):
        return nn.Sequential(nn.Mean(1), nn.Mean(1), nn.Mean(1))


class Convolution3D(KerasLayer):
    """NDHWC volumetric conv. reference: nn/keras/Convolution3D.scala."""

    def __init__(self, nb_filter: int, kernel_dim1: int, kernel_dim2: int,
                 kernel_dim3: int, activation: Optional[str] = None,
                 border_mode: str = "valid", subsample=(1, 1, 1),
                 bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.kernel = (kernel_dim1, kernel_dim2, kernel_dim3)
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = tuple(subsample)
        self.bias = bias

    def _make(self, input_shape):
        cin = input_shape[-1]
        kt, kh, kw = self.kernel
        dt, dh, dw = self.subsample
        if self.border_mode == "same":
            pt = ph = pw = -1
        else:
            pt = ph = pw = 0
        core = nn.VolumetricConvolution(
            cin, self.nb_filter, kt, kw, kh, dt, dw, dh, pt, pw, ph,
            with_bias=self.bias)
        return _with_activation(core, self.activation)


class AtrousConvolution2D(KerasLayer):
    """Dilated conv. reference: nn/keras/AtrousConvolution2D.scala."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None,
                 subsample=(1, 1), atrous_rate=(1, 1),
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.subsample = tuple(subsample)
        self.atrous_rate = tuple(atrous_rate)

    def _make(self, input_shape):
        core = nn.SpatialDilatedConvolution(
            input_shape[-1], self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], 0, 0,
            self.atrous_rate[1], self.atrous_rate[0])
        return _with_activation(core, self.activation)


class AtrousConvolution1D(KerasLayer):
    """Dilated 1-D conv over (N, T, C), composed as a width-1 dilated 2-D
    conv. reference: nn/keras/AtrousConvolution1D.scala."""

    def __init__(self, nb_filter: int, filter_length: int,
                 activation: Optional[str] = None, subsample_length: int = 1,
                 atrous_rate: int = 1,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.subsample_length = subsample_length
        self.atrous_rate = atrous_rate

    def _make(self, input_shape):
        core = nn.Sequential(
            nn.Unsqueeze(2),
            nn.SpatialDilatedConvolution(
                input_shape[-1], self.nb_filter, 1, self.filter_length,
                1, self.subsample_length, 0, 0, 1, self.atrous_rate),
            nn.Squeeze(2))
        return _with_activation(core, self.activation)


class Deconvolution2D(KerasLayer):
    """Transposed conv. reference: nn/keras/Deconvolution2D.scala."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None, subsample=(1, 1),
                 bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.subsample = tuple(subsample)
        self.bias = bias

    def _make(self, input_shape):
        core = nn.SpatialFullConvolution(
            input_shape[-1], self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], with_bias=self.bias)
        return _with_activation(core, self.activation)


class SeparableConvolution2D(KerasLayer):
    """Depthwise + pointwise. reference: nn/keras/SeparableConvolution2D.scala."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None, border_mode: str = "valid",
                 subsample=(1, 1), depth_multiplier: int = 1,
                 bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = tuple(subsample)
        self.depth_multiplier = depth_multiplier
        self.bias = bias

    def _make(self, input_shape):
        pad = -1 if self.border_mode == "same" else 0
        core = nn.SpatialSeparableConvolution(
            input_shape[-1], self.nb_filter, self.depth_multiplier,
            self.nb_col, self.nb_row, self.subsample[1], self.subsample[0],
            pad, pad, with_bias=self.bias)
        return _with_activation(core, self.activation)


class ConvLSTM2D(KerasLayer):
    """Convolutional LSTM over (N, T, H, W, C).
    reference: nn/keras/ConvLSTM2D.scala (square kernels, stride 1,
    withPeephole=false — keras-1 ConvLSTM2D has no peepholes)."""

    def __init__(self, nb_filter: int, nb_kernel: int,
                 return_sequences: bool = False,
                 activation: str = "tanh",
                 inner_activation: str = "hard_sigmoid",
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.nb_kernel = nb_kernel
        self.return_sequences = return_sequences
        self.activation = activation
        self.inner_activation = inner_activation

    def _make(self, input_shape):
        _, t = input_shape[0], input_shape[1]
        cell = nn.ConvLSTMPeephole(input_shape[-1], self.nb_filter,
                                   self.nb_kernel, self.nb_kernel,
                                   with_peephole=False,
                                   gate_activation=self.inner_activation,
                                   activation=self.activation)
        rec = nn.Recurrent(cell)
        if self.return_sequences:
            return rec
        return nn.Sequential(rec, nn.Select(1, t - 1))


class Bidirectional(KerasLayer):
    """Run a recurrent Keras layer forward and backward, merging outputs.
    reference: nn/keras/Bidirectional.scala (merge modes concat/sum/mul/ave)."""

    def __init__(self, layer: "_Rnn", merge_mode: str = "concat",
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        assert merge_mode in ("concat", "sum", "mul", "ave")
        self.layer = layer
        self.merge_mode = merge_mode

    def _make(self, input_shape):
        _, t, f = input_shape
        return nn.BiRecurrent(self.layer._cell(f), self.layer._cell(f),
                              merge=self.merge_mode,
                              return_sequences=self.layer.return_sequences)


class Cropping1D(KerasLayer):
    """Crop (left, right) timesteps off (N, T, C).
    reference: nn/keras/Cropping1D.scala."""

    def __init__(self, cropping=(1, 1),
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.cropping = tuple(cropping)

    def _make(self, input_shape):
        t = input_shape[1]
        l, r = self.cropping
        return nn.Narrow(1, l, t - l - r)


class Cropping3D(KerasLayer):
    """reference: nn/keras/Cropping3D.scala."""

    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)),
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.cropping = tuple(tuple(c) for c in cropping)

    def _make(self, input_shape):
        return nn.Cropping3D(*self.cropping)


class ZeroPadding3D(KerasLayer):
    """reference: nn/keras/ZeroPadding3D.scala."""

    def __init__(self, padding=(1, 1, 1),
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.padding = tuple(padding)

    def _make(self, input_shape):
        return nn.VolumetricZeroPadding(*self.padding)


class MaxoutDense(KerasLayer):
    """Dense with maxout over nb_feature linear pieces: out_j = max_k
    (x W_jk + b_jk). reference: nn/keras/MaxoutDense.scala (wraps Maxout)."""

    def __init__(self, output_dim: int, nb_feature: int = 4,
                 bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.nb_feature = nb_feature
        self.bias = bias

    def _make(self, input_shape):
        return nn.Sequential(
            nn.Linear(input_shape[-1], self.output_dim * self.nb_feature,
                      with_bias=self.bias),
            nn.Reshape((self.nb_feature, self.output_dim)),
            nn.Max(1))


class ThresholdedReLU(KerasLayer):
    """x if x > theta else 0. reference: nn/keras/ThresholdedReLU.scala."""

    def __init__(self, theta: float = 1.0,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.theta = theta

    def _make(self, input_shape):
        return nn.Threshold(self.theta, 0.0)


class LocallyConnected2D(KerasLayer):
    """reference: nn/keras/LocallyConnected2D.scala."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None, subsample=(1, 1),
                 bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.subsample = tuple(subsample)
        self.bias = bias

    def _make(self, input_shape):
        _, h, w, c = input_shape
        core = nn.LocallyConnected2D(
            c, w, h, self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], with_bias=self.bias)
        return _with_activation(core, self.activation)


class LocallyConnected1D(KerasLayer):
    """reference: nn/keras/LocallyConnected1D.scala."""

    def __init__(self, nb_filter: int, filter_length: int,
                 activation: Optional[str] = None, subsample_length: int = 1,
                 bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.subsample_length = subsample_length
        self.bias = bias

    def _make(self, input_shape):
        _, t, c = input_shape
        core = nn.LocallyConnected1D(t, c, self.nb_filter, self.filter_length,
                                     self.subsample_length,
                                     with_bias=self.bias)
        return _with_activation(core, self.activation)


class Merge(KerasLayer):
    """Merge a list of branch layers applied to a Table of inputs.
    reference: nn/keras/Merge.scala (modes sum/mul/ave/max/concat/dot/cos).

    `Merge([l1, l2], mode)` consumes Table{x1, x2}: each branch processes
    its own input, then the mode combines the branch outputs."""

    def __init__(self, layers: Sequence[Module], mode: str = "sum",
                 concat_axis: int = -1,
                 input_shape: Optional[Sequence[Sequence[int]]] = None,
                 name: Optional[str] = None):
        super().__init__(None, name)
        assert mode in ("sum", "mul", "ave", "max", "concat", "dot", "cosine")
        self.branches = list(layers)
        self.mode = mode
        self.concat_axis = concat_axis
        # per-branch declared shapes (batch dim excluded), validated in _make
        self.branch_input_shapes = (
            [tuple(s) for s in input_shape] if input_shape else None)

    def _make(self, input_shape):
        if self.branch_input_shapes is not None:
            actual = [tuple(s)[1:] for s in input_shape]
            if actual != self.branch_input_shapes:
                raise ValueError(
                    f"{self.name}: declared branch shapes "
                    f"{self.branch_input_shapes} do not match data shapes "
                    f"{actual} (batch dim excluded)")
        combine = {
            "sum": nn.CAddTable(), "mul": nn.CMulTable(),
            "ave": nn.CAveTable(), "max": nn.CMaxTable(),
            "concat": nn.JoinTable(self.concat_axis),
            "dot": nn.DotProduct(), "cosine": nn.CosineDistance(),
        }[self.mode]
        return nn.Sequential(nn.ParallelTable(*self.branches), combine)


class LeakyReLU(KerasLayer):
    """Advanced activation. reference: nn/keras/LeakyReLU.scala."""

    def __init__(self, alpha: float = 0.3,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.alpha = alpha

    def _make(self, input_shape):
        return nn.LeakyReLU(self.alpha)


class ELU(KerasLayer):
    """Advanced activation. reference: nn/keras/ELU.scala."""

    def __init__(self, alpha: float = 1.0,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.alpha = alpha

    def _make(self, input_shape):
        return nn.ELU(self.alpha)


class PReLU(KerasLayer):
    """Advanced activation: one learned slope per ELEMENT over the feature
    shape (keras-1 PReLU semantics).  reference: nn/keras/PReLU.scala."""

    def _make(self, input_shape):
        return nn.PReLU(shape=tuple(input_shape[1:]))


class SReLU(KerasLayer):
    """S-shaped ReLU with learned per-element params over the full feature
    shape (keras-1 default), optionally shared along `shared_axes`.
    reference: nn/keras/SReLU.scala (SharedAxes default null)."""

    def __init__(self, shared_axes: Optional[Sequence[int]] = None,
                 input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        super().__init__(input_shape, name)
        self.shared_axes = tuple(shared_axes) if shared_axes else None

    def _make(self, input_shape):
        return nn.SReLU(tuple(input_shape[1:]), share_axes=self.shared_axes)
