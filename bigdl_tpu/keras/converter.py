"""Keras-1.2.2 model-definition converter.

Reference: pyspark/bigdl/keras/converter.py (1759 LoC — loads real Keras
1.2.2 models into BigDL via definition + weight conversion).  Here
`model_from_json_config` rebuilds a `bigdl_tpu.keras.Sequential` from the
JSON produced by Keras-1 `model.to_json()`, and `load_keras_weights`
applies a `get_weights()`-style weight list (delegating layout fixes to
`bigdl_tpu.utils.interop.import_keras_weights`).

Supported layer classes mirror the reference converter's core set: Dense,
Activation, Dropout, Flatten, Reshape, Convolution2D, MaxPooling2D,
AveragePooling2D, GlobalAveragePooling2D, BatchNormalization, Embedding,
LSTM, GRU, SimpleRNN, TimeDistributed(Dense).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

from bigdl_tpu.keras import layers as KL
from bigdl_tpu.keras.topology import Sequential


def _input_shape_of(cfg: Dict[str, Any]) -> Optional[Sequence[int]]:
    shape = cfg.get("batch_input_shape")
    if shape is not None:
        return tuple(s for s in shape[1:])
    return None


def _convert_layer(class_name: str, cfg: Dict[str, Any]):
    shape = _input_shape_of(cfg)
    name = cfg.get("name")
    act = cfg.get("activation")
    if act == "linear":
        act = None
    if class_name == "Dense":
        return KL.Dense(cfg["output_dim"], activation=act,
                        bias=cfg.get("bias", True), input_shape=shape, name=name)
    if class_name == "Activation":
        return KL.Activation(cfg["activation"], input_shape=shape, name=name)
    if class_name == "Dropout":
        return KL.Dropout(cfg["p"], input_shape=shape, name=name)
    if class_name == "Flatten":
        return KL.Flatten(input_shape=shape, name=name)
    if class_name == "Reshape":
        return KL.Reshape(cfg["target_shape"], input_shape=shape, name=name)
    if class_name == "Convolution2D":
        if cfg.get("dim_ordering", "tf") != "tf":
            raise ValueError("only dim_ordering='tf' (NHWC) is supported")
        return KL.Convolution2D(
            cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"], activation=act,
            border_mode=cfg.get("border_mode", "valid"),
            subsample=tuple(cfg.get("subsample", (1, 1))),
            bias=cfg.get("bias", True), input_shape=shape, name=name)
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        cls = KL.MaxPooling2D if class_name == "MaxPooling2D" else KL.AveragePooling2D
        return cls(pool_size=tuple(cfg.get("pool_size", (2, 2))),
                   strides=(tuple(cfg["strides"]) if cfg.get("strides") else None),
                   border_mode=cfg.get("border_mode", "valid"),
                   input_shape=shape, name=name)
    if class_name == "GlobalAveragePooling2D":
        return KL.GlobalAveragePooling2D(input_shape=shape, name=name)
    if class_name == "BatchNormalization":
        return KL.BatchNormalization(epsilon=cfg.get("epsilon", 1e-3),
                                     momentum=cfg.get("momentum", 0.99),
                                     input_shape=shape, name=name)
    if class_name == "Embedding":
        return KL.Embedding(cfg["input_dim"], cfg["output_dim"],
                            input_shape=shape, name=name)
    if class_name in ("LSTM", "GRU", "SimpleRNN"):
        cls = getattr(KL, class_name)
        return cls(cfg["output_dim"],
                   return_sequences=cfg.get("return_sequences", False),
                   input_shape=shape, name=name)
    if class_name == "TimeDistributed":
        inner_def = cfg["layer"]
        inner = _convert_layer(inner_def["class_name"], inner_def["config"])
        return KL.TimeDistributed(inner, input_shape=shape, name=name)
    raise ValueError(f"unsupported Keras layer class {class_name!r} "
                     f"(reference converter: pyspark/bigdl/keras/converter.py)")


def model_from_json_config(json_str_or_dict) -> Sequential:
    """Rebuild a Sequential from Keras-1.2.2 `model.to_json()` output."""
    spec = (json.loads(json_str_or_dict)
            if isinstance(json_str_or_dict, (str, bytes)) else json_str_or_dict)
    class_name = spec.get("class_name")
    if class_name != "Sequential":
        raise ValueError(
            f"only Sequential definitions are supported (got {class_name!r}); "
            f"functional Model graphs load via bigdl_tpu.nn.Graph directly")
    model = Sequential()
    for layer_def in spec["config"]:
        model.add(_convert_layer(layer_def["class_name"], layer_def["config"]))
    return model


def load_keras_weights(model, params, state,
                       layer_weights: List[List]) -> Any:
    """Apply Keras `get_weights()` lists onto built params/state."""
    from bigdl_tpu.utils.interop import import_keras_weights

    return import_keras_weights(model, params, state, layer_weights)
