"""Keras-1.2.2 model-definition converter.

Reference: pyspark/bigdl/keras/converter.py (1759 LoC — loads real Keras
1.2.2 models into BigDL via definition + weight conversion).  Here
`model_from_json_config` rebuilds a `bigdl_tpu.keras.Sequential` from the
JSON produced by Keras-1 `model.to_json()`, and `load_keras_weights`
applies a `get_weights()`-style weight list (delegating layout fixes to
`bigdl_tpu.utils.interop.import_keras_weights`).

Definition coverage spans the wrapper zoo: dense/conv 1-3D (incl. atrous/
deconv/separable/locally-connected), pooling (incl. global, 1/2/3-D),
padding/cropping/upsampling, Permute/RepeatVector, BatchNormalization,
Embedding, recurrent (LSTM/GRU/SimpleRNN/ConvLSTM2D) + Bidirectional +
TimeDistributed(+Dense), advanced activations (LeakyReLU/ELU/PReLU/
ThresholdedReLU/SReLU), MaxoutDense, Highway, SpatialDropout1/2/3D.
`get_weights()` import covers every reference WeightsConverter family
(pyspark/bigdl/keras/converter.py:110-281): Dense, Convolution1/2/3D,
Atrous/Separable/Deconvolution, LocallyConnected1/2D, BatchNormalization,
Embedding, LSTM / GRU / SimpleRNN / ConvLSTM2D (keras-1 gate orders
repacked exactly; GRU via the reset-before cell), Bidirectional,
TimeDistributed(+Dense), Highway, MaxoutDense, SReLU.  Unsupported border
modes raise instead of silently converting.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

from bigdl_tpu.keras import layers as KL
from bigdl_tpu.keras.topology import Sequential


def _input_shape_of(cfg: Dict[str, Any]) -> Optional[Sequence[int]]:
    shape = cfg.get("batch_input_shape")
    if shape is not None:
        return tuple(s for s in shape[1:])
    return None


def _convert_layer(class_name: str, cfg: Dict[str, Any]):
    shape = _input_shape_of(cfg)
    name = cfg.get("name")
    act = cfg.get("activation")
    if act == "linear":
        act = None
    if class_name == "Dense":
        return KL.Dense(cfg["output_dim"], activation=act,
                        bias=cfg.get("bias", True), input_shape=shape, name=name)
    if class_name == "Activation":
        return KL.Activation(cfg["activation"], input_shape=shape, name=name)
    if class_name == "Dropout":
        return KL.Dropout(cfg["p"], input_shape=shape, name=name)
    if class_name == "Flatten":
        return KL.Flatten(input_shape=shape, name=name)
    if class_name == "Reshape":
        return KL.Reshape(cfg["target_shape"], input_shape=shape, name=name)
    if class_name == "Convolution2D":
        if cfg.get("dim_ordering", "tf") != "tf":
            raise ValueError("only dim_ordering='tf' (NHWC) is supported")
        return KL.Convolution2D(
            cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"], activation=act,
            border_mode=cfg.get("border_mode", "valid"),
            subsample=tuple(cfg.get("subsample", (1, 1))),
            bias=cfg.get("bias", True), input_shape=shape, name=name)
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        cls = KL.MaxPooling2D if class_name == "MaxPooling2D" else KL.AveragePooling2D
        return cls(pool_size=tuple(cfg.get("pool_size", (2, 2))),
                   strides=(tuple(cfg["strides"]) if cfg.get("strides") else None),
                   border_mode=cfg.get("border_mode", "valid"),
                   input_shape=shape, name=name)
    if class_name == "GlobalAveragePooling2D":
        return KL.GlobalAveragePooling2D(input_shape=shape, name=name)
    if class_name == "BatchNormalization":
        return KL.BatchNormalization(epsilon=cfg.get("epsilon", 1e-3),
                                     momentum=cfg.get("momentum", 0.99),
                                     input_shape=shape, name=name)
    if class_name == "Embedding":
        return KL.Embedding(cfg["input_dim"], cfg["output_dim"],
                            input_shape=shape, name=name)
    if class_name in ("LSTM", "GRU", "SimpleRNN"):
        cls = getattr(KL, class_name)
        return cls(cfg["output_dim"],
                   return_sequences=cfg.get("return_sequences", False),
                   activation=cfg.get("activation", "tanh"),
                   inner_activation=cfg.get("inner_activation",
                                            "hard_sigmoid"),
                   input_shape=shape, name=name)
    if class_name == "TimeDistributed":
        inner_def = cfg["layer"]
        inner = _convert_layer(inner_def["class_name"], inner_def["config"])
        return KL.TimeDistributed(inner, input_shape=shape, name=name)
    if class_name == "TimeDistributedDense":
        # deprecated keras-1 alias for TimeDistributed(Dense); weights are
        # plain Dense weights (reference convert_timedistributeddense)
        return KL.TimeDistributed(
            KL.Dense(cfg["output_dim"], activation=act,
                     bias=cfg.get("bias", True)),
            input_shape=shape, name=name)
    if class_name == "ConvLSTM2D":
        if cfg.get("dim_ordering", "tf") != "tf":
            raise ValueError("only dim_ordering='tf' (NHWC) is supported")
        if cfg.get("border_mode", "same") != "same":
            raise ValueError("ConvLSTM2D supports border_mode='same' only "
                             "(the hidden recurrence preserves the spatial "
                             "shape)")
        if cfg["nb_row"] != cfg["nb_col"]:
            raise ValueError("ConvLSTM2D requires square kernels "
                             "(reference: nn/keras/ConvLSTM2D.scala)")
        if tuple(cfg.get("subsample", (1, 1))) != (1, 1):
            raise ValueError("ConvLSTM2D supports subsample=(1, 1) only")
        return KL.ConvLSTM2D(
            cfg["nb_filter"], cfg["nb_row"],
            return_sequences=cfg.get("return_sequences", False),
            activation=cfg.get("activation", "tanh"),
            inner_activation=cfg.get("inner_activation", "hard_sigmoid"),
            input_shape=shape, name=name)
    if class_name == "SReLU":
        return KL.SReLU(shared_axes=cfg.get("shared_axes"),
                        input_shape=shape, name=name)
    if class_name == "Convolution1D":
        if cfg.get("border_mode", "valid") != "valid":
            raise ValueError("Convolution1D supports border_mode='valid' only")
        return KL.Convolution1D(
            cfg["nb_filter"], cfg["filter_length"], activation=act,
            subsample_length=cfg.get("subsample_length", 1),
            bias=cfg.get("bias", True), input_shape=shape, name=name)
    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        if cfg.get("border_mode", "valid") != "valid":
            raise ValueError(f"{class_name} supports border_mode='valid' only")
        cls = getattr(KL, class_name)
        return cls(cfg.get("pool_length", 2), stride=cfg.get("stride"),
                   input_shape=shape, name=name)
    if class_name in ("MaxPooling3D", "AveragePooling3D"):
        if cfg.get("border_mode", "valid") != "valid":
            raise ValueError(f"{class_name} supports border_mode='valid' only")
        cls = getattr(KL, class_name)
        return cls(pool_size=tuple(cfg.get("pool_size", (2, 2, 2))),
                   strides=(tuple(cfg["strides"]) if cfg.get("strides") else None),
                   input_shape=shape, name=name)
    if class_name in ("GlobalMaxPooling1D", "GlobalAveragePooling1D",
                      "GlobalMaxPooling2D", "GlobalMaxPooling3D",
                      "GlobalAveragePooling3D"):
        return getattr(KL, class_name)(input_shape=shape, name=name)
    if class_name == "Convolution3D":
        return KL.Convolution3D(
            cfg["nb_filter"], cfg["kernel_dim1"], cfg["kernel_dim2"],
            cfg["kernel_dim3"], activation=act,
            border_mode=cfg.get("border_mode", "valid"),
            subsample=tuple(cfg.get("subsample", (1, 1, 1))),
            bias=cfg.get("bias", True), input_shape=shape, name=name)
    if class_name == "AtrousConvolution2D":
        if cfg.get("border_mode", "valid") != "valid":
            raise ValueError("AtrousConvolution2D supports "
                             "border_mode='valid' only")
        if not cfg.get("bias", True):
            raise ValueError("AtrousConvolution2D without bias unsupported")
        return KL.AtrousConvolution2D(
            cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"], activation=act,
            subsample=tuple(cfg.get("subsample", (1, 1))),
            atrous_rate=tuple(cfg.get("atrous_rate", (1, 1))),
            input_shape=shape, name=name)
    if class_name == "AtrousConvolution1D":
        if cfg.get("border_mode", "valid") != "valid":
            raise ValueError("AtrousConvolution1D supports "
                             "border_mode='valid' only")
        return KL.AtrousConvolution1D(
            cfg["nb_filter"], cfg["filter_length"], activation=act,
            subsample_length=cfg.get("subsample_length", 1),
            atrous_rate=cfg.get("atrous_rate", 1),
            input_shape=shape, name=name)
    if class_name == "Deconvolution2D":
        if cfg.get("border_mode", "valid") != "valid":
            raise ValueError("Deconvolution2D supports border_mode='valid' "
                             "only")
        return KL.Deconvolution2D(
            cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"], activation=act,
            subsample=tuple(cfg.get("subsample", (1, 1))),
            bias=cfg.get("bias", True), input_shape=shape, name=name)
    if class_name == "SeparableConvolution2D":
        return KL.SeparableConvolution2D(
            cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"], activation=act,
            border_mode=cfg.get("border_mode", "valid"),
            subsample=tuple(cfg.get("subsample", (1, 1))),
            depth_multiplier=cfg.get("depth_multiplier", 1),
            bias=cfg.get("bias", True), input_shape=shape, name=name)
    if class_name in ("LocallyConnected1D",):
        return KL.LocallyConnected1D(
            cfg["nb_filter"], cfg["filter_length"], activation=act,
            subsample_length=cfg.get("subsample_length", 1),
            bias=cfg.get("bias", True), input_shape=shape, name=name)
    if class_name == "LocallyConnected2D":
        return KL.LocallyConnected2D(
            cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"], activation=act,
            subsample=tuple(cfg.get("subsample", (1, 1))),
            bias=cfg.get("bias", True), input_shape=shape, name=name)
    if class_name == "ZeroPadding1D":
        return KL.ZeroPadding1D(cfg.get("padding", 1), input_shape=shape,
                                name=name)
    if class_name == "ZeroPadding2D":
        return KL.ZeroPadding2D(tuple(cfg.get("padding", (1, 1))),
                                input_shape=shape, name=name)
    if class_name == "ZeroPadding3D":
        return KL.ZeroPadding3D(tuple(cfg.get("padding", (1, 1, 1))),
                                input_shape=shape, name=name)
    if class_name == "Cropping1D":
        return KL.Cropping1D(tuple(cfg.get("cropping", (1, 1))),
                             input_shape=shape, name=name)
    if class_name == "Cropping2D":
        return KL.Cropping2D(tuple(tuple(c) for c in
                                   cfg.get("cropping", ((0, 0), (0, 0)))),
                             input_shape=shape, name=name)
    if class_name == "Cropping3D":
        return KL.Cropping3D(tuple(tuple(c) for c in
                                   cfg.get("cropping",
                                           ((1, 1), (1, 1), (1, 1)))),
                             input_shape=shape, name=name)
    if class_name == "UpSampling1D":
        return KL.UpSampling1D(cfg.get("length", 2), input_shape=shape,
                               name=name)
    if class_name == "UpSampling2D":
        return KL.UpSampling2D(tuple(cfg.get("size", (2, 2))),
                               input_shape=shape, name=name)
    if class_name == "Permute":
        return KL.Permute(tuple(cfg["dims"]), input_shape=shape, name=name)
    if class_name == "RepeatVector":
        return KL.RepeatVector(cfg["n"], input_shape=shape, name=name)
    if class_name == "Highway":
        return KL.Highway(activation=act, input_shape=shape, name=name)
    if class_name == "MaxoutDense":
        return KL.MaxoutDense(cfg["output_dim"],
                              nb_feature=cfg.get("nb_feature", 4),
                              bias=cfg.get("bias", True),
                              input_shape=shape, name=name)
    if class_name in ("SpatialDropout1D", "SpatialDropout2D",
                      "SpatialDropout3D"):
        return getattr(KL, class_name)(cfg["p"], input_shape=shape, name=name)
    if class_name == "ThresholdedReLU":
        return KL.ThresholdedReLU(cfg.get("theta", 1.0), input_shape=shape,
                                  name=name)
    if class_name == "LeakyReLU":
        return KL.LeakyReLU(cfg.get("alpha", 0.3), input_shape=shape,
                            name=name)
    if class_name == "ELU":
        return KL.ELU(cfg.get("alpha", 1.0), input_shape=shape, name=name)
    if class_name == "PReLU":
        return KL.PReLU(input_shape=shape, name=name)
    if class_name == "Merge":
        # keras-1 pattern: Sequential([Merge([modelA, modelB], mode=...)]) —
        # each branch is a nested model definition; input is a Table of the
        # branch inputs
        branch_defs = cfg.get("layers", [])
        if not branch_defs:
            raise ValueError("Merge config has no nested branch layers")
        branches = [model_from_json_config(b) if b.get("class_name") ==
                    "Sequential" else _convert_layer(b["class_name"],
                                                     b["config"])
                    for b in branch_defs]
        mode = {"cos": "cosine"}.get(cfg.get("mode", "sum"),
                                     cfg.get("mode", "sum"))
        if mode not in ("sum", "mul", "ave", "max", "concat", "dot", "cosine"):
            raise ValueError(f"unsupported Merge mode {cfg.get('mode')!r}")
        if mode == "dot" and cfg.get("dot_axes") not in (None, -1, [-1, -1]):
            raise ValueError("Merge dot_axes other than -1 unsupported")
        return KL.Merge(branches, mode=mode,
                        concat_axis=cfg.get("concat_axis", -1), name=name)
    if class_name == "Bidirectional":
        inner_def = cfg["layer"]
        inner = _convert_layer(inner_def["class_name"], inner_def["config"])
        return KL.Bidirectional(inner,
                                merge_mode=cfg.get("merge_mode", "concat"),
                                input_shape=shape, name=name)
    raise ValueError(f"unsupported Keras layer class {class_name!r} "
                     f"(reference converter: pyspark/bigdl/keras/converter.py)")


def _functional_model_from_config(spec):
    """Rebuild a keras-1 functional `Model` as an nn.Graph: walk
    config["layers"] wiring each layer to its inbound nodes
    (`[[layer, node_idx, tensor_idx], ...]`), inputs/outputs per
    config["input_layers"]/["output_layers"].  Reference:
    pyspark/bigdl/keras/converter.py:289 (DefinitionLoader builds the
    BigDL graph from the keras node graph)."""
    import bigdl_tpu.nn as nn

    cfg = spec["config"]
    # layer name -> list of application Nodes (keras node graph: a SHARED
    # layer has one node per application; `[src, node_idx, tensor_idx]`
    # refs select the application — weight sharing falls out of one
    # module applied to several graph nodes, a single params entry)
    nodes: Dict[str, list] = {}
    input_shapes: Dict[str, Any] = {}

    # (src, node_idx, tensor_idx) -> SelectTable node, so several refs to
    # the same output component share one selector
    select_cache: Dict[tuple, Any] = {}
    # layer name -> number of outputs, for producers whose application
    # yields a Table (nested multi-output Models): EVERY ref into one of
    # those must select a component, including tensor index 0
    multi_out: Dict[str, int] = {}

    def resolve(ref):
        src, node_idx, tensor_idx = ref[0], ref[1], ref[2]
        apps = nodes[src]
        if node_idx >= len(apps):
            raise ValueError(f"inbound ref {ref}: layer {src!r} has only "
                             f"{len(apps)} applications")
        if src not in multi_out:
            if tensor_idx:
                raise ValueError(
                    f"inbound ref {ref}: non-zero tensor index into "
                    f"single-output layer {src!r}")
            return apps[node_idx]
        # multi-output producer: its application yields a Table; the
        # ref's tensor index picks the component (SelectTable is 1-based)
        key = (src, node_idx, tensor_idx)
        if key not in select_cache:
            select_cache[key] = nn.SelectTable(
                tensor_idx + 1,
                name=f"{src}_out{tensor_idx}")(apps[node_idx])
        return select_cache[key]

    for ld in cfg["layers"]:
        class_name, lcfg = ld["class_name"], ld["config"]
        lname = ld.get("name") or lcfg.get("name")
        inbound = ld.get("inbound_nodes") or []
        if class_name == "InputLayer":
            nodes[lname] = [nn.Input(name=lname)]
            shp = lcfg.get("batch_input_shape")
            input_shapes[lname] = tuple(shp) if shp else None
            continue
        if not inbound:
            raise ValueError(f"non-input layer {lname!r} has no inbound "
                             f"nodes")
        if class_name == "Merge" and not lcfg.get("layers"):
            # functional-style Merge: branches arrive via inbound edges,
            # so only the combine op is needed
            mode = {"cos": "cosine"}.get(lcfg.get("mode", "sum"),
                                         lcfg.get("mode", "sum"))
            if mode == "dot" and lcfg.get("dot_axes") not in (None, -1,
                                                              [-1, -1]):
                raise ValueError("Merge dot_axes other than -1 unsupported")
            combine = {
                "sum": lambda: nn.CAddTable(name=lname),
                "mul": lambda: nn.CMulTable(name=lname),
                "ave": lambda: nn.CAveTable(name=lname),
                "max": lambda: nn.CMaxTable(name=lname),
                "concat": lambda: nn.JoinTable(
                    lcfg.get("concat_axis", -1), name=lname),
                "dot": lambda: nn.DotProduct(name=lname),
                "cosine": lambda: nn.CosineDistance(name=lname),
            }.get(mode)
            if combine is None:
                raise ValueError(f"unsupported Merge mode {mode!r}")
            module = combine()
        elif class_name in ("Model", "Sequential"):
            # nested sub-model used as a layer (keras-1 allows Model
            # composition; reference DefinitionLoader handles the nested
            # node graph the same way) — one module, its application
            # nodes below share the single weight set
            module = model_from_json_config(ld)
            module.name = lname
            if class_name == "Model":
                n_out = len(ld["config"].get("output_layers", []))
                if n_out > 1:
                    multi_out[lname] = n_out
        else:
            module = _convert_layer(class_name, lcfg)
            module.name = lname
        nodes[lname] = [module(*[resolve(r) for r in node_refs])
                        for node_refs in inbound]
    from bigdl_tpu.keras.topology import Model as KerasModel

    graph_inputs = [resolve(r) for r in cfg["input_layers"]]
    outs = [resolve(r) for r in cfg["output_layers"]]
    graph = KerasModel(graph_inputs, outs,
                       name=cfg.get("name") or "keras_model")
    # batch_input_shapes in declared input order, for load_keras_model
    graph.keras_batch_input_shapes = [input_shapes[r[0]]
                                      for r in cfg["input_layers"]]
    return graph


def model_from_json_config(json_str_or_dict):
    """Rebuild a model from Keras-1.2.2 `model.to_json()` output:
    Sequential -> keras.Sequential, functional Model -> nn.Graph."""
    spec = (json.loads(json_str_or_dict)
            if isinstance(json_str_or_dict, (str, bytes)) else json_str_or_dict)
    class_name = spec.get("class_name")
    if class_name == "Model":
        return _functional_model_from_config(spec)
    if class_name != "Sequential":
        raise ValueError(
            f"only Sequential and functional Model definitions are "
            f"supported (got {class_name!r})")
    model = Sequential()
    for layer_def in spec["config"]:
        model.add(_convert_layer(layer_def["class_name"], layer_def["config"]))
    return model


def load_keras_weights(model, params, state,
                       layer_weights: List[List]) -> Any:
    """Apply Keras `get_weights()` lists onto built params/state."""
    from bigdl_tpu.utils.interop import import_keras_weights

    return import_keras_weights(model, params, state, layer_weights)


def load_keras_hdf5_weights(model, params, state, h5_path: str):
    """Load a Keras-1 `model.save_weights()` HDF5 file.

    Layout (keras 1.2.2 topology.py save_weights): file attr `layer_names`
    lists layer groups in model order; each group's attr `weight_names`
    lists its datasets in get_weights() order.  Sequential: layers with no
    weights are skipped, matching `load_keras_weights`'s positional
    discipline.  Functional `Model` graphs align BY NAME: each hdf5 group
    maps to the graph child of the same name (two topological orders need
    not tie-break identically, so positional alignment would be fragile).
    """
    import h5py

    from bigdl_tpu import nn

    def _names(attr):
        return [n.decode() if isinstance(n, bytes) else str(n) for n in attr]

    with h5py.File(h5_path, "r") as f:
        groups = []
        for lname in _names(f.attrs["layer_names"]):
            g = f[lname]
            wnames = _names(g.attrs.get("weight_names", []))
            if wnames:
                groups.append((lname, wnames, [g[w][()] for w in wnames]))
    if not isinstance(model, nn.Graph):
        return load_keras_weights(model, params, state,
                                  [ws for _, _, ws in groups])
    for lname, wnames, ws in groups:
        child = model.children.get(lname)
        if child is None:
            raise ValueError(
                f"hdf5 layer {lname!r} has no graph child of that name "
                f"(children: {sorted(model.children)})")
        params[lname], state[lname] = _assign_group(
            child, params.get(lname, {}), state.get(lname, {}), wnames, ws)
    return params, state


# keras-1 weight-name suffixes, longest first ('_running_mean' before '_b')
_KERAS1_WEIGHT_SUFFIXES = (
    "_running_mean", "_running_std", "_embeddings", "_gamma", "_beta",
    "_alphas", "_W", "_U", "_b",
)


def _split_group(wnames, ws):
    """Split one hdf5 group's flat weight list into per-layer (names,
    weights) sublists by the keras-1 '{layer_name}{suffix}' naming (a
    nested sub-model saves as ONE group whose weight_names carry the inner
    layer names).  Returning the names alongside the weights keeps the
    recursive assignment exact even when sibling layer names
    prefix-collide ('conv' vs 'conv_bn')."""
    from collections import OrderedDict

    def base(wn):
        wn = wn.split("/")[-1]
        if wn.endswith(":0"):
            wn = wn[:-2]
        for sf in _KERAS1_WEIGHT_SUFFIXES:
            if wn.endswith(sf):
                return wn[: -len(sf)]
        return wn
    sub: "OrderedDict[str, Tuple[list, list]]" = OrderedDict()
    for wn, w in zip(wnames, ws):
        names, weights = sub.setdefault(base(wn), ([], []))
        names.append(wn)
        weights.append(w)
    return sub


def _assign_group(child, p, s, wnames, ws):
    """Assign one hdf5 layer group to a converted module: leaf layers take
    the flat list; nested sub-models (Graph or Sequential containers) are
    split by inner layer name — name-matched for Graphs, positional for
    Sequentials (keras-1 save_weights order)."""
    from bigdl_tpu import nn

    if isinstance(child, nn.Graph):
        for nname, (nnames, nws) in _split_group(wnames, ws).items():
            nchild = child.children.get(nname)
            if nchild is None:
                raise ValueError(
                    f"nested model has no child {nname!r} for hdf5 weights "
                    f"(children: {sorted(child.children)})")
            p[nname], s[nname] = _assign_group(
                nchild, p.get(nname, {}), s.get(nname, {}), nnames, nws)
        return p, s
    from bigdl_tpu.nn.module import Container

    if isinstance(child, Container):
        sub = _split_group(wnames, ws)
        return load_keras_weights(child, p, s,
                                  [weights for _, weights in sub.values()])
    return load_keras_weights(child, p, s, [ws])


def load_keras_model(json_path: str, h5_path: str = None, *,
                     input_shape=None, seed: int = 0):
    """One-call reference flow: Keras-1 `model.to_json()` file (+ optional
    `save_weights()` HDF5) -> (model, params, state).
    reference: pyspark/bigdl/keras/converter.py load_keras entry."""
    import jax

    from bigdl_tpu.core.table import Table

    with open(json_path) as fh:
        model = model_from_json_config(fh.read())
    shape = input_shape
    if shape is None:
        declared_list = getattr(model, "keras_batch_input_shapes", None)
        if declared_list is not None:  # functional Model
            if any(s is None or any(d is None for d in s[1:])
                   for s in declared_list):
                raise ValueError(
                    "pass input_shape= (an InputLayer declares no concrete "
                    "batch_input_shape)")
            shapes = [(1,) + tuple(s[1:]) for s in declared_list]
            shape = shapes[0] if len(shapes) == 1 else shapes
        else:
            first = model.children[next(iter(model.children))]
            declared = getattr(first, "keras_input_shape", None)
            if declared is None or any(d is None for d in declared):
                raise ValueError(
                    "pass input_shape= (the model JSON declares no concrete "
                    "batch_input_shape — variable dims need an explicit "
                    "shape)")
            shape = (1,) + tuple(declared)
    multi = (isinstance(shape, (list, tuple)) and shape
             and isinstance(shape[0], (list, tuple)))
    build_shape = Table(*[tuple(s) for s in shape]) if multi \
        else tuple(shape)
    params, state, _ = model.build(jax.random.PRNGKey(seed), build_shape)
    if h5_path is not None:
        params, state = load_keras_hdf5_weights(model, params, state, h5_path)
    return model, params, state
