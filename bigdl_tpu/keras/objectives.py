"""Keras loss/metric/optimizer name resolution.

Reference: nn/keras/Topology.scala compile() accepts objects; the Python
Keras API (pyspark/bigdl/nn/keras/topology.py:82-105) accepts strings —
both are supported here.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.criterion import Criterion
from bigdl_tpu.optim import (
    SGD, Adam, Adamax, Adadelta, Adagrad, RMSprop,
    Top1Accuracy, Top5Accuracy, Loss, MAE,
)
from bigdl_tpu.optim.optim_method import OptimMethod
from bigdl_tpu.optim.validation import ValidationMethod


class CategoricalCrossEntropy(Criterion):
    """Keras categorical_crossentropy over logits: -sum(t * log_softmax(x)).

    Targets may be one-hot OR soft/label-smoothed distributions — both are
    honored exactly (argmax-collapsing soft targets would silently optimize
    a different objective)."""

    def forward(self, input, target):
        logp = jax.nn.log_softmax(input, axis=-1)
        return -jnp.mean(jnp.sum(target * logp, axis=-1))


_LOSSES = {
    "categorical_crossentropy": CategoricalCrossEntropy,
    "sparse_categorical_crossentropy": nn.CrossEntropyCriterion,
    "mse": nn.MSECriterion,
    "mean_squared_error": nn.MSECriterion,
    "mae": nn.AbsCriterion,
    "mean_absolute_error": nn.AbsCriterion,
    "binary_crossentropy": nn.BCECriterion,
    "hinge": nn.MarginCriterion,
    "kld": nn.DistKLDivCriterion,
    "kullback_leibler_divergence": nn.DistKLDivCriterion,
    "smooth_l1": nn.SmoothL1Criterion,
    "mape": nn.MeanAbsolutePercentageCriterion,
    "mean_absolute_percentage_error": nn.MeanAbsolutePercentageCriterion,
    "msle": nn.MeanSquaredLogarithmicCriterion,
    "mean_squared_logarithmic_error": nn.MeanSquaredLogarithmicCriterion,
    "poisson": nn.PoissonCriterion,
    "cosine_proximity": nn.CosineProximityCriterion,
    "squared_hinge": lambda: nn.MarginCriterion(squared=True),
}

_OPTIMIZERS = {
    "sgd": lambda: SGD(learning_rate=0.01),
    "adam": lambda: Adam(),
    "adamax": lambda: Adamax(),
    "adadelta": lambda: Adadelta(),
    "adagrad": lambda: Adagrad(),
    "rmsprop": lambda: RMSprop(),
}

_METRICS = {
    "binary_accuracy": __import__("bigdl_tpu.optim.validation", fromlist=["BinaryAccuracy"]).BinaryAccuracy,
    "accuracy": Top1Accuracy,
    "acc": Top1Accuracy,
    "top1": Top1Accuracy,
    "top5": Top5Accuracy,
    "top5accuracy": Top5Accuracy,
    "mae": MAE,
}


def resolve_loss(loss: Union[str, Criterion]) -> Criterion:
    if isinstance(loss, Criterion):
        return loss
    key = str(loss).lower()
    if key not in _LOSSES:
        raise ValueError(f"unknown loss {loss!r}; one of {sorted(_LOSSES)}")
    return _LOSSES[key]()


def resolve_optimizer(opt: Union[str, OptimMethod]) -> OptimMethod:
    if isinstance(opt, OptimMethod):
        return opt
    key = str(opt).lower()
    if key not in _OPTIMIZERS:
        raise ValueError(f"unknown optimizer {opt!r}; one of {sorted(_OPTIMIZERS)}")
    return _OPTIMIZERS[key]()


def resolve_metrics(metrics: Optional[Sequence[Union[str, ValidationMethod]]]
                    ) -> List[ValidationMethod]:
    out: List[ValidationMethod] = []
    for m in metrics or []:
        if isinstance(m, ValidationMethod):
            out.append(m)
            continue
        key = str(m).lower()
        if key not in _METRICS:
            raise ValueError(f"unknown metric {m!r}; one of {sorted(_METRICS)}")
        out.append(_METRICS[key]())
    return out
