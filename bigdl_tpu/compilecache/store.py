"""On-disk executable store: atomic writes, CRC-gated reads, LRU cap.

Layout (one entry = one payload + one commit marker):

    <root>/
      aot/
        <key>.bin    # pickled (serialized_executable, in_tree, out_tree)
        <key>.json   # commit marker: size, crc32, key ingredients, ctime
      xla/           # jax's own persistent compilation cache (2nd layer)

Write discipline mirrors `resilience.async_ckpt.AsyncCheckpointer`:
payload is staged to `tmp.<key>.<pid>`, fsynced, renamed into place, and
the meta json lands LAST (same stage→fsync→rename) — an entry without
its `.json` is an aborted write and is invisible to readers.  Rename is
atomic on POSIX, so a reader never observes a half-written payload and
concurrent writers of the same key simply race to an identical result.

Reads verify size + crc32 against the meta before the payload is
trusted; any mismatch (truncation, bitflip, stray partial file) deletes
the entry and reports a miss so the caller falls back to a real compile.

Eviction is LRU by mtime with a byte cap (`BIGDL_TPU_COMPILE_CACHE_MAX_MB`,
default 512): hits re-touch the payload, and after every put the oldest
entries are dropped until the cache fits.  Corrupt-meta entries sort
first so damage is reclaimed before healthy executables.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("bigdl_tpu.compilecache")

_DEFAULT_MAX_MB = 512.0


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - platform quirk, best effort
        pass


class ExecutableStore:
    """Filesystem-backed byte store for serialized executables."""

    def __init__(self, root: str, max_bytes: Optional[int] = None):
        self.root = os.path.abspath(root)
        self.aot_dir = os.path.join(self.root, "aot")
        os.makedirs(self.aot_dir, exist_ok=True)
        if max_bytes is None:
            mb = float(os.environ.get("BIGDL_TPU_COMPILE_CACHE_MAX_MB",
                                      str(_DEFAULT_MAX_MB)) or _DEFAULT_MAX_MB)
            max_bytes = int(mb * 1024 * 1024)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()

    # -- paths -------------------------------------------------------------

    def _bin(self, key: str) -> str:
        return os.path.join(self.aot_dir, f"{key}.bin")

    def _meta(self, key: str) -> str:
        return os.path.join(self.aot_dir, f"{key}.json")

    # -- read --------------------------------------------------------------

    def get(self, key: str) -> Optional[bytes]:
        """Payload bytes for `key`, or None on miss/corruption.

        A corrupt entry (missing meta, size or crc32 mismatch, unreadable
        payload) is deleted on sight and reported as a miss — the caller
        recompiles and the next `put` rewrites a healthy entry.
        """
        bin_path, meta_path = self._bin(key), self._meta(key)
        try:
            with open(meta_path, "r", encoding="utf-8") as f:
                meta = json.load(f)
        except (OSError, ValueError):
            if os.path.exists(bin_path):
                # payload without a commit marker: aborted write
                self.remove(key)
            return None
        try:
            with open(bin_path, "rb") as f:
                payload = f.read()
        except OSError:
            self.remove(key)
            return None
        if (len(payload) != int(meta.get("size", -1))
                or (zlib.crc32(payload) & 0xFFFFFFFF) != int(meta.get("crc32", -1))):
            logger.warning("compilecache: corrupt entry %s (size/crc mismatch); "
                           "dropping and recompiling", key[:12])
            self.remove(key)
            return None
        try:
            now = time.time()
            os.utime(bin_path, (now, now))  # LRU touch
        except OSError:  # pragma: no cover
            pass
        return payload

    def has(self, key: str) -> bool:
        return os.path.exists(self._meta(key)) and os.path.exists(self._bin(key))

    # -- write -------------------------------------------------------------

    def put(self, key: str, payload: bytes,
            meta: Optional[Dict[str, Any]] = None) -> str:
        """Atomically commit `payload` under `key`; returns the bin path."""
        bin_path, meta_path = self._bin(key), self._meta(key)
        record = dict(meta or {})
        record.update({
            "size": len(payload),
            "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
            "created_at": time.time(),
        })
        pid = os.getpid()
        tmp_bin = os.path.join(self.aot_dir, f"tmp.{key}.{pid}.bin")
        tmp_meta = os.path.join(self.aot_dir, f"tmp.{key}.{pid}.json")
        with self._lock:
            with open(tmp_bin, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp_bin, bin_path)
            with open(tmp_meta, "w", encoding="utf-8") as f:
                json.dump(record, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp_meta, meta_path)  # commit marker lands last
            _fsync_dir(self.aot_dir)
        self.evict_to_cap()
        return bin_path

    def remove(self, key: str) -> None:
        for p in (self._meta(key), self._bin(key)):
            try:
                os.remove(p)
            except OSError:
                pass

    # -- bookkeeping -------------------------------------------------------

    def entries(self) -> List[Tuple[str, int, float]]:
        """[(key, total_bytes, payload_mtime)] for committed entries."""
        out: List[Tuple[str, int, float]] = []
        try:
            names = os.listdir(self.aot_dir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".bin") or name.startswith("tmp."):
                continue
            key = name[:-len(".bin")]
            bin_path, meta_path = self._bin(key), self._meta(key)
            if not os.path.exists(meta_path):
                continue
            try:
                st = os.stat(bin_path)
                size = st.st_size + os.stat(meta_path).st_size
                out.append((key, size, st.st_mtime))
            except OSError:
                continue
        return out

    def total_bytes(self) -> int:
        return sum(size for _, size, _ in self.entries())

    def evict_to_cap(self) -> int:
        """Drop least-recently-used entries until under the byte cap."""
        if self.max_bytes <= 0:
            return 0
        entries = sorted(self.entries(), key=lambda e: e[2])  # oldest first
        total = sum(size for _, size, _ in entries)
        evicted = 0
        while entries and total > self.max_bytes:
            key, size, _ = entries.pop(0)
            self.remove(key)
            total -= size
            evicted += 1
        if evicted:
            logger.info("compilecache: evicted %d LRU entr%s (cap %d bytes)",
                        evicted, "y" if evicted == 1 else "ies", self.max_bytes)
        return evicted
