"""bigdl_tpu.compilecache — persistent executable store for cold starts.

Every deliberate-restart path in this repo (preemption resume, watchdog
rollback, hang-detection restart, registry hot-swap, serving activation)
used to pay full XLA recompilation of every step/bucket executable.
This package makes restart-to-first-step a disk read instead:

  * **AOT layer** (`load_or_compile`): for the executables we control
    end-to-end, `jit_fn.lower(*args)` is hashed into a content key
    (keys.py: StableHLO fingerprint + shapes/dtypes + mesh/sharding +
    donation + jax version + backend/device kind), and the serialized
    executable (`jax.experimental.serialize_executable`) is stored under
    that key (store.py: atomic tmp→rename writes, CRC-gated reads, LRU
    byte cap).  A later process with the same key deserializes in
    milliseconds — no trace, no lower, no backend compile.
  * **XLA layer**: enabling the store also points jax's own persistent
    compilation cache at `<root>/xla`, so programs that go through the
    plain jit path (shapes we didn't pre-warm, helper programs) still
    skip `backend_compile` on a second process.

Gating: set env `BIGDL_TPU_COMPILE_CACHE=/path/to/dir` (or call
`set_cache_dir(path)`).  Unset / "0" / "off" disables both layers —
the default, so behaviour without the env var is byte-identical to the
pre-cache code.  The loaded executable runs the same XLA program the
compiler would produce, so outputs are bitwise-equal cache-on vs
cache-off (tests/test_compilecache.py locks this under strict_transfers).

Observability: hits/misses/corruption land in the obs MetricsRegistry
(`compile/cache_hits`, `compile/cache_misses`, `compile/cache_load_ms`,
`compile/cache_corrupt`, `compile/cache_errors`), loads emit
`compile.cache_load` trace spans, and the CompileMonitor is told about
loads (`note_cache_load`) so a deserialized executable after restart is
never mistaken for a steady-state recompile.

Failure policy: every cache error degrades to the plain jit/compile
path with a warning — a broken cache dir can slow a start, never fail it.
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
import time
from contextlib import nullcontext
from typing import Any, Dict, Optional, Tuple

from bigdl_tpu import obs as _obs
from bigdl_tpu.compilecache.keys import (STORE_VERSION, device_fingerprint,
                                         executable_key, jax_version,
                                         mesh_descriptor)
from bigdl_tpu.compilecache.store import ExecutableStore

logger = logging.getLogger("bigdl_tpu.compilecache")

ENV_VAR = "BIGDL_TPU_COMPILE_CACHE"
_OFF_VALUES = ("", "0", "off", "none", "false")

_UNSET = object()
_lock = threading.Lock()
_override: Any = _UNSET          # set_cache_dir() beats the env var
_store: Optional[ExecutableStore] = None
_store_root: Optional[str] = None
_xla_layer_root: Optional[str] = None

# Process-level live-executable layer (opt-in via `process_scope=`):
# replicas of one fleet in one process share already-loaded executables
# by key, skipping even the disk read + deserialize of a store hit.
_live_lock = threading.Lock()
_live: Dict[str, Any] = {}


# -- gating ----------------------------------------------------------------


def cache_dir() -> Optional[str]:
    """Active cache root, or None when the cache is disabled."""
    if _override is not _UNSET:
        return _override
    val = os.environ.get(ENV_VAR, "").strip()
    if val.lower() in _OFF_VALUES:
        return None
    return val


def enabled() -> bool:
    return cache_dir() is not None


def set_cache_dir(path: Optional[str]) -> None:
    """Programmatic override: a path enables the cache there, None
    disables it (both win over the env var; `reset()` reverts to env)."""
    global _override
    with _lock:
        _override = path if path is None else str(path)
    with _live_lock:
        _live.clear()
    _sync_layers()


def reset() -> None:
    """Back to env-driven gating; drops the store singleton."""
    global _override
    with _lock:
        _override = _UNSET
    with _live_lock:
        _live.clear()
    _sync_layers()


# -- layers ----------------------------------------------------------------


def _configure_xla_layer(root: Optional[str]) -> None:
    """Point jax's persistent compilation cache at `<root>/xla` (None
    detaches it).  Thresholds drop to zero so even the tiny CPU-proxy
    programs in tests/benchmarks persist."""
    global _xla_layer_root
    if root == _xla_layer_root:
        return
    import jax
    try:
        if root is None:
            jax.config.update("jax_compilation_cache_dir", None)
        else:
            xdir = os.path.join(root, "xla")
            os.makedirs(xdir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", xdir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _xla_layer_root = root
    except Exception as e:  # pragma: no cover - config name drift
        logger.warning("compilecache: could not configure jax persistent "
                       "compilation cache (%s); AOT layer still active", e)


def _sync_layers() -> None:
    global _store, _store_root
    root = cache_dir()
    with _lock:
        if root is None:
            _store = None
            _store_root = None
        elif _store is None or _store_root != root:
            _store = ExecutableStore(root)
            _store_root = root
    _configure_xla_layer(root)


def store() -> Optional[ExecutableStore]:
    """The active ExecutableStore (None when disabled); creating it also
    attaches jax's own persistent compilation cache under the same root."""
    if cache_dir() != _store_root or (_store is None) != (cache_dir() is None):
        _sync_layers()
    return _store


# -- the AOT fast path ------------------------------------------------------


def load_or_compile(jit_fn, args: Tuple[Any, ...], *,
                    signature: Optional[str] = None,
                    extra_key: Optional[Dict[str, Any]] = None,
                    process_scope: Optional[str] = None):
    """Executable for `jit_fn(*args)` via the store.

    Returns `(callable, status)`:

      * status "off"   — cache disabled; `callable` IS `jit_fn` untouched.
      * status "hit"   — deserialized executable from disk (no compile),
        or — with `process_scope` set — the already-loaded executable
        shared by an earlier caller in THIS process (no disk read).
      * status "miss"  — compiled AOT now, serialized into the store.
      * status "error" — lowering/packing failed; plain `jit_fn` returned.

    `process_scope` opts in to the process-level live layer: executables
    resolved under the same (scope, content key) are shared across
    callers in one process — how fleet replicas of the same model warm
    without touching disk.  Live hits count in `compile/cache_hits`
    (they ARE cache hits) and additionally `compile/cache_hits_live`.

    The returned callable takes the exact same positional args.  All
    cache failures degrade to a real compile — never to a raised error.
    """
    st = store()
    if st is None:
        return jit_fn, "off"
    reg = _obs.registry()
    mon = _obs.compile_monitor()
    sig = signature or "unattributed"
    try:
        lowered = jit_fn.lower(*args)
        extra = dict(extra_key) if extra_key else {}
        key = executable_key(lowered, extra=extra or None)
    except Exception as e:
        logger.warning("compilecache: lowering failed under %r (%s); "
                       "falling back to the jit path", sig, e)
        reg.inc("compile/cache_errors")
        return jit_fn, "error"

    live_key = None
    if process_scope is not None:
        live_key = f"{process_scope}:{key}"
        with _live_lock:
            shared = _live.get(live_key)
        if shared is not None:
            reg.inc("compile/cache_hits")
            reg.inc("compile/cache_hits_live")
            if mon is not None:
                mon.note_cache_load(sig, 0.0)
            logger.info("compilecache: %s shared live executable "
                        "(scope %s, key %s)", sig, process_scope, key[:12])
            return shared, "hit"

    had_entry = st.has(key)
    blob = st.get(key)
    if blob is None and had_entry:
        reg.inc("compile/cache_corrupt")  # store dropped a damaged entry
    if blob is not None:
        t0 = time.perf_counter()
        try:
            from jax.experimental import serialize_executable as _se
            with _obs.span("compile.cache_load", cat="compile",
                           signature=sig, key=key[:12]):
                payload, in_tree, out_tree = pickle.loads(blob)
                load_scope = (mon.cache_load(sig) if mon is not None
                              else nullcontext())
                with load_scope:
                    compiled = _se.deserialize_and_load(payload, in_tree,
                                                        out_tree)
            dt = time.perf_counter() - t0
            reg.inc("compile/cache_hits")
            reg.set_gauge("compile/cache_load_ms", dt * 1e3)
            if mon is not None:
                mon.note_cache_load(sig, dt)
            logger.info("compilecache: %s loaded from cache in %.1f ms "
                        "(key %s)", sig, dt * 1e3, key[:12])
            if live_key is not None:
                with _live_lock:
                    _live[live_key] = compiled
            return compiled, "hit"
        except Exception as e:
            logger.warning("compilecache: entry %s for %r failed to "
                           "deserialize (%s); dropping it and recompiling",
                           key[:12], sig, e)
            st.remove(key)
            reg.inc("compile/cache_corrupt")

    # Miss: compile ahead-of-time under attribution, then persist.
    attr = mon.attribute(sig) if mon is not None else nullcontext()
    with attr:
        compiled = lowered.compile()
    reg.inc("compile/cache_misses")
    try:
        from jax.experimental import serialize_executable as _se
        payload, in_tree, out_tree = _se.serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree),
                            protocol=pickle.HIGHEST_PROTOCOL)
        st.put(key, blob, meta={
            "v": STORE_VERSION,
            "jax": jax_version(),
            "signature": sig,
            "extra": extra_key,
            **device_fingerprint(),
        })
        logger.info("compilecache: %s compiled and stored (key %s, %d bytes)",
                    sig, key[:12], len(blob))
    except Exception as e:
        logger.warning("compilecache: could not serialize executable for %r "
                       "(%s); it will recompile on next cold start", sig, e)
        reg.inc("compile/cache_errors")
    if live_key is not None:
        with _live_lock:
            _live[live_key] = compiled
    return compiled, "miss"


def stats() -> Dict[str, float]:
    """Cache counters from the active obs registry (all zero when off)."""
    reg = _obs.registry()
    return {
        "hits": reg.get("compile/cache_hits"),
        "hits_live": reg.get("compile/cache_hits_live"),
        "misses": reg.get("compile/cache_misses"),
        "corrupt": reg.get("compile/cache_corrupt"),
        "errors": reg.get("compile/cache_errors"),
        "load_ms": reg.get("compile/cache_load_ms"),
    }


__all__ = [
    "ENV_VAR", "STORE_VERSION", "ExecutableStore", "cache_dir", "enabled",
    "executable_key", "device_fingerprint", "jax_version", "load_or_compile",
    "mesh_descriptor", "reset", "set_cache_dir", "stats", "store",
]
