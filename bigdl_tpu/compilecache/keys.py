"""Content-addressed executable keys.

A cache entry may only be reused when the executable it holds is the one
XLA would have produced right now.  Everything that feeds the compiler is
therefore folded into one digest:

  * the lowered StableHLO module text — this carries the jaxpr structure,
    every static shape/dtype, the donation map (input/output aliasing
    attributes) and the sharding annotations (`mhlo.sharding` +
    `mhlo.num_partitions`) exactly as the compiler will see them;
  * the jax version (a jax upgrade may lower the same program
    differently, and the serialized-executable format is not stable
    across versions);
  * the backend platform, device kind, device count and process count
    (an executable compiled for 8 virtual CPU devices must never load
    onto a 1-device process, and a TPU v4 binary never onto v5e);
  * a store schema version (bump to invalidate every existing entry);
  * an optional caller-supplied `extra` dict (mesh axis layout, donation
    argnums, consumer kind) for facts the HLO text alone may not pin.

Wrong-topology or stale entries are thus rejected BY KEY — they simply
hash elsewhere — rather than by a load-time compatibility check that
would have to enumerate every way two programs can differ.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional

import jax

# Bump to invalidate every entry written by older code (schema change in
# the pickled payload, new key ingredient, serialization format fix...).
STORE_VERSION = 1


def jax_version() -> str:
    """The running jax version (separate function so tests can stub a
    'different jax' and assert the key rejects the old entry)."""
    return jax.__version__


def device_fingerprint() -> Dict[str, Any]:
    """Backend identity: platform, device kind, topology width."""
    devs = jax.devices()
    return {
        "backend": devs[0].platform,
        "device_kind": getattr(devs[0], "device_kind", "unknown"),
        "n_devices": len(devs),
        "process_count": jax.process_count(),
    }


def mesh_descriptor(mesh) -> Optional[Dict[str, int]]:
    """Stable description of a jax.sharding.Mesh (None stays None)."""
    if mesh is None:
        return None
    return {str(name): int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)}


def executable_key(lowered, extra: Optional[Dict[str, Any]] = None) -> str:
    """Digest of a `jax.stages.Lowered` + environment (hex sha256)."""
    hlo = hashlib.sha256(lowered.as_text().encode("utf-8")).hexdigest()
    payload: Dict[str, Any] = {
        "v": STORE_VERSION,
        "jax": jax_version(),
        "hlo": hlo,
        **device_fingerprint(),
    }
    if extra:
        payload["extra"] = extra
    blob = json.dumps(payload, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()
