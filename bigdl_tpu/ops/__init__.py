"""Pure array-level ops: attention cores (dense / ring / Ulysses) and, later,
pallas TPU kernels.  These are functions over jax arrays, independent of the
Module system — the layer in `bigdl_tpu.nn.attention` wraps them.
"""

from bigdl_tpu.ops.attention import (
    dense_attention,
    ring_attention,
    ulysses_attention,
)
from bigdl_tpu.ops.decode_attention import (
    decode_attention_pallas,
    decode_attention_ref,
    decode_impl,
)
from bigdl_tpu.ops.flash_attention import flash_attention
