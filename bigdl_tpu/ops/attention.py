"""Attention cores: dense, ring (sequence-parallel over ICI), Ulysses.

The reference has NO attention at all (survey §5.7: "there is no transformer
in this codebase"); its only sequence machinery is single-device recurrence
(nn/Recurrent.scala:47,241).  Long-context support is therefore designed
fresh, TPU-first:

  * `dense_attention` — the plain softmax(QK^T)V core XLA fuses well for
    moderate sequence lengths.
  * `ring_attention` — blockwise attention with an online softmax whose K/V
    blocks rotate around a mesh axis via `lax.ppermute` (one ICI hop per
    step).  Memory per chip is O(S_local), enabling sequences that cannot fit
    on one chip.  Must run inside `shard_map` with the sequence dimension
    sharded over `axis_name`.
  * `ulysses_attention` — all-to-all sequence parallelism: scatter heads /
    gather sequence (`lax.all_to_all`), run full-sequence attention on a head
    subset per chip, and transpose back.  Cheaper than ring when
    n_heads >= axis_size and the full sequence fits per chip.

All cores take (B, S, H, D)-shaped q/k/v ("BSHD") and return (B, S, H, D).
Causal masking uses GLOBAL positions, so ring/ulysses produce bitwise the
same math as dense attention over the gathered sequence.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _scale(q, sm_scale: Optional[float]):
    return q * (sm_scale if sm_scale is not None else q.shape[-1] ** -0.5)


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False, sm_scale: Optional[float] = None,
                    mask: Optional[jax.Array] = None,
                    q_offset: int | jax.Array = 0,
                    k_offset: int | jax.Array = 0) -> jax.Array:
    """softmax(q k^T) v over (B, S, H, D) inputs.

    `q_offset`/`k_offset` are the global positions of q[0]/k[0] — used by the
    sequence-parallel cores so causal masks line up across shards.
    """
    q = _scale(q, sm_scale)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        causal_mask = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(causal_mask[None, None], logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Ring attention: must run inside shard_map, sequence sharded on
    `axis_name`.  q/k/v are the LOCAL (B, S_local, H, D) shards.

    Each of the `axis_size` steps attends local q against the K/V block that
    originated on device (my_idx - step) mod axis_size, folded into a
    numerically-stable online softmax (running max `m`, normalizer `l`,
    accumulator `acc`), then rotates K/V one ICI hop forward.  This is the
    blockwise-parallel formulation of Liu et al.'s Ring Attention expressed
    with XLA collectives rather than NCCL send/recv.
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    qs = _scale(q, sm_scale)
    qpos = my_idx * s + jnp.arange(s)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, step_idx):
        acc, m, l, kb, vb = carry
        src = (my_idx - step_idx) % axis_size
        logits = jnp.einsum("bqhd,bkhd->bhqk", qs, kb)
        if causal:
            kpos = src * s + jnp.arange(s)
            cm = qpos[:, None] >= kpos[None, :]
            logits = jnp.where(cm[None, None], logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        # guard fully-masked rows (m_new == NEG_INF) against NaN from exp
        m_safe = jnp.where(m_new <= NEG_INF, 0.0, m_new)
        p = jnp.exp(logits - m_safe[..., None])
        correction = jnp.exp(jnp.where(m <= NEG_INF, NEG_INF, m - m_safe))
        l_new = l * correction + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vb)
        acc_new = acc * correction.transpose(0, 2, 1)[..., None] + pv
        kb_next = lax.ppermute(kb, axis_name, perm)
        vb_next = lax.ppermute(vb, axis_name, perm)
        return (acc_new, m_new, l_new, kb_next, vb_next), None

    # derive initial accumulators from qs so they carry the same
    # varying-manual-axes type as the rotating K/V blocks (shard_map scan
    # requires carry-in and carry-out types to match)
    acc0 = jnp.zeros_like(qs)
    m0 = jnp.zeros_like(qs[..., 0]).transpose(0, 2, 1) + NEG_INF
    l0 = jnp.zeros_like(qs[..., 0]).transpose(0, 2, 1)
    (acc, m, l, _, _), _ = lax.scan(step, (acc0, m0, l0, k, v),
                                    jnp.arange(axis_size))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros, not NaN
    return acc / l.transpose(0, 2, 1)[..., None]


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str, causal: bool = False,
                      sm_scale: Optional[float] = None) -> jax.Array:
    """Ulysses (DeepSpeed-style) sequence parallelism: must run inside
    shard_map with the sequence dim sharded on `axis_name`, and n_heads
    divisible by the axis size.

    all_to_all converts the (B, S/N, H, D) sequence shard into a
    (B, S, H/N, D) head shard (gather sequence, scatter heads), full dense
    attention runs locally on the head subset, and a second all_to_all
    transposes back.  Two all-to-alls replace ring's N ppermute steps.
    """
    # (B, S/N, H, D) -> (B, S, H/N, D): split axis 2 (heads), concat axis 1.
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = dense_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    # back: split axis 1 (sequence), concat axis 2 (heads)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)
