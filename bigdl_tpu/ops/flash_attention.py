"""Pallas TPU flash attention (blockwise, online-softmax) with custom VJP.

No reference counterpart (the reference has no attention, survey §5.7); this
is the single-chip hot core under `MultiHeadAttention`, complementing the
cross-chip cores in `bigdl_tpu.ops.attention` (ring/Ulysses move K/V between
chips; flash tiles them through VMEM within a chip).

Design (per /opt/skills/guides/pallas_guide.md):
  * grid = (B*H, Sq/block_q, Sk/block_k); the k-block axis is innermost and
    therefore sequential on TPU, so the online-softmax accumulators (acc, m,
    l) live in VMEM scratch across k iterations.
  * Q blocks stream (block_q, D); K/V blocks stream (block_k, D); logits are
    computed on the MXU with preferred_element_type=float32.
  * The forward also emits the per-row log-sum-exp (LSE); the backward
    recomputes P = exp(S - LSE) blockwise under `lax.scan` (no O(S^2)
    residual is ever materialized), which is the standard FlashAttention-2
    recompute strategy.

`flash_attention` falls back to the dense core when shapes don't tile
(sequence not divisible by the block sizes) so callers can use it
unconditionally.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from bigdl_tpu.ops.attention import dense_attention

NEG_INF = -1e30
# tuned on v5e: 1024-blocks beat 128..512 at S in [2k, 8k] (the (bq, bk)
# f32 probability tile is the VMEM governor: 1024^2*4B = 4M of ~16M)
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, sm_scale: float, causal: bool,
                block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0]  # (block_q, D)
        k = k_ref[0]  # (block_k, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        m_safe = jnp.where(m_new <= NEG_INF, 0.0, m_new)
        p = jnp.exp(s - m_safe)
        correction = jnp.exp(jnp.where(m_prev <= NEG_INF, NEG_INF,
                                       m_prev - m_safe))
        l_ref[:] = l_ref[:] * correction + p.sum(axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * correction + pv
        m_ref[:] = m_new

    if causal:
        # whole block above the diagonal: nothing to add
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        m = m_ref[:]
        lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe))
        lse_ref[0] = lse  # (block_q, 1)


def _flash_fwd_call(q, k, v, sm_scale: float, causal: bool,
                    block_q: int, block_k: int, interpret: bool):
    """q/k/v: (BH, S, D) -> (out (BH, Sq, D), lse (BH, Sq))."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q, sk // block_k)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k)
    scratch = [
        pltpu.VMEM((block_q, d), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        # (BH, Sq, 1): trailing dim 1 == full array dim satisfies the TPU
        # block-tiling rule (last two block dims divisible by (8, 128) OR
        # equal to the array dims)
        jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
    ]
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _bwd_blockwise(q, k, v, out, lse, g, sm_scale: float, causal: bool,
                   block_k: int):
    """Memory-bounded backward: scan over k blocks recomputing P from LSE.

    q/k/v/out/g: (BH, S, D), lse: (BH, Sq).  Standard FlashAttention-2
    gradient: D = rowsum(dO * O); dS = P * (dP - D); dQ = dS K;
    dK = dS^T Q; dV = P^T dO.
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    nk = sk // block_k
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # (BH, Sq)
    qpos = jnp.arange(sq)
    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)

    def kblock(carry, j):
        dq_acc = carry
        kb = lax.dynamic_slice_in_dim(k, j * block_k, block_k, 1).astype(jnp.float32)
        vb = lax.dynamic_slice_in_dim(v, j * block_k, block_k, 1).astype(jnp.float32)
        s = jnp.einsum("bqd,bkd->bqk", qf, kb) * sm_scale
        if causal:
            kpos = j * block_k + jnp.arange(block_k)
            s = jnp.where(qpos[:, None] >= kpos[None, :], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])  # rows with lse=NEG_INF -> exp(-inf)=0
        dv = jnp.einsum("bqk,bqd->bkd", p, gf)
        dp = jnp.einsum("bqd,bkd->bqk", gf, vb)
        ds = p * (dp - delta[..., None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, kb)
        dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq_acc, (dk, dv)

    dq, (dks, dvs) = lax.scan(kblock, jnp.zeros_like(qf), jnp.arange(nk))
    # dks/dvs: (nk, BH, block_k, D) -> (BH, Sk, D)
    dk = jnp.moveaxis(dks, 0, 1).reshape(bh, sk, d)
    dv = jnp.moveaxis(dvs, 0, 1).reshape(bh, sk, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd_call(q, k, v, sm_scale, causal, block_q, block_k,
                             interpret)
    return out


def _flash_core_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_call(q, k, v, sm_scale, causal, block_q, block_k,
                               interpret)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _bwd_blockwise(q, k, v, out, lse, g, sm_scale, causal, block_k)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False) -> jax.Array:
    """Blockwise flash attention over (B, S, H, D) inputs.

    Falls back to `dense_attention` when the sequence doesn't tile by the
    block sizes or pallas is unavailable, so it is always safe to call.

    Measurement history (v5e, causal, bf16 — the default follows the
    measurement, not an assumption):

    * round-3 toolchain (H=8, D=64): this kernel beat the XLA
      einsum-softmax path from S~8k (22.6 vs 28.8 ms) and was the only
      path that compiled at S=32768 (dense died on the scores buffer).
    * round-5 re-measure: INVALID.  bench_transformer.py built q/k/v as
      (B, H, S, D) against cores that take (B, S, H, D), so its sweep
      timed attention over an actual sequence length of D with S heads;
      the "dense wins everywhere, 0.42x-0.76x" verdict and the
      `use_flash=False` default flip drawn from it were artifacts
      (ADVICE.md r5, high).  The layout is fixed; the default is back at
      `use_flash=True` per the round-3 measurement until a valid re-run
      on the current toolchain says otherwise.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    on_tpu = jax.default_backend() == "tpu"
    if (not _HAS_PLTPU) or sq % bq or sk % bk or not (on_tpu or interpret):
        return dense_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    # (B, S, H, D) -> (B*H, S, D)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    out = _flash_core(qt, kt, vt, scale, causal, bq, bk, interpret)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
