"""Decode-specialized attention: length-1 query against a (paged) KV cache.

The generation hot loop (bigdl_tpu/generation/engine.py) spends its life
in exactly one attention shape: ONE new query token per slot against the
slot's cached prefix.  The generic cached path (nn/attention.py) serves
that shape with full machinery — a vmapped materialized `(B, 1, C)` mask
and `dense_attention` logits carrying a dead q-length axis.  This module
is the raw-speed lane for that shape (ROADMAP item 4), in two tiers:

  * `decode_attention_ref` — the specialized XLA lowering: no q-length
    axis anywhere, the position mask computed directly from `lengths`
    (one `(B, C)` compare instead of a vmapped `causal_mask` build).
    This is the BASELINE every kernel must beat, and the shipped default
    where measured to win (see `decode_impl`).
  * `decode_attention_pallas` — a Pallas TPU kernel: fused
    gather-via-block-table (scalar-prefetched table indexes the pool
    block DMA directly — no materialized `(B, C, H, D)` gather), ring
    mask, online softmax and V-accumulate in VMEM scratch; never
    materializes `(1, capacity)` scores in HBM.  Int8 KV dequant happens
    on the block inside the kernel.

Shipping discipline (the round-5 rule, BENCH_APPENDIX "Decode attention
kernel"): a tier is enabled by default ONLY for backends/bucket sizes
where the interleaved A/B (benchmarks/bench_generation.py
--decode-quick, committed in benchmarks/results/decode_quick.json) shows
it beating the incumbent.  `BIGDL_TPU_DECODE_KERNEL` overrides:
`dense` (generic path) | `ref` | `pallas` | `auto` (default, measured
table).  Losing configurations stay OFF and documented.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30

# Measured defaults per backend (decode_quick.json is the evidence; see
# module docstring).  Values: "ref" | "pallas" | "dense".  A backend or
# bucket size missing here falls back to "dense" — the generic path —
# because an unmeasured fast path is a rumor, not a default.
#   * cpu: the interleaved A/B (decode_quick.json, 2026-08) split by
#     capacity — the generic path won at 32/128 (13.8 vs 19.7 us, 35.2
#     vs 58.1 us) and the specialized lowering won from 512 up (1.07x /
#     1.04x / 1.03x at 512/1024/4096).  Only the measured winners ship;
#     unmeasured capacities take the "*" dense fallback rather than
#     interpolating the crossover.
#   * tpu: NO valid on-TPU measurement exists yet for either tier (the
#     container is CPU-only); both stay off by default until a real A/B
#     lands, exactly like the round-5 flash retirement.  Force with
#     BIGDL_TPU_DECODE_KERNEL=ref|pallas to measure.
_MEASURED_DEFAULTS = {
    "cpu": {32: "dense", 128: "dense", 512: "ref", 1024: "ref",
            4096: "ref", "*": "dense"},
    "tpu": {},
}


def decode_impl(capacity: int, platform: Optional[str] = None) -> str:
    """Resolve which decode-attention tier serves a bucket of `capacity`:
    env override first, else the measured default table, else "dense"."""
    env = os.environ.get("BIGDL_TPU_DECODE_KERNEL", "auto").strip().lower()
    if env in ("0", "off", "false", "dense"):
        return "dense"
    if env in ("ref", "xla"):
        return "ref"
    if env == "pallas":
        return "pallas"
    platform = platform or jax.default_backend()
    table = _MEASURED_DEFAULTS.get(platform, {})
    return table.get(capacity, table.get("*", "dense"))


# -- XLA-lowering reference (the baseline to beat) -------------------------


def decode_attention_ref(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         lengths: jax.Array,
                         sm_scale: Optional[float] = None) -> jax.Array:
    """Length-1-query attention over a ring cache, specialized lowering.

    q: (B, H, D) — the single new token per slot, already rope'd.
    k/v: (B, C, H, D) — the resident ring (dequantized if int8).
    lengths: (B,) int32 — the query's absolute position per slot; ring
    column j is attendable iff j <= lengths[b] (same semantics as
    `causal_mask(1, C, q_offset=lengths)` in the generic path).
    Returns (B, H, D).
    """
    d = q.shape[-1]
    qs = q * (sm_scale if sm_scale is not None else d ** -0.5)
    logits = jnp.einsum("bhd,bkhd->bhk", qs, k)
    mask = lengths[:, None] >= jnp.arange(k.shape[1])[None, :]  # (B, C)
    logits = jnp.where(mask[:, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", probs, v)


# -- pallas kernel: fused gather + mask + online softmax + V-accumulate ----


def _decode_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                   sm_scale: float, block_size: int, quant: bool):
    if quant:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)  # (H, D)
    k = k_ref[0]                      # (BLK, H, D) — the table-gathered block
    v = v_ref[0]
    if quant:
        k = k.astype(jnp.float32) * ks_ref[0][..., None]  # (BLK, H) scales
        v = v.astype(jnp.float32) * vs_ref[0][..., None]
    # (H, BLK): contract D, batch over H — one small MXU matmul per head
    s = lax.dot_general(
        q * sm_scale, k, (((1,), (2,)), ((0,), (1,))),
        preferred_element_type=jnp.float32)
    # ring column j*BLK + r is attendable iff <= lengths[b] (the query's
    # absolute position); also excludes the unwritten tail AND trash-block
    # columns of unclaimed table entries
    cols = j * block_size + lax.broadcasted_iota(
        jnp.int32, (1, block_size), 1)
    s = jnp.where(cols <= len_ref[b], s, NEG_INF)

    m_prev = m_ref[:]
    m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
    m_safe = jnp.where(m_new <= NEG_INF, 0.0, m_new)
    p = jnp.exp(s - m_safe)
    correction = jnp.exp(jnp.where(m_prev <= NEG_INF, NEG_INF,
                                   m_prev - m_safe))
    l_ref[:] = l_ref[:] * correction + p.sum(axis=1, keepdims=True)
    # (H, D) += (H, BLK) @ (BLK, H, D) batched over H
    pv = lax.dot_general(p, v.astype(jnp.float32),
                         (((1,), (0,)), ((), ())))  # (H, H, D)? no — see below
    # dot_general without batch dims over (H,BLK)x(BLK,H,D) contracts to
    # (H, H, D); we need the DIAGONAL over the two H axes, so instead use
    # a batched contraction: batch H, contract BLK
    del pv
    pv = lax.dot_general(p, v.astype(jnp.float32),
                         (((1,), (0,)), ((0,), (1,))))
    acc_ref[:] = acc_ref[:] * correction + pv
    m_ref[:] = m_new

    @pl.when(j == nb - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def decode_attention_pallas(q: jax.Array, pool_k: jax.Array,
                            pool_v: jax.Array, table: jax.Array,
                            lengths: jax.Array, *,
                            k_scale: Optional[jax.Array] = None,
                            v_scale: Optional[jax.Array] = None,
                            sm_scale: Optional[float] = None,
                            interpret: bool = False) -> jax.Array:
    """Paged decode attention: the block table drives the K/V block DMA.

    q: (B, H, D); pool_k/pool_v: (n_blocks, BLK, H, D) — ONE layer of the
    shared pool; table: (B, max_blocks) int32 pool block ids (0 = trash
    block, whose columns the ring mask excludes); lengths: (B,) int32.
    Optional k_scale/v_scale: (n_blocks, BLK, H) fp32 for int8 pools.
    Returns (B, H, D) in q's dtype.

    The scalar-prefetched `table`/`lengths` are available before the
    kernel body runs, so the per-(slot, block) grid step DMAs exactly the
    pool block the table names — the gather IS the index map
    (PrefetchScalarGridSpec, per /opt/skills/guides/pallas_guide.md).
    """
    if not _HAS_PLTPU:
        raise NotImplementedError("pallas TPU backend unavailable")
    b, h, d = q.shape
    nb = table.shape[1]
    blk = pool_k.shape[1]
    quant = k_scale is not None
    scale = sm_scale if sm_scale is not None else d ** -0.5
    kernel = functools.partial(_decode_kernel, sm_scale=scale,
                               block_size=blk, quant=quant)
    in_specs = [
        pl.BlockSpec((1, h, d), lambda i, j, tr, lr: (i, 0, 0)),
        pl.BlockSpec((1, blk, h, d), lambda i, j, tr, lr: (tr[i, j], 0, 0, 0)),
        pl.BlockSpec((1, blk, h, d), lambda i, j, tr, lr: (tr[i, j], 0, 0, 0)),
    ]
    args = [q, pool_k, pool_v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, blk, h), lambda i, j, tr, lr: (tr[i, j], 0, 0)),
            pl.BlockSpec((1, blk, h), lambda i, j, tr, lr: (tr[i, j], 0, 0)),
        ]
        args += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nb),  # block axis innermost => sequential on TPU
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda i, j, tr, lr: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(table.astype(jnp.int32), lengths.astype(jnp.int32), *args)
