"""Pallas TPU fused matmul + BN-statistics epilogue, with custom VJP.

The perf lever named by BENCH_APPENDIX.md: training-mode BatchNorm forces
every conv output to materialize in HBM so the stats reduce (Σy, Σy²) can
run before the normalize pass — one extra full read of the conv output
per conv+BN pair.  This kernel computes the per-channel sums IN THE CONV
EPILOGUE while the output tile is still in VMEM, deleting that read.

Scope: 1x1 convolutions, which ARE matmuls ((N·H·W, Cin) × (Cin, Cout))
and carry most of ResNet's conv-output bytes (2 of 3 convs per bottleneck
— including the widest 4C expand).  3x3 convs keep the XLA path.

Reference role: conv+BN fusion is the reference's marquee MKL-DNN
optimization (`nn/mkldnn/Fusion.scala:26-31`); its training-side stats
fusion happens inside MKL-DNN's batchnorm primitive.  This is the
TPU-native equivalent: matmul on the MXU, stats on the VPU, one HBM pass.

Design (per /opt/skills/guides/pallas_guide.md):
  * grid = (N/bn, M/bm, K/bk): k innermost (sequential on TPU) so the f32
    accumulator lives in VMEM scratch across k steps; m next, so the
    (1, bn) stats tiles stay resident while every m block accumulates
    into them; n outermost.
  * matmul on the MXU with preferred_element_type=float32; the epilogue
    (at the last k step) writes the y tile once and adds its column sums
    into the stats tiles — y is never re-read.
  * stats are exact f32 sums; mean = Σy/M, biased var = Σy²/M − mean²,
    matching `nn.BatchNormalization` training semantics bit-for-bit in
    f32 (bf16 y introduces the same rounding the unfused path has).

Backward (custom VJP): d/dy_total = ȳ + s̄1 + 2·y·s̄2 (s1 = Σy, s2 = Σy²),
then the standard matmul cotangents x̄ = ȳ_tot·Wᵀ, W̄ = xᵀ·ȳ_tot — exact,
so gradient parity with the unfused conv+BN is a test invariant, not an
approximation.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

# v5e VMEM governor: bm*bk + bk*bn inputs + bm*bn f32 acc well under 16M
DEFAULT_BLOCK_M = 512
DEFAULT_BLOCK_N = 256
DEFAULT_BLOCK_K = 256


def _kernel(x_ref, w_ref, y_ref, s1_ref, s2_ref, acc_ref):
    mi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(x_ref[:], w_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _epilogue():
        y = acc_ref[:]
        y_ref[:] = y.astype(y_ref.dtype)
        p1 = jnp.sum(y, axis=0, keepdims=True)
        p2 = jnp.sum(y * y, axis=0, keepdims=True)

        @pl.when(mi == 0)
        def _first():
            s1_ref[:] = p1
            s2_ref[:] = p2

        @pl.when(mi > 0)
        def _accum():
            s1_ref[:] += p1
            s2_ref[:] += p2


def _pad_to(a, axis, mult):
    size = a.shape[axis]
    rem = size % mult
    if rem == 0:
        return a
    pads = [(0, 0)] * a.ndim
    pads[axis] = (0, mult - rem)
    return jnp.pad(a, pads)


def _matmul_stats_call(x, w, block_m, block_n, block_k, interpret):
    m, k = x.shape
    _, n = w.shape
    xp = _pad_to(_pad_to(x, 0, block_m), 1, block_k)
    wp = _pad_to(_pad_to(w, 0, block_k), 1, block_n)
    mp, kp = xp.shape
    np_ = wp.shape[1]
    grid = (np_ // block_n, mp // block_m, kp // block_k)
    scratch = [pltpu.VMEM((block_m, block_n), jnp.float32)]
    y, s1, s2 = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda ni, mi, ki: (mi, ki)),
            pl.BlockSpec((block_k, block_n), lambda ni, mi, ki: (ki, ni)),
        ],
        out_specs=[
            pl.BlockSpec((block_m, block_n), lambda ni, mi, ki: (mi, ni)),
            pl.BlockSpec((1, block_n), lambda ni, mi, ki: (0, ni)),
            pl.BlockSpec((1, block_n), lambda ni, mi, ki: (0, ni)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, np_), x.dtype),
            jax.ShapeDtypeStruct((1, np_), jnp.float32),
            jax.ShapeDtypeStruct((1, np_), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(xp, wp)
    # padded rows/cols are zero: they add nothing to the sums
    return y[:m, :n], s1[0, :n], s2[0, :n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _matmul_stats(x, w, block_m, block_n, block_k, interpret):
    return _matmul_stats_call(x, w, block_m, block_n, block_k, interpret)


def _matmul_stats_fwd(x, w, block_m, block_n, block_k, interpret):
    y, s1, s2 = _matmul_stats_call(x, w, block_m, block_n, block_k,
                                   interpret)
    return (y, s1, s2), (x, w, y)


def _matmul_stats_bwd(block_m, block_n, block_k, interpret, res, cot):
    x, w, y = res
    y_bar, s1_bar, s2_bar = cot
    # stats cotangents fold into the y cotangent: s1 = Σ_m y, s2 = Σ_m y²
    g = (y_bar.astype(jnp.float32)
         + s1_bar[None, :]
         + 2.0 * y.astype(jnp.float32) * s2_bar[None, :])
    x_bar = jnp.dot(g, w.astype(jnp.float32).T,
                    preferred_element_type=jnp.float32).astype(x.dtype)
    w_bar = jnp.dot(x.astype(jnp.float32).T, g,
                    preferred_element_type=jnp.float32).astype(w.dtype)
    return x_bar, w_bar


_matmul_stats.defvjp(_matmul_stats_fwd, _matmul_stats_bwd)


def _dense_matmul_stats(x, w):
    """XLA fallback with identical semantics (used off-TPU and for odd
    shapes); jax.grad of this matches the custom VJP above exactly."""
    y = jnp.dot(x, w, preferred_element_type=jnp.float32)
    yf = y.astype(jnp.float32)
    return y.astype(x.dtype), jnp.sum(yf, 0), jnp.sum(yf * yf, 0)


def matmul_bn_stats(x, w, *, block_m: int = DEFAULT_BLOCK_M,
                    block_n: int = DEFAULT_BLOCK_N,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(M, K) × (K, N) -> (y, Σ_M y, Σ_M y²) in one HBM pass over y."""
    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    if (not _HAS_PLTPU) or not (on_tpu or interpret):
        return _dense_matmul_stats(x, w)
    return _matmul_stats(x, w, block_m, block_n, block_k, interpret)


def conv1x1_bn_stats(x, w, *, stride: int = 1, interpret: bool = False
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """1x1 conv (NHWC × HWIO) returning (y, Σy, Σy²) over (N, H, W).

    `stride` subsamples the input first (exactly a strided 1x1 conv).
    The sums divide by M = N·H_out·W_out to give BN's biased moments.
    """
    if w.shape[0] != 1 or w.shape[1] != 1:
        raise ValueError(f"conv1x1_bn_stats needs a 1x1 kernel, got "
                         f"{w.shape[:2]}")
    if stride > 1:
        x = x[:, ::stride, ::stride, :]
    n, h, ww, cin = x.shape
    cout = w.shape[3]
    y2d, s1, s2 = matmul_bn_stats(x.reshape(n * h * ww, cin),
                                  w.reshape(cin, cout),
                                  interpret=interpret)
    return y2d.reshape(n, h, ww, cout), s1, s2
