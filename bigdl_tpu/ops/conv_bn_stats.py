"""Pallas TPU fused matmul + BN-statistics epilogue, with custom VJP.

The perf lever named by BENCH_APPENDIX.md: training-mode BatchNorm forces
every conv output to materialize in HBM so the stats reduce (Σy, Σy²) can
run before the normalize pass — one extra full read of the conv output
per conv+BN pair.  This kernel computes the per-channel sums IN THE CONV
EPILOGUE while the output tile is still in VMEM, deleting that read.

Scope: 1x1 convolutions, which ARE matmuls ((N·H·W, Cin) × (Cin, Cout))
and carry most of ResNet's conv-output bytes (2 of 3 convs per bottleneck
— including the widest 4C expand).  3x3 convs keep the XLA path.

Reference role: conv+BN fusion is the reference's marquee MKL-DNN
optimization (`nn/mkldnn/Fusion.scala:26-31`); its training-side stats
fusion happens inside MKL-DNN's batchnorm primitive.  This is the
TPU-native equivalent: matmul on the MXU, stats on the VPU, one HBM pass.

Design (per /opt/skills/guides/pallas_guide.md):
  * grid = (N/bn, M/bm, K/bk): k innermost (sequential on TPU) so the f32
    accumulator lives in VMEM scratch across k steps; m next, so the
    (1, bn) stats tiles stay resident while every m block accumulates
    into them; n outermost.
  * matmul on the MXU with preferred_element_type=float32; the epilogue
    (at the last k step) writes the y tile once and adds its column sums
    into the stats tiles — y is never re-read.
  * stats are exact f32 sums; mean = Σy/M, biased var = Σy²/M − mean²,
    matching `nn.BatchNormalization` training semantics bit-for-bit in
    f32 (bf16 y introduces the same rounding the unfused path has).

Backward (custom VJP): d/dy_total = ȳ + s̄1 + 2·y·s̄2 (s1 = Σy, s2 = Σy²),
then the standard matmul cotangents x̄ = ȳ_tot·Wᵀ, W̄ = xᵀ·ȳ_tot.  The
cotangent matmuls run in the INPUT dtype with f32 accumulation — the
same precision class as the unfused conv backward (all-f32 matmuls were
measured ~40% slower end-to-end), so gradient parity with the unfused
conv+BN holds to that precision class, bit-exact when inputs are f32.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

# v5e VMEM governor: bm*bk + bk*bn inputs + bm*bn f32 acc well under 16M.
# bm=1024 measured best across all ResNet 1x1 shapes (min-of-3x50 sweep on
# chip: 6-23% under both XLA and bm=512); bm=2048 regresses narrow-N.
DEFAULT_BLOCK_M = 1024
DEFAULT_BLOCK_N = 256
DEFAULT_BLOCK_K = 256


def _kernel(x_ref, w_ref, y_ref, s1_ref, s2_ref, acc_ref):
    mi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(x_ref[:], w_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _epilogue():
        y = acc_ref[:]
        y_ref[:] = y.astype(y_ref.dtype)
        p1 = jnp.sum(y, axis=0, keepdims=True)
        p2 = jnp.sum(y * y, axis=0, keepdims=True)

        @pl.when(mi == 0)
        def _first():
            s1_ref[:] = p1
            s2_ref[:] = p2

        @pl.when(mi > 0)
        def _accum():
            s1_ref[:] += p1
            s2_ref[:] += p2


def _pad_to_mult(v, mult):
    return -(-v // mult) * mult


def _pad_to(a, axis, mult):
    size = a.shape[axis]
    rem = size % mult
    if rem == 0:
        return a
    pads = [(0, 0)] * a.ndim
    pads[axis] = (0, mult - rem)
    return jnp.pad(a, pads)


def _clamp_block(block, dim):
    """Shrink a block size to the actual dim so small channel counts do
    not pad 4x (e.g. N=64 under block_n=256 quadruples the y write and
    the MXU work; measured 27% slower than XLA on the 256->64 reduce
    conv).  A dim under the 128-lane width is used as-is — Mosaic pads
    the VMEM tile internally, which wastes MXU lanes but avoids the HBM
    pad copy a jnp.pad would cost."""
    if dim >= block:
        return block
    return dim if dim <= 128 or dim % 128 == 0 else block


def _matmul_stats_call(x, w, block_m, block_n, block_k, interpret):
    m, k = x.shape
    _, n = w.shape
    block_n = _clamp_block(block_n, n)
    block_k = _clamp_block(block_k, k)
    block_m = min(block_m, _pad_to_mult(m, 8))
    xp = _pad_to(_pad_to(x, 0, block_m), 1, block_k)
    wp = _pad_to(_pad_to(w, 0, block_k), 1, block_n)
    mp, kp = xp.shape
    np_ = wp.shape[1]
    grid = (np_ // block_n, mp // block_m, kp // block_k)
    scratch = [pltpu.VMEM((block_m, block_n), jnp.float32)]
    y, s1, s2 = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda ni, mi, ki: (mi, ki)),
            pl.BlockSpec((block_k, block_n), lambda ni, mi, ki: (ki, ni)),
        ],
        out_specs=[
            pl.BlockSpec((block_m, block_n), lambda ni, mi, ki: (mi, ni)),
            pl.BlockSpec((1, block_n), lambda ni, mi, ki: (0, ni)),
            pl.BlockSpec((1, block_n), lambda ni, mi, ki: (0, ni)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, np_), x.dtype),
            jax.ShapeDtypeStruct((1, np_), jnp.float32),
            jax.ShapeDtypeStruct((1, np_), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(xp, wp)
    # padded rows/cols are zero: they add nothing to the sums
    return y[:m, :n], s1[0, :n], s2[0, :n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _matmul_stats(x, w, block_m, block_n, block_k, interpret):
    return _matmul_stats_call(x, w, block_m, block_n, block_k, interpret)


def _matmul_stats_fwd(x, w, block_m, block_n, block_k, interpret):
    y, s1, s2 = _matmul_stats_call(x, w, block_m, block_n, block_k,
                                   interpret)
    return (y, s1, s2), (x, w, y)


def _matmul_stats_bwd(block_m, block_n, block_k, interpret, res, cot):
    x, w, y = res
    y_bar, s1_bar, s2_bar = cot
    # stats cotangents fold into the y cotangent: s1 = Σ_m y, s2 = Σ_m y²
    g = (y_bar.astype(jnp.float32)
         + s1_bar[None, :]
         + 2.0 * y.astype(jnp.float32) * s2_bar[None, :])
    # the cotangent matmuls run in the INPUT dtype (bf16 on the bench
    # path) with f32 accumulation — the same precision class as the
    # unfused conv backward.  Keeping g in f32 here forces f32 MXU
    # matmuls, several times slower than bf16 (measured: the all-f32
    # backward cost the fused step ~40% end-to-end).
    g = g.astype(x.dtype)
    x_bar = jnp.dot(g, w.T,
                    preferred_element_type=jnp.float32).astype(x.dtype)
    w_bar = jnp.dot(x.T, g,
                    preferred_element_type=jnp.float32).astype(w.dtype)
    return x_bar, w_bar


_matmul_stats.defvjp(_matmul_stats_fwd, _matmul_stats_bwd)


def _dense_matmul_stats(x, w):
    """XLA fallback with identical semantics (used off-TPU and for odd
    shapes); jax.grad of this matches the custom VJP above exactly."""
    y = jnp.dot(x, w, preferred_element_type=jnp.float32)
    yf = y.astype(jnp.float32)
    return y.astype(x.dtype), jnp.sum(yf, 0), jnp.sum(yf * yf, 0)


def _use_pallas(interpret: bool) -> bool:
    """One place for the backend dispatch both entry points share."""
    if not _HAS_PLTPU:
        return False
    return interpret or any(d.platform == "tpu" for d in jax.devices())


def matmul_bn_stats(x, w, *, block_m: int = DEFAULT_BLOCK_M,
                    block_n: int = DEFAULT_BLOCK_N,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(M, K) × (K, N) -> (y, Σ_M y, Σ_M y²) in one HBM pass over y."""
    if not _use_pallas(interpret):
        return _dense_matmul_stats(x, w)
    return _matmul_stats(x, w, block_m, block_n, block_k, interpret)


# ---------------------------------------------------------------------------
# 4D-native path: NHWC in, NHWC out.  The 2D matmul view above costs two
# HBM retiling copies per conv on TPU (the (N*H*W, C) <-> NHWC reshapes are
# NOT bitcasts under tiled layouts — measured +26 GB/step on the b256
# ResNet-50 train step, turning the fusion into a 35% LOSS).  Here the
# (bh*W, C) flattening happens on the VMEM block inside the kernel, where
# it is a no-op relayout whenever W is a multiple of the 8-sublane tile,
# and the backward is expressed as a 1x1 conv + dot_general so no reshape
# ever touches HBM.
# ---------------------------------------------------------------------------


def _kernel4d(x_ref, w_ref, y_ref, s1_ref, s2_ref, acc_ref):
    mi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    _, bh, wdim, bk = x_ref.shape
    xb = x_ref[:].reshape(bh * wdim, bk)
    acc_ref[:] += jnp.dot(xb, w_ref[:], preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _epilogue():
        y = acc_ref[:]
        y_ref[:] = y.reshape(y_ref.shape).astype(y_ref.dtype)
        p1 = jnp.sum(y, axis=0, keepdims=True)
        p2 = jnp.sum(y * y, axis=0, keepdims=True)

        @pl.when(mi == 0)
        def _first():
            s1_ref[:] = p1
            s2_ref[:] = p2

        @pl.when(mi > 0)
        def _accum():
            s1_ref[:] += p1
            s2_ref[:] += p2


def _pick_bh(h: int, w: int, target_rows: int) -> int:
    """Largest divisor of h with bh*w <= target rows (>=1)."""
    best = 1
    for bh in range(1, h + 1):
        if h % bh == 0 and bh * w <= target_rows:
            best = bh
    return best


def _conv_stats_call_4d(x, w2d, block_n, block_k, interpret):
    n, h, wdim, cin = x.shape
    cout = w2d.shape[1]
    bn = _clamp_block(block_n, cout)
    bk = _clamp_block(block_k, cin)
    bh = _pick_bh(h, wdim, DEFAULT_BLOCK_M)
    xp = _pad_to(x, 3, bk)
    wp = _pad_to(_pad_to(w2d, 0, bk), 1, bn)
    kp = xp.shape[3]
    np_ = wp.shape[1]
    grid = (np_ // bn, n * (h // bh), kp // bk)
    h_blocks = h // bh
    y, s1, s2 = pl.pallas_call(
        _kernel4d,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bh, wdim, bk),
                         lambda ni, mi, ki: (mi // h_blocks, mi % h_blocks,
                                             0, ki)),
            pl.BlockSpec((bk, bn), lambda ni, mi, ki: (ki, ni)),
        ],
        out_specs=[
            pl.BlockSpec((1, bh, wdim, bn),
                         lambda ni, mi, ki: (mi // h_blocks, mi % h_blocks,
                                             0, ni)),
            pl.BlockSpec((1, bn), lambda ni, mi, ki: (0, ni)),
            pl.BlockSpec((1, bn), lambda ni, mi, ki: (0, ni)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wdim, np_), x.dtype),
            jax.ShapeDtypeStruct((1, np_), jnp.float32),
            jax.ShapeDtypeStruct((1, np_), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bh * wdim, bn), jnp.float32)],
        interpret=interpret,
    )(xp, wp)
    return y[..., :cout], s1[0, :cout], s2[0, :cout]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _conv_stats_4d(x, w2d, block_n, block_k, interpret):
    return _conv_stats_call_4d(x, w2d, block_n, block_k, interpret)


def _conv_stats_4d_fwd(x, w2d, block_n, block_k, interpret):
    y, s1, s2 = _conv_stats_call_4d(x, w2d, block_n, block_k, interpret)
    return (y, s1, s2), (x, w2d, y)


def _conv_stats_4d_bwd(block_n, block_k, interpret, res, cot):
    x, w2d, y = res
    y_bar, s1_bar, s2_bar = cot
    # stats cotangents fold into y's: s1 = Σ_nhw y, s2 = Σ_nhw y².
    g = (y_bar.astype(jnp.float32)
         + s1_bar[None, None, None, :]
         + 2.0 * y.astype(jnp.float32) * s2_bar[None, None, None, :])
    # bf16 matmuls with f32 accumulation — the unfused conv backward's
    # precision class (all-f32 cotangent matmuls measured ~40% slower
    # end-to-end).
    g = g.astype(x.dtype)
    cin, cout = w2d.shape
    # x̄ = g ∗ Wᵀ as a 1x1 conv: stays NHWC, no reshape through HBM.
    x_bar = jax.lax.conv_general_dilated(
        g, w2d.T.reshape(1, 1, cout, cin), window_strides=(1, 1),
        padding="VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32).astype(x.dtype)
    # W̄ = Σ_nhw x ⊗ g: dot_general contracting the spatial dims directly.
    w_bar = jax.lax.dot_general(
        x, g, (((0, 1, 2), (0, 1, 2)), ((), ())),
        preferred_element_type=jnp.float32).astype(w2d.dtype)
    return x_bar, w_bar


_conv_stats_4d.defvjp(_conv_stats_4d_fwd, _conv_stats_4d_bwd)


def conv1x1_bn_stats(x, w, *, stride: int = 1, interpret: bool = False
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """1x1 conv (NHWC × HWIO) returning (y, Σy, Σy²) over (N, H, W).

    `stride` subsamples the input first (exactly a strided 1x1 conv).
    The sums divide by M = N·H_out·W_out to give BN's biased moments.
    """
    if w.shape[0] != 1 or w.shape[1] != 1:
        raise ValueError(f"conv1x1_bn_stats needs a 1x1 kernel, got "
                         f"{w.shape[:2]}")
    if stride > 1:
        x = x[:, ::stride, ::stride, :]
    n, h, ww, cin = x.shape
    cout = w.shape[3]
    # The pallas path is only profitable when the in-kernel (bh*W, C)
    # flatten is a no-op relayout: W a multiple of the 8-sublane tile.
    # Other widths re-enter the retiling-copy regime measured as a net
    # loss (BENCH_APPENDIX.md), so they take the XLA path regardless of
    # what the caller's width guess was — semantics are identical either
    # way, this is purely a perf-safety gate.
    if not _use_pallas(interpret) or ww % 8 != 0:
        y2d, s1, s2 = _dense_matmul_stats(x.reshape(n * h * ww, cin),
                                          w.reshape(cin, cout))
        return y2d.reshape(n, h, ww, cout), s1, s2
    return _conv_stats_4d(x, w.reshape(cin, cout), DEFAULT_BLOCK_N,
                          DEFAULT_BLOCK_K, interpret)
