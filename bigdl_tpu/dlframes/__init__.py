"""DataFrame ML-pipeline integration.

Reference: dlframes/ — `DLEstimator`/`DLModel`/`DLClassifier`/
`DLClassifierModel` wrap the Optimizer as a Spark-ML Estimator/Transformer
over DataFrame columns (dlframes/DLEstimator.scala), plus
`DLImageTransformer` for image DataFrames.

TPU-native redesign: there is no Spark on the TPU host; the DataFrame of
record is pandas.  The Estimator/Model split and the column-oriented
fit/transform contract are preserved so pipeline code ports 1:1.
"""

from bigdl_tpu.dlframes.estimator import (
    DLEstimator,
    DLModel,
    DLClassifier,
    DLClassifierModel,
    DLImageReader,
    DLImageTransformer,
)
