"""DLEstimator / DLModel / DLClassifier over pandas DataFrames.

Reference: dlframes/DLEstimator.scala — an Estimator whose `fit` trains the
wrapped module with the builder-configured Optimizer over (features, label)
columns and returns a Transformer (`DLModel`) adding a prediction column;
`DLClassifier` specializes to class labels + argmax predictions.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from bigdl_tpu.core.random import RandomGenerator
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.minibatch import MiniBatch
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim import Optimizer, Trigger
from bigdl_tpu.optim.optim_method import Adam, OptimMethod


def _column_to_array(col, size: Sequence[int]) -> np.ndarray:
    rows = [np.asarray(v, np.float32).reshape(size) for v in col]
    return np.stack(rows)


class _FrameDataSet(DataSet):
    def __init__(self, x: np.ndarray, y: np.ndarray, batch_size: int):
        self.x, self.y = x, y
        self.batch_size = batch_size
        self._epoch = 0

    def size(self) -> int:
        return self.x.shape[0]

    def data(self, train: bool):
        n = (self.x.shape[0] // self.batch_size) * self.batch_size
        idx = np.arange(self.x.shape[0])
        if train:
            rs = np.random.RandomState(RandomGenerator.get_seed() + self._epoch)
            idx = rs.permutation(idx)
            self._epoch += 1
        for off in range(0, n, self.batch_size):
            sel = idx[off:off + self.batch_size]
            yield MiniBatch(self.x[sel], self.y[sel])


class DLEstimator:
    """reference: dlframes/DLEstimator.scala — builder config + fit()."""

    def __init__(self, model: Module, criterion, feature_size: Sequence[int],
                 label_size: Sequence[int]):
        self.model = model
        self.criterion = criterion
        self.feature_size = tuple(feature_size)
        self.label_size = tuple(label_size)
        self.batch_size = 32
        self.max_epoch = 10
        self.optim_method: OptimMethod = Adam()
        self.features_col = "features"
        self.label_col = "label"
        self.prediction_col = "prediction"

    # builder API (reference setters)
    def set_batch_size(self, v: int) -> "DLEstimator":
        self.batch_size = v
        return self

    def set_max_epoch(self, v: int) -> "DLEstimator":
        self.max_epoch = v
        return self

    def set_optim_method(self, m: OptimMethod) -> "DLEstimator":
        self.optim_method = m
        return self

    def set_features_col(self, c: str) -> "DLEstimator":
        self.features_col = c
        return self

    def set_label_col(self, c: str) -> "DLEstimator":
        self.label_col = c
        return self

    def set_prediction_col(self, c: str) -> "DLEstimator":
        self.prediction_col = c
        return self

    def _label_array(self, df) -> np.ndarray:
        return _column_to_array(df[self.label_col], self.label_size)

    def fit(self, df) -> "DLModel":
        if len(df) == 0:
            raise ValueError("cannot fit on an empty DataFrame")
        x = _column_to_array(df[self.features_col], self.feature_size)
        y = self._label_array(df)
        batch_size = min(self.batch_size, x.shape[0])
        opt = Optimizer(model=self.model, dataset=_FrameDataSet(x, y, batch_size),
                        criterion=self.criterion,
                        end_trigger=Trigger.max_epoch(self.max_epoch))
        opt.set_optim_method(self.optim_method)
        opt.optimize()
        return self._make_model(batch_size)

    def _make_model(self, batch_size: int) -> "DLModel":
        m = DLModel(self.model, self.feature_size)
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        m.batch_size = batch_size
        return m


class DLModel:
    """Transformer: adds a prediction column.
    reference: dlframes/DLEstimator.scala DLModel."""

    def __init__(self, model: Module, feature_size: Sequence[int]):
        self.model = model
        self.feature_size = tuple(feature_size)
        self.features_col = "features"
        self.prediction_col = "prediction"
        self.batch_size = 32
        self._predictor = None
        self._predictor_batch = None
        self._predictor_params = None

    def _forward(self, df) -> np.ndarray:
        from bigdl_tpu.optim import Predictor

        if len(df) == 0:
            raise ValueError("cannot transform an empty DataFrame")
        x = _column_to_array(df[self.features_col], self.feature_size)
        batch = min(self.batch_size, x.shape[0])
        # cache keyed on (batch, params identity): retraining the shared
        # Module swaps model.params, which must invalidate the jitted closure
        if (self._predictor is None or self._predictor_batch != batch
                or self._predictor_params is not self.model.params):
            self._predictor = Predictor(self.model, self.model.params,
                                        self.model.state, batch_size=batch)
            self._predictor_batch = batch
            self._predictor_params = self.model.params
        preds = self._predictor.predict(x)
        if isinstance(preds, list):
            # multi-output model: one tuple of per-head rows per record
            return list(zip(*preds))
        return np.asarray(preds)

    def transform(self, df):
        out = df.copy()
        preds = self._forward(df)
        out[self.prediction_col] = [row for row in preds]
        return out


class DLClassifier(DLEstimator):
    """Class-index labels; predictions are argmax class ids (1-based in the
    reference's Spark-ML convention — 0-based here, documented delta).
    reference: dlframes/DLClassifier.scala."""

    def __init__(self, model: Module, criterion, feature_size: Sequence[int]):
        super().__init__(model, criterion, feature_size, (1,))

    def _label_array(self, df) -> np.ndarray:
        return np.asarray(df[self.label_col], np.int32)

    def _make_model(self, batch_size: int) -> "DLClassifierModel":
        m = DLClassifierModel(self.model, self.feature_size)
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        m.batch_size = batch_size
        return m


class DLClassifierModel(DLModel):
    def transform(self, df):
        out = df.copy()
        preds = self._forward(df)
        out[self.prediction_col] = np.argmax(preds, axis=-1)
        return out


class DLImageReader:
    """Read image files into an image dataframe.

    Reference: dlframes/DLImageReader.scala — `readImages(path)` produces a
    DataFrame with an `image` struct column (origin, height, width,
    nChannels, data).  Here the frame is a pandas DataFrame whose `image`
    column holds float32 HWC arrays (channel order RGB — the TPU pipeline
    is RGB-native; the reference's BGR is an OpenCV-ism) plus origin/
    height/width/n_channels columns.
    """

    EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif")

    @staticmethod
    def read_images(path: str, recursive: bool = True):
        import glob
        import os

        import pandas as pd
        from PIL import Image

        if os.path.isdir(path):
            pattern = os.path.join(path, "**" if recursive else "", "*")
            names = sorted(glob.glob(pattern, recursive=recursive))
        else:
            names = sorted(glob.glob(path, recursive=recursive))
        rows = []
        for name in names:
            if not name.lower().endswith(DLImageReader.EXTENSIONS):
                continue
            with Image.open(name) as im:
                arr = np.asarray(im.convert("RGB"), np.float32)
            rows.append({"origin": name, "height": arr.shape[0],
                         "width": arr.shape[1], "n_channels": arr.shape[2],
                         "image": arr})
        return pd.DataFrame(rows,
                            columns=["origin", "height", "width", "n_channels", "image"])


class DLImageTransformer:
    """Apply a vision FeatureTransformer to an image column.
    reference: dlframes/DLImageTransformer.scala."""

    def __init__(self, transformer, image_col: str = "image",
                 output_col: str = "output"):
        self.transformer = transformer
        self.image_col = image_col
        self.output_col = output_col

    def transform(self, df):
        from bigdl_tpu.vision import ImageFeature

        out = df.copy()
        results = []
        for img in df[self.image_col]:
            feat = self.transformer(ImageFeature(np.asarray(img, np.float32)))
            results.append(feat.image)
        out[self.output_col] = results
        return out
