"""Standard dataset parsers/loaders.

Reference: pyspark/bigdl/dataset/{mnist,movielens,news20,sentence}.py (+
models/lenet reading idx files, dataset/DataSet.scala CIFAR-10 binary
reader).  The reference downloads then parses; this environment has no
egress, so parsers read LOCAL files and `maybe_download` only checks
existence (raising with the canonical URL in the message when missing).

All parsers return numpy arrays (host data; device placement is the
trainer's job).
"""

from __future__ import annotations

import gzip
import os
import struct
import tarfile
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

MNIST_URL = "http://yann.lecun.com/exdb/mnist/"
CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-binary.tar.gz"
MOVIELENS_URL = "http://files.grouplens.org/datasets/movielens/ml-1m.zip"
NEWS20_URL = "http://qwone.com/~jason/20Newsgroups/20news-19997.tar.gz"
GLOVE_URL = "http://nlp.stanford.edu/data/glove.6B.zip"

# the reference's canonical normalization constants
# (pyspark/bigdl/dataset/mnist.py TRAIN_MEAN/TRAIN_STD)
MNIST_TRAIN_MEAN = 0.13066047740239506 * 255
MNIST_TRAIN_STD = 0.3081078 * 255
CIFAR_MEAN = (125.3, 123.0, 113.9)
CIFAR_STD = (63.0, 62.1, 66.7)


def maybe_download(filename: str, work_dir: str, source_url: str) -> str:
    """Existence check standing in for the reference's downloader
    (zero-egress environment).  reference: pyspark/bigdl/dataset/base.py
    maybe_download."""
    path = os.path.join(work_dir, filename)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found and this environment has no network egress; "
            f"fetch it from {source_url} and place it there")
    return path


def _open_maybe_gzip(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


# ---------------------------------------------------------------------------
# MNIST (idx-ubyte)


def read_mnist_images(path: str) -> np.ndarray:
    """Parse an idx3-ubyte (optionally .gz) image file -> (N, 28, 28, 1)
    float32.  reference: pyspark/bigdl/dataset/mnist.py extract_images."""
    with _open_maybe_gzip(path) as f:
        magic, n, rows, cols = struct.unpack(">iiii", f.read(16))
        if magic != 2051:
            raise ValueError(f"bad idx3 magic {magic} in {path}")
        data = np.frombuffer(f.read(n * rows * cols), np.uint8)
    return data.reshape(n, rows, cols, 1).astype(np.float32)


def read_mnist_labels(path: str) -> np.ndarray:
    with _open_maybe_gzip(path) as f:
        magic, n = struct.unpack(">ii", f.read(8))
        if magic != 2049:
            raise ValueError(f"bad idx1 magic {magic} in {path}")
        return np.frombuffer(f.read(n), np.uint8).astype(np.int32)


def load_mnist(work_dir: str, kind: str = "train",
               normalize: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    prefix = "train" if kind == "train" else "t10k"
    img = None
    for suffix in ("-images-idx3-ubyte.gz", "-images-idx3-ubyte"):
        p = os.path.join(work_dir, prefix + suffix)
        if os.path.exists(p):
            img = p
            break
    if img is None:
        raise FileNotFoundError(
            f"no {prefix}-images-idx3-ubyte[.gz] under {work_dir} "
            f"(source: {MNIST_URL})")
    labels = img.replace("images-idx3", "labels-idx1")
    x = read_mnist_images(img)
    y = read_mnist_labels(labels)
    if normalize:
        x = (x - MNIST_TRAIN_MEAN) / MNIST_TRAIN_STD
    return x, y


# ---------------------------------------------------------------------------
# CIFAR-10 (binary batches)


def read_cifar10_bin(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """One CIFAR-10 binary batch file -> ((N, 32, 32, 3) float32, (N,) int32).
    reference: dataset/DataSet.scala Cifar-10 SeqFile/array pipeline."""
    raw = np.fromfile(path, np.uint8).reshape(-1, 3073)
    labels = raw[:, 0].astype(np.int32)
    imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return imgs.astype(np.float32), labels


def load_cifar10(work_dir: str, kind: str = "train",
                 normalize: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    sub = os.path.join(work_dir, "cifar-10-batches-bin")
    base = sub if os.path.isdir(sub) else work_dir
    names = [f"data_batch_{i}.bin" for i in range(1, 6)] if kind == "train" \
        else ["test_batch.bin"]
    xs, ys = [], []
    for n in names:
        p = os.path.join(base, n)
        if not os.path.exists(p):
            raise FileNotFoundError(f"{p} missing (source: {CIFAR10_URL})")
        x, y = read_cifar10_bin(p)
        xs.append(x)
        ys.append(y)
    x = np.concatenate(xs)
    y = np.concatenate(ys)
    if normalize:
        x = (x - np.asarray(CIFAR_MEAN)) / np.asarray(CIFAR_STD)
    return x.astype(np.float32), y


# ---------------------------------------------------------------------------
# MovieLens ratings


def load_movielens_ratings(path: str, sep: str = "::") -> np.ndarray:
    """ratings.dat -> (N, 3) int32 (user, item, rating).
    reference: pyspark/bigdl/dataset/movielens.py read_data_sets."""
    rows: List[Tuple[int, int, int]] = []
    with open(path, "r", encoding="latin-1") as f:
        for line in f:
            parts = line.strip().split(sep)
            if len(parts) >= 3:
                rows.append((int(parts[0]), int(parts[1]), int(float(parts[2]))))
    return np.asarray(rows, np.int32)


# ---------------------------------------------------------------------------
# News20 (20 newsgroups) text classification


def load_news20(work_dir: str) -> List[Tuple[str, int]]:
    """Directory-of-directories (or .tar.gz) -> [(text, label_idx)].
    reference: pyspark/bigdl/dataset/news20.py get_news20."""
    tar = None
    for cand in os.listdir(work_dir) if os.path.isdir(work_dir) else []:
        if cand.endswith(".tar.gz") and "news" in cand:
            tar = os.path.join(work_dir, cand)
            break
    texts: List[Tuple[str, int]] = []
    if tar is not None:
        # labels assigned by SORTED group name, matching the unpacked-dir
        # path below, so both layouts of the same data agree
        by_group: Dict[str, List[str]] = {}
        with tarfile.open(tar, "r:gz") as tf:
            for m in tf.getmembers():
                if not m.isfile():
                    continue
                parts = m.name.split("/")
                if len(parts) < 2:
                    continue
                data = tf.extractfile(m)
                if data is not None:
                    by_group.setdefault(parts[-2], []).append(
                        data.read().decode("latin-1"))
        for label, g in enumerate(sorted(by_group)):
            texts.extend((t, label) for t in by_group[g])
        return texts
    # unpacked layout: work_dir/<group>/<doc>
    groups = sorted(d for d in os.listdir(work_dir)
                    if os.path.isdir(os.path.join(work_dir, d)))
    if not groups:
        raise FileNotFoundError(
            f"no newsgroup directories or tarball under {work_dir} "
            f"(source: {NEWS20_URL})")
    for label, g in enumerate(groups):
        gdir = os.path.join(work_dir, g)
        for doc in sorted(os.listdir(gdir)):
            with open(os.path.join(gdir, doc), "r", encoding="latin-1") as f:
                texts.append((f.read(), label))
    return texts


def load_glove_embeddings(path: str, dim: int = 100
                          ) -> Tuple[Dict[str, int], np.ndarray]:
    """glove.6B.<dim>d.txt -> (word->row index, (V, dim) float32 matrix).
    reference: pyspark/bigdl/dataset/news20.py get_glove_w2v."""
    vocab: Dict[str, int] = {}
    vecs: List[np.ndarray] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            if len(parts) != dim + 1:
                continue
            vocab[parts[0]] = len(vecs)
            vecs.append(np.asarray(parts[1:], np.float32))
    return vocab, np.stack(vecs) if vecs else np.zeros((0, dim), np.float32)


# ---------------------------------------------------------------------------
# Sentence corpus (PTB-style)


def read_sentence_corpus(path: str) -> List[str]:
    """One sentence per line.  reference: pyspark/bigdl/dataset/sentence.py
    read_localfile."""
    with open(path, "r", encoding="utf-8") as f:
        return [line.strip() for line in f if line.strip()]
