"""Host-side image transformers (numpy, NHWC float32).

Reference: dataset/image/ (24 files — BytesToBGRImg, BGRImgCropper,
BGRImgNormalizer, ColorJitter, Lighting, HFlip, MTLabeledBGRImgToBatch).
The reference decodes/augments on Spark executors with OpenCV + JVM
threads; here augmentation is a host-side numpy pipeline feeding the TPU
input queue (channel order is RGB/NHWC, not BGR/NCHW — a TPU-native
layout decision, documented as a capability-parity delta).

Each transformer is a `Transformer` (iterator combinator, chained with
`>>`) over `LabeledImage` records.  Randomized transforms take a seed and
own a private RandomState so the pipeline is reproducible (the analogue of
the reference's per-executor RNG discipline).
"""

from __future__ import annotations

from typing import Any, Iterator, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


class LabeledImage:
    """One image record: HWC float32 array + label.
    reference: dataset/image/LabeledBGRImage.scala."""

    __slots__ = ("image", "label")

    def __init__(self, image: np.ndarray, label: Any = None):
        self.image = image
        self.label = label


# ---------------------------------------------------------------------------
# numpy kernels (shared with the vision ImageFrame pipeline)
# ---------------------------------------------------------------------------


try:  # SIMD resize for the hot augmentation path (the reference's
    # pipeline is OpenCV too: transform/vision/image/opencv); numpy
    # fallback below keeps the package dependency-free
    import cv2 as _cv2
except ImportError:  # pragma: no cover
    _cv2 = None


def resize_bilinear(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Bilinear resize, HWC (align_corners=False, half-pixel centers —
    OpenCV INTER_LINEAR / tf.image semantics).  Uses OpenCV's SIMD kernel
    when available: the pure-numpy path measured ~14 ms per ImageNet
    frame and capped the host input pipeline at ~33 img/s on 2 cores
    (benchmarks/bench_input_pipeline.py), vs sub-ms in cv2."""
    h, w = img.shape[:2]
    if (h, w) == (out_h, out_w):
        return img.astype(np.float32, copy=False)
    if _cv2 is not None:
        out = _cv2.resize(img.astype(np.float32, copy=False),
                          (out_w, out_h), interpolation=_cv2.INTER_LINEAR)
        if out.ndim < img.ndim:  # cv2 drops a size-1 channel axis
            out = out.reshape(out.shape + (1,) * (img.ndim - out.ndim))
        return out
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0).astype(np.float32)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0).astype(np.float32)[None, :, None]
    img = img.astype(np.float32, copy=False)
    r0, r1 = img[y0], img[y1]  # hoist the row gathers (hot augmentation path)
    top = r0[:, x0] * (1 - wx) + r0[:, x1] * wx
    bot = r1[:, x0] * (1 - wx) + r1[:, x1] * wx
    return top * (1 - wy) + bot * wy


def crop(img: np.ndarray, y: int, x: int, ch: int, cw: int) -> np.ndarray:
    return img[y:y + ch, x:x + cw]


def hflip(img: np.ndarray) -> np.ndarray:
    return img[:, ::-1]


def adjust_brightness(img: np.ndarray, delta: float) -> np.ndarray:
    return img + delta


def adjust_contrast(img: np.ndarray, factor: float) -> np.ndarray:
    mean = img.mean()
    return (img - mean) * factor + mean


def adjust_saturation(img: np.ndarray, factor: float) -> np.ndarray:
    gray = img @ np.asarray([0.299, 0.587, 0.114], np.float32)
    return (img - gray[..., None]) * factor + gray[..., None]


def adjust_hue(img: np.ndarray, delta_deg: float) -> np.ndarray:
    """Rotate hue by `delta_deg` degrees using the YIQ approximation
    (linear, fast — the classic Paeth rotation used by tf.image)."""
    rad = np.deg2rad(delta_deg)
    cos, sin = np.cos(rad), np.sin(rad)
    t_yiq = np.asarray([[0.299, 0.587, 0.114],
                        [0.596, -0.274, -0.321],
                        [0.211, -0.523, 0.311]], np.float32)
    t_rgb = np.linalg.inv(t_yiq).astype(np.float32)
    rot = np.asarray([[1, 0, 0], [0, cos, -sin], [0, sin, cos]], np.float32)
    m = t_rgb @ rot @ t_yiq
    return img @ m.T


# ---------------------------------------------------------------------------
# Transformers
# ---------------------------------------------------------------------------


class PixelBytesToImage(Transformer):
    """Fixed-shape raw pixel byte records -> LabeledImage (the analogue of
    BytesToBGRImg over SequenceFile records,
    dataset/image/BytesToBGRImg.scala).  Input: (bytes, label) tuples."""

    def __init__(self, height: int, width: int, channels: int = 3):
        self.shape = (height, width, channels)

    def __call__(self, it: Iterator[Tuple[bytes, Any]]) -> Iterator[LabeledImage]:
        for raw, label in it:
            arr = np.frombuffer(raw, np.uint8).reshape(self.shape)
            yield LabeledImage(arr.astype(np.float32), label)


class Resize(Transformer):
    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def __call__(self, it):
        for r in it:
            yield LabeledImage(resize_bilinear(r.image, self.h, self.w), r.label)


class RandomCrop(Transformer):
    """reference: dataset/image/BGRImgCropper.scala (CropRandom)."""

    def __init__(self, height: int, width: int, seed: int = 0):
        self.h, self.w = height, width
        self.rs = np.random.RandomState(seed)

    def __call__(self, it):
        for r in it:
            ih, iw = r.image.shape[:2]
            y = self.rs.randint(0, ih - self.h + 1)
            x = self.rs.randint(0, iw - self.w + 1)
            yield LabeledImage(crop(r.image, y, x, self.h, self.w), r.label)


class CenterCrop(Transformer):
    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def __call__(self, it):
        for r in it:
            ih, iw = r.image.shape[:2]
            y, x = (ih - self.h) // 2, (iw - self.w) // 2
            yield LabeledImage(crop(r.image, y, x, self.h, self.w), r.label)


class RandomResizedCrop(Transformer):
    """Inception-style area+aspect random crop then resize (the ImageNet
    training crop; reference: transform/vision/image/augmentation/
    RandomAspectScale + RandomCropper)."""

    def __init__(self, height: int, width: int,
                 area_range: Tuple[float, float] = (0.08, 1.0),
                 aspect_range: Tuple[float, float] = (3 / 4, 4 / 3),
                 seed: int = 0, max_tries: int = 10):
        self.h, self.w = height, width
        self.area_range = area_range
        self.aspect_range = aspect_range
        self.max_tries = max_tries
        self.rs = np.random.RandomState(seed)

    def __call__(self, it):
        for r in it:
            ih, iw = r.image.shape[:2]
            area = ih * iw
            out = None
            for _ in range(self.max_tries):
                target = area * self.rs.uniform(*self.area_range)
                aspect = self.rs.uniform(*self.aspect_range)
                cw = int(round(np.sqrt(target * aspect)))
                ch = int(round(np.sqrt(target / aspect)))
                if cw <= iw and ch <= ih:
                    y = self.rs.randint(0, ih - ch + 1)
                    x = self.rs.randint(0, iw - cw + 1)
                    out = crop(r.image, y, x, ch, cw)
                    break
            if out is None:  # fallback: center crop of the short side
                side = min(ih, iw)
                y, x = (ih - side) // 2, (iw - side) // 2
                out = crop(r.image, y, x, side, side)
            yield LabeledImage(resize_bilinear(out, self.h, self.w), r.label)


class HFlip(Transformer):
    """reference: dataset/image/HFlip.scala."""

    def __init__(self, p: float = 0.5, seed: int = 0):
        self.p = p
        self.rs = np.random.RandomState(seed)

    def __call__(self, it):
        for r in it:
            img = hflip(r.image) if self.rs.rand() < self.p else r.image
            yield LabeledImage(img, r.label)


class Normalizer(Transformer):
    """Per-channel (x - mean) / std.
    reference: dataset/image/BGRImgNormalizer.scala."""

    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, it):
        for r in it:
            yield LabeledImage((r.image - self.mean) / self.std, r.label)


class ColorJitter(Transformer):
    """Random brightness/contrast/saturation in random order.
    reference: dataset/image/ColorJitter.scala (torch ColorJitter port)."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4, seed: int = 0):
        self.strengths = (brightness, contrast, saturation)
        self.rs = np.random.RandomState(seed)

    def __call__(self, it):
        fns = (adjust_brightness, adjust_contrast, adjust_saturation)
        for r in it:
            img = r.image
            order = self.rs.permutation(3)
            for i in order:
                strength = self.strengths[i]
                if strength <= 0:
                    continue
                if fns[i] is adjust_brightness:
                    # reference jitters in 0..255 pixel space multiplicatively
                    img = img * self.rs.uniform(1 - strength, 1 + strength)
                else:
                    img = fns[i](img, self.rs.uniform(1 - strength, 1 + strength))
            yield LabeledImage(img, r.label)


class Lighting(Transformer):
    """AlexNet-style PCA lighting noise with the ImageNet eigen-decomposition
    constants. reference: dataset/image/Lighting.scala."""

    EIG_VAL = np.asarray([0.2175, 0.0188, 0.0045], np.float32)
    EIG_VEC = np.asarray([[-0.5675, 0.7192, 0.4009],
                          [-0.5808, -0.0045, -0.8140],
                          [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha_std: float = 0.1, seed: int = 0):
        self.alpha_std = alpha_std
        self.rs = np.random.RandomState(seed)

    def __call__(self, it):
        for r in it:
            alpha = self.rs.normal(0, self.alpha_std, 3).astype(np.float32)
            noise = (self.EIG_VEC * alpha * self.EIG_VAL).sum(axis=1)
            yield LabeledImage(r.image + noise, r.label)


class ImageToSample(Transformer):
    """LabeledImage -> Sample (feature HWC float32, scalar label)."""

    def __call__(self, it):
        for r in it:
            label = None if r.label is None else np.asarray(r.label)
            yield Sample(np.ascontiguousarray(r.image, np.float32), label)


IMAGENET_MEAN = (0.485 * 255, 0.456 * 255, 0.406 * 255)
IMAGENET_STD = (0.229 * 255, 0.224 * 255, 0.225 * 255)
CIFAR_MEAN = (125.3, 123.0, 113.9)
CIFAR_STD = (63.0, 62.1, 66.7)
