"""DataSet abstractions.

Reference: dataset/DataSet.scala:57-68 (AbstractDataSet{data, shuffle,
size}), LocalDataSet (:113), DistributedDataSet (:167), factories
DataSet.array/rdd/ImageFolder (:322-482).

TPU redesign: there is no RDD; every process hosts the same logical
dataset and the trainer device_puts each global batch with the right
sharding (each host materializes only its shard of the batch under
multi-host jax.make_array_from_process_local_data).  `ArrayDataSet` is the
in-memory path (the DataSet.array analogue); sharded-file datasets
(ImageNet) live in bigdl_tpu/dataset/image.py.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.core.random import RandomGenerator
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


class DataSet:
    """reference: dataset/DataSet.scala:57 (AbstractDataSet)."""

    def data(self, train: bool) -> Iterator[Any]:
        """One pass over the data (shuffled if train)."""
        raise NotImplementedError

    def shuffle(self) -> None:
        pass

    def seek_epoch(self, epoch: int) -> None:
        """Align the per-epoch shuffle stream with driver epoch `epoch`
        (0-based).  The built-in datasets shuffle with
        `seed + epoch_counter`; a resumed run's FRESH dataset object must
        replay the interrupted epoch's exact order for losses to stay
        bitwise-equal to the uninterrupted run, so the trainer calls this
        before every `data(train=True)` — making shuffle order a pure
        function of (seed, driver epoch) instead of call count."""
        if hasattr(self, "_epoch"):
            self._epoch = int(epoch)

    def size(self) -> int:
        raise NotImplementedError

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        """reference: AbstractDataSet.transform / `->` (DataSet.scala:65)."""
        return TransformedDataSet(self, transformer)

    # factory, reference: DataSet.array (DataSet.scala:322)
    @staticmethod
    def array(data: Sequence[Any]) -> "ArrayDataSet":
        return ArrayDataSet(list(data))

    @staticmethod
    def image_folder(path: str, class_dirs: bool = True) -> "ImageFolderDataSet":
        """Directory of images -> Samples; with `class_dirs`, each
        subdirectory is a class (label = sorted subdir index, like the
        reference's ImageFolder local path, DataSet.scala:322-482).
        Only PATHS are listed up front; decoding (PIL on the host — the
        reference used OpenCV) streams lazily per epoch, so an
        ImageNet-scale folder never resides in memory at once."""
        return ImageFolderDataSet(path, class_dirs)

    @staticmethod
    def record_shards(dir_path: str, n_threads: int = 4) -> "RecordShardDataSet":
        """Sharded TFRecord folder -> streaming Sample dataset (the
        reference's SeqFileFolder / Hadoop-SequenceFile ImageNet layout,
        DataSet.scala:482-560; TFRecord is the TPU-native container).
        Shard order reshuffles per epoch; records stream through the
        native prefetching reader."""
        return RecordShardDataSet(dir_path, n_threads)


class ArrayDataSet(DataSet):
    """In-memory dataset with epoch shuffling (seeded via RandomGenerator,
    matching the reference's deterministic shuffle)."""

    def __init__(self, items: List[Any]):
        self.items = list(items)
        self._epoch = 0

    def size(self) -> int:
        return len(self.items)

    def data(self, train: bool) -> Iterator[Any]:
        if train:
            idx = np.arange(len(self.items))
            rs = np.random.RandomState(RandomGenerator.get_seed() + self._epoch)
            rs.shuffle(idx)
            self._epoch += 1
            return (self.items[i] for i in idx)
        return iter(self.items)


LocalDataSet = ArrayDataSet


class ImageFolderDataSet(DataSet):
    """Lazily-decoded image-tree dataset (see DataSet.image_folder)."""

    EXTS = (".png", ".jpg", ".jpeg", ".bmp")

    def __init__(self, path: str, class_dirs: bool = True):
        import glob
        import os

        self.entries = []  # (path, label-or-None)
        if class_dirs:
            classes = sorted(d for d in os.listdir(path)
                             if os.path.isdir(os.path.join(path, d)))
            for label, cls in enumerate(classes):
                for p in sorted(glob.glob(os.path.join(path, cls, "*"))):
                    if p.lower().endswith(self.EXTS):
                        self.entries.append((p, label))
        else:
            for p in sorted(glob.glob(os.path.join(path, "*"))):
                if p.lower().endswith(self.EXTS):
                    self.entries.append((p, None))
        self._epoch = 0

    def size(self) -> int:
        return len(self.entries)

    def data(self, train: bool) -> Iterator[Any]:
        from PIL import Image

        entries = list(self.entries)
        if train:
            rs = np.random.RandomState(RandomGenerator.get_seed() + self._epoch)
            rs.shuffle(entries)
            self._epoch += 1
        for p, label in entries:
            with Image.open(p) as im:
                arr = np.asarray(im.convert("RGB"), np.float32)
            yield Sample(arr, None if label is None else np.int32(label))


class RecordShardDataSet(DataSet):
    """Streaming dataset over a directory of TFRecord shards (the
    reference's DistributedDataSet over SequenceFile folders).  Each epoch
    shuffles SHARD order (record order within a shard is the reader's —
    throughput over order, like the reference's multithreaded decode)."""

    def __init__(self, dir_path: str, n_threads: int = 4):
        import glob
        import os

        paths = sorted(glob.glob(os.path.join(dir_path, "*.tfrecord"))) \
            or sorted(glob.glob(os.path.join(dir_path, "*")))
        # the '*' fallback must not pick up _SUCCESS markers / subdirs
        self.paths = [p for p in paths
                      if os.path.isfile(p)
                      and not os.path.basename(p).startswith(("_", "."))]
        if not self.paths:
            raise FileNotFoundError(f"no record shards under {dir_path}")
        self.n_threads = n_threads
        self._epoch = 0
        self._size: int = -1

    def size(self) -> int:
        if self._size < 0:
            from bigdl_tpu.dataset.tfrecord import count_records

            # frame-length scan only — no payload decode (an ImageNet-scale
            # folder would otherwise stream the whole dataset to count it)
            self._size = sum(count_records(p) for p in self.paths)
        return self._size

    def data(self, train: bool) -> Iterator[Any]:
        from bigdl_tpu.dataset.tfrecord import (PrefetchRecordReader,
                                                record_to_sample)

        paths = list(self.paths)
        if train:
            rs = np.random.RandomState(RandomGenerator.get_seed() + self._epoch)
            rs.shuffle(paths)
            self._epoch += 1
        for rec in PrefetchRecordReader(paths, n_threads=self.n_threads):
            yield record_to_sample(rec)


class TransformedDataSet(DataSet):
    def __init__(self, base: DataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer

    def size(self) -> int:
        return self.base.size()

    def seek_epoch(self, epoch: int) -> None:
        self.base.seek_epoch(epoch)

    def data(self, train: bool) -> Iterator[Any]:
        return self.transformer(self.base.data(train))

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        return TransformedDataSet(self.base, self.transformer >> transformer)
