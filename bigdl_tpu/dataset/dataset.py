"""DataSet abstractions.

Reference: dataset/DataSet.scala:57-68 (AbstractDataSet{data, shuffle,
size}), LocalDataSet (:113), DistributedDataSet (:167), factories
DataSet.array/rdd/ImageFolder (:322-482).

TPU redesign: there is no RDD; every process hosts the same logical
dataset and the trainer device_puts each global batch with the right
sharding (each host materializes only its shard of the batch under
multi-host jax.make_array_from_process_local_data).  `ArrayDataSet` is the
in-memory path (the DataSet.array analogue); sharded-file datasets
(ImageNet) live in bigdl_tpu/dataset/image.py.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.core.random import RandomGenerator
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


class DataSet:
    """reference: dataset/DataSet.scala:57 (AbstractDataSet)."""

    def data(self, train: bool) -> Iterator[Any]:
        """One pass over the data (shuffled if train)."""
        raise NotImplementedError

    def shuffle(self) -> None:
        pass

    def size(self) -> int:
        raise NotImplementedError

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        """reference: AbstractDataSet.transform / `->` (DataSet.scala:65)."""
        return TransformedDataSet(self, transformer)

    # factory, reference: DataSet.array (DataSet.scala:322)
    @staticmethod
    def array(data: Sequence[Any]) -> "ArrayDataSet":
        return ArrayDataSet(list(data))


class ArrayDataSet(DataSet):
    """In-memory dataset with epoch shuffling (seeded via RandomGenerator,
    matching the reference's deterministic shuffle)."""

    def __init__(self, items: List[Any]):
        self.items = list(items)
        self._epoch = 0

    def size(self) -> int:
        return len(self.items)

    def data(self, train: bool) -> Iterator[Any]:
        if train:
            idx = np.arange(len(self.items))
            rs = np.random.RandomState(RandomGenerator.get_seed() + self._epoch)
            rs.shuffle(idx)
            self._epoch += 1
            return (self.items[i] for i in idx)
        return iter(self.items)


LocalDataSet = ArrayDataSet


class TransformedDataSet(DataSet):
    def __init__(self, base: DataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer

    def size(self) -> int:
        return self.base.size()

    def data(self, train: bool) -> Iterator[Any]:
        return self.transformer(self.base.data(train))

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        return TransformedDataSet(self.base, self.transformer >> transformer)
