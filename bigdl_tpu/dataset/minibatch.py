"""MiniBatch — a batch of Samples.

Reference: dataset/MiniBatch.scala:34-91 (getInput/getTarget/slice/set),
ArrayTensorMiniBatch (:111).  Inputs/targets are numpy arrays (or tuples
for multi-io); the trainer device_puts them with the right sharding.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

from bigdl_tpu.dataset.sample import Sample, SparseBag, SparseFeature


class MiniBatch:
    """reference: dataset/MiniBatch.scala:34."""

    def __init__(self, input: Any, target: Optional[Any] = None):
        self.input = input
        self.target = target

    def get_input(self) -> Any:
        return self.input

    def get_target(self) -> Any:
        return self.target

    def size(self) -> int:
        first = self.input[0] if isinstance(self.input, (tuple, list)) else self.input
        return int(first.shape[0])

    def slice(self, offset: int, length: int) -> "MiniBatch":
        """0-based slice (the reference is 1-based)."""

        def sl(x):
            if isinstance(x, (tuple, list)):
                return type(x)(sl(v) for v in x)
            return x[offset:offset + length]

        return MiniBatch(sl(self.input), sl(self.target) if self.target is not None else None)

    def nbytes(self) -> int:
        """Host-memory footprint of the batch payload, in bytes.  The
        reader pool sizes its bounded queue in batches, so `window *
        nbytes()` is the parent-side buffering ceiling — exposed for
        memory accounting and the feed occupancy telemetry."""

        def nb(x):
            if x is None:
                return 0
            if isinstance(x, (tuple, list)):
                return sum(nb(v) for v in x)
            return int(np.asarray(x).nbytes)

        return nb(self.input) + nb(self.target)

    def pad_to(self, n: int) -> "MiniBatch":
        """Pad the batch (leading) dim to `n` rows by repeating the last
        row, keeping XLA batch shapes static across the epoch tail (the
        reference pads rather than recompiling; the trailing partial
        batch otherwise forces a fresh train-step compile every epoch).
        The result's `pad_rows` records how many trailing rows are
        repeats — they DO enter loss/metric means unless the consumer
        masks them, which is why `SampleToMiniBatch(drop_remainder=True)`
        stays the exactness default."""
        k = self.size()
        if k >= n:
            return self

        def pad(x):
            if isinstance(x, (tuple, list)):
                return type(x)(pad(v) for v in x)
            x = np.asarray(x)
            return np.concatenate([x, np.repeat(x[-1:], n - k, axis=0)],
                                  axis=0)

        out = type(self)(pad(self.input),
                         pad(self.target) if self.target is not None else None)
        out.pad_rows = n - k
        return out

    @staticmethod
    def from_samples(samples: Sequence[Sample],
                     feature_padding: Optional[float] = None,
                     label_padding: Optional[float] = None) -> "MiniBatch":
        """Stack samples; optionally pad variable-length features to the
        batch max (reference: SampleToMiniBatch padding params,
        dataset/MiniBatch.scala:579+).  Multi-input samples (tuple of
        feature arrays) stack per component into a tuple of batches."""

        def stack(values, padding):
            arrays = [np.asarray(v) for v in values]
            return _pad_stack(arrays, padding) if padding is not None else np.stack(arrays)

        if isinstance(samples[0].feature, (tuple, list)):
            n_inputs = len(samples[0].feature)
            feats = tuple(stack([s.feature[i] for s in samples], feature_padding)
                          for i in range(n_inputs))
        else:
            feats = stack([s.feature for s in samples], feature_padding)
        labels = None
        if samples[0].label is not None:
            if isinstance(samples[0].label, (tuple, list)):
                labels = tuple(stack([s.label[i] for s in samples], label_padding)
                               for i in range(len(samples[0].label)))
            else:
                labels = stack([s.label for s in samples], label_padding)
        return MiniBatch(feats, labels)

    def __repr__(self):
        def sh(x):
            if isinstance(x, (tuple, list)):
                return tuple(sh(v) for v in x)
            return tuple(x.shape)

        return f"MiniBatch(input={sh(self.input)}, target={sh(self.target) if self.target is not None else None})"


class SparseMiniBatch(MiniBatch):
    """MiniBatch for samples carrying SparseFeature components.

    Reference: dataset/MiniBatch.scala:579 (SparseMiniBatch over
    TensorSample) — batches per-record sparse tensors into one
    (batch, *dense_shape) tensor per component.  The reference keeps the
    batch sparse (feeding SparseLinear's sparse gemm); here a component
    either DENSIFIES at this host-side boundary (SparseFeature — fine for
    narrow vocabs, the MXU eats the dense matmul) or stays device-sparse
    as a padded (ids, values) bag pair (SparseBag — the wide-vocab path:
    work scales with nnz, not vocab).  Mixed dense/sparse components are
    fine — dense ones stack as usual.
    """

    @staticmethod
    def from_samples(samples: Sequence[Sample],
                     feature_padding: Optional[float] = None,
                     label_padding: Optional[float] = None) -> "SparseMiniBatch":
        def batch_one(values, padding):
            if isinstance(values[0], SparseBag):
                caps = {v.nnz_cap for v in values}
                if len(caps) != 1:
                    raise ValueError(f"inconsistent bag capacities: {caps}")
                return (np.stack([v.ids for v in values]),
                        np.stack([v.values for v in values]))
            if isinstance(values[0], SparseFeature):
                shapes = {v.dense_shape for v in values}
                if len(shapes) != 1:
                    raise ValueError(f"inconsistent dense_shapes in batch: {shapes}")
                pad = 0 if padding is None else padding
                return np.stack([v.to_dense(pad) for v in values])
            arrays = [np.asarray(v) for v in values]
            return _pad_stack(arrays, padding) if padding is not None else np.stack(arrays)

        def batch_side(first, get, padding):
            if isinstance(first, (tuple, list)):
                # padding may be per-component (reference: PaddingParam per
                # tensor, MiniBatch.scala:579) or one value for all
                def pad_of(i):
                    return padding[i] if isinstance(padding, (tuple, list)) \
                        else padding

                return tuple(batch_one([get(s)[i] for s in samples],
                                       pad_of(i))
                             for i in range(len(first)))
            return batch_one([get(s) for s in samples], padding)

        feats = batch_side(samples[0].feature, lambda s: s.feature, feature_padding)
        labels = None
        if samples[0].label is not None:
            labels = batch_side(samples[0].label, lambda s: s.label, label_padding)
        return SparseMiniBatch(feats, labels)


def has_sparse_feature(sample: Sample) -> bool:
    parts = sample.feature if isinstance(sample.feature, (tuple, list)) else [sample.feature]
    labels = sample.label if isinstance(sample.label, (tuple, list)) else [sample.label]
    return any(isinstance(p, (SparseFeature, SparseBag))
               for p in list(parts) + list(labels))


def _pad_stack(arrays: List[np.ndarray], pad_value: float) -> np.ndarray:
    ndim = arrays[0].ndim
    max_shape = [max(a.shape[d] for a in arrays) for d in range(ndim)]
    out = np.full((len(arrays),) + tuple(max_shape), pad_value, arrays[0].dtype)
    for i, a in enumerate(arrays):
        sl = (i,) + tuple(slice(0, s) for s in a.shape)
        out[sl] = a
    return out
