"""MiniBatch — a batch of Samples.

Reference: dataset/MiniBatch.scala:34-91 (getInput/getTarget/slice/set),
ArrayTensorMiniBatch (:111).  Inputs/targets are numpy arrays (or tuples
for multi-io); the trainer device_puts them with the right sharding.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

from bigdl_tpu.dataset.sample import Sample


class MiniBatch:
    """reference: dataset/MiniBatch.scala:34."""

    def __init__(self, input: Any, target: Optional[Any] = None):
        self.input = input
        self.target = target

    def get_input(self) -> Any:
        return self.input

    def get_target(self) -> Any:
        return self.target

    def size(self) -> int:
        first = self.input[0] if isinstance(self.input, (tuple, list)) else self.input
        return int(first.shape[0])

    def slice(self, offset: int, length: int) -> "MiniBatch":
        """0-based slice (the reference is 1-based)."""

        def sl(x):
            if isinstance(x, (tuple, list)):
                return type(x)(sl(v) for v in x)
            return x[offset:offset + length]

        return MiniBatch(sl(self.input), sl(self.target) if self.target is not None else None)

    @staticmethod
    def from_samples(samples: Sequence[Sample],
                     feature_padding: Optional[float] = None,
                     label_padding: Optional[float] = None) -> "MiniBatch":
        """Stack samples; optionally pad variable-length features to the
        batch max (reference: SampleToMiniBatch padding params,
        dataset/MiniBatch.scala:579+)."""
        feats = [np.asarray(s.feature) for s in samples]
        if feature_padding is not None:
            feats = _pad_stack(feats, feature_padding)
        else:
            feats = np.stack(feats)
        labels = None
        if samples[0].label is not None:
            labs = [np.asarray(s.label) for s in samples]
            if label_padding is not None:
                labels = _pad_stack(labs, label_padding)
            else:
                labels = np.stack(labs)
        return MiniBatch(feats, labels)

    def __repr__(self):
        def sh(x):
            if isinstance(x, (tuple, list)):
                return tuple(sh(v) for v in x)
            return tuple(x.shape)

        return f"MiniBatch(input={sh(self.input)}, target={sh(self.target) if self.target is not None else None})"


def _pad_stack(arrays: List[np.ndarray], pad_value: float) -> np.ndarray:
    ndim = arrays[0].ndim
    max_shape = [max(a.shape[d] for a in arrays) for d in range(ndim)]
    out = np.full((len(arrays),) + tuple(max_shape), pad_value, arrays[0].dtype)
    for i, a in enumerate(arrays):
        sl = (i,) + tuple(slice(0, s) for s in a.shape)
        out[sl] = a
    return out
