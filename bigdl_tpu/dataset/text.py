"""Text pipeline: tokenization, vocabulary, LM sample construction.

Reference: dataset/text/ — SentenceSplitter/SentenceTokenizer (OpenNLP),
Dictionary (dataset/text/Dictionary.scala), TextToLabeledSentence,
LabeledSentenceToSample; feeds the PTB LSTM LM
(models/rnn/Train.scala:48-59).  The OpenNLP dependency is replaced with
regex tokenization (no JVM on the TPU host path); everything else is
capability-parity.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer

_SENT_RE = re.compile(r"(?<=[.!?])\s+")
_WORD_RE = re.compile(r"[A-Za-z0-9']+|[^\sA-Za-z0-9]")


class SentenceSplitter(Transformer):
    """Text blobs -> sentences. reference: dataset/text/SentenceSplitter.scala
    (OpenNLP SentenceDetector -> regex on terminal punctuation)."""

    def __call__(self, it: Iterator[str]) -> Iterator[str]:
        for blob in it:
            for sent in _SENT_RE.split(blob.strip()):
                if sent:
                    yield sent


class SentenceTokenizer(Transformer):
    """Sentence -> token list. reference: dataset/text/SentenceTokenizer.scala."""

    def __init__(self, lower: bool = True):
        self.lower = lower

    def __call__(self, it: Iterator[str]) -> Iterator[List[str]]:
        for sent in it:
            if self.lower:
                sent = sent.lower()
            yield _WORD_RE.findall(sent)


class SentenceBiPadding(Transformer):
    """Wrap each token list with sentence-start/end markers.
    reference: dataset/text/SentenceBiPadding.scala."""

    START = "SENTENCESTART"
    END = "SENTENCEEND"

    def __call__(self, it: Iterator[List[str]]) -> Iterator[List[str]]:
        for toks in it:
            yield [self.START] + toks + [self.END]


class Dictionary:
    """Token <-> index vocabulary with capped size + UNK.
    reference: dataset/text/Dictionary.scala."""

    UNK = "<unk>"

    def __init__(self, sentences: Optional[Iterable[Sequence[str]]] = None,
                 vocab_size: Optional[int] = None):
        self.word2index: Dict[str, int] = {}
        self.index2word: List[str] = []
        if sentences is not None:
            counts = Counter(tok for s in sentences for tok in s)
            keep = [w for w, _ in counts.most_common(vocab_size)]
            for w in keep:
                self.add_word(w)
        self.add_word(self.UNK)

    def add_word(self, word: str) -> int:
        if word not in self.word2index:
            self.word2index[word] = len(self.index2word)
            self.index2word.append(word)
        return self.word2index[word]

    def vocab_size(self) -> int:
        return len(self.index2word)

    def get_index(self, word: str) -> int:
        return self.word2index.get(word, self.word2index[self.UNK])

    def get_word(self, index: int) -> str:
        return self.index2word[index]

    def encode(self, tokens: Sequence[str]) -> np.ndarray:
        return np.asarray([self.get_index(t) for t in tokens], np.int32)

    def decode(self, ids: Sequence[int]) -> List[str]:
        return [self.get_word(int(i)) for i in ids]

    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            for w in self.index2word:
                fh.write(w + "\n")

    @classmethod
    def load(cls, path: str) -> "Dictionary":
        d = cls()
        d.word2index.clear()
        d.index2word.clear()
        with open(path) as fh:
            for line in fh:
                d.add_word(line.rstrip("\n"))
        if cls.UNK not in d.word2index:
            d.add_word(cls.UNK)
        return d


class LabeledSentence:
    """(input ids, target ids) pair. reference: dataset/text/LabeledSentence.scala."""

    __slots__ = ("data", "label")

    def __init__(self, data: np.ndarray, label: np.ndarray):
        self.data = data
        self.label = label


class TextToLabeledSentence(Transformer):
    """Token ids -> next-token-prediction pair (x = ids[:-1], y = ids[1:]).
    reference: dataset/text/TextToLabeledSentence.scala."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def __call__(self, it: Iterator[Sequence[str]]) -> Iterator[LabeledSentence]:
        for toks in it:
            ids = self.dictionary.encode(toks)
            if len(ids) < 2:
                continue
            yield LabeledSentence(ids[:-1], ids[1:])


class LabeledSentenceToSample(Transformer):
    """LabeledSentence -> fixed-length Sample (pad/truncate so every batch
    is one static XLA shape). reference: dataset/text/LabeledSentenceToSample.scala."""

    def __init__(self, seq_len: Optional[int] = None, pad_id: int = 0,
                 pad_label: int = 0):
        self.seq_len = seq_len
        self.pad_id = pad_id
        self.pad_label = pad_label

    def _fix(self, ids: np.ndarray, pad: int) -> np.ndarray:
        if self.seq_len is None:
            return ids
        if len(ids) >= self.seq_len:
            return ids[:self.seq_len]
        out = np.full(self.seq_len, pad, ids.dtype)
        out[:len(ids)] = ids
        return out

    def __call__(self, it: Iterator[LabeledSentence]) -> Iterator[Sample]:
        for ls in it:
            yield Sample(self._fix(ls.data, self.pad_id),
                         self._fix(ls.label, self.pad_label))


def ptb_stream_batches(ids: np.ndarray, batch_size: int, num_steps: int
                       ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """The PTB continuous-stream batcher: reshape the full token stream into
    `batch_size` parallel lanes, slide a `num_steps` window.
    reference: models/rnn/Train.scala + SequencePreprocess (PTB path)."""
    n = (len(ids) - 1) // (batch_size * num_steps) * batch_size * num_steps
    if n <= 0:
        return
    x = ids[:n].reshape(batch_size, -1)
    y = ids[1:n + 1].reshape(batch_size, -1)
    for off in range(0, x.shape[1], num_steps):
        if off + num_steps <= x.shape[1]:
            yield x[:, off:off + num_steps], y[:, off:off + num_steps]
