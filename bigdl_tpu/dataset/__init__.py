from bigdl_tpu.dataset.sample import Sample, SparseBag, SparseFeature
from bigdl_tpu.dataset.minibatch import MiniBatch, SparseMiniBatch
from bigdl_tpu.dataset.transformer import Transformer, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import DataSet, LocalDataSet, ArrayDataSet
from bigdl_tpu.dataset.feed import DeviceFeed, FeedItem, InlineFeed, make_feed
from bigdl_tpu.dataset.readers import (ChunkWork, ReaderPool, ReaderWork,
                                       ReaderWorkerError, make_reader_source,
                                       reader_work_for)
from bigdl_tpu.dataset.datamining import (RowTransformer, RowTransformSchema,
                                          TableToSample)
from bigdl_tpu.dataset.tfrecord import VarLenFeature
from bigdl_tpu.dataset import image
from bigdl_tpu.dataset import text

__all__ = ["Sample", "SparseBag", "SparseFeature", "MiniBatch", "SparseMiniBatch",
           "Transformer", "SampleToMiniBatch",
           "DataSet", "LocalDataSet", "ArrayDataSet",
           "DeviceFeed", "FeedItem", "InlineFeed", "make_feed",
           "ChunkWork", "ReaderPool", "ReaderWork", "ReaderWorkerError",
           "make_reader_source", "reader_work_for",
           "RowTransformer", "RowTransformSchema", "TableToSample",
           "VarLenFeature", "image", "text"]
from bigdl_tpu.dataset import datasets
from bigdl_tpu.dataset.datasets import (
    load_mnist,
    load_cifar10,
    load_movielens_ratings,
    load_news20,
    load_glove_embeddings,
    read_sentence_corpus,
    maybe_download,
)
