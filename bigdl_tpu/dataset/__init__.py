from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.minibatch import MiniBatch
from bigdl_tpu.dataset.transformer import Transformer, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import DataSet, LocalDataSet, ArrayDataSet
from bigdl_tpu.dataset import image
from bigdl_tpu.dataset import text

__all__ = ["Sample", "MiniBatch", "Transformer", "SampleToMiniBatch",
           "DataSet", "LocalDataSet", "ArrayDataSet", "image", "text"]
