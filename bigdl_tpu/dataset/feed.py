"""DeviceFeed — async host->device input staging.

Reference: dataset/image/MTLabeledBGRImgToBatch.scala — the reference hid
image decode behind the training loop with a multi-threaded batch
assembler.  Here the analogous un-overlapped stage is batch ASSEMBLY
(dataset iteration -> transformer chain -> MiniBatch stack) plus the
host->device transfer of the staged arrays: the step loop paid both
serially before every dispatch (optimizer.py put + device_put per step).

DeviceFeed runs assembly + staging in ONE background worker thread over a
bounded queue (double/triple buffering via `prefetch_depth`), so host
collate and H2D transfer overlap in-flight device compute:

  * batch ORDER is exactly the source iterator's (one worker, FIFO
    queue) — consumers see the same sequence as iterating inline, so
    losses are bitwise-equal feed on vs off;
  * the queue is BOUNDED: a slow consumer backpressures the worker
    instead of ballooning host/device memory past
    `prefetch_depth + 1` staged batches (one in the worker's hands);
  * staging uses the CALLER's put function (the trainer passes its
    sharded `_put_batch`), so arrays land on the mesh with the step's
    `data`-axis NamedSharding before the step wants them;
  * shutdown is deterministic: `close()` (or the `with` block / iterator
    exhaustion) stops the worker, unblocks any pending bounded-queue
    put, and joins the thread — an early `end_when` break, a preemption
    exit (resilience.PreemptionGuard drains the feed through this same
    close()), or an exception in the consumer leaks nothing;
  * `delivered_batches` counts hand-offs to the consumer — the trainer's
    mid-epoch resume bookkeeping (driver `epoch_batch`) cross-checks it;
  * a worker-side exception (bad record, OOM in collate) propagates to
    the consumer's next `__next__` instead of hanging the loop.

Observability counters ride on the feed object: per-item consumer stall
time (how long the step loop waited on the queue), staged-buffer
occupancy at hand-off, and worker assembly throughput — the trainer
surfaces them through Metrics/TrainSummary as FeedStall/FeedOccupancy.

BatchSource seam: `batches` may be ANY iterable of batches — an inline
generator (the in-thread assembler: dataset iteration -> transformer
chain runs inside this worker's `feed.assemble` span) or a remote
source like `readers.ReaderPool`, whose `__next__` only reorders
batches other PROCESSES assembled.  Both shapes share this one worker
loop and the one `feed.h2d_stage` staging path.  A source may opt into
two hooks:

  * `close_with_feed = True` + `close()`: the feed closes the source —
    BEFORE joining its worker for a concurrent-close-safe source (so a
    worker parked in the source's `__next__` unblocks immediately, and
    an early break / preemption exit tears the whole pipeline down
    through one `feed.close()`);
  * `note_feed(stall_s, occupancy)`: called at every consumer hand-off
    with the live stall/occupancy telemetry — the ReaderPool's
    stall-driven autoscaler rides this.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, NamedTuple, Optional

from bigdl_tpu import obs as _obs

__all__ = ["DeviceFeed", "InlineFeed", "FeedItem", "make_feed"]

_DONE = object()


class FeedItem(NamedTuple):
    """One staged batch as handed to the consumer."""

    batch: Any        # the original MiniBatch (shapes, size(), init)
    payload: Any      # whatever put_fn returned (device-staged arrays)
    stall_s: float    # how long the consumer blocked waiting for this item
    occupancy: int    # staged batches ready in the buffer at hand-off


class DeviceFeed:
    """Bounded-depth async feed: assembly + H2D staging off the hot loop.

    Parameters
    ----------
    batches : iterable of batches (typically MiniBatch)
    put_fn : batch -> payload, run IN THE WORKER (device_put lives here)
    prefetch_depth : staged batches the worker may run ahead (>= 1)
    """

    def __init__(self, batches: Iterable[Any], put_fn: Callable[[Any], Any],
                 prefetch_depth: int = 2, name: str = "DeviceFeed",
                 stall_check: Optional[Callable[[], None]] = None):
        if prefetch_depth < 1:
            raise ValueError(f"prefetch_depth must be >= 1, got {prefetch_depth}")
        self.prefetch_depth = int(prefetch_depth)
        self._put = put_fn
        # hang-watchdog hook: called each empty-queue poll in __next__ so
        # a wedged worker raises StalledStep into the consumer instead of
        # stalling the step loop until the phase deadline is forgotten
        self._stall_check = stall_check
        # BatchSource seam: keep the source for close-through and the
        # autoscaler's hand-off hook (see module docstring)
        self._src = batches
        self._note_feed = getattr(batches, "note_feed", None)
        self._it = iter(batches)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_depth)
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._closed = False
        # worker-side counters (read by the consumer after hand-off; a
        # torn read would only skew a metric by one batch)
        self._staged = 0
        self._staged_records = 0
        self._work_s = 0.0
        self._delivered = 0
        # daemon: a crashed consumer must not wedge interpreter exit; the
        # conftest leak guard still flags any feed thread alive post-test
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    # worker
    # ------------------------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                tr = _obs.tracer()  # per batch: picks up late enabling
                t0 = time.perf_counter()
                if tr is not None:
                    with tr.span("feed.assemble", cat="feed",
                                 batch=self._staged):
                        try:
                            batch = next(self._it)
                        except StopIteration:
                            break
                    with tr.span("feed.h2d_stage", cat="feed",
                                 batch=self._staged):
                        payload = self._put(batch)
                else:
                    try:
                        batch = next(self._it)
                    except StopIteration:
                        break
                    payload = self._put(batch)
                self._work_s += time.perf_counter() - t0
                self._staged += 1
                size = getattr(batch, "size", None)
                if callable(size):
                    try:
                        self._staged_records += int(size())
                    except Exception:
                        pass
                if not self._offer((batch, payload)):
                    return  # stopped while blocked on a full queue
        except BaseException as e:  # propagate to the consumer, never hang
            self._error = e
        finally:
            self._offer(_DONE)

    def _offer(self, item: Any) -> bool:
        """Bounded put that a close() can always unblock."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # ------------------------------------------------------------------
    # consumer
    # ------------------------------------------------------------------

    def __iter__(self) -> Iterator[FeedItem]:
        return self

    def __next__(self) -> FeedItem:
        if self._closed:
            raise StopIteration
        t0 = time.perf_counter()
        # timeout-bounded get (mirrors _offer): a worker that dies without
        # posting _DONE — or is killed hard by the OS — surfaces here as
        # an error instead of blocking the step loop forever
        while True:
            try:
                item = self._q.get(timeout=0.05)
                break
            except queue.Empty:
                if self._stall_check is not None:
                    self._stall_check()
                if not self._thread.is_alive():
                    # the worker may have posted its last item (or _DONE)
                    # between our timeout and the aliveness check
                    try:
                        item = self._q.get_nowait()
                        break
                    except queue.Empty:
                        pass
                    self.close()
                    if self._error is not None:
                        raise RuntimeError(
                            f"{self._thread.name} worker failed while "
                            f"assembling/staging a batch") from self._error
                    raise StopIteration
        stall = time.perf_counter() - t0
        if item is _DONE:
            self.close()
            if self._error is not None:
                raise RuntimeError(
                    f"{self._thread.name} worker failed while assembling/"
                    f"staging a batch") from self._error
            raise StopIteration
        batch, payload = item
        self._delivered += 1
        occ = self._q.qsize() + 1
        if self._note_feed is not None:
            # autoscaler hand-off hook (ReaderPool.note_feed): consumer
            # thread, cheap host math only
            self._note_feed(stall, occ)
        return FeedItem(batch, payload, stall, occ)

    def __enter__(self) -> "DeviceFeed":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Idempotent shutdown: stop, unblock, join, surface late errors.

        Ordering matters for the remote-source case: a concurrent-close-
        safe source (`close_with_feed`, e.g. readers.ReaderPool) is
        closed BEFORE the join, so a worker parked inside the source's
        `__next__` (waiting on reader processes) observes the shutdown
        within one poll instead of riding out a full assembly — the join
        below then cannot time out against a stuck producer.  Plain
        generator sources are never closed concurrently (generators
        forbid it) — for those the stop flag + queue drain unblock the
        worker exactly as before."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if getattr(self._src, "close_with_feed", False):
            try:
                self._src.close()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
        reg = _obs.registry()
        reg.inc("feed/staged_batches", self._staged)
        reg.inc("feed/delivered_batches", self._delivered)
        reg.set_gauge("feed/assembly_records_per_s",
                      self.assembly_records_per_s())
        # drain so a worker blocked mid-put can observe the stop flag;
        # keep draining until the worker exits — one pass can lose the
        # race against a worker completing a put between drain and join
        deadline = time.perf_counter() + 5.0
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                if not self._thread.is_alive():
                    break
                if time.perf_counter() > deadline:
                    break
                time.sleep(0.005)
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():  # pragma: no cover - defensive
            raise RuntimeError(f"{self._thread.name} worker did not stop")

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def assembly_records_per_s(self) -> float:
        """Worker-side throughput of assembly + staging (records/s)."""
        return self._staged_records / self._work_s if self._work_s > 0 else 0.0

    @property
    def staged_batches(self) -> int:
        return self._staged

    @property
    def delivered_batches(self) -> int:
        """Batches handed to the consumer (staged ones still queued when
        the feed closes — e.g. on preemption — are NOT counted)."""
        return self._delivered


class InlineFeed:
    """Feed-off fallback: same FeedItem interface, zero threads — assembly
    and staging run inline in the consumer exactly as the pre-feed loop
    did (the bitwise-parity baseline and the `prefetch_depth=0` path)."""

    prefetch_depth = 0

    def __init__(self, batches: Iterable[Any], put_fn: Callable[[Any], Any]):
        self._put = put_fn
        self._src = batches
        self._note_feed = getattr(batches, "note_feed", None)
        self._it = iter(batches)
        self._staged_records = 0
        self._work_s = 0.0
        self._delivered = 0

    def __iter__(self) -> Iterator[FeedItem]:
        return self

    def __next__(self) -> FeedItem:
        tr = _obs.tracer()
        t0 = time.perf_counter()
        if tr is not None:
            with tr.span("feed.inline_stage", cat="feed"):
                batch = next(self._it)
                payload = self._put(batch)
        else:
            batch = next(self._it)
            payload = self._put(batch)
        self._work_s += time.perf_counter() - t0
        size = getattr(batch, "size", None)
        if callable(size):
            try:
                self._staged_records += int(size())
            except Exception:
                pass
        # inline: the "stall" IS the assembly+staging time the loop paid
        self._delivered += 1
        stall = time.perf_counter() - t0
        if self._note_feed is not None:
            self._note_feed(stall, 0)
        return FeedItem(batch, payload, stall, 0)

    def __enter__(self) -> "InlineFeed":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        # close-through: the feed-off (depth=0) path over a ReaderPool
        # must tear down reader processes exactly like the async path
        if getattr(self._src, "close_with_feed", False):
            self._src.close()

    def assembly_records_per_s(self) -> float:
        return self._staged_records / self._work_s if self._work_s > 0 else 0.0

    @property
    def delivered_batches(self) -> int:
        return self._delivered


def make_feed(batches: Iterable[Any], put_fn: Callable[[Any], Any],
              prefetch_depth: int, name: str = "DeviceFeed",
              stall_check: Optional[Callable[[], None]] = None):
    """`prefetch_depth >= 1` -> async DeviceFeed; `<= 0` -> InlineFeed."""
    if prefetch_depth and prefetch_depth > 0:
        return DeviceFeed(batches, put_fn, prefetch_depth, name=name,
                          stall_check=stall_check)
    return InlineFeed(batches, put_fn)
