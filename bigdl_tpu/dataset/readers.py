"""Disaggregated input plane: sharded multi-process reader pool.

Reference: dataset/image/MTLabeledBGRImgToBatch.scala ran decode/augment
on a thread pool INSIDE the training JVM; the GIL makes that a ceiling
here — bench_input_pipeline measured ~25 host cores of decode+augment to
feed one chip, all serialized behind one interpreter lock.  This module
moves batch ASSEMBLY (record read -> decode/augment -> MiniBatch stack)
into N worker *processes*, the tf.data-service-style input split, while
keeping the delivered batch sequence bitwise-identical to the in-thread
assembler so the resilience layer's kill->resume parity survives.

Design:

  * WORK, not shards, is the unit: a picklable `ReaderWork` object
    describes one epoch as an indexed stream of cheap *items* (record
    buffers, path chunks, sample chunks) plus an `assemble(item)` that
    does the expensive part.  Batch `k`'s content is a pure function of
    (work, k) — never of which worker built it.
  * workers CLAIM indices from a shared counter (each claim is one
    batch), skip their cheap item stream forward to the claimed index,
    assemble, and post `(seq, batch)` on a bounded mp queue.  Claiming
    adapts to heterogeneous item cost and to the pool growing or
    shrinking mid-epoch; determinism comes from the reorder stage, not
    from a static worker:shard map.
  * the parent restores STRICT order by sequence number before handing
    batches to the consumer, so `seek_epoch` + skip-batches resume (the
    pool starts claiming at `start_index`) stays bitwise-equal to the
    single-process path.
  * a claim WINDOW (`served + window` is the claim ceiling) bounds
    host memory: at most `window` assembled batches exist across the
    queue, the reorder buffer and workers' hands.
  * worker death is a RETRYABLE fault: a nonzero exitcode (or an
    exception shipped over the queue) surfaces as `ReaderWorkerError`
    from `__next__` within one poll interval — never a deadlock, even
    with the queue full — and the Optimizer's bounded-restart path
    treats it like any transient step failure.
  * the stall-driven AUTOSCALER rides the DeviceFeed telemetry seam:
    `note_feed(stall_s, occupancy)` is called at every consumer
    hand-off; an EMA of the stall grows the pool when the consumer is
    starved and shrinks it when the queue stays ahead, with hysteresis
    (wide grow/shrink band + cooldown) so it never thrashes.  Decisions
    export as the `feed/reader_procs` gauge and `feed.reader_scale`
    trace instants through bigdl_tpu.obs.

Start method: `fork` by default (BIGDL_TPU_READER_START overrides) —
the test/CI environment initializes the real TPU backend at interpreter
startup via sitecustomize, which a `spawn` child would repeat; forked
workers run numpy-only code and never touch jax.  Under `spawn` the
ReaderWork object must be picklable.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue
import threading
import time
import traceback
from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu import obs as _obs

__all__ = ["ReaderWork", "ChunkWork", "ReaderPool", "ReaderWorkerError",
           "reader_work_for", "make_reader_source"]

# message kinds on the worker -> parent queue
_MSG_BATCH = 0   # (kind, seq, batch, corrupt_cumulative)
_MSG_END = 1     # stream exhausted at this claim index
_MSG_ERR = 2     # payload = formatted traceback

_NO_ITEM = object()


class ReaderWorkerError(RuntimeError):
    """A reader worker process failed (exception or hard death).  Raised
    from the pool's `__next__`; the Optimizer's restart path treats it as
    a retryable fault (a fresh pool re-reads the epoch deterministically)."""


class ReaderWork:
    """One epoch of batch-assembly work, split into a CHEAP indexed item
    stream and an EXPENSIVE per-item assemble.  Implementations must be
    deterministic: item `k` and `assemble(item_k)` may not depend on
    process, worker count or wall clock (that is what makes procs=1 and
    procs=N bitwise-equal)."""

    def item_stream(self, start: int) -> Iterator[Any]:
        """Yield work items from global batch index `start` on.  Must be
        cheap per item — every worker iterates this stream and assembles
        only the items it claimed."""
        raise NotImplementedError

    def assemble(self, item: Any) -> Any:
        """Item -> batch (MiniBatch).  The expensive stage; runs only in
        the worker that claimed the item."""
        raise NotImplementedError

    def corrupt_count(self) -> int:
        """Cumulative corrupt records this process observed while reading
        the item stream (shipped with every message; the parent routes the
        max across workers to the dataset's counter)."""
        return 0


class ChunkWork(ReaderWork):
    """List-backed work: `elements` is the epoch's (already shuffled)
    cheap element list; item `k` is the slice
    `elements[k*chunk : (k+1)*chunk]` and `assemble_fn(chunk_list)` turns
    it into one batch.  `keep_tail=False` drops the trailing partial
    chunk (SampleToMiniBatch's drop_remainder semantics)."""

    def __init__(self, elements: Sequence[Any], chunk: int,
                 assemble_fn: Callable[[List[Any]], Any],
                 keep_tail: bool = False):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.elements = list(elements)
        self.chunk = int(chunk)
        self.assemble_fn = assemble_fn
        self.keep_tail = bool(keep_tail)

    def __len__(self) -> int:
        n, rem = divmod(len(self.elements), self.chunk)
        return n + (1 if rem and self.keep_tail else 0)

    def item_stream(self, start: int) -> Iterator[Any]:
        for k in range(start, len(self)):
            yield self.elements[k * self.chunk:(k + 1) * self.chunk]

    def assemble(self, item: Any) -> Any:
        return self.assemble_fn(item)


# ---------------------------------------------------------------------------
# worker process body (module-level: picklable under spawn)
# ---------------------------------------------------------------------------

def _post(q, msg, stop_ev) -> bool:
    """Bounded put the parent's close() can always unblock.  On abort the
    queue's feeder thread is cancelled so process exit never blocks
    flushing into a pipe nobody reads."""
    while not stop_ev.is_set():
        try:
            q.put(msg, timeout=0.05)
            return True
        except queue.Full:
            continue
    q.cancel_join_thread()
    return False


def _reader_worker(work, wid, out_q, claim, served, window, target,
                   stop_ev, start_index):
    """Claim-assemble-post loop.  No jax, no logging, no obs: forked
    children must not touch locks another parent thread might have held
    at fork time; errors ship to the parent as formatted tracebacks."""
    k = -1
    try:
        it = None
        pos = int(start_index)
        while True:
            if stop_ev.is_set():
                out_q.cancel_join_thread()
                return
            if target.value <= wid:  # retired by the autoscaler
                out_q.cancel_join_thread()
                return
            with claim.get_lock():
                k = claim.value
                if k >= served.value + window:
                    k = -1  # claim window full: consumer is behind
                else:
                    claim.value = k + 1
            if k < 0:
                time.sleep(0.002)
                continue
            if it is None:
                it = work.item_stream(int(start_index))
            item = _NO_ITEM
            while pos <= k:
                try:
                    item = next(it)
                except StopIteration:
                    item = _NO_ITEM
                    break
                pos += 1
            if item is _NO_ITEM:
                # stream exhausted before (or at) the claimed index: this
                # claim's slot is the epoch's end marker
                _post(out_q, (_MSG_END, k, None,
                              int(work.corrupt_count())), stop_ev)
                return
            batch = work.assemble(item)
            if not _post(out_q, (_MSG_BATCH, k, batch,
                                 int(work.corrupt_count())), stop_ev):
                return
    except BaseException:
        _post(out_q, (_MSG_ERR, k, traceback.format_exc(),
                      int(getattr(work, "corrupt_count", lambda: 0)())),
              stop_ev)


# ---------------------------------------------------------------------------
# the pool
# ---------------------------------------------------------------------------

class ReaderPool:
    """Multi-process batch source with strict-order delivery.

    Iterates assembled batches in exact `work` index order starting at
    `start_index`; plugs into DeviceFeed as the `batches` source (the
    feed's worker thread then only dequeues + stages, sharing the
    `feed.h2d_stage` path with the in-thread assembler).

    Parameters
    ----------
    work : ReaderWork
    procs : initial worker count (>= 1)
    start_index : first batch index to produce (mid-epoch resume skip)
    max_procs : autoscaler ceiling (default `procs`)
    autoscale : stall-driven grow/shrink between [1, max_procs]
    on_corrupt : callable(delta) fed the skip_corrupt counter deltas
    window : claimed-but-undelivered ceiling (host memory bound in
        batches); default `2 * max_procs + 2`
    """

    # BatchSource protocol (dataset/feed.py): DeviceFeed.close() closes
    # this source CONCURRENTLY with its worker thread — every method
    # here tolerates a close() racing a blocked __next__
    close_with_feed = True

    def __init__(self, work: ReaderWork, procs: int = 1,
                 start_index: int = 0, name: str = "ReaderPool",
                 max_procs: Optional[int] = None, autoscale: bool = False,
                 on_corrupt: Optional[Callable[[int], None]] = None,
                 window: Optional[int] = None,
                 start_method: Optional[str] = None,
                 grow_stall_frac: float = 0.05,
                 shrink_stall_frac: float = 0.005,
                 cooldown_s: float = 1.0):
        if procs < 1:
            raise ValueError(f"procs must be >= 1, got {procs}")
        self.name = name
        self._work = work
        self._min_procs = 1
        self._max_procs = max(int(max_procs or procs), procs)
        self._autoscale = bool(autoscale)
        self._on_corrupt = on_corrupt
        self._window = int(window or (2 * self._max_procs + 2))
        # scale thresholds are FRACTIONS of the consumer's step interval,
        # not absolute milliseconds: a 2 ms stall is starvation on a 5 ms
        # step but idle-regime noise on a 100 ms conv step, and forking a
        # worker into the latter only steals host CPU from XLA
        self._grow_frac = float(grow_stall_frac)
        self._shrink_frac = float(shrink_stall_frac)
        self._cooldown_s = float(cooldown_s)
        method = start_method or os.environ.get(
            "BIGDL_TPU_READER_START", "fork")
        self._ctx = mp.get_context(method)
        self._q = self._ctx.Queue(maxsize=self._window)
        self._stop = self._ctx.Event()
        start = int(start_index)
        self._claim = self._ctx.Value("l", start)
        self._served = self._ctx.Value("l", start)
        self._target = self._ctx.Value("i", int(procs))
        self._start_index = start
        # parent-side state.  _lock covers the worker table: __next__ and
        # its death checks run on the DeviceFeed worker thread while
        # note_feed (autoscale) and close() run on the consumer thread.
        self._lock = threading.Lock()
        self._workers: dict = {}
        self._buf: dict = {}
        self._next_seq = start
        self._delivered = 0
        self._corrupt_reported = 0
        self._error: Optional[BaseException] = None
        self._closed = False
        self._stall_ema: Optional[float] = None
        self._interval_ema: Optional[float] = None
        self._last_note: Optional[float] = None
        self._notes = 0
        self._last_scale = time.monotonic()
        for wid in range(int(procs)):
            self._spawn(wid)
        _obs.registry().set_gauge("feed/reader_procs", int(procs))

    # -- worker management -------------------------------------------------

    def _spawn(self, wid: int) -> None:
        p = self._ctx.Process(
            target=_reader_worker, name=f"{self.name}-w{wid}", daemon=True,
            args=(self._work, wid, self._q, self._claim, self._served,
                  self._window, self._target, self._stop, self._start_index))
        p.start()
        self._workers[wid] = p

    @property
    def procs(self) -> int:
        """Current autoscaler target (== live workers, modulo the short
        ramp while a retired worker finishes its last claim)."""
        return int(self._target.value)

    @property
    def delivered_batches(self) -> int:
        return self._delivered

    # -- consumer side (runs on the DeviceFeed worker thread) --------------

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise StopIteration
        if self._error is not None:
            raise self._wrap_error()
        while self._next_seq not in self._buf:
            if self._stop.is_set():  # concurrent close(): clean end
                raise StopIteration
            try:
                msg = self._q.get(timeout=0.05)
            except queue.Empty:
                self._check_workers()
                continue
            except (OSError, ValueError):  # queue torn down by close()
                raise StopIteration from None
            kind, seq, payload, corrupt = msg
            self._note_corrupt(corrupt)
            if kind == _MSG_ERR:
                self._error = ReaderWorkerError(
                    f"{self.name} worker failed assembling batch "
                    f"{seq}:\n{payload}")
                self.close()
                raise self._wrap_error()
            self._buf[seq] = (kind, payload)
        kind, payload = self._buf.pop(self._next_seq)
        if kind == _MSG_END:
            self.close()
            raise StopIteration
        self._next_seq += 1
        with self._served.get_lock():
            self._served.value = self._next_seq
        self._delivered += 1
        return payload

    def _wrap_error(self) -> BaseException:
        return self._error if self._error is not None else \
            ReaderWorkerError(f"{self.name} failed")

    def _check_workers(self) -> None:
        """Poll for a worker that died WITHOUT posting (kill -9, OOM):
        the bounded-timeout get above plus this check is what makes a
        dead producer surface as an error instead of a consumer hang."""
        with self._lock:
            workers = list(self._workers.values())
        dead_dirty = [p for p in workers
                      if not p.is_alive() and p.exitcode not in (0, None)]
        if dead_dirty:
            p = dead_dirty[0]
            self._error = ReaderWorkerError(
                f"{self.name} worker {p.name} died (exitcode {p.exitcode}) "
                f"before posting its claimed batch")
            self.close()
            raise self._wrap_error()
        if workers and all(not p.is_alive() for p in workers) \
                and self._q.empty() and self._next_seq not in self._buf:
            # every worker exited cleanly yet the sequence has a hole and
            # no END reached us — defensive: surface instead of spinning
            self._error = ReaderWorkerError(
                f"{self.name}: all workers exited without completing the "
                f"epoch (next_seq={self._next_seq})")
            self.close()
            raise self._wrap_error()

    def _note_corrupt(self, cumulative: int) -> None:
        # every worker reads the full (cheap) item stream, so each one
        # observes the same corrupt records: route the MAX across
        # workers, as deltas, to the dataset's counter
        c = int(cumulative or 0)
        if c > self._corrupt_reported:
            delta = c - self._corrupt_reported
            self._corrupt_reported = c
            if self._on_corrupt is not None:
                self._on_corrupt(delta)

    # -- autoscaler (runs on the consumer thread via DeviceFeed) -----------

    def note_feed(self, stall_s: float, occupancy: int) -> None:
        """DeviceFeed hand-off hook: fold the consumer's stall into the
        EMA and apply the grow/shrink policy with hysteresis.  The stall
        is judged as a fraction of the inter-note interval (= the
        consumer's step time, also EMA-tracked), so the policy adapts to
        the step's own speed instead of a fixed millisecond bar."""
        if not self._autoscale or self._closed:
            return
        now = time.monotonic()
        if self._last_note is not None:
            dt = now - self._last_note
            self._interval_ema = dt if self._interval_ema is None \
                else 0.2 * dt + 0.8 * self._interval_ema
        self._last_note = now
        ema = self._stall_ema
        self._stall_ema = stall_s if ema is None \
            else 0.2 * stall_s + 0.8 * ema
        self._notes += 1
        if self._notes < 8:  # warmup: first batches measure pool ramp
            return
        if now - self._last_scale < self._cooldown_s:
            return
        if not self._interval_ema or self._interval_ema <= 0:
            return
        frac = self._stall_ema / self._interval_ema
        ema_ms = self._stall_ema * 1e3
        if frac > self._grow_frac:
            self._scale(+1, now, ema_ms)
        elif frac < self._shrink_frac:
            self._scale(-1, now, ema_ms)

    def _scale(self, delta: int, now: float, ema_ms: float) -> None:
        with self._lock:
            if self._closed:
                return
            cur = int(self._target.value)
            n = min(max(cur + delta, self._min_procs), self._max_procs)
            # reset the decision clock even at the bounds, so a pool
            # pinned at max_procs doesn't spin the policy every note
            self._last_scale = now
            self._stall_ema = None
            self._notes = 0
            if n == cur:
                return
            self._target.value = n
            if n > cur:
                for wid in range(cur, n):
                    p = self._workers.get(wid)
                    if p is not None and p.is_alive():
                        continue  # still draining its retirement
                    self._spawn(wid)
            # shrink: workers with wid >= n observe the target and retire
            # after finishing their current claim; close() reaps them
        _obs.registry().set_gauge("feed/reader_procs", n)
        _obs.instant("feed.reader_scale", cat="feed", procs=n,
                     stall_ms=round(ema_ms, 3))

    # -- shutdown ----------------------------------------------------------

    def __enter__(self) -> "ReaderPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Idempotent shutdown with the bounded-timeout discipline: stop,
        drain (so a worker blocked mid-put can observe the flag), join
        with timeouts, terminate stragglers.  Never blocks unbounded —
        a worker that ignores SIGTERM is SIGKILLed."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        with self._lock:
            workers = list(self._workers.values())
        deadline = time.monotonic() + 5.0
        while any(p.is_alive() for p in workers) \
                and time.monotonic() < deadline:
            try:
                self._q.get(timeout=0.05)
            except queue.Empty:
                pass
            except (OSError, ValueError):  # pragma: no cover - defensive
                break
        for p in workers:
            p.join(timeout=1.0)
            if p.is_alive():
                p.terminate()
                p.join(timeout=1.0)
            if p.is_alive():  # pragma: no cover - SIGTERM-immune worker
                p.kill()
                p.join(timeout=1.0)
        self._buf.clear()
        reg = _obs.registry()
        reg.inc("feed/reader_batches", self._delivered)


# ---------------------------------------------------------------------------
# dataset -> ReaderWork adapters
# ---------------------------------------------------------------------------

def _chain_stages(transformer) -> Optional[List[Any]]:
    """Flatten a Transformer into its stage list, or None if opaque."""
    from bigdl_tpu.dataset.transformer import (ChainedTransformer,
                                               Transformer)
    if isinstance(transformer, ChainedTransformer):
        out: List[Any] = []
        for s in transformer.stages:
            sub = _chain_stages(s)
            if sub is None:
                return None
            out.extend(sub)
        return out
    if isinstance(transformer, Transformer):
        return [transformer]
    return None


def _elementwise_prefix(stages) -> bool:
    """True when every pre-batch stage is 1:1 elementwise, so applying
    the chain to one batch_size chunk of base elements yields exactly the
    batch the streaming path would have built from those elements.  A
    filtering/stateful custom Transformer would silently change batch
    composition — reject those (the caller falls back to in-thread
    assembly)."""
    from bigdl_tpu.dataset.transformer import FnTransformer
    return all(isinstance(s, FnTransformer) for s in stages)


class _TransformChunkWork(ChunkWork):
    """ChunkWork whose assemble runs `decode` per element then the
    transformer chain over the chunk (exactly one SampleToMiniBatch group
    per chunk, so chunk k == batch k of the streaming path)."""

    def __init__(self, elements, batch_size, transformer, decode=None,
                 keep_tail=False):
        super().__init__(elements, batch_size, None, keep_tail=keep_tail)
        self._transformer = transformer
        self._decode = decode

    def assemble(self, item):
        elems = item if self._decode is None \
            else [self._decode(e) for e in item]
        batches = list(self._transformer(iter(elems)))
        if len(batches) != 1:  # pragma: no cover - guarded by adapter
            raise RuntimeError(
                f"reader chunk produced {len(batches)} batches (expected "
                f"1) — transformer chain is not chunk-aligned")
        return batches[0]


def _decode_image_entry(entry):
    """(path, label) -> Sample, the ImageFolderDataSet.data decode moved
    into the worker (module-level: picklable under spawn)."""
    from PIL import Image

    from bigdl_tpu.dataset.sample import Sample
    p, label = entry
    with Image.open(p) as im:
        arr = np.asarray(im.convert("RGB"), np.float32)
    return Sample(arr, None if label is None else np.int32(label))


def reader_work_for(dataset, train: bool) -> Optional[ReaderWork]:
    """Derive this epoch's ReaderWork from `dataset`, or None when its
    assembly cannot be disaggregated safely (caller falls back to the
    in-thread path; bitwise behaviour is then unchanged).

    CONSUMES the epoch exactly like `dataset.data(train)` would: the
    shuffle replay (`RandomState(seed + epoch)`) happens here in the
    parent and the epoch counter advances, so seek_epoch/resume semantics
    are identical pool on or off.
    """
    from bigdl_tpu.core.random import RandomGenerator
    from bigdl_tpu.dataset.dataset import (ArrayDataSet, ImageFolderDataSet,
                                           TransformedDataSet)
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch

    own = getattr(dataset, "reader_work", None)
    if callable(own):
        return own(train)
    if not isinstance(dataset, TransformedDataSet):
        return None
    stages = _chain_stages(dataset.transformer)
    if not stages or not isinstance(stages[-1], SampleToMiniBatch) \
            or not _elementwise_prefix(stages[:-1]):
        return None
    smb: SampleToMiniBatch = stages[-1]
    keep_tail = smb.pad_to_full or not smb.drop_remainder
    base = dataset.base
    if isinstance(base, ArrayDataSet):
        if train:
            idx = np.arange(len(base.items))
            rs = np.random.RandomState(RandomGenerator.get_seed()
                                       + base._epoch)
            rs.shuffle(idx)
            base._epoch += 1
            elements = [base.items[i] for i in idx]
        else:
            elements = list(base.items)
        return _TransformChunkWork(elements, smb.batch_size,
                                   dataset.transformer, keep_tail=keep_tail)
    if isinstance(base, ImageFolderDataSet):
        entries = list(base.entries)
        if train:
            rs = np.random.RandomState(RandomGenerator.get_seed()
                                       + base._epoch)
            rs.shuffle(entries)
            base._epoch += 1
        return _TransformChunkWork(entries, smb.batch_size,
                                   dataset.transformer,
                                   decode=_decode_image_entry,
                                   keep_tail=keep_tail)
    # RecordShardDataSet is out: its multi-thread prefetch order is
    # nondeterministic by design, so there is no single-process sequence
    # to be bitwise-equal to
    return None


def make_reader_source(dataset, train: bool, procs: int,
                       start_index: int = 0, autoscale: bool = False,
                       max_procs: Optional[int] = None,
                       name: str = "ReaderPool",
                       **pool_kw) -> Optional[ReaderPool]:
    """ReaderPool over `dataset`'s epoch, or None when the dataset's
    assembly cannot be disaggregated (the caller keeps the in-thread
    path).  Corrupt-record counts flow back into the dataset's
    `_count_corrupt` so the trainer's CorruptRecords telemetry is
    pool-agnostic."""
    if procs < 1:
        return None
    work = reader_work_for(dataset, train)
    if work is None:
        return None
    on_corrupt = getattr(dataset, "_count_corrupt", None)
    return ReaderPool(work, procs=procs, start_index=start_index,
                      autoscale=autoscale, max_procs=max_procs, name=name,
                      on_corrupt=on_corrupt, **pool_kw)
