"""TFRecord IO + multi-threaded prefetching reader.

Reference: utils/tf/TFRecordIterator + TFRecordInputFormat (JVM readers over
netty/Crc32c.java) and the reference's ImageNet-as-SequenceFiles convention
(dataset/DataSet.scala:482-560 — on TPU the sharded record container of
choice is TFRecord).  The hot path is the native C++ layer
(bigdl_tpu/native/src/{crc32c,tfrecord,prefetch}.cc); a pure-python
fallback keeps everything working where g++ is absent.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
from typing import Iterator, List, Optional, Sequence

import numpy as np

logger = logging.getLogger("bigdl_tpu.dataset")

from bigdl_tpu import native
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


def frame_record(record: bytes) -> bytes:
    """One TFRecord frame: len | masked_crc(len) | data | masked_crc(data).
    The single definition of the wire format (event files use it too)."""
    header = struct.pack("<Q", len(record))
    return (header + struct.pack("<I", native.crc32c_masked(header)) +
            record + struct.pack("<I", native.crc32c_masked(record)))


_warned_corrupt = [False]


def _note_corrupt(on_corrupt, n: int, why: str) -> None:
    """skip_corrupt bookkeeping: count through the caller's hook and warn
    ONCE per process (every further skip is a counter increment, not log
    spam — the per-run total surfaces via dataset.corrupt_records)."""
    if on_corrupt is not None:
        on_corrupt(n)
    if not _warned_corrupt[0]:
        _warned_corrupt[0] = True
        logger.warning(
            "skip_corrupt: dropping corrupt TFRecord data (%s); further "
            "skips are counted silently — see the CorruptRecords metric",
            why)


def iter_framed(fh, what: str = "record", *, skip_corrupt: bool = False,
                on_corrupt=None) -> Iterator[bytes]:
    """Iterate frames from an open binary file, verifying checksums;
    raises IOError (never struct.error) on truncation or corruption.

    `skip_corrupt` drops records whose DATA crc mismatches (the framing
    is intact, so the stream resyncs at the next header) instead of
    raising; each drop calls `on_corrupt(1)` and warns once per process.
    A corrupt length crc or truncation still raises — without a trusted
    length there is no next frame to resync to."""
    while True:
        header = fh.read(12)
        if not header:
            return
        if len(header) != 12:
            raise IOError(f"truncated {what} header")
        (length,) = struct.unpack("<Q", header[:8])
        (len_crc,) = struct.unpack("<I", header[8:])
        if native.crc32c_masked(header[:8]) != len_crc:
            raise IOError(f"corrupt {what} length crc")
        data = fh.read(length)
        tail = fh.read(4)
        if len(data) != length or len(tail) != 4:
            raise IOError(f"truncated {what} body")
        (data_crc,) = struct.unpack("<I", tail)
        if native.crc32c_masked(data) != data_crc:
            if skip_corrupt:
                _note_corrupt(on_corrupt, 1, f"{what} data crc mismatch")
                continue
            raise IOError(f"corrupt {what} data crc")
        yield data


def count_records(path: str) -> int:
    """Count frames by seeking over payloads (length header + skip) —
    no decode, no checksum; cheap size() for shard folders.  Truncation
    raises like iter_framed does, so size() and the actual stream agree."""
    n = 0
    end = os.path.getsize(path)
    with open(path, "rb") as fh:
        while True:
            header = fh.read(12)
            if not header:
                return n
            if len(header) != 12:
                raise IOError(f"truncated record header in {path}")
            (length,) = struct.unpack("<Q", header[:8])
            if fh.tell() + length + 4 > end:
                raise IOError(f"truncated record body in {path}")
            fh.seek(length + 4, 1)  # payload + data crc
            n += 1


class TFRecordWriter:
    """Write length-prefixed, crc32c-masked records."""

    def __init__(self, path: str):
        self.path = path
        self._lib = native.get_lib()
        if self._lib is not None:
            self._h = self._lib.bigdl_tfrecord_writer_open(path.encode())
            if not self._h:
                raise IOError(f"cannot open {path}")
            self._f = None
        else:
            self._f = open(path, "wb")
            self._h = None

    def write(self, record: bytes) -> None:
        if self._h is not None:
            buf = (ctypes.c_uint8 * len(record)).from_buffer_copy(record)
            if self._lib.bigdl_tfrecord_writer_write(self._h, buf, len(record)) != 0:
                raise IOError(f"short write to {self.path}")
        else:
            self._f.write(frame_record(record))

    def close(self) -> None:
        if self._h is not None:
            self._lib.bigdl_tfrecord_writer_close(self._h)
            self._h = None
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_tfrecords(path: str, *, skip_corrupt: bool = False,
                   on_corrupt=None) -> Iterator[bytes]:
    """Iterate records of one file, verifying checksums.

    `skip_corrupt` routes through the python framing reader (which can
    resync past a bad data crc) even when the native reader is built —
    the native reader stops a shard at the first corrupt frame, so the
    lenient policy must own the framing to salvage the tail."""
    lib = native.get_lib()
    if lib is not None and not skip_corrupt:
        h = lib.bigdl_tfrecord_reader_open(path.encode())
        if not h:
            raise IOError(f"cannot open {path}")
        try:
            ptr = ctypes.POINTER(ctypes.c_uint8)()
            while True:
                n = lib.bigdl_tfrecord_reader_next(h, ctypes.byref(ptr))
                if n == -2:  # clean EOF
                    return
                if n < 0:
                    raise IOError(f"corrupt TFRecord in {path}")
                yield ctypes.string_at(ptr, n) if n else b""
        finally:
            lib.bigdl_tfrecord_reader_close(h)
    else:
        with open(path, "rb") as f:
            try:
                yield from iter_framed(f, "TFRecord",
                                       skip_corrupt=skip_corrupt,
                                       on_corrupt=on_corrupt)
            except IOError as e:
                raise IOError(f"{e} in {path}") from None


class PrefetchRecordReader:
    """Background-thread reader over sharded TFRecord files (the native
    analogue of MTLabeledBGRImgToBatch's decode thread pool).  Iterates
    records from all shards; ordering across shards is nondeterministic by
    design (throughput over order, like the reference's multi-thread
    decode)."""

    def __init__(self, paths: Sequence[str], n_threads: int = 4,
                 capacity: int = 256, *, skip_corrupt: bool = False,
                 on_corrupt=None):
        self.paths = list(paths)
        self._lib = native.get_lib()
        self._h = None
        self._n_threads = n_threads
        self._capacity = capacity
        self.skip_corrupt = bool(skip_corrupt)
        self._on_corrupt = on_corrupt

    def __iter__(self) -> Iterator[bytes]:
        if self._lib is None or self.skip_corrupt:
            # python reader: sequential, but the only framing layer that
            # can resync past a corrupt record (see read_tfrecords)
            for p in self.paths:
                yield from read_tfrecords(p, skip_corrupt=self.skip_corrupt,
                                          on_corrupt=self._on_corrupt)
            return
        arr = (ctypes.c_char_p * len(self.paths))(
            *[p.encode() for p in self.paths])
        h = self._lib.bigdl_prefetch_open(arr, len(self.paths),
                                          self._n_threads, self._capacity)
        if not h:
            raise IOError("prefetch loader failed to start")
        try:
            cap = 1 << 16
            buf = (ctypes.c_uint8 * cap)()
            needed = ctypes.c_size_t()
            while True:
                n = self._lib.bigdl_prefetch_next(h, buf, cap,
                                                  ctypes.byref(needed))
                if n == -2:  # drained
                    break
                if n == -1:  # grow buffer and retry
                    cap = max(cap * 2, int(needed.value))
                    buf = (ctypes.c_uint8 * cap)()
                    continue
                yield ctypes.string_at(buf, n) if n else b""
            errs = self._lib.bigdl_prefetch_errors(h)
            if errs:
                raise IOError(f"{errs} corrupt/unreadable TFRecord shard(s)")
        finally:
            self._lib.bigdl_prefetch_close(h)


# ---------------------------------------------------------------------------
# Array <-> record payload (a minimal fixed schema: dtype tag, rank, dims,
# raw feature bytes, then the same for the label)
# ---------------------------------------------------------------------------

_DTYPES = {0: np.float32, 1: np.int32, 2: np.uint8, 3: np.int64, 4: np.float64}
_DTYPE_TAGS = {np.dtype(v): k for k, v in _DTYPES.items()}


def _pack_array(a: Optional[np.ndarray]) -> bytes:
    if a is None:
        return struct.pack("<b", -1)
    a = np.asarray(a)
    # ascontiguousarray promotes 0-d to 1-d: record the TRUE rank/shape
    rank, shape = a.ndim, a.shape
    a = np.ascontiguousarray(a)
    tag = _DTYPE_TAGS[a.dtype]
    head = struct.pack("<bB", tag, rank) + struct.pack(f"<{rank}q", *shape)
    return head + a.tobytes()


def _unpack_array(buf: bytes, off: int):
    (tag,) = struct.unpack_from("<b", buf, off)
    off += 1
    if tag == -1:
        return None, off
    (rank,) = struct.unpack_from("<B", buf, off)
    off += 1
    dims = struct.unpack_from(f"<{rank}q", buf, off)
    off += 8 * rank
    dtype = np.dtype(_DTYPES[tag])
    n = int(np.prod(dims)) if rank else 1
    a = np.frombuffer(buf, dtype, count=n, offset=off).reshape(dims)
    off += n * dtype.itemsize
    return a, off


def sample_to_record(s: Sample) -> bytes:
    return _pack_array(np.asarray(s.feature)) + _pack_array(
        None if s.label is None else np.asarray(s.label))


def record_to_sample(record: bytes) -> Sample:
    feature, off = _unpack_array(record, 0)
    label, _ = _unpack_array(record, off)
    return Sample(feature, label)


def write_sample_shards(samples: Sequence[Sample], dir_path: str,
                        n_shards: int = 1, prefix: str = "data") -> List[str]:
    """Write samples round-robin into n TFRecord shards; returns paths."""
    os.makedirs(dir_path, exist_ok=True)
    paths = [os.path.join(dir_path, f"{prefix}-{i:05d}-of-{n_shards:05d}.tfrecord")
             for i in range(n_shards)]
    writers = [TFRecordWriter(p) for p in paths]
    try:
        for i, s in enumerate(samples):
            writers[i % n_shards].write(sample_to_record(s))
    finally:
        for w in writers:
            w.close()
    return paths


class RecordToSample(Transformer):
    """bytes -> Sample stage for pipelines fed by PrefetchRecordReader."""

    def __call__(self, it: Iterator[bytes]) -> Iterator[Sample]:
        for rec in it:
            yield record_to_sample(rec)


class VarLenFeature:
    """Declaration of a variable-length (sparse) Example feature column.

    Reference: utils/tf/loaders/ParseExample.scala + nn/tf/
    ParsingOps.scala parse VarLen features into COO SparseTensors; here
    each record becomes a host-side `SparseFeature` that SparseMiniBatch
    densifies at the batch boundary (static shapes for jit, MXU-friendly).

    encodings:
    - "positions" (TF parity): values scatter at positions 0..n-1 into a
      (`size`,) vector — a padded ragged list once densified.  Pair with
      feature_padding=-1 to feed LookupTableSparse id bags.
    - "multi_hot": int values are INDICES into a (`size`,)-wide vocab;
      the densified row is their multi-hot (count) encoding — the
      SparseLinear wide-model input (fine for narrow vocabs).
    - "bag": multi_hot semantics WITHOUT densification — the column
      batches as a (ids, values) pair padded to `max_nnz` per record,
      feeding SparseLinear's device-sparse gather path.  Work and HBM
      traffic scale with max_nnz instead of vocab `size`; use this for
      1e5+ vocabs (reference capability: tensor/SparseTensorMath.scala
      sparse gemm).
    """

    def __init__(self, key: str, size: int, dtype: str = "int64",
                 encoding: str = "positions", max_nnz: int = 0):
        if encoding not in ("positions", "multi_hot", "bag"):
            raise ValueError(f"unknown VarLen encoding {encoding!r}")
        if encoding == "bag" and max_nnz <= 0:
            raise ValueError("encoding='bag' needs max_nnz (the static "
                             "per-record id capacity)")
        self.key = key
        self.size = int(size)
        self.dtype = dtype
        self.encoding = encoding
        self.max_nnz = int(max_nnz)

    def to_sparse(self, values):
        import numpy as _np

        from bigdl_tpu.dataset.sample import SparseBag, SparseFeature

        values = _np.asarray(values)
        if self.encoding in ("multi_hot", "bag"):
            if values.size and (values.min() < 0
                                or values.max() >= self.size):
                raise ValueError(
                    f"VarLen {self.key!r}: id out of range [0, {self.size})")
            idx, counts = _np.unique(values.astype(_np.int64),
                                     return_counts=True)
            if self.encoding == "bag":
                return SparseBag(idx, counts.astype(self.dtype),
                                 self.max_nnz)
            return SparseFeature(idx[:, None], counts.astype(self.dtype),
                                 (self.size,))
        if values.size > self.size:
            raise ValueError(
                f"VarLen {self.key!r}: record has {values.size} values, "
                f"declared size {self.size}")
        return SparseFeature(
            _np.arange(values.size, dtype=_np.int64)[:, None],
            values.astype(self.dtype), (self.size,))


def _dense_minibatch(parser, records, label_index, label_dtype,
                     np_only: bool = False):
    """Record buffer -> dense MiniBatch: the one assembly seam shared by
    the in-thread path (`data()`) and the reader-process path
    (`_ParsedExampleWork.assemble`).  `np_only` keeps every column on the
    host (reader workers must not touch the forked jax backend; values
    are bitwise-equal after the feed's staging put canonicalizes)."""
    import numpy as _np

    from bigdl_tpu.dataset.minibatch import MiniBatch

    if np_only:
        cols = parser.compute_np(records)
    else:
        cols = list(parser.compute(_np.asarray(records, dtype=object)))
    y = _np.asarray(cols[label_index]).astype(label_dtype)
    xs = [c for i, c in enumerate(cols) if i != label_index]
    return MiniBatch(xs[0] if len(xs) == 1 else tuple(xs), y)


def _sparse_minibatch(records, dense_keys, dense_shapes, label_key,
                      label_dtype, sparse_features, feature_padding):
    """Per-record parse -> Sample(dense..., SparseFeature...) ->
    SparseMiniBatch (densified at this batch boundary).  Module-level and
    numpy-only for the same reader-process reason as _dense_minibatch."""
    import numpy as _np

    from bigdl_tpu.dataset.minibatch import SparseMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn.tf_ops import parse_example_proto

    samples = []
    for rec in records:
        feats = parse_example_proto(bytes(rec))
        parts = []
        label = None
        for k, sh in zip(dense_keys, dense_shapes):
            v = _np.asarray(feats[k]).reshape(sh)
            if k == label_key:
                label = v.astype(label_dtype)
            else:
                parts.append(v)
        for sf in sparse_features:
            parts.append(sf.to_sparse(feats.get(sf.key, ())))
        samples.append(Sample(tuple(parts) if len(parts) > 1
                              else parts[0], label))
    return SparseMiniBatch.from_samples(
        samples, feature_padding=feature_padding)


class _ParsedExampleWork:
    """ReaderWork (dataset/readers.py protocol) over TFRecord Example
    shards: items are full record buffers (cheap framing reads + the
    reservoir-shuffle replay), assemble is the proto parse -> MiniBatch
    stack (the expensive stage).

    Determinism: records stream through the SEQUENTIAL framing reader
    (`read_tfrecords` per shard, in the parent's shuffled path order) —
    never the native multi-thread prefetcher, whose cross-shard order is
    a thread race.  Every worker therefore sees the identical record
    stream and batch `k` is a pure function of (paths, rs, k), which is
    what makes procs=1 vs procs=N bitwise-equal.  The `skip_corrupt`
    resync policy applies per shard exactly as in-thread."""

    def __init__(self, paths, batch_size, dense_keys, dense_shapes,
                 label_key, label_dtype, sparse_features, feature_padding,
                 skip_corrupt, rs):
        self.paths = list(paths)
        self.batch_size = int(batch_size)
        self.dense_keys = list(dense_keys)
        self.dense_shapes = [tuple(s) for s in dense_shapes]
        self.label_key = label_key
        self.label_dtype = label_dtype
        self.sparse_features = list(sparse_features)
        self.feature_padding = feature_padding
        self.skip_corrupt = bool(skip_corrupt)
        self._rs = rs  # post-path-shuffle RandomState (None for eval)
        self._li = self.dense_keys.index(label_key)
        self._corrupt = 0
        self._parser = None  # built lazily in the worker

    def corrupt_count(self) -> int:
        return self._corrupt

    def _bump_corrupt(self, n: int) -> None:
        self._corrupt += int(n)

    def item_stream(self, start: int):
        rs = self._rs

        def records():
            for p in self.paths:
                yield from read_tfrecords(p, skip_corrupt=self.skip_corrupt,
                                          on_corrupt=self._bump_corrupt)

        def shuffled():
            it = records()
            if rs is None:
                yield from it
                return
            # the reservoir window replay: same rs draws per record as
            # ParsedExampleDataSet.data, so the shuffled stream (and the
            # rs state) is identical in every worker
            window: List[bytes] = []
            cap = max(4 * self.batch_size, 1024)
            for rec in it:
                window.append(rec)
                if len(window) >= cap:
                    k = rs.randint(len(window))
                    window[k], window[-1] = window[-1], window[k]
                    yield window.pop()
            rs.shuffle(window)
            yield from window

        buf: List[bytes] = []
        k = 0
        for rec in shuffled():
            buf.append(rec)
            if len(buf) == self.batch_size:
                if k >= start:
                    yield buf
                buf = []
                k += 1
        # trailing partial batch dropped, as in data()

    def assemble(self, records):
        if self.sparse_features:
            return _sparse_minibatch(records, self.dense_keys,
                                     self.dense_shapes, self.label_key,
                                     self.label_dtype, self.sparse_features,
                                     self.feature_padding)
        if self._parser is None:
            from bigdl_tpu.nn.tf_ops import ParseExample

            self._parser = ParseExample(self.dense_keys, self.dense_shapes)
        return _dense_minibatch(self._parser, records, self._li,
                                self.label_dtype, np_only=True)


class ParsedExampleDataSet(DataSet):
    """TFRecord shards of serialized tf.train.Examples -> MiniBatches via
    the host-side ParseExample op: the imported-graph training data path
    (reference: utils/tf/TFRecordInputFormat + nn/tf/ParsingOps.scala
    feeding Session.train, example/tensorflow).

    Each batch parses `batch_size` serialized Examples into dense feature
    columns (`dense_keys`/`dense_shapes` order); `label_key` becomes the
    target, the remaining columns the (tuple of) inputs.  The trailing
    partial batch is dropped so the jitted step sees one static shape.

    `sparse_features` (VarLenFeature declarations) append sparse columns
    after the dense ones; batches then come out as SparseMiniBatch with
    each sparse column densified per its encoding (`feature_padding`
    fills the unset positions — scalar or per-column tuple over the
    FULL input column list, dense columns first).
    """

    def __init__(self, paths: Sequence[str], batch_size: int,
                 dense_keys: Sequence[str],
                 dense_shapes: Sequence[Sequence[int]],
                 label_key: str, n_threads: int = 4,
                 label_dtype: str = "int32",
                 sparse_features: Sequence[VarLenFeature] = (),
                 feature_padding=None, skip_corrupt: bool = False):
        from bigdl_tpu.nn.tf_ops import ParseExample

        self.paths = list(paths)
        self.batch_size = batch_size
        self.dense_keys = list(dense_keys)
        self.label_key = label_key
        if label_key not in self.dense_keys:
            raise ValueError(f"label_key {label_key!r} not in dense_keys")
        self.n_threads = n_threads
        self.label_dtype = label_dtype
        self.sparse_features = list(sparse_features)
        self.feature_padding = feature_padding
        # skip_corrupt: drop records with a bad data crc (count + warn
        # once) instead of killing the epoch — long-lived corpora on
        # flaky storage rot one record at a time, and one bad record
        # should cost one record, not the run.  Default strict.
        self.skip_corrupt = bool(skip_corrupt)
        self._corrupt = 0
        self._dense_shapes = [tuple(s) for s in dense_shapes]
        self._parser = ParseExample(dense_keys, dense_shapes)
        self._epoch = 0
        self._size = -1

    @property
    def corrupt_records(self) -> int:
        """Records dropped by the skip_corrupt policy so far (the trainer
        surfaces this as the CorruptRecords metric)."""
        return self._corrupt

    def _count_corrupt(self, n: int) -> None:
        self._corrupt += int(n)
        # mirror onto the obs metrics plane (per-dataset count stays the
        # source of truth for the trainer's CorruptRecords scalar)
        from bigdl_tpu import obs as _obs
        _obs.registry().inc("dataset/corrupt_records", int(n))

    def size(self) -> int:
        if self._size < 0:
            self._size = sum(count_records(p) for p in self.paths)
        return self._size

    def data(self, train: bool):
        import numpy as _np

        from bigdl_tpu.core.random import RandomGenerator
        from bigdl_tpu.dataset.minibatch import MiniBatch

        rs = None
        paths = list(self.paths)
        if train:
            rs = _np.random.RandomState(RandomGenerator.get_seed()
                                        + self._epoch)
            rs.shuffle(paths)
            self._epoch += 1
        li = self.dense_keys.index(self.label_key)

        def records():
            it = PrefetchRecordReader(paths, n_threads=self.n_threads,
                                      skip_corrupt=self.skip_corrupt,
                                      on_corrupt=self._count_corrupt)
            if rs is None:
                yield from it
                return
            # within-shard shuffle buffer (reservoir style): shard-order
            # shuffling alone leaves single-shard training in identical
            # order every epoch, degrading SGD
            window: List[bytes] = []
            cap = max(4 * self.batch_size, 1024)
            for rec in it:
                window.append(rec)
                if len(window) >= cap:
                    k = rs.randint(len(window))
                    window[k], window[-1] = window[-1], window[k]
                    yield window.pop()
            rs.shuffle(window)
            yield from window

        buf: List[bytes] = []
        for rec in records():
            buf.append(rec)
            if len(buf) == self.batch_size:
                if self.sparse_features:
                    yield self._sparse_batch(buf)
                else:
                    yield _dense_minibatch(self._parser, buf, li,
                                           self.label_dtype)
                buf = []

    def reader_work(self, train: bool) -> "_ParsedExampleWork":
        """This epoch's assembly as ReaderWork for `readers.ReaderPool`.
        Consumes the epoch exactly like `data(train)`: the path shuffle
        runs HERE (same RandomState draws) and `_epoch` advances, so
        seek_epoch + skip-batches resume behaves identically pool on or
        off.  The post-shuffle rs ships to the workers, whose reservoir
        replay continues its state."""
        import numpy as _np

        from bigdl_tpu.core.random import RandomGenerator

        paths = list(self.paths)
        rs = None
        if train:
            rs = _np.random.RandomState(RandomGenerator.get_seed()
                                        + self._epoch)
            rs.shuffle(paths)
            self._epoch += 1
        return _ParsedExampleWork(paths, self.batch_size, self.dense_keys,
                                  self._dense_shapes, self.label_key,
                                  self.label_dtype, self.sparse_features,
                                  self.feature_padding, self.skip_corrupt,
                                  rs)

    def _sparse_batch(self, records: Sequence[bytes]):
        return _sparse_minibatch(records, self.dense_keys,
                                 self._dense_shapes, self.label_key,
                                 self.label_dtype, self.sparse_features,
                                 self.feature_padding)
