"""Row -> Table transformers for tabular (dataframe-style) records.

Reference: dataset/datamining/RowTransformer.scala — a Transformer[Row,
Table] holding RowTransformSchemas; each schema selects row columns (by
field name or index) and assembles them into one tensor, and the output
Table is keyed by schemaKey.  Here a "Row" is any mapping (dict, pandas
Series) or plain sequence, and the emitted Table is the framework's Table
keyed by schema key — ready to feed Sample/MiniBatch.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.core.table import Table
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


def _row_keys(row: Any) -> List[Any]:
    if hasattr(row, "keys"):
        return list(row.keys())
    return list(range(len(row)))


class RowTransformSchema:
    """One output tensor: which columns it reads and how they combine.
    reference: RowTransformSchema (datamining/RowTransformer.scala)."""

    def __init__(self, key: str, field_names: Sequence[Any] = (),
                 indices: Sequence[int] = (),
                 transform: Optional[Callable[[List[Any]], np.ndarray]] = None):
        if field_names and indices:
            raise ValueError("give field_names or indices, not both")
        self.key = key
        self.field_names = list(field_names)
        self.indices = list(indices)
        self.transform = transform or (lambda values: np.asarray(values, np.float32))

    def select(self, row: Any) -> List[Any]:
        if self.field_names:
            return [row[f] for f in self.field_names]
        if self.indices:
            keys = _row_keys(row)
            return [row[keys[i]] for i in self.indices]
        return [row[k] for k in _row_keys(row)]


class RowTransformer(Transformer):
    """reference: datamining/RowTransformer.scala (Transformer[Row, Table])."""

    def __init__(self, schemas: Sequence[RowTransformSchema],
                 row_size: Optional[int] = None):
        seen = set()
        for s in schemas:
            if s.key in seen:
                raise ValueError(f"replicated schemaKey: {s.key}")
            seen.add(s.key)
            if s.indices and row_size is not None:
                if not all(0 <= i < row_size for i in s.indices):
                    raise ValueError(f"indices out of bound: {s.indices}")
        self.schemas = list(schemas)

    def __call__(self, it: Iterator[Any]) -> Iterator[Table]:
        for row in it:
            out = Table()
            for schema in self.schemas:
                out[schema.key] = schema.transform(schema.select(row))
            yield out

    # -- factory helpers (reference: RowTransformer.atomic / numeric) -----

    @staticmethod
    def atomic(field_names: Sequence[Any]) -> "RowTransformer":
        """One scalar tensor per column, keyed by the column name."""
        return RowTransformer(
            [RowTransformSchema(str(f), field_names=[f]) for f in field_names])

    @staticmethod
    def numeric(key: str, field_names: Sequence[Any]) -> "RowTransformer":
        """All named columns assembled into one numeric vector."""
        return RowTransformer([RowTransformSchema(key, field_names=field_names)])


class TableToSample(Transformer):
    """Table (from RowTransformer) -> Sample, picking feature/label keys."""

    def __init__(self, feature_keys: Sequence[str], label_key: Optional[str] = None):
        self.feature_keys = list(feature_keys)
        self.label_key = label_key

    def __call__(self, it: Iterator[Table]) -> Iterator[Sample]:
        for t in it:
            feats = [np.asarray(t[k]) for k in self.feature_keys]
            feature = feats[0] if len(feats) == 1 else tuple(feats)
            label = np.asarray(t[self.label_key]) if self.label_key is not None else None
            yield Sample(feature, label)
