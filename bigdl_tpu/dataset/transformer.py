"""Transformer — composable preprocessing combinators.

Reference: dataset/Transformer.scala:44-50,86 — a serializable
`Iterator[A] -> Iterator[B]` chained with `->`, used identically on the
local and RDD paths.  Here a Transformer is `__call__(iterator) ->
iterator` chained with `>>` (python has no `->` operator); it runs on the
HOST (numpy), feeding the device via MiniBatch.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.minibatch import MiniBatch, SparseMiniBatch, has_sparse_feature
from bigdl_tpu.dataset.sample import Sample


class Transformer:
    """reference: dataset/Transformer.scala:44."""

    def __call__(self, it: Iterator[Any]) -> Iterator[Any]:
        raise NotImplementedError

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        """`a >> b` pipes a's output into b (the reference's `->`)."""
        return ChainedTransformer([self, other])

    def apply_to(self, data: Iterable[Any]) -> Iterator[Any]:
        return self(iter(data))


class ChainedTransformer(Transformer):
    def __init__(self, stages: List[Transformer]):
        self.stages = list(stages)

    def __call__(self, it: Iterator[Any]) -> Iterator[Any]:
        for s in self.stages:
            it = s(it)
        return it

    def __rshift__(self, other: Transformer) -> "ChainedTransformer":
        return ChainedTransformer(self.stages + [other])


class FnTransformer(Transformer):
    """Wrap a per-element function."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def __call__(self, it: Iterator[Any]) -> Iterator[Any]:
        return (self.fn(x) for x in it)


class SampleToMiniBatch(Transformer):
    """Group Samples into MiniBatches.
    reference: dataset/MiniBatch.scala SampleToMiniBatch (:579+).

    `drop_remainder=True` keeps batch shapes static for XLA (the trailing
    partial batch would force a recompile; the reference pads instead).
    `pad_to_full=True` is the reference's pad alternative: the trailing
    partial batch is kept and padded to `batch_size` by repeating its
    last sample (`MiniBatch.pad_to`), so every record trains each epoch
    under ONE compiled step shape — at the cost of the repeated rows
    entering the tail batch's loss mean (the padded batch carries
    `pad_rows` for consumers that want to mask)."""

    def __init__(self, batch_size: int, feature_padding: Optional[float] = None,
                 label_padding: Optional[float] = None, drop_remainder: bool = True,
                 pad_to_full: bool = False):
        self.batch_size = batch_size
        self.feature_padding = feature_padding
        self.label_padding = label_padding
        self.drop_remainder = drop_remainder
        self.pad_to_full = pad_to_full

    def __call__(self, it: Iterator[Sample]) -> Iterator[MiniBatch]:
        buf: List[Sample] = []
        for s in it:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._batch(buf)
                buf = []
        if buf and (self.pad_to_full or not self.drop_remainder):
            tail = self._batch(buf)
            yield tail.pad_to(self.batch_size) if self.pad_to_full else tail

    def _batch(self, buf: List[Sample]) -> MiniBatch:
        # samples carrying SparseFeatures batch via SparseMiniBatch, like the
        # reference routes TensorSamples with sparse tensors (MiniBatch.scala:579)
        cls = SparseMiniBatch if has_sparse_feature(buf[0]) else MiniBatch
        return cls.from_samples(buf, self.feature_padding, self.label_padding)
