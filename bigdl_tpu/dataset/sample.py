"""Sample — one training record.

Reference: dataset/Sample.scala:32,138,250 (ArraySample: feature tensors +
label tensors packed contiguously).  Here a Sample is a light pair of
numpy arrays (or tuples of arrays for multi-input models); contiguous
packing is pointless on the host side — batching is where device layout
begins.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

ArrayLike = Union[np.ndarray, Sequence[np.ndarray]]


class Sample:
    """One record: feature(s) + label(s). reference: dataset/Sample.scala:32."""

    __slots__ = ("feature", "label")

    def __init__(self, feature: ArrayLike, label: Optional[ArrayLike] = None):
        self.feature = feature
        self.label = label

    @staticmethod
    def from_ndarray(feature: np.ndarray, label: Optional[Any] = None) -> "Sample":
        if label is not None and np.isscalar(label):
            label = np.asarray(label)
        return Sample(np.asarray(feature), label)

    def feature_size(self) -> Tuple[int, ...]:
        return tuple(np.asarray(self.feature).shape)

    def label_size(self) -> Tuple[int, ...]:
        return tuple(np.asarray(self.label).shape) if self.label is not None else ()

    def __repr__(self):
        return f"Sample(feature={self.feature_size()}, label={self.label_size()})"


class SparseFeature:
    """COO-encoded sparse feature of one record.

    Reference: tensor/SparseTensor.scala (the per-record sparse tensors that
    TensorSample carries into SparseMiniBatch, dataset/Sample.scala:250).
    `indices` is (nnz, ndim) int coordinates into `dense_shape`; `values`
    is (nnz,).  TPU-native note: these exist only on the host side — the
    batching step (SparseMiniBatch) densifies, because scatter/gather sparse
    matmul loses to the MXU's dense matmul at the feature widths BigDL's
    wide-and-deep workloads use (see nn/SparseLinear docstring).
    """

    __slots__ = ("indices", "values", "dense_shape")

    def __init__(self, indices, values, dense_shape: Sequence[int]):
        self.indices = np.atleast_2d(np.asarray(indices, np.int64))
        self.values = np.asarray(values)
        self.dense_shape = tuple(int(s) for s in dense_shape)
        if self.indices.size and self.indices.shape[1] != len(self.dense_shape):
            raise ValueError(
                f"indices ndim {self.indices.shape[1]} != dense rank {len(self.dense_shape)}")

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.dense_shape

    def to_dense(self, pad=0) -> np.ndarray:
        """Densify; `pad` fills the non-stored positions (e.g. -1 for id
        bags feeding LookupTableSparse, whose padding id is -1)."""
        out = np.full(self.dense_shape, pad, self.values.dtype)
        if self.values.size:
            out[tuple(self.indices.T)] = self.values
        return out

    def __repr__(self):
        return (f"SparseFeature(nnz={self.values.size}, "
                f"dense_shape={self.dense_shape})")
