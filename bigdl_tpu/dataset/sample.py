"""Sample — one training record.

Reference: dataset/Sample.scala:32,138,250 (ArraySample: feature tensors +
label tensors packed contiguously).  Here a Sample is a light pair of
numpy arrays (or tuples of arrays for multi-input models); contiguous
packing is pointless on the host side — batching is where device layout
begins.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

ArrayLike = Union[np.ndarray, Sequence[np.ndarray]]


class Sample:
    """One record: feature(s) + label(s). reference: dataset/Sample.scala:32."""

    __slots__ = ("feature", "label")

    def __init__(self, feature: ArrayLike, label: Optional[ArrayLike] = None):
        self.feature = feature
        self.label = label

    @staticmethod
    def from_ndarray(feature: np.ndarray, label: Optional[Any] = None) -> "Sample":
        if label is not None and np.isscalar(label):
            label = np.asarray(label)
        return Sample(np.asarray(feature), label)

    def feature_size(self) -> Tuple[int, ...]:
        return tuple(np.asarray(self.feature).shape)

    def label_size(self) -> Tuple[int, ...]:
        return tuple(np.asarray(self.label).shape) if self.label is not None else ()

    def __repr__(self):
        return f"Sample(feature={self.feature_size()}, label={self.label_size()})"
