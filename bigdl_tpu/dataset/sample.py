"""Sample — one training record.

Reference: dataset/Sample.scala:32,138,250 (ArraySample: feature tensors +
label tensors packed contiguously).  Here a Sample is a light pair of
numpy arrays (or tuples of arrays for multi-input models); contiguous
packing is pointless on the host side — batching is where device layout
begins.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

ArrayLike = Union[np.ndarray, Sequence[np.ndarray]]


class Sample:
    """One record: feature(s) + label(s). reference: dataset/Sample.scala:32."""

    __slots__ = ("feature", "label")

    def __init__(self, feature: ArrayLike, label: Optional[ArrayLike] = None):
        self.feature = feature
        self.label = label

    @staticmethod
    def from_ndarray(feature: np.ndarray, label: Optional[Any] = None) -> "Sample":
        if label is not None and np.isscalar(label):
            label = np.asarray(label)
        return Sample(np.asarray(feature), label)

    def feature_size(self) -> Tuple[int, ...]:
        return tuple(np.asarray(self.feature).shape)

    def label_size(self) -> Tuple[int, ...]:
        return tuple(np.asarray(self.label).shape) if self.label is not None else ()

    def __repr__(self):
        return f"Sample(feature={self.feature_size()}, label={self.label_size()})"


class SparseFeature:
    """COO-encoded sparse feature of one record.

    Reference: tensor/SparseTensor.scala (the per-record sparse tensors that
    TensorSample carries into SparseMiniBatch, dataset/Sample.scala:250).
    `indices` is (nnz, ndim) int coordinates into `dense_shape`; `values`
    is (nnz,).  TPU-native note: these exist only on the host side — the
    batching step (SparseMiniBatch) densifies, because scatter/gather sparse
    matmul loses to the MXU's dense matmul at the feature widths BigDL's
    wide-and-deep workloads use (see nn/SparseLinear docstring).
    """

    __slots__ = ("indices", "values", "dense_shape")

    def __init__(self, indices, values, dense_shape: Sequence[int]):
        self.indices = np.atleast_2d(np.asarray(indices, np.int64))
        self.values = np.asarray(values)
        self.dense_shape = tuple(int(s) for s in dense_shape)
        if self.indices.size and self.indices.shape[1] != len(self.dense_shape):
            raise ValueError(
                f"indices ndim {self.indices.shape[1]} != dense rank {len(self.dense_shape)}")

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.dense_shape

    def to_dense(self, pad=0) -> np.ndarray:
        """Densify; `pad` fills the non-stored positions (e.g. -1 for id
        bags feeding LookupTableSparse, whose padding id is -1)."""
        out = np.full(self.dense_shape, pad, self.values.dtype)
        if self.values.size:
            out[tuple(self.indices.T)] = self.values
        return out

    def to_bag(self, nnz_cap: int) -> "SparseBag":
        """Re-encode a 1-D sparse feature as a padded (ids, values) bag —
        the DEVICE-sparse input encoding (see SparseBag)."""
        if len(self.dense_shape) != 1:
            raise ValueError(
                f"to_bag needs a 1-D sparse feature, got dense rank "
                f"{len(self.dense_shape)}")
        return SparseBag(self.indices[:, 0] if self.indices.size else [],
                         self.values, nnz_cap)

    def __repr__(self):
        return (f"SparseFeature(nnz={self.values.size}, "
                f"dense_shape={self.dense_shape})")


class SparseBag:
    """Padded (ids, values) bag of one record — the device-sparse encoding.

    Reference capability: tensor/SparseTensor.scala + SparseTensorMath
    .scala execute sparse gemm natively so wide features never densify.
    The TPU-native equivalent keeps (ids, values) as DENSE arrays padded
    to a static `nnz_cap` (id -1 = empty slot): on device, SparseLinear /
    LookupTableSparse gather the referenced weight rows and do a masked
    weighted reduce — work and HBM traffic scale with nnz, not vocab
    width, while shapes stay static for jit (the batched-gather layout of
    segment_sum with fixed-size segments)."""

    __slots__ = ("ids", "values")

    def __init__(self, ids, values, nnz_cap: int):
        # preserve the dtype of typed inputs even when empty (batches
        # must not flip dtype when a record happens to have zero ids);
        # only untyped empty python sequences default to float32
        vdtype = getattr(values, "dtype", None)
        values = np.asarray(values).ravel()
        if vdtype is None:
            vdtype = values.dtype if values.size else np.float32
        ids = np.asarray(ids, np.int64).ravel()
        if ids.size != values.size:
            raise ValueError(f"{ids.size} ids vs {values.size} values")
        if ids.size > nnz_cap:
            raise ValueError(
                f"record has {ids.size} entries, bag capacity {nnz_cap}")
        self.ids = np.full((int(nnz_cap),), -1, np.int32)
        self.ids[:ids.size] = ids
        self.values = np.zeros((int(nnz_cap),), vdtype)
        self.values[:values.size] = values

    @property
    def nnz_cap(self) -> int:
        return self.ids.shape[0]

    def __repr__(self):
        return (f"SparseBag(nnz={int((self.ids >= 0).sum())}, "
                f"cap={self.nnz_cap})")
