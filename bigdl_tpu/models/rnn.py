"""PTB-style language models (BASELINE config 5).

Reference: models/rnn/SimpleRNN.scala:29-31 (LookupTable -> Recurrent(RnnCell)
-> TimeDistributed(Linear) -> LogSoftMax over TimeDistributed) and
example/languagemodel/PTBModel.scala (embedding -> stacked LSTM ->
TimeDistributed(Linear)).  The reference's JVM timestep loop is a lax.scan.
"""

from __future__ import annotations

import jax.numpy as jnp

import bigdl_tpu.nn as nn


def SimpleRNN(input_size: int = 4001, hidden_size: int = 40,
              output_size: int = 4001) -> nn.Sequential:
    """reference: models/rnn/SimpleRNN.scala."""
    return nn.Sequential(
        nn.LookupTable(input_size, hidden_size),
        nn.RnnLayer(hidden_size, hidden_size, activation=jnp.tanh),
        nn.TimeDistributed(nn.Linear(hidden_size, output_size)),
        nn.TimeDistributed(nn.LogSoftMax()),
    )


def PTBModel(vocab_size: int = 10001, embedding_dim: int = 650,
             hidden_size: int = 650, num_layers: int = 2,
             keep_prob: float = 0.5) -> nn.Sequential:
    """reference: example/languagemodel/PTBModel.scala (stacked-LSTM LM)."""
    layers = [nn.LookupTable(vocab_size, embedding_dim)]
    if keep_prob < 1.0:
        layers.append(nn.Dropout(1.0 - keep_prob))
    in_size = embedding_dim
    for _ in range(num_layers):
        layers.append(nn.LSTM(in_size, hidden_size))
        if keep_prob < 1.0:
            layers.append(nn.Dropout(1.0 - keep_prob))
        in_size = hidden_size
    layers += [
        nn.TimeDistributed(nn.Linear(hidden_size, vocab_size)),
        nn.TimeDistributed(nn.LogSoftMax()),
    ]
    return nn.Sequential(*layers)
