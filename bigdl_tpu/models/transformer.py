"""Transformer language model — the long-context flagship.

No reference counterpart (the reference's only LM is the PTB LSTM,
models/rnn/Train.scala); this is the designed-fresh TPU capability the
rebuild adds: decoder-only LM with RoPE, causal attention, optional ring /
Ulysses sequence parallelism, and scan-over-layers so N blocks compile as
ONE scanned XLA loop body (fast compiles, weight-stationary layout) with
optional rematerialization (`jax.checkpoint`) to trade FLOPs for HBM.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.attention import TransformerBlock
from bigdl_tpu.nn.embedding import LookupTable
from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.norm import LayerNormalization


def _axis_bound(name: str) -> bool:
    """True when `name` is a bound mesh axis in the current trace (i.e. we
    are inside shard_map over it)."""
    try:
        lax.axis_index(name)
        return True
    except NameError:
        return False


class TransformerLM(Module):
    """Decoder-only LM over int32 token ids (B, S) -> log-probs (B, S, V)."""

    def __init__(self, vocab_size: int, hidden_size: int = 512, n_layer: int = 6,
                 n_head: int = 8, *, max_len: int = 2048, dropout: float = 0.0,
                 rope: bool = True, tie_embeddings: bool = True,
                 seq_parallel: Optional[str] = None, scan_layers: bool = True,
                 remat: bool = False, use_flash: bool = True,
                 moe_experts: int = 0, moe_k: int = 1,
                 pipeline_axis: Optional[str] = None,
                 pipeline_microbatches: int = 4,
                 pipeline_interleave: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.n_layer = n_layer
        self.n_head = n_head
        self.max_len = max_len
        self.rope = rope
        self.tie_embeddings = tie_embeddings
        self.scan_layers = scan_layers
        self.remat = remat
        self.dropout = dropout
        # pipeline parallelism (parallel/pipeline.py): when `pipeline_axis`
        # is set AND bound (the trainer runs apply inside shard_map), the
        # block stack executes as a GPipe/interleaved microbatch pipeline;
        # embed/ln_f/head run outside the pipelined region, replicated over
        # the pipeline axis (the scaling-book partitioning).  Outside
        # shard_map (predict/eval on one device) apply falls back to the
        # sequential scan, so params stay in model order everywhere.
        self.pipeline_axis = pipeline_axis
        self.pipeline_microbatches = pipeline_microbatches
        self.pipeline_interleave = pipeline_interleave
        if pipeline_axis is not None and not scan_layers:
            raise ValueError("pipeline_axis requires scan_layers=True "
                             "(stacked block params)")
        self.embed = LookupTable(vocab_size, hidden_size,
                                 weight_init=init_mod.RandomNormal(0.0, 0.02))
        self.block = TransformerBlock(hidden_size, n_head, causal=True,
                                      dropout=dropout, rope=rope,
                                      seq_parallel=seq_parallel,
                                      use_flash=use_flash,
                                      moe_experts=moe_experts, moe_k=moe_k)
        self.ln_f = LayerNormalization(hidden_size)

    def build(self, rng, input_shape):
        b, s = input_shape
        d = self.hidden_size
        k_emb, k_pos, k_blocks, k_head = jax.random.split(rng, 4)
        params = {"embed": self.embed.build(k_emb, input_shape)[0]}
        if not self.rope:
            params["pos"] = init_mod.RandomNormal(0.0, 0.02)(
                k_pos, (self.max_len, d), self.max_len, d)
        block_shape = (b, s, d)
        blocks = [self.block.build(jax.random.fold_in(k_blocks, i), block_shape)[0]
                  for i in range(self.n_layer)]
        if self.scan_layers:
            params["blocks"] = jax.tree_util.tree_map(
                lambda *leaves: jnp.stack(leaves), *blocks)
        else:
            params["blocks"] = {str(i): p for i, p in enumerate(blocks)}
        params["ln_f"] = self.ln_f.build(jax.random.fold_in(rng, 3), block_shape)[0]
        if not self.tie_embeddings:
            params["head"] = init_mod.Xavier()(k_head, (d, self.vocab_size),
                                               d, self.vocab_size)
        return params, {}, (b, s, self.vocab_size)

    def apply(self, params, state, x, *, training=False, rng=None):
        b, s = x.shape
        h, _ = self.embed.apply(params["embed"], {}, x)
        if not self.rope:
            h = h + params["pos"][:s][None]

        blk = self.block

        def body(carry, layer_params):
            h, i = carry
            r = None if rng is None else jax.random.fold_in(rng, i)
            out, _ = blk.apply(layer_params, {}, h, training=training, rng=r)
            return (out, i + 1), None

        if self.pipeline_axis is not None and _axis_bound(self.pipeline_axis):
            from bigdl_tpu.parallel.pipeline import pipeline_apply

            def layer_fn(lp, hh, uid):
                # dropout rng: fold by the schedule's (microbatch, layer)
                # uid so every pipelined block application draws a
                # distinct mask
                r = None if rng is None else jax.random.fold_in(rng, uid)
                out, _ = blk.apply(lp, {}, hh, training=training, rng=r)
                return out

            h = pipeline_apply(layer_fn, params["blocks"], h,
                               n_microbatch=self.pipeline_microbatches,
                               axis_name=self.pipeline_axis,
                               remat=self.remat,
                               interleave=self.pipeline_interleave,
                               with_uid=True)
        elif self.scan_layers:
            fn = jax.checkpoint(body) if self.remat else body
            (h, _), _ = lax.scan(fn, (h, 0), params["blocks"])
        else:
            for i in range(self.n_layer):
                (h, _), _ = body((h, i), params["blocks"][str(i)])

        h, _ = self.ln_f.apply(params["ln_f"], {}, h)
        head = params["embed"]["weight"].T if self.tie_embeddings else params["head"]
        logits = h @ head
        return jax.nn.log_softmax(logits, axis=-1), state

    # -- autoregressive generation (bigdl_tpu.generation) ------------------

    def init_cache(self, slots: int, capacity: int, dtype=jnp.float32):
        """Zeroed ring-buffer KV cache for `slots` concurrent requests of
        up to `capacity` resident tokens (generation/kvcache.py)."""
        from bigdl_tpu.generation.kvcache import alloc

        if not self.rope and capacity > self.max_len:
            raise ValueError(
                f"cache capacity {capacity} exceeds max_len {self.max_len} "
                "(learned positions cannot extrapolate; use rope=True for "
                "ring wrap-around past max_len)")
        return alloc(self.n_layer, slots, capacity, self.n_head,
                     self.hidden_size // self.n_head, dtype)

    def apply_cached(self, params, tokens, cache, *, wrapped_append=False):
        """Cache-aware forward: `tokens` (B, S) are NEW tokens appended at
        absolute positions `cache.lengths[b]..+S-1`; returns (log-probs
        (B, S, V), updated cache with lengths += S).

        `wrapped_append=True` selects the wrap-safe multi-token mask
        (nn/attention.py) so a chunked prefill or spec-decode verify
        append that crosses the ring boundary stays causally correct;
        boolean-identical to the default mask while writes fit the ring.

        `cache` is either a ring `KVCache` or a paged `PagedKVCache`
        (generation/pagedkv.py) — the layout difference is static pytree
        structure, so each compiles to its own (still shape-stable)
        executable.  Either may carry int8 K/V with fp32 scale planes;
        the per-layer kv dict handed to the block advertises both via
        its keys (nn/attention.py apply_cached).

        Prefill is one call with the prompt (S <= capacity, fresh cache);
        decode is S=1 against the cached prefix — a length-1 query, RoPE
        offset by position, masked by the offset causal mask
        (nn/attention.py causal_mask), bitwise the same math as re-running
        the full context (tests/test_generation.py locks the parity).
        Dropout/training paths are deliberately absent: this is the
        inference hot loop.
        """
        from bigdl_tpu.generation.pagedkv import PagedKVCache

        b, s = tokens.shape
        h, _ = self.embed.apply(params["embed"], {}, tokens)
        lengths = cache.lengths
        if not self.rope:
            pos = jnp.minimum(lengths[:, None] + jnp.arange(s)[None, :],
                              self.max_len - 1)
            h = h + jnp.take(params["pos"], pos, axis=0)

        blk = self.block
        paged = isinstance(cache, PagedKVCache)
        quant = cache.k_scale is not None

        def layer_kv(kl, vl, ksl, vsl):
            kv = {"k": kl, "v": vl}
            if quant:
                kv["k_scale"], kv["v_scale"] = ksl, vsl
            if paged:
                # the table is shared by every layer (one claim covers
                # all layers' pool planes), so it rides via closure, not
                # as a scanned input
                kv["table"] = cache.block_tables
            return kv

        if self.scan_layers:
            def body(hh, xs):
                out, kv = blk.apply_cached(
                    xs["lp"], hh,
                    layer_kv(xs["k"], xs["v"], xs.get("ks"), xs.get("vs")),
                    lengths=lengths, wrapped_append=wrapped_append)
                ys = {"k": kv["k"], "v": kv["v"]}
                if quant:
                    ys["ks"], ys["vs"] = kv["k_scale"], kv["v_scale"]
                return out, ys

            xs = {"lp": params["blocks"], "k": cache.k, "v": cache.v}
            if quant:
                xs["ks"], xs["vs"] = cache.k_scale, cache.v_scale
            h, ys = lax.scan(body, h, xs)
            nk, nv = ys["k"], ys["v"]
            nks, nvs = ys.get("ks"), ys.get("vs")
        else:
            ks, vs, kss, vss = [], [], [], []
            for i in range(self.n_layer):
                h, kv = blk.apply_cached(
                    params["blocks"][str(i)], h,
                    layer_kv(cache.k[i], cache.v[i],
                             cache.k_scale[i] if quant else None,
                             cache.v_scale[i] if quant else None),
                    lengths=lengths, wrapped_append=wrapped_append)
                ks.append(kv["k"])
                vs.append(kv["v"])
                if quant:
                    kss.append(kv["k_scale"])
                    vss.append(kv["v_scale"])
            nk, nv = jnp.stack(ks), jnp.stack(vs)
            nks = jnp.stack(kss) if quant else None
            nvs = jnp.stack(vss) if quant else None

        h, _ = self.ln_f.apply(params["ln_f"], {}, h)
        head = params["embed"]["weight"].T if self.tie_embeddings \
            else params["head"]
        logits = h @ head
        new_cache = cache._replace(k=nk, v=nv, lengths=lengths + s,
                                   k_scale=nks, v_scale=nvs)
        return jax.nn.log_softmax(logits, axis=-1), new_cache

    def output_shape(self, input_shape):
        return tuple(input_shape) + (self.vocab_size,)

    def prepare_pipeline_params(self, params, n_stage: int):
        """Trainer hook, called at the GLOBAL (jit) level before shard_map:
        permutes the block stack into the interleaved schedule's layout
        (parallel/pipeline.py interleave_stack).  Stored params stay in
        model order, so checkpoints are layout-independent."""
        if not self.pipeline_interleave:
            return params
        from bigdl_tpu.parallel.pipeline import interleave_stack

        return dict(params, blocks=interleave_stack(params["blocks"], n_stage))


def transformer_lm_small(vocab_size: int = 32000, **kw) -> TransformerLM:
    return TransformerLM(vocab_size, hidden_size=512, n_layer=8, n_head=8, **kw)


def transformer_lm_base(vocab_size: int = 32000, **kw) -> TransformerLM:
    return TransformerLM(vocab_size, hidden_size=768, n_layer=12, n_head=12, **kw)
