"""Model zoo — the reference's models/ directory rebuilt NHWC/TPU-first.

Reference: models/{lenet,vgg,resnet,inception,rnn,autoencoder} (survey §2.8).
Each module exposes a builder returning an nn.Module plus a `Train` entry
point mirroring the reference's scopt-driven Train objects.
"""

from bigdl_tpu.models.lenet import LeNet5
from bigdl_tpu.models.vgg import VggForCifar10, Vgg16, Vgg19
from bigdl_tpu.models.resnet import ResNet, resnet50, resnet_cifar
from bigdl_tpu.models.inception import InceptionV1, InceptionV2
from bigdl_tpu.models.rnn import PTBModel, SimpleRNN
from bigdl_tpu.models.autoencoder import Autoencoder
from bigdl_tpu.models.transformer import (
    TransformerLM,
    transformer_lm_small,
    transformer_lm_base,
)
from bigdl_tpu.models.pipelined_conv import PipelinedConvNet

__all__ = ["LeNet5", "VggForCifar10", "Vgg16", "Vgg19", "ResNet", "resnet50",
           "resnet_cifar", "InceptionV1", "PTBModel", "SimpleRNN", "Autoencoder",
           "TransformerLM", "transformer_lm_small", "transformer_lm_base",
           "PipelinedConvNet"]
