"""LeNet-5 for MNIST (BASELINE config 1).

Reference: models/lenet/LeNet5.scala (conv 6@5x5 -> pool -> conv 12@5x5 ->
pool -> fc 100 -> fc 10, tanh activations) and models/lenet/Train.scala.
Input is NHWC (N, 28, 28, 1); the reference reshapes 1x28x28 NCHW.
"""

from __future__ import annotations

import bigdl_tpu.nn as nn


def LeNet5(class_num: int = 10) -> nn.Sequential:
    """reference: models/lenet/LeNet5.scala."""
    return nn.Sequential(
        nn.SpatialConvolution(1, 6, 5, 5, name="conv1_5x5"),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2),
        nn.Tanh(),
        nn.SpatialConvolution(6, 12, 5, 5, name="conv2_5x5"),
        nn.SpatialMaxPooling(2, 2),
        nn.Flatten(),
        nn.Linear(12 * 4 * 4, 100, name="fc1"),
        nn.Tanh(),
        nn.Linear(100, class_num, name="fc2"),
        nn.LogSoftMax(),
    )
