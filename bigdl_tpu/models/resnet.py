"""ResNet (BASELINE config 3: ResNet-50/ImageNet, ResNet-20/CIFAR).

Reference: models/resnet/ResNet.scala (basicBlock/bottleneck builders,
shortcut types A/B/C, shareGradInput trick, iChannels bookkeeping) and
models/resnet/TrainImageNet.scala (v1.5 stride placement: stride lives on
the 3x3 conv of the bottleneck, not the 1x1 — matching the mkldnn graph
the reference actually benchmarks).

TPU redesign notes:
  * NHWC + HWIO; all convs hit the MXU directly.
  * `shareGradInput` (reference memory-aliasing trick) has no analogue —
    XLA's buffer assignment already reuses gradient buffers.
  * zero-init of the last BN gamma in each residual block ("zero gamma"
    warmup trick from the reference's ImageNet recipe) is kept, as it is a
    numerics choice, not a memory one.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn import init as init_mod


class _ZeroGamma(init_mod.InitializationMethod):
    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)


def _bn(c: int, zero_init: bool = False) -> nn.SpatialBatchNormalization:
    bn = nn.SpatialBatchNormalization(c)
    if zero_init:
        orig_build = bn.build

        def build(rng, input_shape):
            params, state, out = orig_build(rng, input_shape)
            params["weight"] = jnp.zeros_like(params["weight"])
            return params, state, out

        bn.build = build
    return bn


def _conv(cin, cout, k, stride=1, pad=0):
    return nn.SpatialConvolution(cin, cout, k, k, stride, stride, pad, pad,
                                 with_bias=False,
                                 weight_init=init_mod.MsraFiller(False))


def basic_block(cin: int, cout: int, stride: int = 1) -> nn.Module:
    """reference: models/resnet/ResNet.scala basicBlock."""
    inp = nn.Input()
    h = _conv(cin, cout, 3, stride, 1)(inp)
    h = _bn(cout)(h)
    h = nn.ReLU()(h)
    h = _conv(cout, cout, 3, 1, 1)(h)
    h = _bn(cout, zero_init=True)(h)
    if stride != 1 or cin != cout:
        sc = _conv(cin, cout, 1, stride, 0)(inp)
        sc = _bn(cout)(sc)
    else:
        sc = inp
    out = nn.CAddTable()(h, sc)
    out = nn.ReLU()(out)
    return nn.Graph(inp, out)


def bottleneck(cin: int, planes: int, stride: int = 1,
               expansion: int = 4, fuse_bn: bool = False,
               feat_w: int = None) -> nn.Module:
    """reference: models/resnet/ResNet.scala bottleneck; stride on the 3x3
    (v1.5) like TrainImageNet's mkldnn graph.

    fuse_bn=True replaces 1x1 conv+BN pairs (the reduce, the 4C expand,
    and the stride-1 downsample shortcut) with `nn.SpatialConvolutionBN` —
    the pallas conv-epilogue-stats kernel that removes the BN stats-reduce
    HBM pass (BENCH_APPENDIX.md's named lever; reference fusion role:
    nn/mkldnn/Fusion.scala:26-31).

    `feat_w` is the static input feature-map width.  When given, a pair is
    fused ONLY where the kernel's (N*H*W, C) <-> NHWC reshapes are layout
    bitcasts — conv output width a multiple of 8 (the TPU sublane tile)
    and stride 1.  Elsewhere (w=28/14/7 stages) the reshape is a genuine
    retiling copy: two extra HBM passes per conv that cost more than the
    stats read the fusion saves, and enough duplicate buffers to OOM a
    b256 step (measured, BENCH_APPENDIX.md).  feat_w=None fuses every
    pair (CPU/interpret tests, where there is no tiled layout)."""
    cout = planes * expansion
    inp = nn.Input()

    def _ok(w_out, conv_stride=1):
        if not fuse_bn:
            return False
        if feat_w is None:
            return True
        return conv_stride == 1 and w_out is not None and w_out % 8 == 0

    w_in = feat_w
    w_mid = (feat_w - 1) // stride + 1 if feat_w is not None else None
    if _ok(w_in):
        h = nn.SpatialConvolutionBN(cin, planes)(inp)
    else:
        h = _conv(cin, planes, 1)(inp)
        h = _bn(planes)(h)
    h = nn.ReLU()(h)
    h = _conv(planes, planes, 3, stride, 1)(h)
    h = _bn(planes)(h)
    h = nn.ReLU()(h)
    if _ok(w_mid):
        h = nn.SpatialConvolutionBN(planes, cout, zero_gamma=True)(h)
    else:
        h = _conv(planes, cout, 1)(h)
        h = _bn(cout, zero_init=True)(h)
    if stride != 1 or cin != cout:
        if _ok(w_mid, stride):
            sc = nn.SpatialConvolutionBN(cin, cout, stride=stride)(inp)
        else:
            sc = _conv(cin, cout, 1, stride, 0)(inp)
            sc = _bn(cout)(sc)
    else:
        sc = inp
    out = nn.CAddTable()(h, sc)
    out = nn.ReLU()(out)
    return nn.Graph(inp, out)


def ResNet(depth: int = 50, class_num: int = 1000,
           dataset: str = "imagenet", remat: bool = False,
           fuse_bn: bool = False) -> nn.Sequential:
    """reference: models/resnet/ResNet.scala apply().

    remat=True wraps every residual block in nn.Remat (activations
    recomputed in backward) — the HBM-bandwidth lever on training steps
    with spare MXU headroom (BENCH_APPENDIX.md)."""
    if dataset == "imagenet":
        cfgs = {
            18: ([2, 2, 2, 2], basic_block, 1),
            34: ([3, 4, 6, 3], basic_block, 1),
            50: ([3, 4, 6, 3], bottleneck, 4),
            101: ([3, 4, 23, 3], bottleneck, 4),
            152: ([3, 8, 36, 3], bottleneck, 4),
        }
        if depth not in cfgs:
            raise ValueError(f"unsupported imagenet resnet depth {depth}")
        blocks, block_fn, expansion = cfgs[depth]
        if fuse_bn and block_fn is not bottleneck:
            raise ValueError(
                "fuse_bn=True is only implemented for bottleneck ResNets "
                "(depth 50/101/152) — basic_block has no 1x1 conv+BN pairs")
        layers: List[nn.Module] = [
            _conv(3, 64, 7, 2, 3),
            _bn(64),
            nn.ReLU(),
            nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1),
        ]
        cin = 64
        # 224 input -> conv7/s2 -> 112 -> maxpool/s2 -> 56.  A width
        # HINT for picking which pairs to fuse at trace time; if the
        # model is built on a different resolution, conv1x1_bn_stats's
        # runtime w%8 gate still falls back to the XLA path per conv, so
        # a wrong hint costs nothing but a missed fusion.
        feat_w = 56
        for stage, n_blocks in enumerate(blocks):
            planes = 64 * (2 ** stage)
            for b in range(n_blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                block = block_fn(cin, planes, stride, fuse_bn=fuse_bn,
                                 feat_w=feat_w) \
                    if block_fn is bottleneck else block_fn(cin, planes,
                                                            stride)
                feat_w = (feat_w - 1) // stride + 1
                layers.append(nn.Remat(block) if remat else block)
                cin = planes * expansion
        layers += [
            nn.GlobalAveragePooling2D(),
            nn.Linear(cin, class_num),
            nn.LogSoftMax(),
        ]
        return nn.Sequential(*layers)
    elif dataset == "cifar10":
        if fuse_bn:
            raise ValueError("fuse_bn=True is only implemented for "
                             "bottleneck ResNets (imagenet depth 50/101/152)")
        return resnet_cifar(depth, class_num)
    raise ValueError(f"unknown dataset {dataset}")


def resnet50(class_num: int = 1000, remat: bool = False,
             fuse_bn: bool = False) -> nn.Sequential:
    return ResNet(50, class_num, remat=remat, fuse_bn=fuse_bn)


def resnet_cifar(depth: int = 20, class_num: int = 10) -> nn.Sequential:
    """reference: models/resnet/ResNet.scala (cifar10 path: 6n+2 layers)."""
    assert (depth - 2) % 6 == 0, "cifar depth must be 6n+2"
    n = (depth - 2) // 6
    layers: List[nn.Module] = [
        _conv(3, 16, 3, 1, 1),
        _bn(16),
        nn.ReLU(),
    ]
    cin = 16
    for stage in range(3):
        planes = 16 * (2 ** stage)
        for b in range(n):
            stride = 2 if (stage > 0 and b == 0) else 1
            layers.append(basic_block(cin, planes, stride))
            cin = planes
    layers += [
        nn.GlobalAveragePooling2D(),
        nn.Linear(cin, class_num),
        nn.LogSoftMax(),
    ]
    return nn.Sequential(*layers)
