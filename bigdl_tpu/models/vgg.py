"""VGG models (BASELINE config 2: VGG on CIFAR-10).

Reference: models/vgg/VggForCifar10.scala (conv-BN-relu blocks + 512-wide
classifier with dropout+BN) and models/vgg/Vgg_16.scala / Vgg_19.scala
(ImageNet).  NHWC layout.
"""

from __future__ import annotations

import bigdl_tpu.nn as nn


def _conv_bn_relu(cin: int, cout: int) -> list:
    return [
        nn.SpatialConvolution(cin, cout, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(cout, eps=1e-3),
        nn.ReLU(),
    ]


def VggForCifar10(class_num: int = 10, has_dropout: bool = True) -> nn.Sequential:
    """reference: models/vgg/VggForCifar10.scala."""
    cfg = [(3, 64), (64, 64), "M", (64, 128), (128, 128), "M",
           (128, 256), (256, 256), (256, 256), "M",
           (256, 512), (512, 512), (512, 512), "M",
           (512, 512), (512, 512), (512, 512), "M"]
    layers = []
    for item in cfg:
        if item == "M":
            layers.append(nn.SpatialMaxPooling(2, 2, 2, 2, ceil_mode=True))
        else:
            layers.extend(_conv_bn_relu(*item))
    classifier = [
        nn.Flatten(),
        nn.Linear(512, 512),
        nn.BatchNormalization(512),
        nn.ReLU(),
    ]
    if has_dropout:
        classifier.append(nn.Dropout(0.5))
    classifier += [nn.Linear(512, class_num), nn.LogSoftMax()]
    return nn.Sequential(*(layers + classifier))


def _vgg_block(layers: list, cin: int, cout: int, n: int, with_bn: bool = False) -> int:
    for i in range(n):
        layers.append(nn.SpatialConvolution(cin if i == 0 else cout, cout, 3, 3, 1, 1, 1, 1))
        if with_bn:
            layers.append(nn.SpatialBatchNormalization(cout))
        layers.append(nn.ReLU())
    layers.append(nn.SpatialMaxPooling(2, 2, 2, 2))
    return cout


def Vgg16(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """reference: models/vgg/Vgg_16.scala (ImageNet, 224x224 NHWC input)."""
    layers = []
    for cin, cout, n in [(3, 64, 2), (64, 128, 2), (128, 256, 3),
                         (256, 512, 3), (512, 512, 3)]:
        _vgg_block(layers, cin, cout, n)
    layers += [nn.Flatten(), nn.Linear(512 * 7 * 7, 4096), nn.ReLU()]
    if has_dropout:
        layers.append(nn.Dropout(0.5))
    layers += [nn.Linear(4096, 4096), nn.ReLU()]
    if has_dropout:
        layers.append(nn.Dropout(0.5))
    layers += [nn.Linear(4096, class_num), nn.LogSoftMax()]
    return nn.Sequential(*layers)


def Vgg19(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """reference: models/vgg/Vgg_19.scala."""
    layers = []
    for cin, cout, n in [(3, 64, 2), (64, 128, 2), (128, 256, 4),
                         (256, 512, 4), (512, 512, 4)]:
        _vgg_block(layers, cin, cout, n)
    layers += [nn.Flatten(), nn.Linear(512 * 7 * 7, 4096), nn.ReLU()]
    if has_dropout:
        layers.append(nn.Dropout(0.5))
    layers += [nn.Linear(4096, 4096), nn.ReLU()]
    if has_dropout:
        layers.append(nn.Dropout(0.5))
    layers += [nn.Linear(4096, class_num), nn.LogSoftMax()]
    return nn.Sequential(*layers)
