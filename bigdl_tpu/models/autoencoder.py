"""MNIST autoencoder.

Reference: models/autoencoder/Autoencoder.scala (784 -> 32 -> 784 with
sigmoid output trained against MSE on the input).
"""

from __future__ import annotations

import bigdl_tpu.nn as nn


def Autoencoder(class_num: int = 32) -> nn.Sequential:
    """reference: models/autoencoder/Autoencoder.scala."""
    return nn.Sequential(
        nn.Flatten(),
        nn.Linear(28 * 28, class_num),
        nn.ReLU(),
        nn.Linear(class_num, 28 * 28),
        nn.Sigmoid(),
    )
