"""Conv+BN residual stack with pipeline-parallel STATEFUL stages.

The stateful-pipeline demonstration model: the repeated middle blocks
(conv3x3 SAME -> BatchNorm -> ReLU, residual) carry BatchNorm running
stats as per-stage state stacked like the block params and sharded
P('pipeline') — parallel/pipeline.py threads it through the microbatch
schedule (each layer sees microbatches in order; fill/drain ticks are
masked), so pipelining is purely an execution-schedule transformation of
the microbatched program.  Shape-changing ends (stem conv+BN, pooled
classifier head) run outside the pipelined region, replicated over the
pipeline axis, exactly like TransformerLM's embed/head.

Under shard_map every BN syncs its batch statistics over the 'data' mesh
axis (sync-BN, the reference's setParallism semantics — survey §2.10):
that is what makes the replicated stem state and the pipeline-sharded
block state single-valued along the data axis, so shard_map's state
out-specs are well-defined.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.engine import AXIS_DATA
from bigdl_tpu.nn.conv import SpatialConvolution
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.norm import SpatialBatchNormalization


def _axis_bound(name: str) -> bool:
    try:
        lax.axis_index(name)
        return True
    except NameError:
        return False


class PipelinedConvNet(Module):
    """NHWC image classifier: stem conv+BN -> n_layer residual conv+BN
    blocks (pipelined over `pipeline_axis` when bound) -> GAP -> linear
    -> log-probs."""

    def __init__(self, n_input: int, n_class: int, width: int = 32,
                 n_layer: int = 8, *,
                 pipeline_axis: Optional[str] = None,
                 pipeline_microbatches: int = 4,
                 pipeline_interleave: bool = False,
                 sync_bn_axis: str = AXIS_DATA,
                 microbatch_sequential: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_input = n_input
        self.n_class = n_class
        self.width = width
        self.n_layer = n_layer
        self.pipeline_axis = pipeline_axis
        self.pipeline_microbatches = pipeline_microbatches
        self.pipeline_interleave = pipeline_interleave
        self.sync_bn_axis = sync_bn_axis
        # microbatch the sequential fallback too, so a pipeline-configured
        # model computes the SAME function whether or not the pipeline
        # axis is bound (BN stats are per-microbatch either way); also the
        # parity oracle for the pipelined run
        self.microbatch_sequential = microbatch_sequential
        self.stem = SpatialConvolution(n_input, width, 3, 3, 1, 1, -1, -1,
                                       with_bias=False)
        self.stem_bn = SpatialBatchNormalization(width)
        self.conv = SpatialConvolution(width, width, 3, 3, 1, 1, -1, -1,
                                       with_bias=False)
        self.bn = SpatialBatchNormalization(width)
        self.head = Linear(width, n_class)

    def build(self, rng, input_shape):
        b, h, w, _ = input_shape
        ks = jax.random.split(rng, 4)
        params = {"stem": self.stem.build(ks[0], input_shape)[0]}
        stem_shape = (b, h, w, self.width)
        pb, sb, _ = self.stem_bn.build(ks[1], stem_shape)
        params["stem_bn"] = pb
        state = {"stem_bn": sb}
        blocks_p, blocks_s = [], []
        for i in range(self.n_layer):
            ki = jax.random.fold_in(ks[2], i)
            cp, _, _ = self.conv.build(ki, stem_shape)
            bp, bs, _ = self.bn.build(jax.random.fold_in(ki, 1), stem_shape)
            blocks_p.append({"conv": cp, "bn": bp})
            blocks_s.append({"bn": bs})
        stack = lambda *xs: jnp.stack(xs)  # noqa: E731
        params["blocks"] = jax.tree_util.tree_map(stack, *blocks_p)
        state["blocks"] = jax.tree_util.tree_map(stack, *blocks_s)
        params["head"] = self.head.build(ks[3], (b, self.width))[0]
        return params, state, (b, self.n_class)

    def _block(self, lp, ls, h, training):
        h2, _ = self.conv.apply(lp["conv"], {}, h)
        h2, ns = self.bn.apply(lp["bn"], ls["bn"], h2, training=training)
        return jax.nn.relu(h2) + h, {"bn": ns}

    def apply(self, params, state, x, *, training=False, rng=None):
        # sync-BN only where the mesh axis is actually bound (inside the
        # trainer's shard_map); at jit level the batch is already global
        sync = self.sync_bn_axis if _axis_bound(self.sync_bn_axis) else None
        self.stem_bn.axis_name = sync
        self.bn.axis_name = sync

        h, _ = self.stem.apply(params["stem"], {}, x)
        h, stem_bn_state = self.stem_bn.apply(
            params["stem_bn"], state["stem_bn"], h, training=training)
        h = jax.nn.relu(h)

        if self.pipeline_axis is not None and _axis_bound(self.pipeline_axis):
            from bigdl_tpu.parallel.pipeline import pipeline_apply

            h, blocks_state = pipeline_apply(
                lambda lp, ls, hh: self._block(lp, ls, hh, training),
                params["blocks"], h,
                n_microbatch=self.pipeline_microbatches,
                axis_name=self.pipeline_axis,
                interleave=self.pipeline_interleave,
                stage_state=state["blocks"])
        elif ((self.microbatch_sequential
               or (self.pipeline_axis is not None
                   and self.pipeline_microbatches > 1))
              and h.shape[0] % self.pipeline_microbatches == 0):
            # batches not divisible by M (e.g. single-sample predict) fall
            # through to the plain scan below — identical at eval (BN
            # reads running stats), and training batches are static/
            # divisible under the trainer
            # microbatched sequential program — what the pipeline schedule
            # is an execution-reordering of; layer l sees microbatches in
            # order and threads its state exactly like the pipelined run
            M = self.pipeline_microbatches
            b = h.shape[0]
            micro = h.reshape((M, b // M) + h.shape[1:])

            def outer(bs, hm):
                def inner(hh, ps):
                    lp, ls = ps
                    h2, ns = self._block(lp, ls, hh, training)
                    return h2, ns

                hm2, new_bs = lax.scan(inner, hm, (params["blocks"], bs))
                return new_bs, hm2

            blocks_state, outs = lax.scan(outer, state["blocks"], micro)
            h = outs.reshape((b,) + outs.shape[2:])
        else:
            def body(hh, ps):
                lp, ls = ps
                h2, ns = self._block(lp, ls, hh, training)
                return h2, ns

            h, blocks_state = lax.scan(
                body, h, (params["blocks"], state["blocks"]))

        h = jnp.mean(h, axis=(1, 2))  # global average pool
        logits, _ = self.head.apply(params["head"], {}, h)
        new_state = {"stem_bn": stem_bn_state, "blocks": blocks_state}
        return jax.nn.log_softmax(logits, axis=-1), new_state

    def output_shape(self, input_shape):
        return (input_shape[0], self.n_class)

    def prepare_pipeline_params(self, params, n_stage: int):
        if not self.pipeline_interleave:
            return params
        from bigdl_tpu.parallel.pipeline import interleave_stack

        return dict(params, blocks=interleave_stack(params["blocks"], n_stage))

    def prepare_pipeline_state(self, state, n_stage: int):
        if not self.pipeline_interleave:
            return state
        from bigdl_tpu.parallel.pipeline import interleave_stack

        return dict(state, blocks=interleave_stack(state["blocks"], n_stage))

    def restore_pipeline_state(self, state, n_stage: int):
        """Undo the interleaved-schedule layout on the state coming OUT of
        the pipelined step, so stored state stays in model order (params
        never come back out, their gradients flow through the permutation
        instead)."""
        if not self.pipeline_interleave:
            return state
        from bigdl_tpu.parallel.pipeline import deinterleave_stack

        return dict(state, blocks=deinterleave_stack(state["blocks"],
                                                     n_stage))
