"""Inception v1 / GoogLeNet (BASELINE config 4 — the reference whitepaper's
scaling-benchmark model).

Reference: models/inception/Inception_v1.scala (inception module built from
Concat of 1x1 / 3x3-reduce+3x3 / 5x5-reduce+5x5 / pool-proj branches; the
no-aux-classifier variant Inception_v1_NoAuxClassifier).  NHWC, so the
feature concat is on axis 3.
"""

from __future__ import annotations

from typing import Optional

import bigdl_tpu.nn as nn
from bigdl_tpu.nn import init as init_mod


def _conv(cin, cout, k, stride=1, pad=0, name: Optional[str] = None):
    return nn.Sequential(
        nn.SpatialConvolution(cin, cout, k, k, stride, stride, pad, pad,
                              weight_init=init_mod.Xavier(), name=name),
        nn.ReLU(),
    )


def inception_module(cin: int, c1x1: int, c3x3r: int, c3x3: int,
                     c5x5r: int, c5x5: int, pool_proj: int) -> nn.Concat:
    """reference: Inception_v1.scala inception()."""
    return nn.Concat(
        3,
        _conv(cin, c1x1, 1),
        nn.Sequential(_conv(cin, c3x3r, 1), _conv(c3x3r, c3x3, 3, 1, 1)),
        nn.Sequential(_conv(cin, c5x5r, 1), _conv(c5x5r, c5x5, 5, 1, 2)),
        nn.Sequential(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1), _conv(cin, pool_proj, 1)),
    )


def InceptionV1(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """reference: models/inception/Inception_v1.scala
    (Inception_v1_NoAuxClassifier topology; 224x224 NHWC input)."""
    layers = [
        _conv(3, 64, 7, 2, 3, name="conv1/7x7_s2"),
        nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
        _conv(64, 64, 1, name="conv2/3x3_reduce"),
        _conv(64, 192, 3, 1, 1, name="conv2/3x3"),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
        nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True),
        inception_module(192, 64, 96, 128, 16, 32, 32),     # 3a -> 256
        inception_module(256, 128, 128, 192, 32, 96, 64),   # 3b -> 480
        nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True),
        inception_module(480, 192, 96, 208, 16, 48, 64),    # 4a -> 512
        inception_module(512, 160, 112, 224, 24, 64, 64),   # 4b -> 512
        inception_module(512, 128, 128, 256, 24, 64, 64),   # 4c -> 512
        inception_module(512, 112, 144, 288, 32, 64, 64),   # 4d -> 528
        inception_module(528, 256, 160, 320, 32, 128, 128),  # 4e -> 832
        nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True),
        inception_module(832, 256, 160, 320, 32, 128, 128),  # 5a -> 832
        inception_module(832, 384, 192, 384, 48, 128, 128),  # 5b -> 1024
        nn.GlobalAveragePooling2D(),
    ]
    if has_dropout:
        layers.append(nn.Dropout(0.4))
    layers += [
        nn.Linear(1024, class_num, weight_init=init_mod.Xavier(), name="loss3/classifier"),
        nn.LogSoftMax(),
    ]
    return nn.Sequential(*layers)


def _conv_bn(cin, cout, k, stride=1, pad=0, name: Optional[str] = None):
    """conv + BN(eps 1e-3) + ReLU — the BN-Inception building block
    (reference: models/inception/Inception_v2.scala Inception_Layer_v2)."""
    return nn.Sequential(
        nn.SpatialConvolution(cin, cout, k, k, stride, stride, pad, pad,
                              weight_init=init_mod.Xavier(), name=name),
        nn.SpatialBatchNormalization(cout, eps=1e-3),
        nn.ReLU(),
    )


def inception_module_v2(cin: int, c1x1: int, c3x3: tuple, cd3x3: tuple,
                        pool: tuple, name: Optional[str] = None):
    """BN-Inception module: 1x1 / 3x3 / double-3x3 / pool branches concat on
    channels.  `pool` = ("avg"|"max", proj_channels); proj 0 with "max"
    marks a stride-2 grid-reduction module (no 1x1 branch, strided convs,
    passthrough max pool).
    reference: models/inception/Inception_v2.scala:27-105."""
    pool_kind, pool_proj = pool
    reduce_grid = pool_kind == "max" and pool_proj == 0
    stride = 2 if reduce_grid else 1
    branches = []
    if c1x1:
        branches.append(_conv_bn(cin, c1x1, 1))
    branches.append(nn.Sequential(
        _conv_bn(cin, c3x3[0], 1),
        _conv_bn(c3x3[0], c3x3[1], 3, stride, 1)))
    branches.append(nn.Sequential(
        _conv_bn(cin, cd3x3[0], 1),
        _conv_bn(cd3x3[0], cd3x3[1], 3, 1, 1),
        _conv_bn(cd3x3[1], cd3x3[1], 3, stride, 1)))
    if reduce_grid:
        branches.append(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True))
    else:
        pool_layer = (nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1, ceil_mode=True)
                      if pool_kind == "max"
                      else nn.SpatialAveragePooling(3, 3, 1, 1, 1, 1,
                                                    ceil_mode=True))
        branches.append(nn.Sequential(
            pool_layer, _conv_bn(cin, pool_proj, 1)))
    return nn.Concat(3, *branches, name=name)


def InceptionV2(class_num: int = 1000) -> nn.Sequential:
    """BN-Inception / Inception-v2 for 224x224x3 (NHWC).
    reference: models/inception/Inception_v2.scala
    Inception_v2_NoAuxClassifier:188-231 (channel configs verbatim)."""
    return nn.Sequential(
        _conv_bn(3, 64, 7, 2, 3, name="conv1/7x7_s2"),
        nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True),
        _conv_bn(64, 64, 1, name="conv2/3x3_reduce"),
        _conv_bn(64, 192, 3, 1, 1, name="conv2/3x3"),
        nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True),
        inception_module_v2(192, 64, (64, 64), (64, 96), ("avg", 32),
                            name="inception_3a"),
        inception_module_v2(256, 64, (64, 96), (64, 96), ("avg", 64),
                            name="inception_3b"),
        inception_module_v2(320, 0, (128, 160), (64, 96), ("max", 0),
                            name="inception_3c"),
        inception_module_v2(576, 224, (64, 96), (96, 128), ("avg", 128),
                            name="inception_4a"),
        inception_module_v2(576, 192, (96, 128), (96, 128), ("avg", 128),
                            name="inception_4b"),
        inception_module_v2(576, 160, (128, 160), (128, 160), ("avg", 96),
                            name="inception_4c"),
        inception_module_v2(576, 96, (128, 192), (160, 192), ("avg", 96),
                            name="inception_4d"),
        inception_module_v2(576, 0, (128, 192), (192, 256), ("max", 0),
                            name="inception_4e"),
        inception_module_v2(1024, 352, (192, 320), (160, 224), ("avg", 128),
                            name="inception_5a"),
        inception_module_v2(1024, 352, (192, 320), (192, 224), ("max", 128),
                            name="inception_5b"),
        nn.GlobalAveragePooling2D(),
        nn.Linear(1024, class_num, name="loss3/classifier"),
        nn.LogSoftMax(),
    )
