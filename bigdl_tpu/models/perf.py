"""Synthetic-input throughput harnesses.

Reference: models/utils/DistriOptimizerPerf.scala:32-86 and
LocalOptimizerPerf.scala — select a model (inception/vgg/resnet/lenet/
transformer), feed random ImageNet-shaped batches, report records/sec the
same way DistriOptimizer logs Throughput
(optim/DistriOptimizer.scala:402-407).

CLI:
    python -m bigdl_tpu.models.perf --model resnet50 --batch-size 64 \
        --iteration 20 [--distributed]

`--distributed` shards the batch over the Engine mesh (all local devices on
the data axis) — the DistriOptimizerPerf analogue; without it the step runs
single-device (LocalOptimizerPerf).
"""

from __future__ import annotations

import argparse
import time
from typing import Tuple

import numpy as np


def build_model_and_shape(name: str, batch: int):
    import bigdl_tpu.nn as nn
    from bigdl_tpu import models

    if name == "lenet":
        return models.LeNet5(10), (batch, 28, 28, 1), 10
    if name == "vgg16":
        return models.Vgg16(1000), (batch, 224, 224, 3), 1000
    if name == "resnet50":
        return models.resnet50(1000), (batch, 224, 224, 3), 1000
    if name == "resnet50_fused":
        # fused conv+BN-stats training variant (pallas epilogue kernel)
        return models.resnet50(1000, fuse_bn=True), (batch, 224, 224, 3), 1000
    if name == "inception":
        return models.InceptionV1(1000), (batch, 224, 224, 3), 1000
    if name == "inception_v2":
        return models.InceptionV2(1000), (batch, 224, 224, 3), 1000
    # sequence models: input is int32 token ids (B, S), label (B, S)
    if name == "transformer":
        m = models.TransformerLM(vocab_size=32_000, hidden_size=768,
                                 n_layer=12, n_head=12, max_len=1024)
        return m, (batch, 1024), 32_000
    if name == "ptb_lstm":
        # the reference PTB 'medium' LM (example/languagemodel/PTBModel)
        return (models.PTBModel(vocab_size=10_000, embedding_dim=650,
                                hidden_size=650, num_layers=2,
                                keep_prob=1.0),
                (batch, 35), 10_000)
    raise ValueError(f"unknown model {name!r} "
                     f"(lenet | vgg16 | resnet50 | resnet50_fused | inception | "
                     f"inception_v2 | transformer | ptb_lstm)")


def run_perf(model_name: str = "inception", batch_size: int = 32,
             iterations: int = 10, warmup: int = 3, distributed: bool = False,
             dtype: str = "float32") -> Tuple[float, float]:
    """Returns (records_per_sec, ms_per_iteration)."""
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.core.engine import Engine
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.parallel import batch_sharding

    model, shape, classes = build_model_and_shape(model_name, batch_size)
    is_seq = len(shape) == 2  # (B, S) token-id models
    params, state, _ = model.build(jax.random.PRNGKey(0), shape)
    optim = SGD(learning_rate=0.01, momentum=0.9, dampening=0.0)
    opt_state = optim.init(params)
    criterion = nn.TimeDistributedCriterion(
        nn.ClassNLLCriterion(), size_average=True) if is_seq \
        else nn.ClassNLLCriterion()
    compute_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

    def train_step(params, model_state, opt_state, x, y, rng):
        def loss_fn(p):
            p_c = jax.tree_util.tree_map(lambda a: a.astype(compute_dtype), p)
            s_c = jax.tree_util.tree_map(lambda a: a.astype(compute_dtype),
                                         model_state)
            xc = x if jnp.issubdtype(x.dtype, jnp.integer) \
                else x.astype(compute_dtype)
            out, new_state = model.apply(p_c, s_c, xc,
                                         training=True, rng=rng)
            new_state = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32), new_state)
            return criterion.forward(out.astype(jnp.float32), y), new_state

        (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = optim.step(grads, params, opt_state)
        return new_params, new_state, new_opt, loss

    rs = np.random.RandomState(0)
    if is_seq:
        x = jnp.asarray(rs.randint(0, classes, shape), jnp.int32)
        y = jnp.asarray(rs.randint(0, classes, shape), jnp.int32)
    else:
        x = jnp.asarray(rs.rand(*shape), jnp.float32)
        y = jnp.asarray(rs.randint(0, classes, shape[0]))
    if distributed:
        mesh = Engine.init() if Engine._mesh is None else Engine._mesh
        x = jax.device_put(x, batch_sharding(mesh))
        y = jax.device_put(y, batch_sharding(mesh))

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rng = jax.random.PRNGKey(0)  # fixed mask per step: throughput-neutral

    def sync(tree):
        # host readback: the only true sync through the remote-TPU tunnel
        return float(jnp.sum(jax.tree_util.tree_leaves(tree)[0]
                             .astype(jnp.float32)))

    for _ in range(warmup):
        params, state, opt_state, loss = step(params, state, opt_state,
                                              x, y, rng)
    sync(params)
    t0 = time.perf_counter()
    for _ in range(iterations):
        params, state, opt_state, loss = step(params, state, opt_state,
                                              x, y, rng)
    sync(params)
    dt = time.perf_counter() - t0
    rec_s = batch_size * iterations / dt
    return rec_s, dt / iterations * 1e3


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="inception")
    ap.add_argument("-b", "--batch-size", type=int, default=32)
    ap.add_argument("-i", "--iteration", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--distributed", action="store_true")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    args = ap.parse_args(argv)
    rec_s, ms = run_perf(args.model, args.batch_size, args.iteration,
                         args.warmup, args.distributed, args.dtype)
    print(f"[{args.model}] Throughput is {rec_s:.1f} records/second, "
          f"{ms:.1f} ms/iteration")


if __name__ == "__main__":
    main()
