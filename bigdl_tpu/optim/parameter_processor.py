"""Gradient processors (clipping).

Reference: parameters/ParameterOperations.scala:33-89 —
ConstantClippingProcessor and L2NormClippingProcessor.  The reference
computes the global L2 norm with a cross-node collect; here grads inside
the jitted step are global arrays, so the norm is global by construction
(one more way the Spark control plane disappears into XLA).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


class ParameterProcessor:
    def process(self, grads: Any) -> Any:
        raise NotImplementedError


class ConstantClippingProcessor(ParameterProcessor):
    """Clip each gradient element to [min, max].
    reference: ParameterOperations.scala ConstantClippingProcessor."""

    def __init__(self, min_value: float, max_value: float):
        self.min_value = min_value
        self.max_value = max_value

    def process(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min_value, self.max_value), grads)


class L2NormClippingProcessor(ParameterProcessor):
    """Scale grads so the GLOBAL l2 norm <= max_norm.
    reference: ParameterOperations.scala L2NormClippingProcessor."""

    def __init__(self, l2_norm_threshold: float):
        self.max_norm = l2_norm_threshold

    def process(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        global_norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = jnp.minimum(1.0, self.max_norm / jnp.maximum(global_norm, 1e-12))
        return jax.tree_util.tree_map(lambda g: g * scale, grads)
