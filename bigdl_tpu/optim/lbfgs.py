"""L-BFGS with strong-Wolfe cubic line search.

Reference: optim/LBFGS.scala + optim/LineSearch.scala (the `lswolfe`
interpolating line search).  Like the reference, this is a *closure-driven*
full-batch method: `optimize(feval, params)` where
`feval(params) -> (loss, grads)`; the reference signature is
`optimize(feval: Tensor => (T, Tensor), x: Tensor)`.  It runs driver-side
(Python loop over inner iterations — data-dependent termination cannot live
inside one XLA program), but `feval` itself is typically a jitted
value_and_grad, so every heavy evaluation is one compiled TPU step.

State is kept on a raveled 1-D vector (jax.flatten_util.ravel_pytree), the
same flattened-parameter view the reference's `model.getParameters()`
produces (optim/DistriOptimizer.scala:809).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from bigdl_tpu.optim.optim_method import OptimMethod


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    """Minimizer of the cubic through (x1,f1,g1),(x2,f2,g2).
    reference: optim/LineSearch.scala polyinterp."""
    if bounds is not None:
        xmin_bound, xmax_bound = bounds
    else:
        xmin_bound, xmax_bound = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    d2_square = d1 ** 2 - g1 * g2
    if d2_square >= 0:
        d2 = d2_square ** 0.5
        if x1 <= x2:
            min_pos = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
        else:
            min_pos = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
        return min(max(min_pos, xmin_bound), xmax_bound)
    return (xmin_bound + xmax_bound) / 2.0


def _strong_wolfe(feval_1d: Callable[[float], Tuple[float, float]],
                  t: float, f0: float, g0: float,
                  c1: float = 1e-4, c2: float = 0.9,
                  tolerance_change: float = 1e-9,
                  max_ls: int = 25) -> Tuple[float, float, int]:
    """Strong-Wolfe line search on the 1-D slice f(t) = feval(x + t*d).

    Returns (f_new, t, n_evals).  reference: optim/LineSearch.scala lswolfe.
    """
    f_prev, g_prev, t_prev = f0, g0, 0.0
    f_new, g_new = feval_1d(t)
    ls_iter = 1

    # bracketing phase
    bracket = None
    while ls_iter < max_ls:
        if f_new > f0 + c1 * t * g0 or (ls_iter > 1 and f_new >= f_prev):
            bracket = (t_prev, f_prev, g_prev, t, f_new, g_new)
            break
        if abs(g_new) <= -c2 * g0:
            return f_new, t, ls_iter
        if g_new >= 0:
            bracket = (t, f_new, g_new, t_prev, f_prev, g_prev)
            break
        t_next = _cubic_interpolate(t_prev, f_prev, g_prev, t, f_new, g_new,
                                    bounds=(t + 0.01 * (t - t_prev),
                                            t * 10))
        t_prev, f_prev, g_prev = t, f_new, g_new
        t = t_next
        f_new, g_new = feval_1d(t)
        ls_iter += 1
    if bracket is None:  # ran out while bracketing
        return f_new, t, ls_iter

    # zoom phase
    t_lo, f_lo, g_lo, t_hi, f_hi, g_hi = bracket
    while ls_iter < max_ls:
        if abs(t_hi - t_lo) * 1.0 < tolerance_change:
            break
        t = _cubic_interpolate(t_lo, f_lo, g_lo, t_hi, f_hi, g_hi)
        # keep t a sensible fraction inside the bracket
        lo, hi = (t_lo, t_hi) if t_lo <= t_hi else (t_hi, t_lo)
        eps = 0.1 * (hi - lo)
        if min(t - lo, hi - t) < eps:
            t = max(min(t, hi - eps), lo + eps)
        f_new, g_new = feval_1d(t)
        ls_iter += 1
        if f_new > f0 + c1 * t * g0 or f_new >= f_lo:
            t_hi, f_hi, g_hi = t, f_new, g_new
        else:
            if abs(g_new) <= -c2 * g0:
                return f_new, t, ls_iter
            if g_new * (t_hi - t_lo) >= 0:
                t_hi, f_hi, g_hi = t_lo, f_lo, g_lo
            t_lo, f_lo, g_lo = t, f_new, g_new
    return f_lo, t_lo, ls_iter


class LBFGS(OptimMethod):
    """Limited-memory BFGS. reference: optim/LBFGS.scala.

    `optimize(feval, params)` performs up to `max_iter` quasi-Newton
    iterations on the full batch and returns `(new_params, f_history)` —
    the reference returns `(x, history of f)` the same way.
    """

    def __init__(self, max_iter: int = 20, max_eval: Optional[float] = None,
                 tolerance_fun: float = 1e-5, tolerance_x: float = 1e-9,
                 n_correction: int = 100, learning_rate: float = 1.0,
                 line_search: bool = True,
                 line_search_options: Optional[dict] = None):
        super().__init__(learning_rate)
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 1.25
        self.tolerance_fun = tolerance_fun
        self.tolerance_x = tolerance_x
        self.n_correction = n_correction
        self.line_search = line_search
        self.line_search_options = line_search_options or {}

    def optimize(self, feval: Callable[[Any], Tuple[Any, Any]],
                 params: Any) -> Tuple[Any, List[float]]:
        x0, unravel = ravel_pytree(params)

        def eval_flat(x):
            loss, grads = feval(unravel(x))
            g, _ = ravel_pytree(grads)
            return jnp.asarray(loss, jnp.float32), g.astype(x.dtype)

        x = x0
        f, g = eval_flat(x)
        f_hist = [float(f)]
        n_eval = 1
        if float(jnp.abs(g).sum()) <= self.tolerance_fun:
            return unravel(x), f_hist  # already at a critical point

        old_dirs: List[jnp.ndarray] = []  # y_k
        old_steps: List[jnp.ndarray] = []  # s_k
        ro: List[jnp.ndarray] = []
        h_diag = 1.0
        g_prev = None
        d = -g
        t = min(1.0, 1.0 / float(jnp.abs(g).sum())) * self.learning_rate

        for n_iter in range(self.max_iter):
            if n_iter > 0:
                y = g - g_prev
                s = d * t
                ys = float(jnp.dot(y, s))
                if ys > 1e-10:
                    if len(old_dirs) == self.n_correction:
                        old_dirs.pop(0)
                        old_steps.pop(0)
                        ro.pop(0)
                    old_dirs.append(y)
                    old_steps.append(s)
                    ro.append(1.0 / ys)
                    h_diag = ys / float(jnp.dot(y, y))
                # two-loop recursion
                k = len(old_dirs)
                al = [0.0] * k
                q = -g
                for i in range(k - 1, -1, -1):
                    al[i] = float(jnp.dot(old_steps[i], q)) * ro[i]
                    q = q - al[i] * old_dirs[i]
                d = q * h_diag
                for i in range(k):
                    be_i = float(jnp.dot(old_dirs[i], d)) * ro[i]
                    d = d + old_steps[i] * (al[i] - be_i)
            g_prev = g

            gtd = float(jnp.dot(g, d))
            if gtd > -self.tolerance_x:
                break  # not a descent direction
            if n_iter > 0:
                t = self.learning_rate

            f_old = float(f)
            if self.line_search:
                # cache (f, g) per step size so the accepted point's full
                # gradient is reused instead of re-evaluating feval
                cache = {}

                def feval_1d(step, x=x, d=d):
                    f_s, g_s = eval_flat(x + step * d)
                    cache[float(step)] = (f_s, g_s)
                    return float(f_s), float(jnp.dot(g_s, d))

                f_new, t, ls_evals = _strong_wolfe(
                    feval_1d, t, float(f), gtd, **self.line_search_options)
                n_eval += ls_evals
                x = x + t * d
                if float(t) in cache:
                    f, g = cache[float(t)]
                else:
                    f, g = eval_flat(x)
                    n_eval += 1
            else:
                x = x + t * d
                f, g = eval_flat(x)
                n_eval += 1
            f_hist.append(float(f))

            # termination checks (reference: LBFGS.scala end-of-loop tests)
            if n_eval >= self.max_eval:
                break
            if float(jnp.abs(g).sum()) <= self.tolerance_fun:
                break
            if float(jnp.abs(t * d).sum()) <= self.tolerance_x:
                break
            if abs(float(f) - f_old) < self.tolerance_fun:
                break

        return unravel(x), f_hist

    def step(self, grads, params, opt_state, lr=None):
        raise NotImplementedError(
            "LBFGS is closure-driven; use optimize(feval, params) "
            "(reference: optim/LBFGS.scala optimize(feval, x))")

    def get_hyper_parameter(self) -> str:
        return (f"maxIter={self.max_iter} nCorrection={self.n_correction} "
                f"lineSearch={'wolfe' if self.line_search else 'fixed'}")
