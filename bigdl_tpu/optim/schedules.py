"""Learning-rate schedules.

Reference: optim/SGD.scala:200-680 — the 12-schedule zoo (Default, Poly,
Step, MultiStep, EpochDecay, EpochStep, NaturalExp, Exponential, Plateau,
Warmup, SequentialSchedule, EpochSchedule + EpochDecayWithWarmUp used by the
ResNet ImageNet baseline).  These are load-bearing for baseline parity.

Redesign: each schedule is a pure function of the iteration/epoch counters,
`schedule(base_lr, iteration, epoch) -> lr` with jnp scalars, so the LR
computation traces into the jitted train step (no host round-trip per step).
`Plateau` is the one metric-driven schedule — it runs host-side between
epochs (`on_score`) and the resulting LR is fed into the step as an argument.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax


class LearningRateSchedule:
    """lr(base_lr, iteration, epoch) with traced int32 counters.

    `iteration` counts optimizer steps (the reference's state("neval")),
    `epoch` counts epochs from 0 (the reference is 1-based)."""

    def __call__(self, base_lr, iteration, epoch):
        raise NotImplementedError

    # host-side hook for metric-driven schedules; default no-op
    def on_score(self, score: float) -> None:
        pass


class Default(LearningRateSchedule):
    """lr / (1 + n*decay). reference: SGD.Default."""

    def __init__(self, leaning_rate_decay: float = 0.0):
        self.decay = leaning_rate_decay

    def __call__(self, base_lr, iteration, epoch):
        return base_lr / (1.0 + iteration * self.decay)


class Poly(LearningRateSchedule):
    """lr * (1 - iter/max_iter)^power; 0 after max. reference: SGD.Poly."""

    def __init__(self, power: float, max_iteration: int):
        self.power = power
        self.max_iteration = max_iteration

    def __call__(self, base_lr, iteration, epoch):
        frac = jnp.minimum(iteration / self.max_iteration, 1.0)
        return base_lr * (1.0 - frac) ** self.power


class Step(LearningRateSchedule):
    """lr * gamma^(floor(iter/step_size)). reference: SGD.Step."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size = step_size
        self.gamma = gamma

    def __call__(self, base_lr, iteration, epoch):
        return base_lr * self.gamma ** jnp.floor(iteration / self.step_size)


class MultiStep(LearningRateSchedule):
    """lr * gamma^(#milestones passed). reference: SGD.MultiStep."""

    def __init__(self, step_sizes: Sequence[int], gamma: float):
        self.step_sizes = list(step_sizes)
        self.gamma = gamma

    def __call__(self, base_lr, iteration, epoch):
        passed = sum((iteration >= s).astype(jnp.float32) if hasattr(iteration, "astype")
                     else jnp.float32(iteration >= s)
                     for s in [jnp.int32(s) for s in self.step_sizes])
        return base_lr * self.gamma ** passed


class EpochDecay(LearningRateSchedule):
    """lr * 0.1^decay_fn(epoch); the reference takes an arbitrary
    Int=>Double fn. reference: SGD.EpochDecay."""

    def __init__(self, decay_fn):
        self.decay_fn = decay_fn

    def __call__(self, base_lr, iteration, epoch):
        # decay_fn must be jnp-traceable (e.g. lambda e: (e // 30))
        return base_lr * 0.1 ** self.decay_fn(epoch)


class EpochStep(LearningRateSchedule):
    """lr * gamma^(floor(epoch/step)). reference: SGD.EpochStep."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size = step_size
        self.gamma = gamma

    def __call__(self, base_lr, iteration, epoch):
        return base_lr * self.gamma ** jnp.floor(epoch / self.step_size)


class NaturalExp(LearningRateSchedule):
    """lr * exp(-decay_rate * floor(iter/decay_step)).
    reference: SGD.NaturalExp."""

    def __init__(self, decay_step: int, decay_rate: float):
        self.decay_step = decay_step
        self.decay_rate = decay_rate

    def __call__(self, base_lr, iteration, epoch):
        return base_lr * jnp.exp(-self.decay_rate * jnp.floor(iteration / self.decay_step))


class Exponential(LearningRateSchedule):
    """lr * decay_rate^(iter/decay_step), optionally staircased.
    reference: SGD.Exponential."""

    def __init__(self, decay_step: int, decay_rate: float, stair_case: bool = False):
        self.decay_step = decay_step
        self.decay_rate = decay_rate
        self.stair_case = stair_case

    def __call__(self, base_lr, iteration, epoch):
        p = iteration / self.decay_step
        if self.stair_case:
            p = jnp.floor(p)
        return base_lr * self.decay_rate ** p


class Warmup(LearningRateSchedule):
    """Linear ramp by `delta` per iteration (combined via SequentialSchedule).
    reference: SGD.Warmup."""

    def __init__(self, delta: float):
        self.delta = delta

    def __call__(self, base_lr, iteration, epoch):
        return base_lr + self.delta * iteration


class SequentialSchedule(LearningRateSchedule):
    """Chain schedules, each active for `maxIteration` steps.
    reference: SGD.SequentialSchedule."""

    def __init__(self):
        self.schedules: List[Tuple[LearningRateSchedule, int]] = []

    def add(self, schedule: LearningRateSchedule, max_iteration: int) -> "SequentialSchedule":
        self.schedules.append((schedule, max_iteration))
        return self

    def __call__(self, base_lr, iteration, epoch):
        lr = base_lr
        offset = 0
        result = None
        remaining = iteration
        for sched, max_it in self.schedules:
            local = jnp.clip(iteration - offset, 0, max_it)
            candidate = sched(base_lr, local, epoch)
            active = (iteration >= offset)
            result = candidate if result is None else jnp.where(active, candidate, result)
            offset += max_it
        return result if result is not None else lr


class EpochSchedule(LearningRateSchedule):
    """Explicit per-epoch-range LRs. reference: SGD.EpochSchedule
    (Regime list)."""

    def __init__(self, regimes: Sequence[Tuple[int, int, float]]):
        # regimes: (start_epoch, end_epoch, lr) — 0-based inclusive ranges
        self.regimes = list(regimes)

    def __call__(self, base_lr, iteration, epoch):
        lr = base_lr
        for start, end, r_lr in self.regimes:
            inside = jnp.logical_and(epoch >= start, epoch <= end)
            lr = jnp.where(inside, r_lr, lr)
        return lr


class EpochDecayWithWarmUp(LearningRateSchedule):
    """Linear warmup for `warmupEpoch` epochs then step decay by epoch —
    the ResNet-50 ImageNet baseline schedule
    (reference: SGD.EpochDecayWithWarmUp, models/resnet/TrainImageNet.scala:100-123)."""

    def __init__(self, warmup_epoch: int, warmup_delta: float, decay_fn):
        self.warmup_epoch = warmup_epoch
        self.warmup_delta = warmup_delta
        self.decay_fn = decay_fn

    def __call__(self, base_lr, iteration, epoch):
        warm = base_lr + self.warmup_delta * epoch
        decayed = (base_lr + self.warmup_delta * (self.warmup_epoch - 1)) * \
            0.1 ** self.decay_fn(epoch)
        return jnp.where(epoch < self.warmup_epoch, warm, decayed)


class Plateau(LearningRateSchedule):
    """Reduce LR when a monitored metric stops improving.  Host-side: call
    `on_score(score)` after each validation; `current_factor` multiplies the
    base LR.  reference: SGD.Plateau."""

    def __init__(self, monitor: str = "score", factor: float = 0.1,
                 patience: int = 10, mode: str = "min", epsilon: float = 1e-4,
                 cooldown: int = 0, min_lr: float = 0.0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.epsilon = epsilon
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.current_factor = 1.0
        self._best: Optional[float] = None
        self._wait = 0
        self._cooldown_left = 0

    def on_score(self, score: float) -> None:
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            self._wait = 0
        improved = (
            self._best is None
            or (self.mode == "min" and score < self._best - self.epsilon)
            or (self.mode == "max" and score > self._best + self.epsilon)
        )
        if improved:
            self._best = score
            self._wait = 0
        elif self._cooldown_left <= 0:
            self._wait += 1
            if self._wait >= self.patience:
                self.current_factor *= self.factor
                self._cooldown_left = self.cooldown
                self._wait = 0

    def __call__(self, base_lr, iteration, epoch):
        return jnp.maximum(base_lr * self.current_factor, self.min_lr)

    def host_value(self, base_lr: float) -> float:
        """Host-side twin of __call__: Plateau state is host floats, so
        the driver can read the current lr without a device round-trip.
        f32 math mirrors the device computation bit-for-bit so the value
        that reaches the step is identical either way."""
        return float(np.maximum(np.float32(base_lr)
                                * np.float32(self.current_factor),
                                np.float32(self.min_lr)))
