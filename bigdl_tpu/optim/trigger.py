"""Composable triggers for validation/checkpoint/termination.

Reference: optim/Trigger.scala:30-132 (everyEpoch, severalIteration,
maxEpoch, maxIteration, maxScore, minLoss, and, or).  A trigger is a
predicate over the driver-side training state dict
{"epoch", "neval", "loss", "score", "record_count", "epoch_finished"}.
"""

from __future__ import annotations

from typing import Callable, Dict


class Trigger:
    def __init__(self, fn: Callable[[Dict], bool], desc: str = "trigger",
                 deterministic: bool = False):
        self._fn = fn
        self.desc = desc
        # deterministic: the predicate reads only process-identical driver
        # state (epoch/neval/epoch_finished), so every process computes the
        # same answer and no cross-host agreement collective is needed.
        # Defaults to False — user-constructed triggers get the safe
        # broadcast path; the factory methods opt in where provable.
        self.deterministic = deterministic

    def __call__(self, state: Dict) -> bool:
        return self._fn(state)

    def __repr__(self):
        return f"Trigger({self.desc})"

    # -- factories (reference: optim/Trigger.scala) ---------------------
    @staticmethod
    def every_epoch() -> "Trigger":
        return Trigger(lambda s: s.get("epoch_finished", False), "everyEpoch", deterministic=True)

    @staticmethod
    def several_iteration(interval: int) -> "Trigger":
        return Trigger(lambda s: s["neval"] > 0 and s["neval"] % interval == 0,
                       f"severalIteration({interval})", deterministic=True)

    @staticmethod
    def max_epoch(max_e: int) -> "Trigger":
        return Trigger(lambda s: s["epoch"] >= max_e, f"maxEpoch({max_e})", deterministic=True)

    @staticmethod
    def max_iteration(max_it: int) -> "Trigger":
        return Trigger(lambda s: s["neval"] >= max_it, f"maxIteration({max_it})", deterministic=True)

    @staticmethod
    def max_score(max_s: float) -> "Trigger":
        return Trigger(lambda s: s.get("score") is not None and s["score"] > max_s,
                       f"maxScore({max_s})", deterministic=False)

    @staticmethod
    def min_loss(min_l: float) -> "Trigger":
        return Trigger(lambda s: s.get("loss") is not None and s["loss"] < min_l,
                       f"minLoss({min_l})", deterministic=False)

    @staticmethod
    def and_(*triggers: "Trigger") -> "Trigger":
        det = all(getattr(t, "deterministic", False) for t in triggers)
        return Trigger(lambda s: all(t(s) for t in triggers), "and",
                       deterministic=det)

    @staticmethod
    def or_(*triggers: "Trigger") -> "Trigger":
        det = all(getattr(t, "deterministic", False) for t in triggers)
        return Trigger(lambda s: any(t(s) for t in triggers), "or",
                       deterministic=det)
