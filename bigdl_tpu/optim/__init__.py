"""Optimization & training.

Reference packages: optim/ (Optimizer, DistriOptimizer, LocalOptimizer,
OptimMethod zoo, Trigger, ValidationMethod) and parameters/
(AllReduceParameter — replaced by XLA collectives; see optimizer.py).
"""

from bigdl_tpu.optim.optim_method import (
    OptimMethod, SGD, Adam, ParallelAdam, Adamax, Adadelta, Adagrad,
    RMSprop, Ftrl,
)
from bigdl_tpu.optim.lbfgs import LBFGS
from bigdl_tpu.optim import schedules
from bigdl_tpu.optim.schedules import (
    Default, Poly, Step, MultiStep, EpochDecay, EpochStep, NaturalExp,
    Exponential, Warmup, SequentialSchedule, EpochSchedule,
    EpochDecayWithWarmUp, Plateau,
)
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import (
    ValidationMethod, ValidationResult, Top1Accuracy, BinaryAccuracy,
    Top5Accuracy, Loss, PerOutput,
    MAE, HitRatio, NDCG, TreeNNAccuracy,
)
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.parameter_processor import (
    ParameterProcessor, ConstantClippingProcessor, L2NormClippingProcessor,
)
from bigdl_tpu.optim.optimizer import (Optimizer, LocalOptimizer,
                                       DistriOptimizer, ParallelOptimizer)
from bigdl_tpu.optim.profiling import layer_times, profiler_trace
from bigdl_tpu.optim.regularizer import (L1L2Regularizer, L1Regularizer,
                                         L2Regularizer, Regularizer)
from bigdl_tpu.optim.predictor import (
    Predictor,
    LocalPredictor,
    Evaluator,
    Validator,
    PredictionService,
)

# deprecated-name parity (reference optim/Validator.scala family)
LocalValidator = Validator
DistriValidator = Validator
