"""Optimization methods (the OptimMethod zoo).

Reference: optim/OptimMethod.scala + SGD/Adam/ParallelAdam/Adamax/Adadelta/
Adagrad/RMSprop/Ftrl (optim/*.scala).  The reference mutates a flattened
1-D parameter tensor in place with a `Table` state bag; here each method is
a pure pytree transform

    opt_state = method.init(params)
    params, opt_state = method.step(grads, params, opt_state[, lr])

that traces into the jitted train step.  Counters (`neval`, `epoch`) live in
opt_state so LR schedules compute on-device.  `ParallelAdam` (the
reference's multi-threaded Adam) is an alias for `Adam`: intra-host
parallelism is XLA's job on TPU.

Weight decay follows the reference semantics (L2 added to the gradient
before momentum, optim/SGD.scala) — not decoupled AdamW; `Ftrl` matches the
TF/reference formulation.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.optim.schedules import Default, LearningRateSchedule


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


class OptimMethod:
    """Base. reference: optim/OptimMethod.scala."""

    def __init__(self, learning_rate: float = 1e-3,
                 schedule: Optional[LearningRateSchedule] = None):
        self.learning_rate = learning_rate
        self.schedule = schedule

    # ------------------------------------------------------------------
    def init(self, params: Any) -> Dict[str, Any]:
        state = self._init_slots(params)
        state["neval"] = jnp.zeros((), jnp.int32)
        state["epoch"] = jnp.zeros((), jnp.int32)
        return state

    def _init_slots(self, params: Any) -> Dict[str, Any]:
        return {}

    def current_lr(self, opt_state: Dict[str, Any]):
        it = opt_state["neval"]
        ep = opt_state["epoch"]
        if self.schedule is None:
            return jnp.asarray(self.learning_rate, jnp.float32)
        return self.schedule(jnp.asarray(self.learning_rate, jnp.float32), it, ep)

    def step(self, grads: Any, params: Any, opt_state: Dict[str, Any],
             lr: Optional[jnp.ndarray] = None):
        """Pure update; returns (new_params, new_opt_state)."""
        raise NotImplementedError

    def get_hyper_parameter(self) -> str:
        return f"lr={self.learning_rate}"


class SGD(OptimMethod):
    """SGD with momentum/nesterov/dampening/weightDecay + schedules.
    reference: optim/SGD.scala:39."""

    def __init__(self, learning_rate: float = 1e-3, learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0, momentum: float = 0.0,
                 dampening: Optional[float] = None, nesterov: bool = False,
                 schedule: Optional[LearningRateSchedule] = None):
        if schedule is None and learning_rate_decay > 0.0:
            schedule = Default(learning_rate_decay)
        super().__init__(learning_rate, schedule)
        self.weight_decay = weight_decay
        self.momentum = momentum
        self.dampening = momentum if dampening is None else dampening
        self.nesterov = nesterov
        if nesterov and (momentum <= 0 or self.dampening != 0):
            raise ValueError("nesterov requires momentum > 0 and dampening = 0")

    def _init_slots(self, params):
        if self.momentum > 0:
            return {"velocity": _tree_map(jnp.zeros_like, params)}
        return {}

    def step(self, grads, params, opt_state, lr=None):
        lr = self.current_lr(opt_state) if lr is None else lr
        if self.weight_decay > 0:
            grads = _tree_map(lambda g, p: g + self.weight_decay * p, grads, params)
        if self.momentum > 0:
            vel = _tree_map(
                lambda v, g: self.momentum * v + (1.0 - self.dampening) * g,
                opt_state["velocity"], grads)
            if self.nesterov:
                upd = _tree_map(lambda g, v: g + self.momentum * v, grads, vel)
            else:
                upd = vel
            new_params = _tree_map(lambda p, u: p - lr * u, params, upd)
            new_state = dict(opt_state, velocity=vel, neval=opt_state["neval"] + 1)
        else:
            new_params = _tree_map(lambda p, g: p - lr * g, params, grads)
            new_state = dict(opt_state, neval=opt_state["neval"] + 1)
        return new_params, new_state


class Adam(OptimMethod):
    """reference: optim/Adam.scala (and ParallelAdam.scala — on TPU the
    multi-threaded variant is the same compiled program)."""

    def __init__(self, learning_rate: float = 1e-3, learning_rate_decay: float = 0.0,
                 beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8,
                 schedule: Optional[LearningRateSchedule] = None):
        if schedule is None and learning_rate_decay > 0.0:
            schedule = Default(learning_rate_decay)
        super().__init__(learning_rate, schedule)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, params):
        return {"m": _tree_map(jnp.zeros_like, params),
                "v": _tree_map(jnp.zeros_like, params)}

    def step(self, grads, params, opt_state, lr=None):
        lr = self.current_lr(opt_state) if lr is None else lr
        t = opt_state["neval"] + 1
        b1, b2 = self.beta1, self.beta2
        m = _tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, opt_state["m"], grads)
        v = _tree_map(lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g), opt_state["v"], grads)
        bc1 = 1 - b1 ** t.astype(jnp.float32)
        bc2 = 1 - b2 ** t.astype(jnp.float32)
        new_params = _tree_map(
            lambda p, m_, v_: p - lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + self.epsilon),
            params, m, v)
        return new_params, dict(opt_state, m=m, v=v, neval=t)


ParallelAdam = Adam


class Adamax(OptimMethod):
    """reference: optim/Adamax.scala."""

    def __init__(self, learning_rate: float = 2e-3, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-38):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, params):
        return {"m": _tree_map(jnp.zeros_like, params),
                "u": _tree_map(jnp.zeros_like, params)}

    def step(self, grads, params, opt_state, lr=None):
        lr = self.current_lr(opt_state) if lr is None else lr
        t = opt_state["neval"] + 1
        b1 = self.beta1
        m = _tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, opt_state["m"], grads)
        u = _tree_map(lambda u_, g: jnp.maximum(self.beta2 * u_, jnp.abs(g) + self.epsilon),
                      opt_state["u"], grads)
        bc = 1 - b1 ** t.astype(jnp.float32)
        new_params = _tree_map(lambda p, m_, u_: p - (lr / bc) * m_ / u_, params, m, u)
        return new_params, dict(opt_state, m=m, u=u, neval=t)


class Adadelta(OptimMethod):
    """reference: optim/Adadelta.scala."""

    def __init__(self, decay_rate: float = 0.9, epsilon: float = 1e-10):
        super().__init__(1.0)
        self.rho = decay_rate
        self.epsilon = epsilon

    def _init_slots(self, params):
        return {"accum": _tree_map(jnp.zeros_like, params),
                "accum_update": _tree_map(jnp.zeros_like, params)}

    def step(self, grads, params, opt_state, lr=None):
        rho, eps = self.rho, self.epsilon
        accum = _tree_map(lambda a, g: rho * a + (1 - rho) * jnp.square(g),
                          opt_state["accum"], grads)
        delta = _tree_map(
            lambda g, a, au: g * jnp.sqrt(au + eps) / jnp.sqrt(a + eps),
            grads, accum, opt_state["accum_update"])
        accum_update = _tree_map(lambda au, d: rho * au + (1 - rho) * jnp.square(d),
                                 opt_state["accum_update"], delta)
        new_params = _tree_map(lambda p, d: p - d, params, delta)
        return new_params, dict(opt_state, accum=accum, accum_update=accum_update,
                                neval=opt_state["neval"] + 1)


class Adagrad(OptimMethod):
    """reference: optim/Adagrad.scala."""

    def __init__(self, learning_rate: float = 1e-3, learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0):
        super().__init__(learning_rate, Default(learning_rate_decay)
                         if learning_rate_decay > 0 else None)
        self.weight_decay = weight_decay

    def _init_slots(self, params):
        return {"accum": _tree_map(jnp.zeros_like, params)}

    def step(self, grads, params, opt_state, lr=None):
        lr = self.current_lr(opt_state) if lr is None else lr
        if self.weight_decay > 0:
            grads = _tree_map(lambda g, p: g + self.weight_decay * p, grads, params)
        accum = _tree_map(lambda a, g: a + jnp.square(g), opt_state["accum"], grads)
        new_params = _tree_map(lambda p, g, a: p - lr * g / (jnp.sqrt(a) + 1e-10),
                               params, grads, accum)
        return new_params, dict(opt_state, accum=accum, neval=opt_state["neval"] + 1)


class RMSprop(OptimMethod):
    """reference: optim/RMSprop.scala."""

    def __init__(self, learning_rate: float = 1e-2, learning_rate_decay: float = 0.0,
                 decay_rate: float = 0.99, epsilon: float = 1e-8):
        super().__init__(learning_rate, Default(learning_rate_decay)
                         if learning_rate_decay > 0 else None)
        self.decay_rate = decay_rate
        self.epsilon = epsilon

    def _init_slots(self, params):
        return {"accum": _tree_map(jnp.zeros_like, params)}

    def step(self, grads, params, opt_state, lr=None):
        lr = self.current_lr(opt_state) if lr is None else lr
        rho = self.decay_rate
        accum = _tree_map(lambda a, g: rho * a + (1 - rho) * jnp.square(g),
                          opt_state["accum"], grads)
        new_params = _tree_map(lambda p, g, a: p - lr * g / (jnp.sqrt(a) + self.epsilon),
                               params, grads, accum)
        return new_params, dict(opt_state, accum=accum, neval=opt_state["neval"] + 1)


class Ftrl(OptimMethod):
    """Follow-the-regularized-leader. reference: optim/Ftrl.scala."""

    def __init__(self, learning_rate: float = 1e-3, learning_rate_power: float = -0.5,
                 initial_accumulator_value: float = 0.1,
                 l1_regularization_strength: float = 0.0,
                 l2_regularization_strength: float = 0.0,
                 l2_shrinkage_regularization_strength: float = 0.0):
        super().__init__(learning_rate)
        self.lr_power = learning_rate_power
        self.init_accum = initial_accumulator_value
        self.l1 = l1_regularization_strength
        self.l2 = l2_regularization_strength
        self.l2_shrinkage = l2_shrinkage_regularization_strength

    def _init_slots(self, params):
        return {"accum": _tree_map(lambda p: jnp.full_like(p, self.init_accum), params),
                "linear": _tree_map(jnp.zeros_like, params)}

    def step(self, grads, params, opt_state, lr=None):
        lr = self.current_lr(opt_state) if lr is None else lr

        def upd(p, g, a, l):
            g_shr = g + 2 * self.l2_shrinkage * p
            a_new = a + jnp.square(g)
            sigma = (a_new ** -self.lr_power - a ** -self.lr_power) / lr
            l_new = l + g_shr - sigma * p
            quad = a_new ** -self.lr_power / lr + 2 * self.l2
            l_clip = jnp.clip(l_new, -self.l1, self.l1)
            p_new = (l_clip - l_new) / quad
            return p_new, a_new, l_new

        flat_p, tdef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_a = jax.tree_util.tree_leaves(opt_state["accum"])
        flat_l = jax.tree_util.tree_leaves(opt_state["linear"])
        outs = [upd(p, g, a, l) for p, g, a, l in zip(flat_p, flat_g, flat_a, flat_l)]
        new_params = jax.tree_util.tree_unflatten(tdef, [o[0] for o in outs])
        accum = jax.tree_util.tree_unflatten(tdef, [o[1] for o in outs])
        linear = jax.tree_util.tree_unflatten(tdef, [o[2] for o in outs])
        return new_params, dict(opt_state, accum=accum, linear=linear,
                                neval=opt_state["neval"] + 1)
