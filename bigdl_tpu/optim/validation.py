"""Validation metrics.

Reference: optim/ValidationMethod.scala:118-500 (Top1Accuracy, Top5Accuracy,
Loss, MAE, HitRatio@k, NDCG, TreeNNAccuracy) and ValidationResult merge
semantics (`+`, optim/ValidationMethod.scala:52).

Each method has a jittable per-batch part `batch(output, target) ->
(value, count)` and results merge associatively so distributed eval is a
psum (the reference reduces ValidationResults over the RDD).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from bigdl_tpu.nn.criterion import Criterion


class ValidationResult:
    """(result, count) pair with `+` merge. reference: AccuracyResult/
    LossResult (optim/ValidationMethod.scala:52-117)."""

    def __init__(self, value: float, count: int, name: str = ""):
        self.value = float(value)
        self.count = int(count)
        self.name = name

    def result(self) -> Tuple[float, int]:
        return (self.value / max(self.count, 1), self.count)

    def __add__(self, other: "ValidationResult") -> "ValidationResult":
        return ValidationResult(self.value + other.value, self.count + other.count,
                                self.name)

    def __repr__(self):
        v, c = self.result()
        return f"{self.name}: {v:.6f} (count {c})"


class ValidationMethod:
    name = "validation"

    def batch(self, output, target):
        """Jittable: returns (sum_value, count) jnp scalars for one batch."""
        raise NotImplementedError

    def to_result(self, value, count) -> ValidationResult:
        return ValidationResult(float(value), int(count), self.name)

    def __repr__(self):
        return self.name


class Top1Accuracy(ValidationMethod):
    """reference: optim/ValidationMethod.scala Top1Accuracy."""

    name = "Top1Accuracy"

    def batch(self, output, target):
        pred = jnp.argmax(output, axis=-1)
        correct = jnp.sum((pred == target.astype(pred.dtype)).astype(jnp.float32))
        return correct, jnp.asarray(target.shape[0], jnp.int32)


class BinaryAccuracy(ValidationMethod):
    """keras binary_accuracy: elementwise mean of (round(pred) == target) —
    what keras means by metrics=['accuracy'] under binary_crossentropy
    (K.mean(K.equal(y_true, K.round(y_pred)))), including multi-label
    sigmoid heads.  Top1Accuracy on a 1-unit output would degenerate to
    argmax==0."""

    name = "BinaryAccuracy"

    def batch(self, output, target):
        pred = (jnp.reshape(output, (output.shape[0], -1)) > 0.5)
        tgt = (jnp.reshape(target, (target.shape[0], -1)) > 0.5)
        correct = jnp.sum((pred == tgt).astype(jnp.float32))
        return correct, jnp.asarray(pred.shape[0] * pred.shape[1], jnp.int32)


class Top5Accuracy(ValidationMethod):
    """reference: optim/ValidationMethod.scala Top5Accuracy."""

    name = "Top5Accuracy"

    def batch(self, output, target):
        top5 = jnp.argsort(output, axis=-1)[..., -5:]
        hit = jnp.any(top5 == target.astype(top5.dtype)[..., None], axis=-1)
        return jnp.sum(hit.astype(jnp.float32)), jnp.asarray(target.shape[0], jnp.int32)


class Loss(ValidationMethod):
    """Criterion value as a metric. reference: ValidationMethod.Loss."""

    name = "Loss"

    def __init__(self, criterion: Criterion):
        self.criterion = criterion

    def batch(self, output, target):
        from bigdl_tpu.core.table import Table
        first = output[1] if isinstance(output, Table) else output
        n = first.shape[0]
        val = self.criterion.forward(output, target)
        # mean-reducing criteria contribute mean*n (so merge yields the
        # dataset mean); sum-reducing ones already carry the batch total
        if getattr(self.criterion, "size_average", True):
            val = val * n
        return val, jnp.asarray(n, jnp.int32)


class PerOutput(ValidationMethod):
    """Route a per-tensor metric to ONE head of a multi-output model:
    select entry `index` of the output/target activity Tables and
    delegate to the wrapped method.  This is how keras-style per-output
    metric lists (reference: nn/keras/Topology.scala:55-158, compile's
    per-output metrics) evaluate on models whose output is a Table.

    A single (non-Table) target is shared across heads, matching
    ParallelCriterion(repeat_target=True) semantics."""

    def __init__(self, inner: ValidationMethod, index: int):
        self.inner = inner
        self.index = index
        self.name = f"{inner.name}[out{index}]"

    @staticmethod
    def _entry(activity, i):
        from bigdl_tpu.core.table import Table
        if isinstance(activity, Table):
            return activity[i + 1]  # Tables are 1-indexed
        if isinstance(activity, (list, tuple)):
            return activity[i]
        return activity  # one shared tensor (repeat_target)

    def batch(self, output, target):
        return self.inner.batch(self._entry(output, self.index),
                                self._entry(target, self.index))


class MAE(ValidationMethod):
    """Mean absolute error. reference: ValidationMethod.MAE."""

    name = "MAE"

    def batch(self, output, target):
        n = output.shape[0]
        return jnp.sum(jnp.mean(jnp.abs(output - target),
                                axis=tuple(range(1, output.ndim)))), jnp.asarray(n, jnp.int32)


class HitRatio(ValidationMethod):
    """HR@k over (positive-first) ranking rows: output (N, candidates),
    position 0 is the positive item. reference: ValidationMethod.HitRatio."""

    name = "HitRatio"

    def __init__(self, k: int = 10):
        self.k = k
        self.name = f"HitRatio@{k}"

    def batch(self, output, target):
        # rank of item 0 among all candidates (0 = best)
        pos_score = output[:, :1]
        rank = jnp.sum((output > pos_score).astype(jnp.int32), axis=-1)
        hit = (rank < self.k).astype(jnp.float32)
        return jnp.sum(hit), jnp.asarray(output.shape[0], jnp.int32)


class NDCG(ValidationMethod):
    """NDCG@k with a single positive at column 0.
    reference: ValidationMethod.NDCG."""

    name = "NDCG"

    def __init__(self, k: int = 10):
        self.k = k
        self.name = f"NDCG@{k}"

    def batch(self, output, target):
        pos_score = output[:, :1]
        rank = jnp.sum((output > pos_score).astype(jnp.int32), axis=-1)
        gain = jnp.where(rank < self.k, 1.0 / jnp.log2(rank.astype(jnp.float32) + 2.0), 0.0)
        return jnp.sum(gain), jnp.asarray(output.shape[0], jnp.int32)


class TreeNNAccuracy(ValidationMethod):
    """Root-node classification accuracy for tree models: output is
    (B, n_nodes, C) per-node scores.  The root is the LAST node in this
    framework's children-before-parent topological encoding
    (nn/treelstm.py); the reference selects its first-stored node
    (optim/ValidationMethod.scala TreeNNAccuracy) — same capability,
    different node order convention.

    For batches of padded trees, pass per-example root indices as
    `target = Table(labels, root_indices)` — a heuristic cannot recover
    the root once a classifier head has made padding rows non-zero.
    """

    name = "TreeNNAccuracy"

    def __init__(self, root_index: int = -1):
        self.root_index = root_index

    def batch(self, output, target):
        from bigdl_tpu.core.table import Table

        if isinstance(target, Table):
            labels, roots = target[1], target[2]
            root = output[jnp.arange(output.shape[0]),
                          roots.astype(jnp.int32)]
        else:
            labels = target
            root = output[:, self.root_index, :]
        pred = jnp.argmax(root, axis=-1)
        labels = labels.reshape(pred.shape)
        correct = jnp.sum((pred == labels.astype(pred.dtype)).astype(jnp.float32))
        return correct, jnp.asarray(labels.shape[0], jnp.int32)
