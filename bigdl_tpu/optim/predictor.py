"""Inference: Predictor / Evaluator / PredictionService.

Reference:
- optim/Predictor.scala:35-188 + LocalPredictor — distributed/local batched
  inference over RDD[Sample]/ImageFrame, weights shared per node via
  ModelBroadcast.
- optim/Evaluator.scala:40-95 — broadcast model, mapPartitions over the
  Sample RDD, reduce ValidationResults with `+`.
- optim/PredictionService.scala:56,79-128 — concurrent serving facade:
  a pool of module instances in a LinkedBlockingQueue plus a byte-array
  request/response API.  Served here by `bigdl_tpu.serving` (dynamic
  micro-batching runtime); PredictionService below is the compat facade.

TPU-native redesign: "broadcast the model" is device placement of one
params pytree; per-node replicas become batch sharding over the mesh's
data axis; the hot path is one jitted forward reused across batches.  The
ragged final batch is padded to the compiled batch size so XLA sees one
static shape (a recompile costs more than the padded FLOPs), and padded
rows are dropped (Predictor) or masked out of the metric sums (Evaluator).
"""

from __future__ import annotations

import io
from typing import Any, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.engine import AXIS_DATA
from bigdl_tpu.core.table import Table
from bigdl_tpu.dataset.feed import make_feed
from bigdl_tpu.dataset.minibatch import MiniBatch
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult

try:  # NamedSharding only matters when a mesh is supplied
    from jax.sharding import NamedSharding, PartitionSpec as P
except ImportError:  # pragma: no cover
    NamedSharding = None


def _as_batches(data: Any, batch_size: int) -> Iterable[MiniBatch]:
    """Accept ndarray / Table / list[Sample] / DataSet / iterable of MiniBatch."""
    if isinstance(data, MiniBatch):
        yield data
        return
    if isinstance(data, Table):  # one multi-input batch
        yield MiniBatch(data)
        return
    if isinstance(data, (np.ndarray, jnp.ndarray)):
        n = data.shape[0]
        for off in range(0, n, batch_size):
            yield MiniBatch(np.asarray(data[off:off + batch_size]))
        return
    if hasattr(data, "data") and callable(getattr(data, "data")):
        it = data.data(train=False)
        for item in it:
            if isinstance(item, MiniBatch):
                yield item
            else:
                raise TypeError(
                    "DataSet for prediction must yield MiniBatch; chain a "
                    "SampleToMiniBatch transformer")
        return
    buf: List[Sample] = []
    for item in data:
        if isinstance(item, MiniBatch):
            yield item
            continue
        buf.append(item)
        if len(buf) == batch_size:
            yield MiniBatch.from_samples(buf)
            buf = []
    if buf:
        yield MiniBatch.from_samples(buf)


def _to_device(x: Any) -> Any:
    if isinstance(x, Table):
        return Table(*[_to_device(v) for v in x])
    if isinstance(x, (list, tuple)):  # multi-input x / multi-output y
        return type(x)(_to_device(v) for v in x)
    return jax.device_put(np.asarray(x))  # explicit h2d, guard-friendly


def _batch_rows(x: Any) -> int:
    """Leading-dim row count for an array, Table, or tuple/list batch."""
    if isinstance(x, Table):
        return next(iter(x)).shape[0]
    if isinstance(x, (list, tuple)):
        return x[0].shape[0]
    return x.shape[0]


def _pad_batch(x: Any, to: int) -> Any:
    """Pad the batch (leading) dim to `to` rows by repeating the last row."""
    if isinstance(x, Table):
        return Table(*[_pad_batch(v, to) for v in x])
    if isinstance(x, (list, tuple)):
        return type(x)(_pad_batch(v, to) for v in x)
    x = np.asarray(x)
    n = x.shape[0]
    if n == to:
        return x
    pad = np.repeat(x[-1:], to - n, axis=0)
    return np.concatenate([x, pad], axis=0)


class Predictor:
    """Batched jitted inference (reference: optim/Predictor.scala:35-188).

    `mesh` shards the batch over the data axis; None = single chip.
    """

    def __init__(self, model: Module, params: Any, state: Any,
                 mesh=None, batch_size: int = 32,
                 prefetch_depth: Optional[int] = None):
        self.model = model
        self.params = params
        self.state = state
        self.mesh = mesh
        self.batch_size = int(batch_size)
        # batches this many stage-ahead H2D puts behind a DeviceFeed
        # worker (None = BIGDL_TPU_FEED_DEPTH, 0 = synchronous)
        self.prefetch_depth = prefetch_depth
        if mesh is not None:
            sharding = NamedSharding(mesh, P())
            self.params = jax.device_put(params, sharding)
            self.state = jax.device_put(state, sharding)
        else:
            # commit once at construction: host-resident leaves would
            # otherwise re-transfer on EVERY _fwd call (implicit h2d per
            # batch), which the strict transfer guard rejects
            self.params = jax.device_put(params)
            self.state = jax.device_put(state)

        model_ref = self.model

        def fwd(params, state, x):
            out, _ = model_ref.apply(params, state, x, training=False)
            return out

        self._fwd = jax.jit(fwd)

    def _put(self, x):
        if isinstance(x, Table):
            return Table(*[self._put(v) for v in x])
        if isinstance(x, (list, tuple)):  # keras multi-input batches
            return type(x)(self._put(v) for v in x)
        if self.mesh is None:
            return jax.device_put(np.asarray(x))
        return jax.device_put(jnp.asarray(x), NamedSharding(self.mesh, P(AXIS_DATA)))

    def predict(self, data: Any, batch_size: Optional[int] = None):
        """Returns stacked outputs for every input record; a multi-output
        model (Table output) returns a LIST of stacked arrays, one per
        head (reference: Predictor handles Table activities)."""
        bs = batch_size or self.batch_size
        outs: List[Any] = []
        multi = False

        def stage(batch):
            # pad-to-compiled-shape + H2D put, in the feed worker: the
            # next batch stages while the device runs the current forward
            x = batch.get_input()
            n = _batch_rows(x)
            xp = _pad_batch(x, bs) if n < bs else x
            return n, self._put(xp)

        depth = self.prefetch_depth
        if depth is None:
            from bigdl_tpu.core.engine import Engine

            depth = Engine.config().feed_depth
        with make_feed(_as_batches(data, bs), stage, depth,
                       name="DeviceFeed-predict") as feed:
            for item in feed:
                n, xd = item.payload
                y = self._fwd(self.params, self.state, xd)
                # slice on device and keep the handle: forwards dispatch
                # async back-to-back instead of host-syncing per batch
                if isinstance(y, (Table, list, tuple)):
                    multi = True
                    outs.append([h[:n] for h in y])
                else:
                    outs.append(y[:n])
        # the one sanctioned device->host pull of the whole predict
        outs = jax.device_get(outs)
        if multi:
            return [np.concatenate([o[i] for o in outs], axis=0)
                    for i in range(len(outs[0]))]
        return np.concatenate(outs, axis=0)

    def predict_class(self, data: Any, batch_size: Optional[int] = None):
        """argmax over the class dim (reference: Predictor.predictClass).
        Multi-output models return a list, one argmax array per head."""
        y = self.predict(data, batch_size)
        if isinstance(y, list):
            return [np.argmax(h, axis=-1) for h in y]
        return np.argmax(y, axis=-1)


LocalPredictor = Predictor  # single-chip is the mesh=None case


class Evaluator:
    """Distributed evaluation (reference: optim/Evaluator.scala:40-95).

    Per-batch metric sums are jitted (with a padded-row mask folded in by
    evaluating only the first n rows' contributions via a weight vector);
    results merge with ValidationResult.+ exactly like the reference's RDD
    reduce.
    """

    def __init__(self, model: Module, mesh=None):
        self.model = model
        self.mesh = mesh
        self._step = None
        self._step_key = None

    def _build(self, methods: Sequence[ValidationMethod]):
        # cache the jitted step across test() calls (keyed on the method
        # objects): re-tracing per evaluate() would pay a full XLA
        # recompile in monitoring loops
        key = tuple(id(m) for m in methods)
        if self._step is not None and self._step_key == key:
            return self._step
        model = self.model

        def step(params, state, x, y):
            out, _ = model.apply(params, state, x, training=False)
            return [m.batch(out, y) for m in methods]

        self._step = jax.jit(step)
        self._step_key = key
        return self._step

    def test(self, params: Any, state: Any, data: Any,
             methods: Sequence[ValidationMethod],
             batch_size: int = 32) -> List[ValidationResult]:
        step = self._build(methods)
        if self.mesh is not None:
            sharding = NamedSharding(self.mesh, P())
            params = jax.device_put(params, sharding)
            state = jax.device_put(state, sharding)
        totals: List[Optional[Any]] = [None] * len(methods)
        for batch in _as_batches(data, batch_size):
            x, y = batch.get_input(), batch.get_target()
            n = _batch_rows(x)
            if n < batch_size:
                # evaluate the ragged tail unpadded (and unsharded); metric
                # sums would count repeated pad rows otherwise.  One extra
                # compile at most.
                pairs = step(params, state, _to_device(x), _to_device(y))
            else:
                xp = self._put_batch(x)
                yp = self._put_batch(y)
                pairs = step(params, state, xp, yp)
            # accumulate (sum, count) ON DEVICE — to_result per batch
            # would host-sync O(N) times; the adds dispatch async
            for i, (v, c) in enumerate(pairs):
                tv, tc = totals[i] if totals[i] is not None else (0.0, 0)
                totals[i] = (tv + v, tc + c)
        done = [(i, t) for i, t in enumerate(totals) if t is not None]
        # single end-of-eval transfer; ValidationResult.+ is plain
        # addition, so summing device scalars first is equivalent
        host = jax.device_get([t for _, t in done])
        return [methods[i].to_result(v, c)
                for (i, _), (v, c) in zip(done, host)]

    def _put_batch(self, x):
        from bigdl_tpu.optim.optimizer import put_batch_array

        if isinstance(x, Table):
            return Table(*[self._put_batch(v) for v in x])
        if isinstance(x, (tuple, list)):  # multi-io batches
            return type(x)(self._put_batch(v) for v in x)
        sh = None if self.mesh is None \
            else NamedSharding(self.mesh, P(AXIS_DATA))
        return put_batch_array(x, sh)


class PredictionService:
    """Concurrent serving facade (reference: optim/PredictionService.scala:56).

    Since the serving subsystem landed this is a THIN compatibility facade
    over `bigdl_tpu.serving.ServingRuntime`: same constructor and
    predict/predict_bytes surface, but concurrent requests now coalesce
    into bucketed fixed-shape micro-batches (one jitted forward per
    bucket) instead of each running alone.  The reference pooled N module
    clones in a LinkedBlockingQueue because its modules cache activations;
    here `concurrency` survives as an admission-queue sizing hint only.

    New-code path: use `bigdl_tpu.serving.ServingRuntime` directly (hot
    swap, deadlines, metrics — docs/serving.md).
    """

    def __init__(self, model: Module, params: Any, state: Any,
                 concurrency: int = 4, batch_size: int = 1,
                 buckets: Optional[Sequence[int]] = None,
                 max_wait_ms: float = 2.0):
        from bigdl_tpu.serving import ServingConfig, ServingRuntime

        if buckets is None:
            # cover the legacy per-request batch size plus the coalescing
            # sweet spots, deduped (e.g. batch_size=8 -> (1, 8, 32))
            buckets = tuple(sorted({1, int(batch_size), 8, 32}))
        self.runtime = ServingRuntime(
            model, params, state,
            config=ServingConfig(buckets=buckets, max_wait_ms=max_wait_ms,
                                 capacity=max(16, int(concurrency) * 16)))

    def predict(self, x: Any) -> np.ndarray:
        return self.runtime.predict(
            x if isinstance(x, Table) else np.asarray(x))

    def close(self, drain: bool = True) -> None:
        self.runtime.close(drain=drain)

    # Byte-array request/response API (reference: PredictionService.scala:79-128
    # serves protobuf-serialized activities; here the wire format is npz).
    def predict_bytes(self, request: bytes) -> bytes:
        with np.load(io.BytesIO(request)) as npz:
            # npz.files preserves savez insertion order; sorting would
            # scramble arr_10 before arr_2.
            arrays = [npz[k] for k in npz.files]
        x = arrays[0] if len(arrays) == 1 else Table(*arrays)
        y = self.predict(x)
        out = io.BytesIO()
        if isinstance(y, list):  # multi-output model: one entry per head
            np.savez(out, **{f"output_{i}": h for i, h in enumerate(y)})
        else:
            np.savez(out, output=y)
        return out.getvalue()


class Validator(Evaluator):
    """Deprecated-name parity (reference: optim/Validator.scala, superseded
    by Evaluator there).  The legacy form Validator(model, dataset) is
    rejected with a pointer to the current API instead of silently binding
    the dataset to the mesh argument."""

    def __init__(self, model, mesh=None):
        from bigdl_tpu.dataset.dataset import DataSet

        if isinstance(mesh, DataSet):
            raise TypeError(
                "Validator(model, dataset) is the deprecated reference API; "
                "construct Validator(model) and call "
                ".test(params, state, dataset, methods) (Evaluator API)")
        super().__init__(model, mesh=mesh)
