"""Per-parameter weight regularization.

Reference: optim/Regularizer.scala — `L1Regularizer`/`L2Regularizer`/
`L1L2Regularizer` attached to individual layers as `wRegularizer`/
`bRegularizer`; their contribution is added to the parameter's gradient
inside `accGradParameters` (gradWeight += l2*w + l1*sign(w)).

TPU design: layers store the regularizer objects (`w_regularizer`/
`b_regularizer` kwargs); the Optimizer collects them with
`collect_regularizers` (a walk mirroring the params tree) and adds
`reg.grad(param)` to the matching gradient leaf inside the jitted train
step — the same gradient-side semantics, fused by XLA into the update.
"""

from __future__ import annotations

import logging
from typing import Any, List, Tuple

import jax.numpy as jnp

logger = logging.getLogger("bigdl_tpu.optim")


class Regularizer:
    """reference: optim/Regularizer.scala (trait Regularizer)."""

    l1: float = 0.0
    l2: float = 0.0

    def grad(self, p):
        """d(penalty)/dp — what accGradParameters adds to the gradient."""
        g = jnp.zeros_like(p)
        if self.l1:
            g = g + self.l1 * jnp.sign(p)
        if self.l2:
            g = g + self.l2 * p
        return g

    def penalty(self, p):
        """The scalar loss term (for reporting; the trainer uses grad())."""
        val = 0.0
        if self.l1:
            val = val + self.l1 * jnp.sum(jnp.abs(p))
        if self.l2:
            val = val + 0.5 * self.l2 * jnp.sum(jnp.square(p))
        return val

    def __repr__(self):
        return f"{type(self).__name__}(l1={self.l1}, l2={self.l2})"


class L1L2Regularizer(Regularizer):
    def __init__(self, l1: float, l2: float):
        self.l1 = float(l1)
        self.l2 = float(l2)


class L1Regularizer(L1L2Regularizer):
    def __init__(self, l1: float):
        super().__init__(l1, 0.0)


class L2Regularizer(L1L2Regularizer):
    def __init__(self, l2: float):
        super().__init__(0.0, l2)


_SLOTS = (("w_regularizer", "weight"), ("b_regularizer", "bias"))


def collect_regularizers(model) -> List[Tuple[Tuple[str, ...], str, Regularizer]]:
    """Walk the module tree (mirroring build()'s params keys) and return
    [(path, param_key, regularizer)] for every attached regularizer.

    Only `children`-held submodules map onto param paths the trainer can
    address; a regularizer on an attribute-held submodule (e.g. a custom
    Module keeping `self.fc = Linear(...)` outside `children`) would be
    silently inert, so it is reported loudly instead.
    """
    out: List[Tuple[Tuple[str, ...], str, Regularizer]] = []
    covered = set()

    def walk(m, path):
        covered.add(id(m))
        for attr, key in _SLOTS:
            reg = getattr(m, attr, None)
            if reg is not None:
                out.append((path, key, reg))
        children = getattr(m, "children", None)
        if children:
            for k, child in children.items():
                walk(child, path + (k,))

    walk(model, ())

    # second pass: find attribute-held submodules the children walk cannot
    # reach, and warn if they carry regularizers (which would be inert)
    def scan_attrs(m, seen):
        if id(m) in seen:
            return
        seen.add(id(m))
        for v in list(vars(m).values()):
            vals = v if isinstance(v, (list, tuple)) else \
                (list(v.values()) if isinstance(v, dict) else [v])
            for item in vals:
                if not hasattr(item, "apply") or not hasattr(item, "build"):
                    continue  # not a Module
                if id(item) not in covered:
                    for attr, _ in _SLOTS:
                        if getattr(item, attr, None) is not None:
                            logger.warning(
                                "%s on %r is unreachable through the children "
                                "tree and will NOT be applied (hold the layer "
                                "in a container, not as a plain attribute)",
                                attr, item.name)
                scan_attrs(item, seen)

    scan_attrs(model, set())
    return out


def apply_regularizers(grads: Any, params: Any, regs) -> Any:
    """grads[path][key] += reg.grad(params[path][key]) for each entry.
    A missing param KEY (e.g. with_bias=False dropping 'bias') is fine —
    the reference's null-gradWeight guard; a missing PATH means the module
    tree and params tree disagree (e.g. scan-stacked layers renaming keys)
    and is reported, since the regularizer would silently not apply."""
    for path, key, reg in regs:
        g = grads
        p = params
        ok = True
        for part in path:
            if not (isinstance(g, dict) and part in g):
                ok = False
                break
            g = g[part]
            p = p[part]
        if not ok:
            logger.warning("regularizer path %s not found in params tree; "
                           "not applied", "/".join(path))
            continue
        if not isinstance(g, dict) or key not in g:
            continue  # e.g. with_bias=False
        g[key] = g[key] + reg.grad(p[key])
    return grads
