"""Per-layer timing and trace capture.

Reference (survey §5.1): AbstractModule.forward/backward accumulate
per-layer wall time (`forwardTime`/`backwardTime`,
nn/abstractnn/AbstractModule.scala:254-288), exposed via `getTimes()`;
DistriOptimizer feeds `moduleTimeList` into straggler detection; plus the
driver-side Metrics registry (optim/Metrics.scala).

TPU redesign: inside one jitted step there are no per-layer host
timestamps — XLA fuses across layer boundaries.  The honest equivalents:

  * `layer_times(model, ...)` — an offline attribution harness: each child
    of a Sequential chain is jitted and timed in isolation (forward and
    VJP), which is what per-layer wall times mean on an accelerator.
  * `profiler_trace(log_dir)` — a context manager over `jax.profiler`
    producing xplane traces for TensorBoard, the real production profiling
    path (replaces the reference's "no sampling profiler" gap upward).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class LayerTime(NamedTuple):
    name: str
    forward_s: float
    backward_s: float


def _sync(x) -> None:
    # through the remote-TPU tunnel block_until_ready can return before
    # execution finishes; a host readback is the only real sync
    leaf = jax.tree_util.tree_leaves(x)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))


def layer_times(model: Module, params: Any, state: Any, x: Any, *,
                training: bool = False, iters: int = 5,
                warmup: int = 2) -> List[LayerTime]:
    """Time each child of a Sequential-style chain (reference: getTimes).

    Returns one (name, forward_s, backward_s) entry per child, averaged
    over `iters` runs after `warmup`.  backward_s is the VJP time for
    children with parameters (0.0 for parameter-free layers whose backward
    fuses away).
    """
    if not getattr(model, "children", None):
        raise ValueError("layer_times needs a container with children "
                         "(Sequential or models built from one)")
    warmup = max(warmup, 1)  # at least one run to compile (and to bind y/g)
    results: List[LayerTime] = []
    act = x
    for key, child in model.children.items():
        p, s = params.get(key, {}), state.get(key, {})

        fwd = jax.jit(lambda p_, a, _c=child, _s=s:
                      _c.apply(p_, _s, a, training=training)[0])
        for _ in range(warmup):
            y = fwd(p, act)
        _sync(y)
        t0 = time.perf_counter()
        for _ in range(iters):
            y = fwd(p, act)
        _sync(y)
        f_t = (time.perf_counter() - t0) / iters

        b_t = 0.0
        if jax.tree_util.tree_leaves(p):
            def loss(p_, a, _c=child, _s=s):
                out, _ = _c.apply(p_, _s, a, training=training)
                return jnp.sum(out.astype(jnp.float32))

            bwd = jax.jit(jax.grad(loss, argnums=(0, 1)))
            for _ in range(warmup):
                g = bwd(p, act)
            _sync(g)
            t0 = time.perf_counter()
            for _ in range(iters):
                g = bwd(p, act)
            _sync(g)
            b_t = (time.perf_counter() - t0) / iters

        results.append(LayerTime(child.name, f_t, b_t))
        act = y  # feed the next layer this layer's (last) output
    return results


def summarize(times: List[LayerTime]) -> str:
    """Human-readable table, slowest first (reference: getTimes dumps)."""
    total = sum(t.forward_s + t.backward_s for t in times) or 1.0
    lines = [f"{'layer':<28} {'fwd ms':>9} {'bwd ms':>9} {'%':>6}"]
    for t in sorted(times, key=lambda t: -(t.forward_s + t.backward_s)):
        pct = 100.0 * (t.forward_s + t.backward_s) / total
        lines.append(f"{t.name:<28} {t.forward_s * 1e3:>9.3f} "
                     f"{t.backward_s * 1e3:>9.3f} {pct:>5.1f}%")
    return "\n".join(lines)


@contextlib.contextmanager
def profiler_trace(log_dir: str):
    """jax.profiler xplane trace for TensorBoard (survey §5.1's "TPU
    equivalent: jax profiler/xplane traces").  Degrades to a no-op if the
    backend can't trace (e.g. tunneled devices)."""
    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:  # pragma: no cover - backend-dependent
        pass
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover
                pass
