"""Named per-phase metrics.

Reference: optim/Metrics.scala:31-103 — Spark-accumulator-backed named
counters ("computing time average", "put gradient", ...) summarized per
iteration.  Here there is no cross-process accumulation to do (the train
step is one compiled program), so Metrics is a host-side registry of named
timers/counters feeding the driver log and TrainSummary.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict


class Metrics:
    def __init__(self):
        self._sums: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)

    def add(self, name: str, value: float) -> None:
        self._sums[name] += value
        self._counts[name] += 1

    def set(self, name: str, value: float) -> None:
        self._sums[name] = value
        self._counts[name] = 1

    def get(self, name: str) -> float:
        c = self._counts[name]
        return self._sums[name] / c if c else 0.0

    def summary(self) -> str:
        parts = [f"{k}: {self.get(k):.6g}" for k in sorted(self._sums)]
        return "[" + ", ".join(parts) + "]"

    class Timer:
        def __init__(self, metrics: "Metrics", name: str):
            self.metrics = metrics
            self.name = name

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.metrics.add(self.name, time.perf_counter() - self.t0)

    def timer(self, name: str) -> "Metrics.Timer":
        return Metrics.Timer(self, name)
