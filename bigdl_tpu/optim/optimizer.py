"""Optimizer — the training loop.

Reference: optim/Optimizer.scala:47 (builder API: setValidation,
setCheckpoint, setTrainSummary, setOptimMethod, setEndWhen,
setGradientClipping; factory picks DistriOptimizer vs LocalOptimizer from
the DataSet type, :602-697) and optim/DistriOptimizer.scala:49 (the
distributed trainer detailed in survey §3.2).

TPU redesign — the core claim of this framework: BigDL's entire two-Spark-
jobs-per-iteration structure (broadcast weights -> per-core fwd/bwd ->
fp16 BlockManager shuffle -> sharded update -> republish) collapses into
ONE jitted train step over a device mesh:

  * batch arrays are device_put with a `data`-axis NamedSharding;
  * params/optimizer slots are replicated; XLA inserts the gradient
    all-reduce where sharding propagation demands it (the
    AllReduceParameter, parameters/AllReduceParameter.scala:84, is gone);
  * fp16 wire compression is the bf16 dtype policy;
  * `subModelNumber` intra-node replicas = the data-axis shards;
  * straggler dropping (DistriOptimizer.scala:177-183) is meaningless on a
    synchronous mesh — documented capability delta.

LocalOptimizer and DistriOptimizer share this loop; they differ only in
mesh (single device vs Engine.mesh()).  Failure retry from the latest
checkpoint matches optim/DistriOptimizer.scala:855-935.
"""

from __future__ import annotations

import logging
import sys
import time
from collections import deque
from contextlib import nullcontext
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu import obs as _obs
from bigdl_tpu.core.engine import AXIS_DATA, Engine
from bigdl_tpu.core.random import RandomGenerator
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.feed import make_feed
from bigdl_tpu.dataset.minibatch import MiniBatch
from bigdl_tpu.nn.criterion import Criterion
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.optim.parameter_processor import (
    ConstantClippingProcessor,
    L2NormClippingProcessor,
    ParameterProcessor,
)
from bigdl_tpu.optim.regularizer import apply_regularizers, collect_regularizers
from bigdl_tpu.optim.schedules import Plateau
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult
from bigdl_tpu.health.integrity import verify_enabled as _ckpt_verify_enabled
from bigdl_tpu.health.watchdog import (
    DivergenceAbort,
    DivergenceWatchdog,
    HangWatchdog,
    NumericDivergence,
    WatchdogConfig,
)
from bigdl_tpu.resilience.async_ckpt import AsyncCheckpointer
from bigdl_tpu.analysis.runtime import strict_transfers, strict_transfers_enabled
from bigdl_tpu.resilience.chaos import POISON_GRAD, POISON_LOSS
from bigdl_tpu.resilience.preemption import Preempted, clear_marker, write_marker
from bigdl_tpu.utils.checkpoint import latest_checkpoint, load_checkpoint, save_checkpoint
from bigdl_tpu.utils.summary import TrainSummary, ValidationSummary

logger = logging.getLogger("bigdl_tpu.optim")


# fixed-structure driver-loop helpers, compiled once per structure/backend:
# eager equivalents pay per-op dispatch every step (fold_in) or a fresh
# XLA compile per burst length (stack) — measured as the dominant loop
# overhead in benchmarks/bench_trainer_overhead.py
_fold_in = jax.jit(jax.random.fold_in)


def _put_scalar(v, dtype=np.int32, sharding=None):
    """Explicit h2d put for per-step driver scalars (step index, ring slot).

    The transfer itself is not new — jit argument canonicalization was
    already putting these Python ints every step.  Making it explicit
    keeps the strict transfer guard (analysis.runtime) quiet and pins
    the dtype so the first call doesn't retrace on weak-typed ints.
    Under a mesh, pass the replicated sharding so the scalar lands on
    every device up front — consumers like _ring_write take mesh-resident
    operands, and an implicit single-device→mesh broadcast at dispatch
    would trip strict_transfers."""
    if sharding is None:
        return jax.device_put(dtype(v))
    return jax.device_put(dtype(v), sharding)


@jax.jit
def _ring_write(ring, slot, loss, lr):
    """Append (loss, lr) into the device-side telemetry ring.

    The drain reads the ring SNAPSHOT of a step that has already executed
    (depth/2 behind the dispatch head) — one small transfer with no queue
    wait.  Running any packing program at drain time instead would
    enqueue it BEHIND the in-flight steps on the in-order device: each
    drain then stalls for queue_depth x step_time (measured 1.3 s per
    drain at depth 32 on the 100 ms tunnel — the whole batching win
    eaten).  NOT donated: pending holds per-step snapshots."""
    entry = jnp.stack([loss.astype(jnp.float32), lr.astype(jnp.float32)])
    return ring.at[slot].set(entry)


@jax.jit
def _ring_write_h(ring, slot, loss, lr, health):
    """3-column ring writer for the watchdog path: (loss, lr, healthy).

    A separate jitted function (not a width-polymorphic _ring_write) so
    the watchdog-OFF hot loop keeps its exact existing program — zero
    overhead when the feature is disabled.  Same no-packing-at-drain
    rules as _ring_write."""
    entry = jnp.stack([loss.astype(jnp.float32), lr.astype(jnp.float32),
                       health.astype(jnp.float32)])
    return ring.at[slot].set(entry)


def _gate_tree(healthy, new, old):
    """Device-side skip: keep `new` where the step was healthy, `old`
    otherwise (the watchdog's skip_batch rung — the bad update never
    lands, no host round-trip involved).  `healthy` is a traced bool
    scalar; where() broadcasts it over every leaf."""
    if new is None:
        return None
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(healthy, n, o), new, old)


def _finish_step_health(loss_fn, params, model_state, opt_state, lr,
                        lr_scale, poison, optim, processors, regs, host_lr):
    """Shared tail of every watchdog-enabled train step: poison -> grads
    -> finite check on loss + grad global-norm -> gated update.

    ONE extra f32 (the health flag) rides the telemetry ring; detection
    is pure device math, so the strict transfer guard stays silent.  The
    optimizer's step counter still advances on a skipped step — the
    device neval must stay aligned with the driver's, or the per-step
    rng folding would fork after the first skip."""
    (loss, new_model_state), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params)
    # chaos: NaNInjector's device-side poison.  The loss poison is
    # additive-constant wrt params (grads stay finite; detection is the
    # loss isfinite); the grad poison lands on every leaf post-autodiff
    # (loss stays finite; detection is the gnorm isfinite).
    loss = loss + jnp.where(poison == POISON_LOSS,
                            jnp.float32(jnp.nan), jnp.float32(0.0))
    bad_g = jnp.where(poison == POISON_GRAD,
                      jnp.float32(jnp.nan), jnp.float32(0.0))
    grads = jax.tree_util.tree_map(
        lambda g: g + bad_g.astype(g.dtype), grads)
    grads = apply_regularizers(grads, params, regs)
    for proc in processors:
        grads = proc.process(grads)
    # global grad norm (squared; the sqrt adds nothing to a finite check)
    gnorm_sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                   for g in jax.tree_util.tree_leaves(grads))
    healthy = jnp.isfinite(loss) & jnp.isfinite(gnorm_sq)
    # lr_backoff rung: a device-side scale on the effective lr, updated
    # by re-putting ONE scalar — no recompile, no per-step transfer
    lr_eff = (lr if host_lr else optim.current_lr(opt_state)) * lr_scale
    new_params, new_opt_state = optim.step(grads, params, opt_state,
                                           lr=lr_eff)
    new_params = _gate_tree(healthy, new_params, params)
    new_model_state = _gate_tree(healthy, new_model_state, model_state)
    new_opt_state = _gate_tree(healthy, new_opt_state, opt_state)
    # the counter advances even on a skip (see docstring)
    new_opt_state = dict(new_opt_state, neval=opt_state["neval"] + 1)
    return (new_params, new_model_state, new_opt_state, loss, lr_eff,
            healthy.astype(jnp.float32))


_NULLCTX = nullcontext()  # reusable: hot paths must not allocate one per use


def _phase(hang, name):
    """Hang-watchdog phase bracket, or a free nullcontext when disabled."""
    return hang.phase(name) if hang is not None else _NULLCTX


def _guarded_iter(feed, hang, tr=None):
    """Iterate the feed with each blocking __next__ under the hang
    watchdog's `feed_next` phase: a wedged assembly worker (or a source
    that stops producing) raises StalledStep into the step loop instead
    of parking it forever.  The in-between consumer work is NOT in the
    phase — only the waits are on the clock.  `tr` (obs.SpanTracer, or
    None when tracing is off) records the same waits as `feed_next`
    spans on the consumer lane."""
    it = iter(feed)
    while True:
        with _phase(hang, "feed_next"), \
                (tr.span("feed_next", cat="trainer") if tr is not None
                 else _NULLCTX):
            try:
                item = next(it)
            except StopIteration:
                return
        yield item


_warned_shard_equiv = [False]


def put_batch_array(arr, sh):
    """Place one batch array under sharding `sh` (None = single device).

    Device-resident batches with an EQUIVALENT layout are returned as-is:
    device_put to a merely differently-expressed sharding
    (SingleDeviceSharding vs a 1-shard NamedSharding) is a real per-step
    on-device copy (~1s/step for a b256 batch through the remote tunnel,
    measured).  Global jax.Arrays never round-trip through np.asarray —
    they reshard on device; host arrays go through
    make_array_from_process_local_data under multi-process."""
    if sh is None:
        return jnp.asarray(arr)
    if isinstance(arr, jax.Array):
        try:
            if arr.sharding.is_equivalent_to(sh, arr.ndim):
                return arr
        except (AttributeError, TypeError):
            if not _warned_shard_equiv[0]:
                _warned_shard_equiv[0] = True
                logger.warning(
                    "sharding equivalence check unavailable on this jax "
                    "version; device-resident batches will be re-put "
                    "every step (a per-step on-device copy)")
        return jax.device_put(arr, sh)
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(sh, np.asarray(arr))
    return jax.device_put(jnp.asarray(arr), sh)


def _cast_floats(tree, dtype):
    """astype(dtype) on floating leaves, everything else untouched."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
        else a, tree)


class Optimizer:
    """Builder + training loop. reference: optim/Optimizer.scala:47."""

    def __init__(self, model: Module, dataset: DataSet, criterion: Criterion,
                 optim_method: Optional[OptimMethod] = None,
                 mesh: Optional[Mesh] = None,
                 end_trigger: Optional[Trigger] = None,
                 sharding_rules: Optional["ShardingRules"] = None,
                 batch_partition: Optional[P] = None,
                 compute_dtype: Optional[Any] = None):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.optim_method = optim_method or SGD()
        self.mesh = mesh
        # Mixed-precision policy: compute_dtype (e.g. jnp.bfloat16 or
        # "bfloat16") runs forward/backward in that dtype while params,
        # optimizer slots and BN running stats stay fp32 masters — the
        # MXU-native policy bench.py measures, now a public builder
        # feature.  The criterion always sees fp32 outputs.  Replaces the
        # reference's fp16 wire compression, which was a bandwidth policy
        # (parameters/FP16CompressedTensor.scala:30-60), with a compute
        # policy the hardware rewards.
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype is not None else None)
        # tensor/sequence/expert parallelism through the SAME builder entry
        # (reference keeps one entry point for all training,
        # optim/Optimizer.scala:47): `sharding_rules` maps parameter paths
        # to PartitionSpecs (parallel/sharding.py), `batch_partition`
        # overrides the default P('data') batch layout (e.g.
        # P('data','sequence') for sequence-parallel token batches)
        self.sharding_rules = sharding_rules
        self.batch_partition = batch_partition
        self.end_when = end_trigger or Trigger.max_epoch(1)
        # validation
        self.val_trigger: Optional[Trigger] = None
        self.val_dataset: Optional[DataSet] = None
        self.val_methods: Optional[List[ValidationMethod]] = None
        # checkpoint (async writer + retention: bigdl_tpu/resilience)
        self.ckpt_path: Optional[str] = None
        self.ckpt_trigger: Optional[Trigger] = None
        self.ckpt_async: Optional[bool] = None  # None = Engine config
        self.ckpt_keep_last: Optional[int] = None
        self.ckpt_keep_every: Optional[int] = None
        self.ckpt_layout: Optional[str] = None  # None = Engine config
        self._ckpt_writer: Optional[AsyncCheckpointer] = None
        # fault tolerance: bounded restarts with exponential backoff
        self.max_restarts: Optional[int] = None  # None = Engine config
        self.backoff_base_s: Optional[float] = None
        self._preempt_guard = None
        self._chaos = None
        self._ckpt_fault = None
        self._ckpt_corrupt = None
        self._resume_skip = 0  # batches of the current epoch already trained
        # numeric-divergence watchdog (bigdl_tpu.health): None = follow
        # BIGDL_TPU_WATCHDOG, False = forced off, WatchdogConfig = on.
        # The DivergenceWatchdog instance persists across in-process
        # restarts: the marked bad-step set and the rollback budget must
        # outlive the trajectory they rolled back.
        self._watchdog_cfg: Any = None
        self._watchdog: Optional[DivergenceWatchdog] = None
        self._hang: Optional[HangWatchdog] = None
        # summaries
        self.train_summary: Optional[TrainSummary] = None
        self.val_summary: Optional[ValidationSummary] = None
        # input feed: None = Engine.config().feed_depth; 0 = synchronous
        self.feed_depth: Optional[int] = None
        # disaggregated readers: None = Engine.config().reader_procs;
        # 0 = in-thread assembly (dataset/readers.py)
        self.reader_procs: Optional[int] = None
        self.reader_autoscale: Optional[bool] = None
        # strict-transfer debug guard: None = BIGDL_TPU_STRICT_TRANSFERS
        self._strict_transfers: Optional[bool] = None
        # gradient processing
        self.processors: List[ParameterProcessor] = []
        # state — adopt weights already on the model so repeated fit()s
        # continue training instead of silently re-initializing (Keras fit
        # is incremental; reference fit reuses the trained module in place)
        self.params = getattr(model, "params", None)
        self.model_state = getattr(model, "state", None)
        self._adopted_params = self.params is not None
        self.opt_state = None
        self.metrics = Metrics()
        self._n_params: Optional[int] = None  # cached for the MFU gauge
        self._compiled = None
        self._compiled_key = None
        # AOT executables resolved through bigdl_tpu.compilecache (None
        # when the cache is off: dispatch then calls the plain jit fn)
        self._aot_steps: Dict[Any, Any] = {}
        self._aot_eval = None
        self._aot_eval_key = None
        self._driver_state: Dict[str, Any] = {"epoch": 0, "neval": 0, "loss": None,
                                              "score": None, "epoch_finished": False,
                                              "epoch_batch": 0}

    # ------------------------------------------------------------------
    # Builder API (reference: optim/Optimizer.scala:111-452)
    # ------------------------------------------------------------------

    def set_validation(self, trigger: Trigger, dataset: DataSet,
                       methods: Sequence[ValidationMethod]) -> "Optimizer":
        self.val_trigger = trigger
        self.val_dataset = dataset
        self.val_methods = list(methods)
        return self

    def set_checkpoint(self, path: str, trigger: Trigger, *,
                       async_save: Optional[bool] = None,
                       keep_last: Optional[int] = None,
                       keep_every: Optional[int] = None,
                       layout: Optional[str] = None) -> "Optimizer":
        """Trigger-driven checkpoints under `path`.

        `async_save` (default `BIGDL_TPU_CKPT_ASYNC`, on): the step loop
        pays only an on-device snapshot; transfer + atomic commit run in
        the bounded AsyncCheckpointer writer thread.  False restores the
        synchronous in-loop save; multi-process runs are always
        synchronous (the save is a collective).  `keep_last`/`keep_every`
        set the retention policy (resilience.apply_retention).

        `layout` (default `BIGDL_TPU_CKPT_LAYOUT`, "chunked"): the v2
        sharded layout — per-shard chunk files with a mesh descriptor and
        per-chunk CRCs, host memory bounded by one chunk, restorable onto
        a DIFFERENT topology (a run killed on N chips resumes on M) —
        or "monolithic" for the v1 per-tree .npz.  Restore accepts both,
        so the knob only affects new saves."""
        self.ckpt_path = path
        self.ckpt_trigger = trigger
        self.ckpt_async = async_save
        self.ckpt_keep_last = keep_last
        self.ckpt_keep_every = keep_every
        self.ckpt_layout = layout
        return self

    def set_fault_tolerance(self, max_restarts: Optional[int] = None,
                            backoff_base_s: Optional[float] = None) -> "Optimizer":
        """Bound the failure-restart loop: up to `max_restarts` restores
        from the latest committed checkpoint, sleeping
        `backoff_base_s * 2^attempt` (capped at the config's
        failure_retry_interval_s) between attempts.  Defaults come from
        `BIGDL_TPU_FAILURE_RETRY_TIMES` / `BIGDL_TPU_BACKOFF_BASE_S`."""
        if max_restarts is not None:
            self.max_restarts = int(max_restarts)
        if backoff_base_s is not None:
            self.backoff_base_s = float(backoff_base_s)
        return self

    def set_preemption(self, guard: Any = True) -> "Optimizer":
        """Cooperative preemption handling: SIGTERM/SIGINT (or the
        `BIGDL_TPU_PREEMPT_FILE` poll) stop training at the next batch
        boundary with one final synchronous checkpoint, a resumable
        `PREEMPTED.json` marker, and a `Preempted` exception — instead of
        dying mid-step.  Pass a configured
        `resilience.PreemptionGuard`, True for the default, or False/None
        to disable."""
        if guard is True:
            from bigdl_tpu.resilience.preemption import PreemptionGuard

            guard = PreemptionGuard(
                preempt_file=Engine.config().preempt_file)
        self._preempt_guard = guard or None
        return self

    def set_strict_transfers(self, flag: bool = True) -> "Optimizer":
        """Debug guard: wrap the per-step dispatch section (and validate's
        per-batch eval) in `jax.transfer_guard("disallow")` so any
        implicit device transfer a future change sneaks into the hot loop
        raises at the offending line instead of silently serializing the
        pipeline.  Default (None) follows `BIGDL_TPU_STRICT_TRANSFERS`;
        the guard is thread-local and does not affect the DeviceFeed
        worker's deliberate H2D staging.  See docs/analysis.md."""
        self._strict_transfers = flag
        return self

    def set_chaos(self, hook: Any = None, *, ckpt_fault: Any = None,
                  ckpt_corrupt: Any = None) -> "Optimizer":
        """Deterministic fault injection (tests/benchmarks only):
        `hook.on_step(neval)` runs before every step dispatch and may
        raise (resilience.chaos.StepFaultInjector) or trigger the
        preemption guard (SimulatedPreemption); a hook exposing
        `poison_code(step)` (NaNInjector) poisons the step's numerics ON
        DEVICE when the watchdog is enabled.  `ckpt_fault` is passed to
        the AsyncCheckpointer as its write-fault hook; `ckpt_corrupt`
        (BitFlipCheckpointFault) as its post-commit hook."""
        self._chaos = hook
        self._ckpt_fault = ckpt_fault
        self._ckpt_corrupt = ckpt_corrupt
        return self

    def set_watchdog(self, config: Any = True) -> "Optimizer":
        """Numeric-divergence watchdog (bigdl_tpu.health): a finite check
        on loss + gradient global-norm folded into the jitted step (one
        extra f32 in the telemetry ring, zero added host syncs), with the
        policy ladder skip_batch -> lr_backoff -> rollback_to_last_good
        -> abort.  Rollback restores the newest checkpoint STAMPED
        healthy (meta.json watchdog verdict) through the fault-tolerance
        machinery and marks the offending step range so the replay skips
        it without re-escalating.  Pass a `health.WatchdogConfig`, True
        for defaults, or False to force off; default (unset) follows
        `BIGDL_TPU_WATCHDOG`.  See docs/training.md "Numeric health"."""
        if config is False or config is None:
            self._watchdog_cfg = False
            self._watchdog = None
        elif config is True:
            self._watchdog_cfg = WatchdogConfig()
        else:
            self._watchdog_cfg = config
        self._compiled = None  # the step signature changes with the flag
        self._compiled_key = None
        return self

    def _watchdog_enabled(self) -> bool:
        if self._watchdog_cfg is None:
            return bool(Engine.config().watchdog)
        return self._watchdog_cfg is not False

    def _ensure_watchdog(self) -> Optional[DivergenceWatchdog]:
        if not self._watchdog_enabled():
            return None
        if self._watchdog is None:
            cfg = self._watchdog_cfg \
                if isinstance(self._watchdog_cfg, WatchdogConfig) \
                else WatchdogConfig()
            self._watchdog = DivergenceWatchdog(cfg)
        return self._watchdog

    def set_train_summary(self, summary: TrainSummary) -> "Optimizer":
        self.train_summary = summary
        return self

    def set_val_summary(self, summary: ValidationSummary) -> "Optimizer":
        self.val_summary = summary
        return self

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        return self

    def set_gradient_clipping_by_value(self, min_value: float, max_value: float) -> "Optimizer":
        self.processors.append(ConstantClippingProcessor(min_value, max_value))
        return self

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float) -> "Optimizer":
        self.processors.append(L2NormClippingProcessor(clip_norm))
        return self

    def disable_gradient_clipping(self) -> "Optimizer":
        self.processors = []
        return self

    def set_feed(self, prefetch_depth: Optional[int] = None,
                 reader_procs: Optional[int] = None,
                 reader_autoscale: Optional[bool] = None) -> "Optimizer":
        """Input-feed wiring: prefetch depth and the reader-process pool.

        `prefetch_depth` — how many batches the DeviceFeed worker
        assembles and stages on the mesh AHEAD of the step loop,
        overlapping host collate + H2D transfer with in-flight device
        compute (dataset/feed.py).  0 forces synchronous staging (the
        bitwise-identical baseline); default comes from
        `BIGDL_TPU_FEED_DEPTH` (2).

        `reader_procs` — batch ASSEMBLY moves into this many reader
        processes (dataset/readers.py), feeding the same DeviceFeed
        staging path through the reorder stage.  0 keeps assembly
        in-thread; default comes from `BIGDL_TPU_READER_PROCS` (0).
        `reader_autoscale` turns the stall-driven autoscaler on/off
        within [1, reader_procs] (`BIGDL_TPU_READER_AUTOSCALE`, on).

        Batch order, RNG folding and losses are identical under every
        combination — the feed/readers only move WHERE the assembly and
        staging work runs (datasets whose assembly cannot be
        disaggregated silently keep the in-thread path)."""
        if prefetch_depth is not None:
            self.feed_depth = int(prefetch_depth)
        if reader_procs is not None:
            self.reader_procs = int(reader_procs)
        if reader_autoscale is not None:
            self.reader_autoscale = bool(reader_autoscale)
        return self

    def set_profile(self, enabled: bool = True) -> "Optimizer":
        """Per-layer fwd/bwd attribution on the LIVE training path
        (reference: AbstractModule forwardTime/backwardTime accumulated in
        every forward/backward, nn/abstractnn/AbstractModule.scala:254-288,
        surfaced via getTimes()).  One jitted step has no per-layer host
        timestamps — XLA fuses across layers — so after the first step the
        trainer runs the per-child attribution harness
        (optim/profiling.layer_times) on the live batch and surfaces the
        shares through Metrics ("layer <name> forward/backward") and the
        TrainSummary, then logs the getTimes()-style table."""
        self._profile = enabled
        return self

    # ------------------------------------------------------------------
    # Compilation
    # ------------------------------------------------------------------

    def _batch_sharding(self):
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, self.batch_partition
                             if self.batch_partition is not None
                             else P(AXIS_DATA))

    def _replicated(self):
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P())

    def _put_batch(self, arr):
        if isinstance(arr, (tuple, list)):
            return type(arr)(self._put_batch(a) for a in arr)
        return put_batch_array(arr, self._batch_sharding())

    def _put_replicated(self, tree):
        sh = self._replicated()
        if sh is None:
            return tree
        return jax.device_put(tree, sh)

    def _host_lr(self) -> bool:
        sched = self.optim_method.schedule
        return isinstance(sched, Plateau)

    def _pipeline_axis(self) -> Optional[str]:
        """The model's pipeline axis, when it is actually in this mesh."""
        ax = getattr(self.model, "pipeline_axis", None)
        if ax is not None and self.mesh is not None and ax in self.mesh.shape \
                and self.mesh.shape[ax] > 1:
            return ax
        return None

    def _pipeline_forward(self, training: bool):
        """shard_map-wrapped model.apply for pipelined models: params enter
        by their sharding_rules specs (the block stack P('pipeline')), the
        batch by batch_partition; inside, the model runs its microbatch
        schedule (models/transformer.py pipeline path).  Returns
        fwd(params, model_state, x, rng) -> output, for use at jit level."""
        import jax as _jax
        from bigdl_tpu.parallel.sharding import spec_tree

        model, mesh = self.model, self.mesh
        ax = self._pipeline_axis()
        n_stage = mesh.shape[ax]
        batch_spec = self.batch_partition if self.batch_partition is not None \
            else P(AXIS_DATA)
        prepare = getattr(model, "prepare_pipeline_params", lambda p, n: p)
        # stateful pipelined models (conv+BN stages): per-layer state is
        # stacked like the params, enters sharded P(pipeline) by the same
        # sharding_rules, and comes back out through the same specs; the
        # restore hook undoes any schedule-layout permutation so stored
        # state stays in model order (like params/checkpoints)
        prepare_state = getattr(model, "prepare_pipeline_state",
                                lambda s, n: s)
        restore_state = getattr(model, "restore_pipeline_state",
                                lambda s, n: s)

        def fwd(params, model_state, x, rng):
            p = prepare(params, n_stage)
            s = prepare_state(model_state, n_stage)
            specs = spec_tree(p, self.sharding_rules)
            state_specs = spec_tree(s, self.sharding_rules)
            # without a rule mapping the block stack to P(pipeline_axis),
            # every device would hold ALL layers and the schedule would
            # silently apply the full stack n_stage times
            if not any(ax in _flatten_spec_axes(s_)
                       for s_ in jax.tree_util.tree_leaves(
                           specs, is_leaf=lambda v: isinstance(v, P))):
                raise ValueError(
                    f"pipelined model needs sharding_rules that place the "
                    f"block stack on the {ax!r} mesh axis, e.g. "
                    f"ShardingRules().add(r'^blocks/', P({ax!r}))")
            sm = _jax.shard_map(
                lambda p_, s_, x_, r_: model.apply(
                    p_, s_, x_, training=training, rng=r_),
                mesh=mesh, in_specs=(specs, state_specs, batch_spec, P()),
                out_specs=(batch_spec, state_specs))
            out, new_state = sm(p, s, x, rng)
            return out, restore_state(new_state, n_stage)

        return fwd

    def _cast_compute(self, tree):
        """Cast float leaves to the compute dtype (no-op without a policy)."""
        if self.compute_dtype is None:
            return tree
        return _cast_floats(tree, self.compute_dtype)

    def _build_step(self):
        # cache across optimize() calls ON THIS INSTANCE: rebuilding the
        # jit closure forces a retrace (and through a remote compile
        # service, a recompile) even though nothing changed.  Keras
        # fit() constructs a fresh Optimizer per call, so repeated fit()s
        # rely on jax's own trace cache keyed by the jitted function —
        # which this instance cache bypasses rebuilding but cannot share.
        # content-derived key for the mutable rule table: id() would miss
        # in-place rule edits (stale compiled step) and can false-hit
        # after rebinding to a recycled address
        rules_key = None if self.sharding_rules is None else tuple(
            (pat.pattern, spec) for pat, spec in self.sharding_rules.rules)
        key = (self.compute_dtype, id(self.model), id(self.criterion),
               id(self.optim_method), self.mesh,
               tuple(self.processors), self._pipeline_axis(),
               rules_key, self.batch_partition, self._watchdog_enabled())
        if self._compiled is not None and self._compiled_key == key:
            return self._compiled
        self._compiled = self._build_step_uncached()
        self._compiled_key = key
        return self._compiled

    def _resolve_step_call(self, step_fn, args, bs: int):
        """The callable dispatch actually invokes for the train step.

        With the executable cache off (the default) this IS `step_fn`.
        With `BIGDL_TPU_COMPILE_CACHE` set, the step is lowered once,
        content-hashed, and served from the on-disk AOT store — so a
        restarted process (preemption resume, watchdog rollback, fresh
        driver) reaches its first step on a deserialize instead of a
        full XLA compile.  Resolved at FIRST dispatch (concrete args are
        needed to lower) and instance-cached alongside `_compiled_key`;
        any cache failure falls back to the plain jit path.
        """
        from bigdl_tpu import compilecache as _cc
        if not _cc.enabled():
            return step_fn
        key = (self._compiled_key, bs, len(args))
        fn = self._aot_steps.get(key)
        if fn is not None:
            return fn
        fn, status = _cc.load_or_compile(
            step_fn, args, signature=f"train/step/bs={bs}",
            extra_key={"kind": "train", "donate": [0, 1, 2],
                       "mesh": _cc.mesh_descriptor(self.mesh)})
        if status == "error":
            fn = step_fn
        self._aot_steps[key] = fn
        return fn

    def _resolve_eval_call(self, args):
        """Same contract as `_resolve_step_call`, for the eval step."""
        from bigdl_tpu import compilecache as _cc
        if not _cc.enabled():
            return self._compiled_eval
        key = (self._compiled_eval_key, tuple(
            (tuple(l.shape), str(l.dtype))
            for l in jax.tree_util.tree_leaves(args[2:])))
        if self._aot_eval is not None and self._aot_eval_key == key:
            return self._aot_eval
        fn, status = _cc.load_or_compile(
            self._compiled_eval, args, signature="eval/step",
            extra_key={"kind": "eval",
                       "mesh": _cc.mesh_descriptor(self.mesh)})
        if status == "error":
            fn = self._compiled_eval
        self._aot_eval, self._aot_eval_key = fn, key
        return fn

    def _build_step_uncached(self):
        if self._pipeline_axis() is not None:
            return self._build_pipeline_step()
        model, criterion = self.model, self.criterion
        optim, processors = self.optim_method, list(self.processors)
        regs = collect_regularizers(model)
        cast = self._cast_compute
        has_policy = self.compute_dtype is not None
        # hoisted: reading self inside the jitted closure freezes the
        # answer at trace time anyway, and invites retraces (linter:
        # recompile rule) — bind the bool once, here
        host_lr = self._host_lr()
        watchdog = self._watchdog_enabled()

        def make_loss_fn(model_state, x, y, rng):
            def loss_fn(p):
                p = cast(p)
                out, new_state = model.apply(p, model_state, cast(x),
                                             training=True, rng=rng)
                if has_policy:
                    # running stats stay fp32 masters; loss math in fp32
                    new_state = _cast_floats(new_state, jnp.float32)
                    out = _cast_floats(out, jnp.float32)
                return criterion.forward(out, y), new_state
            return loss_fn

        if watchdog:
            # health variant: same math plus poison + finite check + gated
            # update (_finish_step_health); two extra DEVICE scalar args
            # (lr_scale, poison), one extra f32 output (the health flag)
            def train_step_h(params, model_state, opt_state, x, y, rng, lr,
                             lr_scale, poison):
                return _finish_step_health(
                    make_loss_fn(model_state, x, y, rng), params,
                    model_state, opt_state, lr, lr_scale, poison, optim,
                    processors, regs, host_lr)

            return jax.jit(train_step_h, donate_argnums=(0, 1, 2))

        def train_step(params, model_state, opt_state, x, y, rng, lr):
            (loss, new_model_state), grads = jax.value_and_grad(
                make_loss_fn(model_state, x, y, rng), has_aux=True)(params)
            # per-layer wRegularizer/bRegularizer contributions
            # (reference: accGradParameters + optim/Regularizer.scala)
            grads = apply_regularizers(grads, params, regs)
            for proc in processors:
                grads = proc.process(grads)
            # the applied lr travels back as a DEVICE scalar so the driver
            # can log it without a host round-trip per step
            lr_used = lr if host_lr else optim.current_lr(opt_state)
            new_params, new_opt_state = optim.step(
                grads, params, opt_state, lr=(lr if host_lr else None))
            return new_params, new_model_state, new_opt_state, loss, lr_used

        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    def _build_pipeline_step(self):
        """Train step for a pipelined model: the forward runs inside
        shard_map (GPipe/interleaved microbatch schedule over the
        'pipeline' axis, parallel/pipeline.py); criterion, autodiff (which
        transposes the schedule into the backward pipeline), gradient
        processing and the optimizer update happen at the jit level where
        XLA's sharding propagation places them."""
        criterion = self.criterion
        optim, processors = self.optim_method, list(self.processors)
        regs = collect_regularizers(self.model)
        fwd = self._pipeline_forward(training=True)
        cast = self._cast_compute
        has_policy = self.compute_dtype is not None
        host_lr = self._host_lr()
        watchdog = self._watchdog_enabled()

        def make_loss_fn(model_state, x, y, rng):
            def loss_fn(p):
                out, new_state = fwd(cast(p), model_state, cast(x), rng)
                if has_policy:
                    # pipelined models are stateless (asserted upstream),
                    # so the state cast is a no-op kept for symmetry with
                    # the non-pipeline path's fp32-master policy
                    new_state = _cast_floats(new_state, jnp.float32)
                    out = _cast_floats(out, jnp.float32)
                return criterion.forward(out, y), new_state
            return loss_fn

        if watchdog:
            def train_step_h(params, model_state, opt_state, x, y, rng, lr,
                             lr_scale, poison):
                return _finish_step_health(
                    make_loss_fn(model_state, x, y, rng), params,
                    model_state, opt_state, lr, lr_scale, poison, optim,
                    processors, regs, host_lr)

            return jax.jit(train_step_h, donate_argnums=(0, 1, 2))

        def train_step(params, model_state, opt_state, x, y, rng, lr):
            (loss, new_model_state), grads = jax.value_and_grad(
                make_loss_fn(model_state, x, y, rng), has_aux=True)(params)
            grads = apply_regularizers(grads, params, regs)
            for proc in processors:
                grads = proc.process(grads)
            lr_used = lr if host_lr else optim.current_lr(opt_state)
            new_params, new_opt_state = optim.step(
                grads, params, opt_state, lr=(lr if host_lr else None))
            return new_params, new_model_state, new_opt_state, loss, lr_used

        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    def _build_eval_step(self):
        model, methods = self.model, self.val_methods

        if self._pipeline_axis() is not None:
            fwd = self._pipeline_forward(training=False)
            rng = jax.random.PRNGKey(0)

            def eval_step(params, model_state, x, y):
                out, _ = fwd(params, model_state, x, rng)
                return [m.batch(out, y) for m in methods]

            return jax.jit(eval_step)

        def eval_step(params, model_state, x, y):
            out, _ = model.apply(params, model_state, x, training=False)
            return [m.batch(out, y) for m in methods]

        return jax.jit(eval_step)

    def _init_model(self, first_batch: MiniBatch):
        if self.params is None:
            shape = _shape_of_input(first_batch.get_input())
            self.params, self.model_state, _ = self.model.build(
                RandomGenerator.next_key(), shape)
        elif self._adopted_params:
            # weights adopted from the model: the jitted step DONATES its
            # buffers, so train on copies — an interrupt mid-optimize must
            # not leave model.params pointing at deleted arrays
            self.params = jax.tree_util.tree_map(jnp.copy, self.params)
            self.model_state = jax.tree_util.tree_map(jnp.copy, self.model_state)
            self._adopted_params = False
        if self.opt_state is None:
            self.opt_state = self.optim_method.init(self.params)
        if self.mesh is not None and self.sharding_rules is not None:
            # tp/sp/ep layouts: params by rule, optimizer slots mirror the
            # params' shardings, model state (BN stats) replicated — XLA
            # propagates these through the jitted step and inserts the
            # collectives (the declarative AllReduceParameter)
            from bigdl_tpu.parallel.sharding import shard_opt_state, shard_params

            self.params = shard_params(self.params, self.mesh, self.sharding_rules)
            self.model_state = shard_params(self.model_state, self.mesh)
            self.opt_state = shard_opt_state(self.opt_state, self.params,
                                             self.mesh, self.sharding_rules)
        else:
            self.params = self._put_replicated(self.params)
            self.model_state = self._put_replicated(self.model_state)
            self.opt_state = self._put_replicated(self.opt_state)

    # ------------------------------------------------------------------
    # The loop (reference: optim/DistriOptimizer.scala:786 optimize())
    # ------------------------------------------------------------------

    def optimize(self):
        cfg = Engine.config()
        max_restarts = self.max_restarts if self.max_restarts is not None \
            else cfg.failure_retry_times
        backoff = self.backoff_base_s if self.backoff_base_s is not None \
            else cfg.backoff_base_s
        cap = max(backoff, float(cfg.failure_retry_interval_s))
        guard = self._preempt_guard
        attempt = 0
        if guard is not None:
            guard.install()
        wd = self._ensure_watchdog()
        if wd is not None and self._hang is None \
                and wd.config.hang_deadlines is not None:
            self._hang = HangWatchdog(wd.config.hang_deadlines,
                                      poll_s=wd.config.hang_poll_s).start()
        try:
            while True:
                try:
                    return self._optimize_impl()
                except (KeyboardInterrupt, Preempted, DivergenceAbort):
                    # a preemption exit is intentional (the final
                    # checkpoint + marker are already on disk; restarting
                    # would fight the scheduler evicting us), and
                    # DivergenceAbort means the watchdog's own rollback
                    # budget is spent — a restart would replay the same
                    # divergence a sixth time
                    raise
                except NumericDivergence as e:
                    # watchdog rollback rung: restore the newest HEALTHY
                    # checkpoint (verdict-stamped, CRC-verified) and
                    # replay — the marked bad steps are skipped on device
                    # without re-escalating.  Deliberately does NOT spend
                    # the generic restart budget: max_rollbacks bounds
                    # this path (note_rollback -> DivergenceAbort).
                    if self.ckpt_path is None:
                        raise
                    self._ckpt_wait()
                    ckpt = latest_checkpoint(self.ckpt_path, gc_partial=True,
                                             require_healthy=True)
                    if ckpt is None:
                        raise
                    wd = self._watchdog
                    wd.note_rollback()
                    logger.warning(
                        "numeric divergence at step(s) %s: rolling back to "
                        "%s (rollback %d/%d)", list(e.bad_steps), ckpt,
                        wd.rollbacks, wd.config.max_rollbacks)
                    self.metrics.add("rollback count", 1)
                    if self.train_summary is not None:
                        step = self._driver_state["neval"]
                        self.train_summary.add_scalar(
                            "RollbackCount", wd.rollbacks, step)
                        self.train_summary.add_event(
                            "rollback", {"to": ckpt,
                                         "bad_steps": list(e.bad_steps)},
                            step)
                    if self._hang is not None:
                        self._hang.clear()
                    self._restore(ckpt)
                except Exception:
                    # bounded restart from the latest COMMITTED checkpoint
                    # with exponential backoff — replaces the reference's
                    # unbounded driver retry
                    # (optim/DistriOptimizer.scala:855-935)
                    if attempt >= max_restarts or self.ckpt_path is None:
                        raise
                    attempt += 1
                    self._ckpt_wait()
                    ckpt = latest_checkpoint(
                        self.ckpt_path, gc_partial=True,
                        verify=_ckpt_verify_enabled(None) or None)
                    delay = min(backoff * (2 ** (attempt - 1)), cap)
                    logger.exception(
                        "training failed; restart %d/%d from %s after "
                        "%.2fs backoff", attempt, max_restarts,
                        ckpt or "current in-memory state", delay)
                    if self._hang is not None:
                        self._hang.clear()
                    if ckpt is not None:
                        self._restore(ckpt)
                    if delay > 0:
                        time.sleep(delay)
        finally:
            if guard is not None:
                guard.uninstall()
            if self._hang is not None:
                self._hang.stop()
                self._hang = None
            if self._ckpt_writer is not None:
                self._ckpt_writer.close()
                self._ckpt_writer = None

    def _ckpt_wait(self) -> None:
        """Drain the async writer under the hang watchdog's ckpt_wait
        phase: a wedged writer thread (stuck remote fs) raises StalledStep
        instead of blocking the driver indefinitely."""
        if self._ckpt_writer is None:
            return
        hang = self._hang
        with _phase(hang, "ckpt_wait"), _obs.span("ckpt_wait",
                                                  cat="trainer"):
            self._ckpt_writer.wait(
                stall_check=hang.check if hang is not None else None)

    def _restore(self, ckpt_dir: str) -> None:
        # templates are the LIVE trees, already sharded over the current
        # mesh — for a chunked (v2) checkpoint the loader assembles each
        # target shard from exactly the intersecting chunks, so a run
        # saved under mesh A resumes here under mesh B (different dp/tp
        # split, fewer or more chips) without ever gathering the full
        # tree on host
        self.params, self.model_state, self.opt_state, driver = load_checkpoint(
            ckpt_dir, self.params, self.model_state, self.opt_state)
        # commit the restored host trees to device NOW: the next dispatch
        # may run under strict_transfers, where a numpy leaf reaching the
        # jitted step is an (intended-to-be-fatal) implicit h2d transfer
        self.params = jax.device_put(self.params)
        if self.model_state is not None:
            self.model_state = jax.device_put(self.model_state)
        if self.opt_state is not None:
            self.opt_state = jax.device_put(self.opt_state)
        # the restored trees are freshly committed: drop any AOT step
        # resolved against the pre-restore arrays so the next dispatch
        # re-lowers with the new shardings (a disk hit when unchanged)
        self._aot_steps.clear()
        driver = dict(driver)
        seed = driver.pop("rng_seed", None)
        if seed is not None and int(seed) != RandomGenerator.get_seed():
            # step rng and epoch shuffles derive from the global seed: a
            # resume under a different seed would fork the trajectory from
            # the uninterrupted run
            logger.warning("restore: adopting global seed %s from "
                           "checkpoint (was %s)", seed,
                           RandomGenerator.get_seed())
            RandomGenerator.set_seed(int(seed))
        # the watchdog verdict stamped at save time: a fresh process
        # resuming after a rollback must keep skipping the marked bad
        # steps (and must NOT copy the stamp into live driver state)
        health = driver.pop("health", None)
        if health is not None and self._ensure_watchdog() is not None:
            self._watchdog.adopt_marked(health.get("bad_steps", ()))
        self._driver_state.update(driver)
        # mid-epoch checkpoints record how far into the epoch they are;
        # the epoch loop replays the SAME shuffled order (seek_epoch) and
        # skips exactly this many batches before training resumes.  No
        # reader-pool state survives a restore: the pool is per-epoch
        # (closed in the epoch's finally before the restart ladder runs)
        # and the next epoch builds a fresh one whose workers start
        # claiming at this skip index — the reorder stage makes the
        # resumed sequence bitwise-equal to the uninterrupted run.
        self._resume_skip = int(driver.get("epoch_batch", 0) or 0)

    def resume_from(self, ckpt_path: str) -> "Optimizer":
        """Explicit resume (reference: Train --model/--state snapshots).
        Interrupted partial checkpoint dirs found next to the committed
        ones are garbage-collected with a warning."""
        ckpt = latest_checkpoint(ckpt_path, gc_partial=True,
                                 verify=_ckpt_verify_enabled(None) or None) \
            if not ckpt_path.endswith(".json") else ckpt_path
        if ckpt is None:
            raise FileNotFoundError(f"no checkpoint under {ckpt_path}")
        # Need built params first: build lazily on first batch then restore
        self._pending_restore = ckpt
        # a clean finish retires the preemption marker at this root even
        # when the resumed run itself writes no checkpoints
        if not ckpt_path.endswith(".json"):
            self._resume_root = ckpt_path
        return self

    def _async_depth(self) -> int:
        """How many in-flight steps the driver keeps before reading one
        back.  0 = fully synchronous — required when any trigger reads
        locally-divergent floats (min_loss/max_score), which must see the
        loss of the step that JUST ran.  Deterministic triggers (the
        common max_epoch/max_iteration/every_* family) allow async
        dispatch: the device pipelines steps while the host reads results
        a few steps behind, so `Optimizer.optimize()` throughput matches
        the raw jitted step instead of stalling on float(loss) every
        iteration."""
        triggers = [self.end_when]
        if self.val_trigger is not None:
            triggers.append(self.val_trigger)
        if getattr(self, "ckpt_trigger", None) is not None:
            triggers.append(self.ckpt_trigger)
        if all(getattr(t, "deterministic", False) for t in triggers):
            return max(0, Engine.config().async_depth)
        return 0

    def _feed_depth(self) -> int:
        if self.feed_depth is not None:
            return max(0, self.feed_depth)
        return max(0, Engine.config().feed_depth)

    def _reader_procs(self) -> int:
        if self.reader_procs is not None:
            return max(0, self.reader_procs)
        return max(0, Engine.config().reader_procs)

    def _reader_autoscale(self) -> bool:
        if self.reader_autoscale is not None:
            return self.reader_autoscale
        return bool(Engine.config().reader_autoscale)

    def _make_train_source(self, skip: int):
        """This epoch's batch source: a ReaderPool when the disaggregated
        input plane is on AND the dataset's assembly can move out of
        process, else the in-thread `data(train=True)` generator.  Either
        way the epoch is consumed exactly once (the pool adapter replays
        the same shuffle draws data() would), and a resume skip lands as
        the pool's `start_index` — workers skip ITEMS cheaply instead of
        assembling and discarding `skip` batches."""
        procs = self._reader_procs()
        if procs > 0:
            from bigdl_tpu.dataset.readers import make_reader_source

            pool = make_reader_source(
                self.dataset, True, procs=procs, start_index=skip,
                autoscale=self._reader_autoscale(), max_procs=procs,
                name="ReaderPool-train")
            if pool is not None:
                return pool, pool
        src = self.dataset.data(train=True)
        if skip:
            src = _skip_batches(src, skip)
        return src, None

    def _stage_batch(self, batch: MiniBatch):
        """Assembly hand-off -> device staging, run in the feed worker:
        the arrays land under the step's data-axis sharding before the
        loop asks for them."""
        tgt = batch.get_target()
        return (self._put_batch(batch.get_input()),
                None if tgt is None else self._put_batch(tgt))

    def _optimize_impl(self):
        state = self._driver_state
        state.setdefault("epoch_batch", 0)
        from bigdl_tpu import compilecache as _cc
        if _cc.enabled():
            # attach the XLA persistent-cache layer before the FIRST
            # compile of this run, so helper programs (rng fold-in,
            # telemetry ring writes) persist across restarts too
            _cc.store()
        step_fn = None
        # AOT-resolved at first dispatch (compilecache); re-resolved when
        # the batch size changes (ragged final batch = its own executable)
        step_call = None
        step_call_bs = None
        # the step-rng root is a NAMED stream, not next_key(): a resumed
        # process (fresh key counter) must derive the same per-step rng
        # (fold_in(root, neval)) as the uninterrupted run for losses to
        # stay bitwise-equal across restarts
        root_key = RandomGenerator.key_for("optimizer/train-step")
        wall_start = time.time()

        # Resume must restore BEFORE the first end_when check so a
        # fully-trained checkpoint does not get an extra step.
        if getattr(self, "_pending_restore", None):
            first = next(iter(self.dataset.data(train=False)))
            self._init_model(first)
            self._restore(self._pending_restore)
            self._pending_restore = None

        depth = self._async_depth()
        # numeric-divergence watchdog: the drain's verdict must arrive at
        # most max_lag steps after the bad step (the policy acts on what
        # the drain reads), so the async depth is capped by it
        wd = self._ensure_watchdog()
        hang = self._hang
        if wd is not None:
            depth = min(depth, max(0, wd.config.max_lag))
        feed_depth = self._feed_depth()
        feed_ref = [None]  # current epoch's feed, for drain-side telemetry
        reader_ref = [None]  # current epoch's ReaderPool (None = in-thread)
        # (epoch, neval, bs, slot, ring_snapshot, feed_stall_s, feed_occ)
        pending = deque()
        drain_clock = [time.perf_counter(), 1.0]  # [last drain t, last dt]
        lr_cache = [None, None]  # [host float, device scalar]
        lr_zero = jnp.zeros((), jnp.float32)
        # loop invariants hoisted: reading self per step inside the loop
        # (or worse, inside the jitted closure) is the stale-closure /
        # retrace hazard the analysis linter's recompile rule flags
        host_lr = self._host_lr()
        strict = strict_transfers_enabled(self._strict_transfers)
        # obs plane, hoisted once (the hot-loop contract): tr is None when
        # tracing is off, and every span below is guarded on that — the
        # tracing-off loop is byte-for-byte the pre-obs loop
        tr = _obs.tracer()
        mon = _obs.compile_monitor()
        obs_reg = _obs.registry()
        ring_cap = depth + 2  # burst span never exceeds depth+1 entries
        ring = jnp.zeros((ring_cap, 3 if wd is not None else 2), jnp.float32)
        rep = self._replicated()  # None off-mesh; NamedSharding(mesh, P())
        if rep is not None:
            # commit the ring (and below, the slot scalars) onto the mesh
            # at creation: _ring_write's other inputs (loss, lr) live on
            # the mesh, so a default-device ring would need an implicit
            # d2d broadcast at the first dispatch — exactly what
            # strict_transfers disallows
            ring = jax.device_put(ring, rep)
        # watchdog device scalars, re-put only on CHANGE (lr_backoff is a
        # once-per-escalation event; poison codes repeat from a tiny set)
        scale_cache = [None, None]       # [host float, device scalar]
        poison_cache: Dict[int, Any] = {}  # code -> device scalar
        poison_fn = getattr(self._chaos, "poison_code", None) \
            if self._chaos is not None else None
        corrupt_seen = [0]  # dataset corrupt-record count already reported

        def drain(keep: int):
            """Read back completed steps, keeping `keep` in flight.

            Reads ONE telemetry-ring snapshot for the whole backlog
            instead of one host round-trip per step: per-step float()
            calls degrade the dispatch rate to one round trip per
            iteration (measured 0.3 s/step through the remote-TPU tunnel
            vs 0.1 s of compute).  The snapshot comes from a step that
            already EXECUTED (depth/2 behind the dispatch head), so the
            read never waits behind the in-flight queue — see
            _ring_write for why no packing program may run here.
            Per-iteration logs still appear for every step, `depth`
            steps late at most."""
            if len(pending) <= keep:
                return
            # flush down to keep//2, not keep: the steps left in flight
            # cover the device while the host waits on the readback, so
            # the pipeline has no bubble at the flush boundary
            target = keep // 2
            burst = []
            while len(pending) > target:
                burst.append(pending.popleft())
            # ONE transfer for every burst entry's loss AND lr: read the
            # NEWEST burst entry's ring snapshot — that step sits depth/2
            # behind the dispatch head, so its buffer is (about) done
            # executing and the read is a pure round trip; the older
            # entries' slots are still intact in that snapshot (overwrites
            # only happen in newer snapshots).  See _ring_write for why no
            # packing program may run at drain time.
            packed = np.asarray(burst[-1][4], np.float32)  # (ring_cap, 2|3)
            now = time.perf_counter()
            dt_total = now - drain_clock[0]
            per_step = dt_total / len(burst) if dt_total > 1e-7 \
                else drain_clock[1]
            drain_clock[0], drain_clock[1] = now, per_step
            for ep, it, bs, slot, _, stall_s, occ in burst:
                loss_f = float(packed[slot, 0])
                lr_f = float(packed[slot, 1])
                if wd is not None:
                    # the health flag rode the same snapshot as the loss —
                    # the verdict costs no extra transfer.  `it` is the
                    # post-increment neval, so the step index is it - 1.
                    # observe() may raise NumericDivergence (rollback) or
                    # DivergenceAbort; both unwind to optimize()'s ladder.
                    healthy = bool(packed[slot, 2] >= 0.5)
                    action = wd.observe(it - 1, healthy)
                    if action != "ok":
                        self.metrics.add("health events", 1)
                        self.metrics.add("skipped batches", 1)
                        logger.warning(
                            "health: step %d non-finite -> %s "
                            "(skipped %d, lr_scale %g)", it - 1, action,
                            wd.skipped, wd.lr_scale)
                        if self.train_summary is not None:
                            self.train_summary.add_scalar(
                                "SkippedBatches", wd.skipped, it - 1)
                            self.train_summary.add_scalar(
                                "HealthEvents", len(wd.events), it - 1)
                            self.train_summary.add_event(
                                "health", {"action": action,
                                           "lr_scale": wd.lr_scale}, it - 1)
                state["loss"] = loss_f
                throughput = bs / per_step
                self.metrics.add("computing time", per_step)
                self.metrics.set("throughput", throughput)
                self.metrics.add("feed stall", stall_s)
                self.metrics.set("feed occupancy", occ)
                obs_reg.inc("train/steps")
                obs_reg.set_gauge("train/loss", loss_f)
                obs_reg.set_gauge("train/throughput", throughput)
                # step-time-derived MFU: param count is host shape
                # metadata (no device sync), peak comes from
                # BIGDL_TPU_PEAK_TFLOPS — without a declared peak only
                # the achieved model-FLOPs gauge exports
                if self._n_params is None:
                    self._n_params = sum(
                        int(l.size) for l in
                        jax.tree_util.tree_leaves(self.params))
                est = _obs.mfu_estimate(self._n_params, bs, per_step)
                obs_reg.set_gauge("train/model_flops_per_s",
                                  est["model_flops_per_s"])
                if est["mfu"]:
                    obs_reg.set_gauge("train/mfu", est["mfu"])
                obs_reg.set_gauge("feed/stall_ms", stall_s * 1e3)
                obs_reg.set_gauge("feed/occupancy", occ)
                # driver log (reference: DistriOptimizer.scala:402-407);
                # `extra` fields land in the JSONL records when
                # BIGDL_TPU_LOG_JSON=1 (utils/logger_filter.py)
                logger.info(
                    "Epoch %d iteration %d: loss %.6f, throughput %.1f "
                    "records/s, lr %.6g", ep, it, loss_f, throughput, lr_f,
                    extra={"step": it, "epoch": ep})
                if tr is not None:
                    tr.instant("step_drained", cat="trainer", step=it,
                               loss=loss_f)
                if self.train_summary is not None:
                    s = self.train_summary
                    if s.should_log("Loss", it):
                        s.add_scalar("Loss", loss_f, it)
                    if s.should_log("Throughput", it):
                        s.add_scalar("Throughput", throughput, it)
                    if s.should_log("LearningRate", it):
                        s.add_scalar("LearningRate", lr_f, it)
                    if s.should_log("FeedStallMs", it):
                        s.add_scalar("FeedStallMs", stall_s * 1e3, it)
                    if s.should_log("FeedOccupancy", it):
                        s.add_scalar("FeedOccupancy", occ, it)
            feed = feed_ref[0]
            if feed is not None and feed.prefetch_depth > 0:
                # one aggregate feed line per drain burst (Loss/Throughput
                # stay on their own per-iteration lines above)
                asm = feed.assembly_records_per_s()
                self.metrics.set("feed assembly throughput", asm)
                logger.info(
                    "Feed: stall %.2f ms/step, occupancy %.1f/%d, "
                    "assembly %.0f records/s",
                    1e3 * sum(e[5] for e in burst) / len(burst),
                    sum(e[6] for e in burst) / len(burst),
                    feed.prefetch_depth, asm)
            pool = reader_ref[0]
            if pool is not None:
                # reader-pool telemetry on the same drain cadence: the
                # autoscaler's current target (gauge also set at each
                # scale decision; this keeps it fresh when idle)
                n_procs = pool.procs
                self.metrics.set("reader procs", n_procs)
                obs_reg.set_gauge("feed/reader_procs", n_procs)
                if self.train_summary is not None:
                    last_it = burst[-1][1]
                    if self.train_summary.should_log("ReaderProcs", last_it):
                        self.train_summary.add_scalar(
                            "ReaderProcs", n_procs, last_it)
            # tfrecord skip_corrupt telemetry: surface newly skipped
            # records through the same drain cadence as the feed stats
            corrupt = int(getattr(self.dataset, "corrupt_records", 0) or 0)
            if corrupt > corrupt_seen[0]:
                corrupt_seen[0] = corrupt
                self.metrics.set("corrupt records", corrupt)
                last_it = burst[-1][1]
                if self.train_summary is not None:
                    self.train_summary.add_scalar(
                        "CorruptRecords", corrupt, last_it)
                logger.warning("dataset: %d corrupt record(s) skipped so "
                               "far (skip_corrupt policy)", corrupt)

        while not self._agreed_trigger(self.end_when, state):
            state["epoch_finished"] = False
            epoch_start = time.time()
            record_count_epoch = 0
            completed_epoch = True
            # deterministic epoch order: shuffle is a pure function of
            # (seed, driver epoch), so a resumed run replays the
            # interrupted epoch's exact batch sequence
            seek = getattr(self.dataset, "seek_epoch", None)
            if callable(seek):
                seek(state["epoch"])
            skip = int(self._resume_skip or 0)
            self._resume_skip = 0
            if skip:
                # mid-epoch resume: drop the batches the checkpoint
                # already trained on (in-thread: assembly of the skipped
                # batches runs lazily in the feed worker; pool: workers
                # skip the cheap item stream and assemble nothing)
                logger.info("resume: skipping %d already-trained batch(es) "
                            "of epoch %d", skip, state["epoch"] + 1)
            else:
                state["epoch_batch"] = 0
            src, reader_pool = self._make_train_source(skip)
            reader_ref[0] = reader_pool
            # batch assembly (iteration -> transformer chain -> stack) and
            # the H2D put run in the feed worker, `feed_depth` batches
            # ahead of the dispatch head; the bounded queue backpressures
            # instead of accumulating host memory.  close() in the finally
            # makes an end_when break, a raising step or a preemption exit
            # leak no thread.
            feed = make_feed(src, self._stage_batch, feed_depth,
                             name="DeviceFeed-train",
                             stall_check=hang.check if hang is not None
                             else None)
            feed_ref[0] = feed
            try:
                for item in _guarded_iter(feed, hang, tr):
                    if hang is not None:
                        # surface a stall another thread detected (e.g.
                        # the writer wedged) at the batch boundary, where
                        # the StalledStep is cleanly retryable
                        hang.check()
                    if self._agreed_trigger(self.end_when, state):
                        completed_epoch = False
                        break
                    if self._preempt_guard is not None \
                            and self._preempt_guard.requested():
                        # batch boundary: params/opt_state are consistent
                        # here — final sync save + marker, then raise
                        self._handle_preemption(state, feed)
                    if self._chaos is not None:
                        self._chaos.on_step(state["neval"])
                    batch = item.batch
                    if self.params is None or step_fn is None:
                        self._init_model(batch)
                        step_fn = self._build_step()
                        step_call = None
                        step_call_bs = None
                    bs = batch.size()
                    x, y = item.payload
                    # strict_transfers is a no-op unless enabled: any
                    # IMPLICIT transfer a future change sneaks into this
                    # dispatch section then raises at the offending line
                    with _phase(hang, "step_dispatch"), \
                            (tr.span("step_dispatch", cat="trainer",
                                     step=state["neval"])
                             if tr is not None else _NULLCTX), \
                            (mon.attribute(f"train/step/bs={bs}")
                             if mon is not None else _NULLCTX), \
                            strict_transfers(strict):
                        rng = _fold_in(root_key,
                                       _put_scalar(state["neval"]))
                        if host_lr:
                            # schedules hold the lr constant for stretches
                            # of steps; Plateau state lives on host, so
                            # the current lr is host math — no device
                            # round-trip — and the device scalar is put
                            # once per lr CHANGE, not per step
                            lr_f = self._current_lr_host()
                            if lr_cache[0] != lr_f:
                                lr_cache[0] = lr_f
                                lr_cache[1] = _put_scalar(lr_f, np.float32)
                            lr = lr_cache[1]
                        else:
                            lr = lr_zero  # unused; device schedule
                        if wd is not None:
                            # watchdog scalars: marked steps replay as
                            # forced skips (poison code LOSS) so a rolled-
                            # back trajectory never re-trains a bad step;
                            # both device scalars are cached puts, not
                            # per-step transfers
                            if scale_cache[0] != wd.lr_scale:
                                scale_cache[0] = wd.lr_scale
                                scale_cache[1] = _put_scalar(wd.lr_scale,
                                                             np.float32)
                            code = poison_fn(state["neval"]) \
                                if poison_fn is not None else 0
                            if code == 0 and state["neval"] in wd.marked:
                                code = POISON_LOSS
                            pdev = poison_cache.get(code)
                            if pdev is None:
                                pdev = poison_cache.setdefault(
                                    code, _put_scalar(code))
                            step_args = (self.params, self.model_state,
                                         self.opt_state, x, y, rng, lr,
                                         scale_cache[1], pdev)
                            if step_call is None or step_call_bs != bs:
                                step_call = self._resolve_step_call(
                                    step_fn, step_args, bs)
                                step_call_bs = bs
                            (self.params, self.model_state, self.opt_state,
                             loss, lr_used, health) = step_call(*step_args)
                            state["neval"] += 1
                            state["epoch_batch"] += 1
                            slot = (state["neval"] - 1) % ring_cap
                            ring = _ring_write_h(ring,
                                                 _put_scalar(slot,
                                                             sharding=rep),
                                                 loss, lr_used, health)
                        else:
                            step_args = (self.params, self.model_state,
                                         self.opt_state, x, y, rng, lr)
                            if step_call is None or step_call_bs != bs:
                                step_call = self._resolve_step_call(
                                    step_fn, step_args, bs)
                                step_call_bs = bs
                            (self.params, self.model_state, self.opt_state,
                             loss, lr_used) = step_call(*step_args)
                            state["neval"] += 1
                            state["epoch_batch"] += 1
                            slot = (state["neval"] - 1) % ring_cap
                            ring = _ring_write(ring,
                                               _put_scalar(slot,
                                                           sharding=rep),
                                               loss, lr_used)
                    pending.append((state["epoch"] + 1, state["neval"], bs,
                                    slot, ring, item.stall_s, item.occupancy))
                    drain(depth)
                    if getattr(self, "_profile", False) \
                            and not getattr(self, "_profiled", False):
                        self._profiled = True
                        self._run_profile(x)
                    record_count_epoch += bs
                    t_cb = time.perf_counter()
                    self._maybe_validate(state)
                    self._maybe_checkpoint(state)
                    dt_cb = time.perf_counter() - t_cb
                    if dt_cb > 1e-3:
                        # exclude validation/checkpoint time from the next
                        # drain's per-step throughput attribution; clamp to
                        # 'now' — callbacks overlap in-flight device compute,
                        # and an unclamped advance can pass the next drain's
                        # timestamp, making dt_total<=0 there
                        drain_clock[0] = min(time.perf_counter(),
                                             drain_clock[0] + dt_cb)
            finally:
                # close-through: a ReaderPool source is torn down inside
                # feed.close() (before the join, so a worker parked on the
                # pool unblocks); the explicit pool.close() is idempotent
                # insurance for a feed that failed to construct
                feed.close()
                if reader_pool is not None:
                    reader_pool.close()
                    reader_ref[0] = None
            # epoch boundary: under async depth the backlog can ride
            # across epochs (deterministic triggers never read
            # state['loss']); the synchronous path (depth=0) still
            # flushes here so min_loss/max_score see the current epoch
            drain(depth)
            if not completed_epoch:
                break
            state["epoch"] += 1
            state["epoch_batch"] = 0
            state["epoch_finished"] = True
            if self.opt_state is not None:
                # preserve the old leaf's sharding: a plain jnp.asarray
                # here changes the step signature (SingleDeviceSharding vs
                # the step output's NamedSharding) and forces a ~20s FULL
                # RECOMPILE of the train step at every epoch boundary.
                # Only device_put when the old leaf was COMMITTED, though:
                # committing it in a single-device run (where every other
                # arg is uncommitted) flips the pjit argument mapping from
                # UnspecifiedValue to a concrete sharding and triggers the
                # exact recompile pair this branch exists to prevent (the
                # obs CompileMonitor flags them as steady_recompiles)
                new_epoch = jnp.asarray(state["epoch"], jnp.int32)
                old = self.opt_state.get("epoch")
                if hasattr(old, "sharding") and getattr(old, "committed",
                                                        False):
                    new_epoch = jax.device_put(new_epoch, old.sharding)
                self.opt_state = dict(self.opt_state, epoch=new_epoch)
            logger.info("Epoch %d done: %d records in %.1fs",
                        state["epoch"], record_count_epoch, time.time() - epoch_start)
            t_cb = time.perf_counter()
            self._maybe_validate(state)
            self._maybe_checkpoint(state)
            dt_cb = time.perf_counter() - t_cb
            if dt_cb > 1e-3:
                drain_clock[0] = min(time.perf_counter(),
                                     drain_clock[0] + dt_cb)
        drain(0)
        if self._ckpt_writer is not None:
            # wait() barrier: every queued async save is committed before
            # optimize() returns — latest_checkpoint right after training
            # must see the final state
            t0 = time.perf_counter()
            self._ckpt_wait()
            dt = time.perf_counter() - t0
            if dt > 1e-3:
                logger.info("drained async checkpoint writer (%.2fs)", dt)
        for root in {self.ckpt_path, getattr(self, "_resume_root", None)}:
            if root is not None:
                # a clean finish retires any stale preemption marker
                clear_marker(root)
        logger.info("Training finished after %d iterations (%.1fs)",
                    state["neval"], time.time() - wall_start)
        self.model.params = self.params
        self.model.state = self.model_state
        return self.model

    def _run_profile(self, x) -> None:
        from bigdl_tpu.optim.profiling import layer_times, summarize

        try:
            times = layer_times(self.model, self.params, self.model_state, x,
                                training=True)
        except ValueError as e:
            logger.warning("profile=True: %s", e)
            return
        for t in times:
            self.metrics.set(f"layer {t.name} forward", t.forward_s)
            self.metrics.set(f"layer {t.name} backward", t.backward_s)
            if self.train_summary is not None:
                step = self._driver_state["neval"]
                self.train_summary.add_scalar(
                    f"LayerTime/{t.name}/forward_ms", t.forward_s * 1e3, step)
                self.train_summary.add_scalar(
                    f"LayerTime/{t.name}/backward_ms", t.backward_s * 1e3, step)
        logger.info("per-layer times (live batch):\n%s", summarize(times))

    def _current_lr(self):
        if self.opt_state is None:
            return self.optim_method.learning_rate
        return self.optim_method.current_lr(self.opt_state)

    def _current_lr_host(self) -> float:
        """Current lr as a host float WITHOUT a device round-trip.

        Only meaningful for host-driven schedules (Plateau): their state
        (current_factor, min_lr) lives on host, so the lr is pure host
        math.  The old `float(self._current_lr())` pulled a device
        scalar every step — the per-step d2h sync the analysis linter's
        host-sync rule exists to catch."""
        sched = self.optim_method.schedule
        return sched.host_value(self.optim_method.learning_rate)

    # ------------------------------------------------------------------

    def _agreed_trigger(self, trigger, state) -> bool:
        """Trigger decision binding on every process.  Validation batches
        and checkpoint gathers are collective under multi-process, so a
        trigger reading locally-divergent floats (min_loss/max_score) must
        defer to process 0; deterministic triggers skip the broadcast."""
        fired = bool(trigger(state))
        if getattr(trigger, "deterministic", False):
            return fired
        from bigdl_tpu.utils.checkpoint import agree_from_process_zero

        return bool(agree_from_process_zero(int(fired)))

    def _maybe_validate(self, state):
        if self.val_trigger is None or self.val_dataset is None:
            return
        if not self._agreed_trigger(self.val_trigger, state):
            return
        results = self.validate()
        for r in results:
            v, _ = r.result()
            logger.info("Validation %s: %.6f", r.name, v)
            if self.val_summary is not None:
                self.val_summary.add_scalar(r.name, v, state["neval"])
        if results:
            state["score"] = results[0].result()[0]
            sched = self.optim_method.schedule
            if sched is not None:
                sched.on_score(state["score"])

    def validate(self) -> List[ValidationResult]:
        """Distributed eval (reference: optim/AbstractOptimizer.scala:93 +
        Evaluator.scala — RDD mapPartitions becomes batched jitted eval)."""
        if self.val_dataset is None or self.val_methods is None:
            raise ValueError("call set_validation(trigger, dataset, methods) first")
        if self.params is None:
            raise ValueError("model not built yet: run optimize() (or init) first")
        # key the compiled eval step on the method list so swapping
        # val_methods recompiles instead of silently reusing the old closure
        # (strong refs, not id()s: a freed method's address can be reused)
        key = tuple(self.val_methods)
        cached_key = getattr(self, "_compiled_eval_key", None)
        if getattr(self, "_compiled_eval", None) is None or cached_key is None \
                or len(cached_key) != len(key) \
                or any(a is not b for a, b in zip(cached_key, key)):
            self._compiled_eval = self._build_eval_step()
            self._compiled_eval_key = key
        # Numerators/counts accumulate ON DEVICE across batches (eager adds
        # dispatch async, no host sync); ONE packed transfer at the end
        # converts every method's totals.  The old per-batch float(v)/
        # int(c) pattern host-synced O(N) times — each sync a full queue
        # wait + round trip (~100 ms through the remote tunnel).  Batch
        # staging runs through the same DeviceFeed as training.
        totals_v = totals_c = None
        # guard covers dispatch + on-device accumulation; the feed worker
        # thread stages batches outside it (transfer_guard is thread-local)
        # and the sanctioned end-of-eval pull below sits after the block
        strict = strict_transfers_enabled(self._strict_transfers)
        with make_feed(self.val_dataset.data(train=False), self._stage_batch,
                       self._feed_depth(), name="DeviceFeed-eval") as feed, \
                _obs.span("validate", cat="trainer"), \
                _obs.attribute("eval/step"), \
                strict_transfers(strict):
            eval_call = None
            eval_shape = None
            for item in feed:
                x, y = item.payload
                eval_args = (self.params, self.model_state, x, y)
                sh = tuple(l.shape
                           for l in jax.tree_util.tree_leaves((x, y)))
                if eval_call is None or eval_shape != sh:
                    # ragged final batch resolves its own executable
                    eval_call = self._resolve_eval_call(eval_args)
                    eval_shape = sh
                outs = eval_call(*eval_args)
                if totals_v is None:
                    totals_v = [v for v, _ in outs]
                    totals_c = [c for _, c in outs]
                else:
                    totals_v = [tv + v for tv, (v, _) in zip(totals_v, outs)]
                    totals_c = [tc + c for tc, (_, c) in zip(totals_c, outs)]
        if totals_v is None:
            return [ValidationResult(0.0, 0, m.name) for m in self.val_methods]
        # the single sanctioned device->host transfer of the whole eval
        vals = np.asarray(jnp.stack(totals_v), np.float64)  # tpu-lint: disable=host-sync
        cnts = np.asarray(jnp.stack(totals_c))  # tpu-lint: disable=host-sync
        return [ValidationResult(float(v), int(c), m.name)
                for v, c, m in zip(vals, cnts, self.val_methods)]

    # ------------------------------------------------------------------
    # Checkpointing + preemption (bigdl_tpu/resilience)
    # ------------------------------------------------------------------

    def _use_async_ckpt(self) -> bool:
        if jax.process_count() > 1:
            return False  # the multi-process save is a collective
        if self.ckpt_async is not None:
            return bool(self.ckpt_async)
        return bool(Engine.config().ckpt_async)

    def _ensure_ckpt_writer(self) -> AsyncCheckpointer:
        if self._ckpt_writer is None:
            layout = self.ckpt_layout
            if layout is None:
                layout = Engine.config().ckpt_layout
            self._ckpt_writer = AsyncCheckpointer(
                self.ckpt_path, keep_last=self.ckpt_keep_last,
                keep_every=self.ckpt_keep_every, fault=self._ckpt_fault,
                post_commit=self._ckpt_corrupt, layout=layout)
        return self._ckpt_writer

    def _driver_snapshot(self, state) -> Dict[str, Any]:
        driver = {k: v for k, v in state.items()
                  if k in ("epoch", "neval", "loss", "score", "epoch_batch")}
        # the seed travels with the checkpoint so a fresh process resumes
        # the same step-rng stream and epoch shuffles
        driver["rng_seed"] = RandomGenerator.get_seed()
        if self._watchdog is not None:
            # stamp the watchdog verdict: rollback restores only from
            # checkpoints whose stamp says the trajectory was healthy when
            # they were taken (latest_checkpoint require_healthy)
            driver["health"] = self._watchdog.verdict(state["neval"])
        return driver

    def _sync_save(self, state) -> str:
        if jax.process_count() > 1:
            from bigdl_tpu.resilience.async_ckpt import apply_retention

            d = save_checkpoint(self.ckpt_path, state["neval"], self.params,
                                self.model_state, self.opt_state,
                                driver_state=self._driver_snapshot(state))
            if jax.process_index() == 0:
                apply_retention(self.ckpt_path, self.ckpt_keep_last,
                                self.ckpt_keep_every)
            return d
        return self._ensure_ckpt_writer().save_sync(
            state["neval"], self.params, self.model_state, self.opt_state,
            self._driver_snapshot(state))

    def _maybe_checkpoint(self, state):
        if self.ckpt_path is None or self.ckpt_trigger is None:
            return
        if not self._agreed_trigger(self.ckpt_trigger, state):
            return
        t0 = time.perf_counter()
        with _obs.span("ckpt_save", cat="trainer", step=state["neval"]):
            if self._use_async_ckpt():
                # the loop pays only the on-device snapshot dispatch (and,
                # if the bounded writer queue is full, the backpressure
                # wait)
                self._ensure_ckpt_writer().save_async(
                    state["neval"], self.params, self.model_state,
                    self.opt_state, self._driver_snapshot(state))
                logger.info("Checkpoint step %d queued (async)",
                            state["neval"], extra={"step": state["neval"]})
            else:
                d = self._sync_save(state)
                logger.info("Checkpoint saved to %s", d,
                            extra={"step": state["neval"]})
        stall = time.perf_counter() - t0
        self.metrics.add("checkpoint stall", stall)
        _obs.registry().set_gauge("ckpt/stall_ms", stall * 1e3)
        if self.train_summary is not None \
                and self.train_summary.should_log("CheckpointStallMs",
                                                  state["neval"]):
            self.train_summary.add_scalar("CheckpointStallMs", stall * 1e3,
                                          state["neval"])

    def _handle_preemption(self, state, feed) -> None:
        guard = self._preempt_guard
        reason = guard.reason
        step = state["neval"]
        logger.warning(
            "preemption (%s): stopping at step %d (%d batch(es) into epoch "
            "%d; feed delivered %d)", reason, step,
            state.get("epoch_batch", 0), state["epoch"] + 1,
            getattr(feed, "delivered_batches", -1))
        ckpt_dir = None
        if self.ckpt_path is not None:
            self._ckpt_wait()  # queued saves commit first
            ckpt_dir = self._sync_save(state)
            write_marker(self.ckpt_path, step=step, epoch=state["epoch"],
                         checkpoint=ckpt_dir, reason=reason,
                         health=self._watchdog.verdict(step)
                         if self._watchdog is not None else None)
            logger.warning("preemption: final checkpoint %s and resumable "
                           "marker written", ckpt_dir)
        raise Preempted(reason, step=step, checkpoint=ckpt_dir)


def _skip_batches(it, n: int):
    """Drop the first `n` batches of an epoch iterator (mid-epoch resume:
    the checkpoint already trained on them; the replayed shuffle order
    makes the remainder identical to the uninterrupted run).  Lazy, so the
    skipping assembles in the feed worker, not on the step loop."""
    for i, item in enumerate(it):
        if i >= n:
            yield item


def _flatten_spec_axes(spec) -> set:
    """Mesh axis names referenced by a PartitionSpec."""
    axes = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.update(entry)
        else:
            axes.add(entry)
    return axes


def _shape_of_input(x) -> Any:
    if isinstance(x, (tuple, list)):
        return [tuple(np.asarray(v).shape) for v in x]
    return tuple(np.asarray(x).shape)


class LocalOptimizer(Optimizer):
    """Single-device trainer. reference: optim/LocalOptimizer.scala:45 —
    its per-core replica fan-out is XLA's job now."""

    def __init__(self, model: Module, dataset: DataSet, criterion: Criterion,
                 optim_method: Optional[OptimMethod] = None,
                 end_trigger: Optional[Trigger] = None,
                 compute_dtype: Optional[Any] = None):
        super().__init__(model, dataset, criterion, optim_method,
                         mesh=None, end_trigger=end_trigger,
                         compute_dtype=compute_dtype)


class DistriOptimizer(Optimizer):
    """Mesh-parallel trainer. reference: optim/DistriOptimizer.scala:49.
    Defaults to the Engine mesh (all devices on the data axis)."""

    def __init__(self, model: Module, dataset: DataSet, criterion: Criterion,
                 optim_method: Optional[OptimMethod] = None,
                 mesh: Optional[Mesh] = None,
                 end_trigger: Optional[Trigger] = None,
                 sharding_rules: Optional["ShardingRules"] = None,
                 batch_partition: Optional[P] = None,
                 compute_dtype: Optional[Any] = None):
        super().__init__(model, dataset, criterion, optim_method,
                         mesh=mesh or Engine.mesh(), end_trigger=end_trigger,
                         sharding_rules=sharding_rules,
                         batch_partition=batch_partition,
                         compute_dtype=compute_dtype)


class ParallelOptimizer(DistriOptimizer):
    """Layer-wise overlapped gradient sync.

    Reference: optim/ParallelOptimizer.scala:580 + the
    BlockManagerParameterSynchronizer (utils/DistriParameterSynchronizer.
    scala:36-135): each layer's gradient is published/reduced as its own
    block the moment its backward finishes, on a priority queue ordered by
    layer depth, so communication overlaps the rest of backward.

    TPU design: the step is built with `jax.shard_map` over the data axis.
    Each device runs fwd/bwd on its batch shard, and every parameter
    leaf's gradient is `lax.pmean`-reduced as its OWN collective (emitted
    per-leaf in backward order) instead of one fused all-reduce of the flat
    parameter vector.  XLA's latency-hiding scheduler then hoists each
    collective to run concurrently with the remaining backward computation
    — the hand-built priority-queue overlap, for free, at finer (per-leaf)
    granularity than the reference's per-layer blocks.

    `sharding_rules` COMPOSE with the overlap: only the 'data' axis is
    MANUAL in the shard_map (`axis_names={'data'}`); every other mesh
    axis stays under GSPMD, so tensor-parallel layouts propagate from the
    rule-sharded params exactly as on the DistriOptimizer path while the
    data-axis gradient sync keeps its per-leaf overlap schedule.

    BatchNormalization layers are switched to cross-shard statistics
    (`set_axis_name`) so training semantics match the pjit path's global
    batch stats (and the reference's `setParallism` sync-BN).
    """

    def optimize(self):
        if self.batch_partition is not None:
            raise ValueError(
                "ParallelOptimizer shards the batch P('data') only; use "
                "DistriOptimizer for a custom batch_partition")
        # sync-BN only while THIS trainer's shard_map step is being traced:
        # set the axis name for the run and restore afterwards, so the same
        # model can later train under plain jit (where a bound 'data' axis
        # would be an error)
        from bigdl_tpu.nn.conv import SpatialConvolutionBN
        from bigdl_tpu.nn.norm import BatchNormalization

        # flattened walk: residual-net BNs live nested inside Graph blocks
        # (a direct-children scan would silently skip them and lose the
        # sync-BN semantics).  keras-adapter layers build their inner nn
        # module lazily during _init_model, so a second patch pass runs
        # there (see _init_model below) — by then every inner exists.
        self._syncbn_saved = []
        self._patch_sync_bn()
        try:
            return super().optimize()
        finally:
            for m, a in self._syncbn_saved:
                m.set_axis_name(a)
            # None (not []): _init_model outside optimize() must not
            # re-patch axis names with no paired restore
            self._syncbn_saved = None

    def _patch_sync_bn(self) -> None:
        from bigdl_tpu.nn.conv import SpatialConvolutionBN
        from bigdl_tpu.nn.norm import BatchNormalization

        already = {id(m) for m, _ in self._syncbn_saved}
        stack = list(self.model.flattened_modules())
        visited = set()
        while stack:
            m = stack.pop()
            if id(m) in visited:
                continue
            visited.add(id(m))
            # keras-adapter layers hold their (lazily built) nn module as
            # `.inner`, which flattened_modules deliberately skips; after
            # _init_model it exists and its BNs need the axis too
            inner = getattr(m, "inner", None)
            if isinstance(inner, Module):
                stack.extend(inner.flattened_modules())
            if isinstance(m, (BatchNormalization, SpatialConvolutionBN)) \
                    and id(m) not in already:
                self._syncbn_saved.append((m, m.axis_name))
                m.set_axis_name(AXIS_DATA)

    def _init_model(self, first_batch) -> None:
        super()._init_model(first_batch)
        # lazily-built keras-adapter inners now exist; patch any BNs that
        # appeared, BEFORE the step is traced.  Without this second pass a
        # BN inside a keras layer silently trained on per-shard statistics
        # (PARITY known-gap, now closed).
        if getattr(self, "_syncbn_saved", None) is not None:
            self._patch_sync_bn()

    def _build_step(self):
        model, criterion = self.model, self.criterion
        optim, processors = self.optim_method, list(self.processors)
        regs = collect_regularizers(model)
        mesh = self.mesh
        host_lr = self._host_lr()
        watchdog = self._watchdog_enabled()

        def make_loss_fn(model_state, x, y, rng):
            def loss_fn(p):
                out, new_state = model.apply(p, model_state, x, training=True,
                                             rng=rng)
                # pmean the per-shard loss: autodiff then emits one psum per
                # parameter leaf (shard_map makes the cotangent of the
                # replicated params unvarying) — one overlappable collective
                # per layer tensor, the DistriParameterSynchronizer block
                # analogue.  An explicit post-grad pmean would double-count:
                # those cotangent psums already happened.
                local = criterion.forward(out, y)
                return jax.lax.pmean(local, AXIS_DATA), new_state
            return loss_fn

        rep = P()
        data = P(AXIS_DATA)
        if watchdog:
            # health flag, lr_scale and poison are replicated scalars; the
            # pmean'd loss and psum'd grads feeding the finite check are
            # replicated too, so the health out_spec is rep like the rest
            def shard_step_h(params, model_state, opt_state, x, y, rng, lr,
                             lr_scale, poison):
                return _finish_step_health(
                    make_loss_fn(model_state, x, y, rng), params,
                    model_state, opt_state, lr, lr_scale, poison, optim,
                    processors, regs, host_lr)

            sharded_h = jax.shard_map(
                shard_step_h, mesh=mesh,
                in_specs=(rep, rep, rep, data, data, rep, rep, rep, rep),
                out_specs=(rep, rep, rep, rep, rep, rep),
                axis_names=frozenset({AXIS_DATA}))
            return jax.jit(sharded_h, donate_argnums=(0, 1, 2))

        def shard_step(params, model_state, opt_state, x, y, rng, lr):
            (loss, new_model_state), grads = jax.value_and_grad(
                make_loss_fn(model_state, x, y, rng), has_aux=True)(params)
            grads = apply_regularizers(grads, params, regs)
            for proc in processors:
                grads = proc.process(grads)
            lr_used = lr if host_lr else optim.current_lr(opt_state)
            new_params, new_opt_state = optim.step(
                grads, params, opt_state, lr=(lr if host_lr else None))
            return new_params, new_model_state, new_opt_state, loss, lr_used

        # manual over 'data' only: the in/out specs constrain just the
        # data axis (params replicated over it), while tp/ep axes stay
        # AUTO — GSPMD propagates the rule-applied param shardings
        # through the body and inserts the model-axis collectives,
        # composing with the per-leaf data-axis gradient psums.  (On a
        # data-only mesh this equals full-manual shard_map.)
        sharded = jax.shard_map(
            shard_step, mesh=mesh,
            in_specs=(rep, rep, rep, data, data, rep, rep),
            out_specs=(rep, rep, rep, rep, rep),
            axis_names=frozenset({AXIS_DATA}))
        return jax.jit(sharded, donate_argnums=(0, 1, 2))
