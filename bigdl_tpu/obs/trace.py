"""Host-side span tracer: bounded ring, monotonic clocks, zero device syncs.

The tracer records *host* phase seams — the ones the HangWatchdog already
names (`feed_next`, `step_dispatch`, `ckpt_wait`) plus the serving request
lifecycle — into a lock-protected ring of plain tuples.  Nothing here ever
touches a device array, so traced hot loops stay legal under
`strict_transfers()` (jax.transfer_guard "disallow"); the only clock is
`time.perf_counter_ns()` (monotonic, ~20ns per read).

Export is Chrome-trace JSON (`chrome://tracing` / https://ui.perfetto.dev):
one lane per thread (pid = process, tid = thread ident, thread_name
metadata from the recording thread), "X" complete events for spans, "i"
instant events for point occurrences (watchdog stalls, checkpoint commits,
serving admissions).  Correlation ids ride in the event `args` so a
request can be followed across the submitter thread, the batcher lane,
and the dispatch lane.

The ring is bounded (`capacity` events, default 65536 ≈ a few MB); old
events fall off the front and `dropped` counts them, so an always-on
tracer can never grow without bound.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# Event tuples (kind, name, cat, tid, tname, ts_ns, dur_ns, args):
#   kind "X": complete span (dur_ns set), kind "i": instant (dur_ns = 0).
_KIND_SPAN = "X"
_KIND_INSTANT = "i"


class _SpanCtx:
    """Reusable-per-call span context: stamps enter/exit on one thread."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self._t0 = 0

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        self._tracer._append(_KIND_SPAN, self._name, self._cat,
                             self._t0, t1 - self._t0, self._args)
        return False


class SpanTracer:
    """Bounded in-memory trace ring with Chrome-trace export.

    `lane` / `lane_name` give the tracer an explicit pid-like lane: a
    merged fleet trace holds one SpanTracer per replica, and without an
    explicit lane every ring would export under the same os.getpid() and
    collide on tid.  `lane_name` becomes `M process_name` metadata so
    Perfetto shows "replica:r0" instead of a bare number."""

    def __init__(self, capacity: int = 65536, lane: Optional[int] = None,
                 lane_name: Optional[str] = None):
        self.capacity = int(capacity)
        self.lane = lane
        self.lane_name = lane_name
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self.dropped = 0
        # epoch so exported ts starts near 0 (µs since tracer creation)
        self._epoch_ns = time.perf_counter_ns()

    # -- recording (hot path: one lock + one deque append) -----------------

    def _append(self, kind: str, name: str, cat: str, ts_ns: int,
                dur_ns: int, args: Optional[Dict[str, Any]]) -> None:
        t = threading.current_thread()
        ev = (kind, name, cat, t.ident, t.name, ts_ns, dur_ns, args)
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(ev)

    def span(self, name: str, cat: str = "host", **args) -> _SpanCtx:
        """Context manager timing one host phase on the calling thread."""
        return _SpanCtx(self, name, cat, args or None)

    def instant(self, name: str, cat: str = "event", **args) -> None:
        """Point event (watchdog stall, ckpt commit, request admission)."""
        self._append(_KIND_INSTANT, name, cat, time.perf_counter_ns(), 0,
                     args or None)

    # -- inspection / export (cold path) -----------------------------------

    def events(self) -> List[tuple]:
        """Snapshot of the ring, oldest first (copies under the lock)."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    def to_chrome(self, epoch_ns: Optional[int] = None) -> Dict[str, Any]:
        """Chrome-trace dict: spans as "X", instants as "i", one
        thread_name metadata event per lane (+ a process_name metadata
        event when the tracer carries an explicit lane).  `epoch_ns`
        overrides the tracer's own epoch so rings from several tracers
        in one process export onto a shared timeline."""
        pid = self.lane if self.lane is not None else os.getpid()
        events = self.events()
        out: List[Dict[str, Any]] = []
        lanes: Dict[int, str] = {}
        epoch = self._epoch_ns if epoch_ns is None else int(epoch_ns)
        for kind, name, cat, tid, tname, ts_ns, dur_ns, args in events:
            lanes.setdefault(tid, tname)
            ev: Dict[str, Any] = {
                "ph": kind, "name": name, "cat": cat, "pid": pid,
                "tid": tid, "ts": (ts_ns - epoch) / 1e3,
            }
            if kind == _KIND_SPAN:
                ev["dur"] = dur_ns / 1e3
            else:
                ev["s"] = "t"  # thread-scoped instant
            if args:
                ev["args"] = dict(args)
            out.append(ev)
        meta: List[Dict[str, Any]] = []
        if self.lane_name is not None:
            meta.append({"ph": "M", "name": "process_name", "pid": pid,
                         "tid": 0, "args": {"name": self.lane_name}})
        meta.extend({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": tid, "args": {"name": tname}}
                    for tid, tname in lanes.items())
        return {"traceEvents": meta + out, "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self.dropped}}

    def export_chrome(self, path: str) -> Dict[str, Any]:
        """Write the Chrome-trace JSON to `path`; returns the dict."""
        doc = self.to_chrome()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return doc
