"""bigdl_tpu.obs — unified tracing, compile attribution, metrics plane.

One spine for everything the subsystems measure (docs/observability.md):

  * `SpanTracer` — host-side span/instant ring (trace.py), exported as
    Chrome-trace JSON via `export_trace(path)`; open in ui.perfetto.dev.
  * `CompileMonitor` — jax.monitoring-driven XLA compile attribution and
    steady-state recompile alarm (compile_monitor.py).
  * `MetricsRegistry` — counters/gauges with JSONL + Prometheus-textfile
    exporters and a TrainSummary/ServingSummary bridge (metrics.py).

Gating (`set_observability()` / env `BIGDL_TPU_OBS`):

  * metrics + compile monitor: DEFAULT ON (cheap: dict increments behind
    a lock, one listener callback per actual XLA compile).
  * tracing: OPT-IN (`BIGDL_TPU_OBS=trace` or
    `set_observability(tracing=True)`) — span recording costs ~1-2µs per
    span, bounded ring, still <1% of a step (bench_trainer_overhead
    --obs).  `BIGDL_TPU_OBS=0` turns the whole plane off.

Hot-loop contract: call `obs.tracer()` ONCE before the loop (returns None
when tracing is off) and guard each span with `if tr is not None`; the
module-level `span()`/`instant()` helpers do that lookup per call and are
for cold/warm paths only.  Nothing in this package touches device arrays,
so traced hot loops stay legal under `strict_transfers()`.
"""

from __future__ import annotations

import itertools
import os
import threading
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, Optional

from bigdl_tpu.obs.compile_monitor import (  # noqa: F401
    BACKEND_COMPILE_EVENT,
    PERSISTENT_CACHE_HIT_EVENT,
    CompileMonitor,
    install_monitor,
)
from bigdl_tpu.obs.flight import FlightRecorder  # noqa: F401
from bigdl_tpu.obs.flight import build_fleet_trace as _build_fleet_trace
from bigdl_tpu.obs.flight import request_timeline as _request_timeline
from bigdl_tpu.obs.metrics import MetricsRegistry, NullRegistry  # noqa: F401
from bigdl_tpu.obs.slo import SloMonitor, SLOObjective, mfu_estimate  # noqa: F401
from bigdl_tpu.obs.trace import SpanTracer  # noqa: F401

_NULL = nullcontext()

_state_lock = threading.Lock()
_tracer: Optional[SpanTracer] = None
_registry: MetricsRegistry = MetricsRegistry()
_monitor: Optional[CompileMonitor] = None
_flight: Optional[FlightRecorder] = None
_metrics_on = True
_cid_counter = itertools.count(1)


def _env_mode() -> str:
    return os.environ.get("BIGDL_TPU_OBS", "").strip().lower()


def set_observability(metrics: Optional[bool] = None,
                      tracing: Optional[bool] = None,
                      compile_monitor: Optional[bool] = None,
                      trace_capacity: int = 65536,
                      flight: Optional[bool] = None,
                      flight_dir: Optional[str] = None,
                      flight_min_interval_s: float = 30.0) -> Dict[str, bool]:
    """Flip parts of the plane; `None` leaves a part unchanged.  Enabling
    tracing swaps in a FRESH tracer ring (capacity `trace_capacity`);
    disabling drops it.  Enabling `flight` installs a FlightRecorder
    writing postmortem bundles under `flight_dir` (temp dir when None).
    Returns the resulting {metrics, tracing, compile_monitor, flight}
    state."""
    global _tracer, _monitor, _metrics_on, _registry, _flight
    with _state_lock:
        if metrics is not None:
            _metrics_on = bool(metrics)
            if not _metrics_on and not isinstance(_registry, NullRegistry):
                _registry = NullRegistry()
            elif _metrics_on and isinstance(_registry, NullRegistry):
                _registry = MetricsRegistry()
        if tracing is not None:
            _tracer = SpanTracer(trace_capacity) if tracing else None
        if compile_monitor is not None:
            if compile_monitor:
                _monitor = CompileMonitor(registry_fn=registry,
                                          tracer_fn=tracer)
            else:
                _monitor = None
            install_monitor(_monitor)
        if flight is not None:
            if _flight is not None:
                _flight.close()
                _flight = None
            if flight:
                _flight = FlightRecorder(
                    out_dir=flight_dir,
                    min_interval_s=flight_min_interval_s,
                    registry_fn=registry, tracer_fn=tracer,
                    state_fn=observability)
    return observability()


def observability() -> Dict[str, bool]:
    return {"metrics": _metrics_on, "tracing": _tracer is not None,
            "compile_monitor": _monitor is not None,
            "flight": _flight is not None}


def _init_from_env() -> None:
    mode = _env_mode()
    if mode in ("0", "off", "none"):
        set_observability(metrics=False, tracing=False,
                          compile_monitor=False)
    elif mode in ("1", "on", "trace", "full"):
        set_observability(metrics=True, tracing=True, compile_monitor=True)
    else:  # unset / "metrics": the default-on metrics plane
        set_observability(metrics=True, tracing=False, compile_monitor=True)
    # flight recorder: BIGDL_TPU_FLIGHT=1 (temp bundles) or =/some/dir
    fl = os.environ.get("BIGDL_TPU_FLIGHT", "").strip()
    if fl and fl not in ("0", "off", "none"):
        set_observability(flight=True,
                          flight_dir=None if fl in ("1", "on") else fl)
    # structured driver logs ride the same init: BIGDL_TPU_LOG_JSON=1
    # switches the bigdl_tpu logger to JSONL (utils/logger_filter.py)
    from bigdl_tpu.utils.logger_filter import maybe_enable_json_logs
    maybe_enable_json_logs()


# -- accessors (hot loops hoist these once per loop) -----------------------


def tracer() -> Optional[SpanTracer]:
    """Active tracer, or None when tracing is off (the hot-loop guard)."""
    return _tracer


def registry() -> MetricsRegistry:
    """Active metrics registry (a NullRegistry when metrics are off)."""
    return _registry


def set_registry(reg: MetricsRegistry) -> MetricsRegistry:
    """Swap the active registry (test isolation); returns the old one."""
    global _registry
    with _state_lock:
        old, _registry = _registry, reg
    return old


def compile_monitor() -> Optional[CompileMonitor]:
    return _monitor


def flight_recorder() -> Optional[FlightRecorder]:
    """Active flight recorder, or None when off."""
    return _flight


def flight_notify(reason: str, **details) -> Optional[str]:
    """A postmortem trigger fired (replica death, watchdog policy,
    steady-recompile alarm, budget exhaustion, SIGTERM).  No-op when the
    flight recorder is off; otherwise dedupes per reason and returns the
    bundle path when one was written."""
    fr = _flight
    return fr.notify(reason, **details) if fr is not None else None


def dump_flight(reason: str = "manual", **details) -> Optional[str]:
    """Explicitly write a postmortem bundle now (no dedupe).  Returns
    the bundle directory, or None when the recorder is off."""
    fr = _flight
    return fr.dump(reason, **details) if fr is not None else None


def next_cid() -> str:
    """Process-unique correlation id for one serving request."""
    return "r-%d" % next(_cid_counter)


# -- cold/warm-path conveniences -------------------------------------------


def span(name: str, cat: str = "host", **args):
    """Span ctx on the active tracer; a shared nullcontext when off.
    Cold/warm paths only — hot loops hoist `tracer()` instead."""
    tr = _tracer
    return tr.span(name, cat, **args) if tr is not None else _NULL


def instant(name: str, cat: str = "event", **args) -> None:
    tr = _tracer
    if tr is not None:
        tr.instant(name, cat, **args)


def attribute(signature: str):
    """Compile-attribution scope on the active monitor (nullcontext when
    the monitor is off)."""
    mon = _monitor
    return mon.attribute(signature) if mon is not None else _NULL


def export_trace(path: str) -> Dict[str, Any]:
    """Write the active tracer's ring as Chrome-trace JSON ({} if off)."""
    tr = _tracer
    if tr is None:
        return {}
    return tr.export_chrome(path)


def export_fleet_trace(path: Optional[str] = None,
                       extra_tracers=()) -> Dict[str, Any]:
    """Stitched fleet trace: router lane + one process-lane per replica
    + flow events linking each cid's admit -> dispatch -> complete chain
    (see obs/flight.py).  `extra_tracers` merges rings from tracers with
    explicit lanes (out-of-process replicas).  Returns {} when tracing
    is off; writes Chrome-trace JSON to `path` when given."""
    import json as _json

    tr = _tracer
    if tr is None:
        return {}
    doc = _build_fleet_trace(tr, extra_tracers)
    if path is not None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            _json.dump(doc, f)
        os.replace(tmp, path)
    return doc


def request_timeline(cid: str) -> Dict[str, Any]:
    """Hop-by-hop latency breakdown for one request cid from the active
    ring (queue wait, redispatches, batcher wait, device time, settle).
    {} when tracing is off."""
    tr = _tracer
    if tr is None:
        return {}
    return _request_timeline(tr, cid)


@contextmanager
def device_profile(logdir: str):
    """Opt-in jax.profiler session around a block, so a device profile
    and the host spans cover the same wall-clock window (correlate by
    timestamps; the host trace notes the profile bounds as instants)."""
    import jax
    instant("device_profile.start", cat="profile", logdir=logdir)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        instant("device_profile.stop", cat="profile", logdir=logdir)


_init_from_env()

__all__ = [
    "BACKEND_COMPILE_EVENT", "PERSISTENT_CACHE_HIT_EVENT",
    "CompileMonitor", "FlightRecorder", "MetricsRegistry",
    "NullRegistry", "SLOObjective", "SloMonitor", "SpanTracer",
    "attribute", "compile_monitor", "device_profile", "dump_flight",
    "export_fleet_trace", "export_trace", "flight_notify",
    "flight_recorder", "install_monitor", "instant", "mfu_estimate",
    "next_cid", "observability", "registry", "request_timeline",
    "set_observability", "set_registry", "span", "tracer",
]
