"""bigdl_tpu.obs — unified tracing, compile attribution, metrics plane.

One spine for everything the subsystems measure (docs/observability.md):

  * `SpanTracer` — host-side span/instant ring (trace.py), exported as
    Chrome-trace JSON via `export_trace(path)`; open in ui.perfetto.dev.
  * `CompileMonitor` — jax.monitoring-driven XLA compile attribution and
    steady-state recompile alarm (compile_monitor.py).
  * `MetricsRegistry` — counters/gauges with JSONL + Prometheus-textfile
    exporters and a TrainSummary/ServingSummary bridge (metrics.py).

Gating (`set_observability()` / env `BIGDL_TPU_OBS`):

  * metrics + compile monitor: DEFAULT ON (cheap: dict increments behind
    a lock, one listener callback per actual XLA compile).
  * tracing: OPT-IN (`BIGDL_TPU_OBS=trace` or
    `set_observability(tracing=True)`) — span recording costs ~1-2µs per
    span, bounded ring, still <1% of a step (bench_trainer_overhead
    --obs).  `BIGDL_TPU_OBS=0` turns the whole plane off.

Hot-loop contract: call `obs.tracer()` ONCE before the loop (returns None
when tracing is off) and guard each span with `if tr is not None`; the
module-level `span()`/`instant()` helpers do that lookup per call and are
for cold/warm paths only.  Nothing in this package touches device arrays,
so traced hot loops stay legal under `strict_transfers()`.
"""

from __future__ import annotations

import itertools
import os
import threading
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, Optional

from bigdl_tpu.obs.compile_monitor import (  # noqa: F401
    BACKEND_COMPILE_EVENT,
    PERSISTENT_CACHE_HIT_EVENT,
    CompileMonitor,
    install_monitor,
)
from bigdl_tpu.obs.metrics import MetricsRegistry, NullRegistry  # noqa: F401
from bigdl_tpu.obs.trace import SpanTracer  # noqa: F401

_NULL = nullcontext()

_state_lock = threading.Lock()
_tracer: Optional[SpanTracer] = None
_registry: MetricsRegistry = MetricsRegistry()
_monitor: Optional[CompileMonitor] = None
_metrics_on = True
_cid_counter = itertools.count(1)


def _env_mode() -> str:
    return os.environ.get("BIGDL_TPU_OBS", "").strip().lower()


def set_observability(metrics: Optional[bool] = None,
                      tracing: Optional[bool] = None,
                      compile_monitor: Optional[bool] = None,
                      trace_capacity: int = 65536) -> Dict[str, bool]:
    """Flip parts of the plane; `None` leaves a part unchanged.  Enabling
    tracing swaps in a FRESH tracer ring (capacity `trace_capacity`);
    disabling drops it.  Returns the resulting {metrics, tracing,
    compile_monitor} state."""
    global _tracer, _monitor, _metrics_on, _registry
    with _state_lock:
        if metrics is not None:
            _metrics_on = bool(metrics)
            if not _metrics_on and not isinstance(_registry, NullRegistry):
                _registry = NullRegistry()
            elif _metrics_on and isinstance(_registry, NullRegistry):
                _registry = MetricsRegistry()
        if tracing is not None:
            _tracer = SpanTracer(trace_capacity) if tracing else None
        if compile_monitor is not None:
            if compile_monitor:
                _monitor = CompileMonitor(registry_fn=registry,
                                          tracer_fn=tracer)
            else:
                _monitor = None
            install_monitor(_monitor)
    return observability()


def observability() -> Dict[str, bool]:
    return {"metrics": _metrics_on, "tracing": _tracer is not None,
            "compile_monitor": _monitor is not None}


def _init_from_env() -> None:
    mode = _env_mode()
    if mode in ("0", "off", "none"):
        set_observability(metrics=False, tracing=False,
                          compile_monitor=False)
    elif mode in ("1", "on", "trace", "full"):
        set_observability(metrics=True, tracing=True, compile_monitor=True)
    else:  # unset / "metrics": the default-on metrics plane
        set_observability(metrics=True, tracing=False, compile_monitor=True)
    # structured driver logs ride the same init: BIGDL_TPU_LOG_JSON=1
    # switches the bigdl_tpu logger to JSONL (utils/logger_filter.py)
    from bigdl_tpu.utils.logger_filter import maybe_enable_json_logs
    maybe_enable_json_logs()


# -- accessors (hot loops hoist these once per loop) -----------------------


def tracer() -> Optional[SpanTracer]:
    """Active tracer, or None when tracing is off (the hot-loop guard)."""
    return _tracer


def registry() -> MetricsRegistry:
    """Active metrics registry (a NullRegistry when metrics are off)."""
    return _registry


def set_registry(reg: MetricsRegistry) -> MetricsRegistry:
    """Swap the active registry (test isolation); returns the old one."""
    global _registry
    with _state_lock:
        old, _registry = _registry, reg
    return old


def compile_monitor() -> Optional[CompileMonitor]:
    return _monitor


def next_cid() -> str:
    """Process-unique correlation id for one serving request."""
    return "r-%d" % next(_cid_counter)


# -- cold/warm-path conveniences -------------------------------------------


def span(name: str, cat: str = "host", **args):
    """Span ctx on the active tracer; a shared nullcontext when off.
    Cold/warm paths only — hot loops hoist `tracer()` instead."""
    tr = _tracer
    return tr.span(name, cat, **args) if tr is not None else _NULL


def instant(name: str, cat: str = "event", **args) -> None:
    tr = _tracer
    if tr is not None:
        tr.instant(name, cat, **args)


def attribute(signature: str):
    """Compile-attribution scope on the active monitor (nullcontext when
    the monitor is off)."""
    mon = _monitor
    return mon.attribute(signature) if mon is not None else _NULL


def export_trace(path: str) -> Dict[str, Any]:
    """Write the active tracer's ring as Chrome-trace JSON ({} if off)."""
    tr = _tracer
    if tr is None:
        return {}
    return tr.export_chrome(path)


@contextmanager
def device_profile(logdir: str):
    """Opt-in jax.profiler session around a block, so a device profile
    and the host spans cover the same wall-clock window (correlate by
    timestamps; the host trace notes the profile bounds as instants)."""
    import jax
    instant("device_profile.start", cat="profile", logdir=logdir)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        instant("device_profile.stop", cat="profile", logdir=logdir)


_init_from_env()

__all__ = [
    "BACKEND_COMPILE_EVENT", "PERSISTENT_CACHE_HIT_EVENT",
    "CompileMonitor", "MetricsRegistry",
    "NullRegistry", "SpanTracer", "attribute", "compile_monitor",
    "device_profile", "export_trace", "install_monitor", "instant",
    "next_cid", "observability", "registry", "set_observability",
    "set_registry", "span", "tracer",
]
