"""MetricsRegistry: one spine for the counters scattered across subsystems.

Before this module, every subsystem grew its own mutable counter state —
`health.integrity.INTEGRITY_COUNTERS` (module-global dict),
`ParsedExampleDataSet.corrupt_records`, ServingMetrics' locked dict, the
FeedStallMs/FeedOccupancy scalars the trainer pushes straight into
TrainSummary.  The registry absorbs them behind one API:

  * `inc(name, n)`        — monotonically increasing counter
  * `set_gauge(name, v)`  — last-value gauge (throughput, occupancy, p99)
  * `get(name)`           — read either kind (counters win on collision)
  * `snapshot()`          — {"counters": {...}, "gauges": {...}} copy
  * `export_jsonl(path)`  — append one JSON line per call (tail-able)
  * `export_prometheus(path)` — node_exporter textfile-collector format
  * `to_summary(summary, step)` — bridge into TrainSummary/ServingSummary

Names are slash-namespaced (`integrity/verified`, `serving/batches`,
`feed/stall_ms`); exporters sanitize for their own formats.  A name may
carry a LABEL SUFFIX after `|` (`serving/latency_p99_ms|tenant=acme`,
comma-separated `k=v` pairs): the JSONL exporter passes it through
verbatim, while the Prometheus exporter renders it as a label set on the
base metric (`bigdl_tpu_serving_latency_p99_ms{tenant="acme"}`) — so a
multi-tenant fleet exports per-tenant series through the SAME registry
and metric family instead of a parallel metrics path.  The active
registry is process-global (`bigdl_tpu.obs.registry()`) but swappable
(`set_registry`) so parallel tests stop sharing counters — the back-compat
`INTEGRITY_COUNTERS` mapping in `health.integrity` reads *through* the
active registry rather than owning state.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, Optional, Tuple

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_]")


def prom_series(name: str, namespace: str = "bigdl_tpu") -> Tuple[str, str]:
    """Split a registry name into (prom_metric_name, label_block).

    `serving/p99|tenant=acme,tier=interactive` ->
    (`bigdl_tpu_serving_p99`, `{tenant="acme",tier="interactive"}`);
    label VALUES are escaped per the exposition format, label KEYS are
    sanitized like metric names.  No `|` -> empty label block.
    """
    base, _, labelpart = name.partition("|")
    prom = namespace + "_" + _PROM_BAD.sub("_", base)
    if not labelpart:
        return prom, ""
    pairs = []
    for item in labelpart.split(","):
        k, _, v = item.partition("=")
        v = v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
        pairs.append(f'{_PROM_BAD.sub("_", k)}="{v}"')
    return prom, "{" + ",".join(pairs) + "}"


class MetricsRegistry:
    """Thread-safe counter/gauge registry with JSONL + Prometheus export."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}

    # -- write path --------------------------------------------------------

    def inc(self, name: str, n: float = 1) -> float:
        with self._lock:
            v = self._counters.get(name, 0) + n
            self._counters[name] = v
            return v

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    # -- read path ---------------------------------------------------------

    def get(self, name: str, default: float = 0) -> float:
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, default)

    def counters(self, prefix: str = "") -> Dict[str, float]:
        with self._lock:
            return {k: v for k, v in self._counters.items()
                    if k.startswith(prefix)}

    def gauges(self, prefix: str = "") -> Dict[str, float]:
        with self._lock:
            return {k: v for k, v in self._gauges.items()
                    if k.startswith(prefix)}

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges)}

    def reset(self, prefix: str = "") -> None:
        """Zero counters and drop gauges under `prefix` ("" = everything)."""
        with self._lock:
            for k in list(self._counters):
                if k.startswith(prefix):
                    del self._counters[k]
            for k in list(self._gauges):
                if k.startswith(prefix):
                    del self._gauges[k]

    # -- exporters (cold path; never called from hot loops) ----------------

    def export_jsonl(self, path: str, step: Optional[int] = None,
                     extra: Optional[Dict[str, Any]] = None) -> None:
        """Append one snapshot line; a run's file is a tail-able series."""
        snap = self.snapshot()
        line: Dict[str, Any] = {"ts": time.time()}
        if step is not None:
            line["step"] = int(step)
        if extra:
            line.update(extra)
        line.update(snap)
        with open(path, "a") as f:
            f.write(json.dumps(line) + "\n")

    def export_prometheus(self, path: str,
                          namespace: str = "bigdl_tpu") -> None:
        """Write node_exporter textfile-collector format (atomic rename)."""
        snap = self.snapshot()
        lines = []
        for kind, series in (("counter", snap["counters"]),
                             ("gauge", snap["gauges"])):
            typed = set()  # one TYPE line per metric family, labels or not
            for name in sorted(series):
                prom, labels = prom_series(name, namespace)
                if prom not in typed:
                    typed.add(prom)
                    lines.append(f"# TYPE {prom} {kind}")
                lines.append(f"{prom}{labels} {series[name]}")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(lines) + "\n")
        os.replace(tmp, path)

    def to_summary(self, summary, step: int, prefix: str = "") -> None:
        """Bridge into TrainSummary/ServingSummary: one scalar per metric
        (slashes kept — the summary machinery namespaces on them)."""
        snap = self.snapshot()
        for series in (snap["counters"], snap["gauges"]):
            for name, value in series.items():
                if name.startswith(prefix):
                    summary.add_scalar(name, float(value), step)


class NullRegistry(MetricsRegistry):
    """Registry with recording disabled (`set_observability(metrics=False)`):
    writes are no-ops, reads return defaults, exporters write empties."""

    def inc(self, name: str, n: float = 1) -> float:  # noqa: ARG002
        return 0

    def set_gauge(self, name: str, value: float) -> None:
        pass
