"""Flight recorder + cross-replica trace stitching (the fleet black box).

Two halves, both host-only (no device arrays, monotonic clocks — traced
hot loops stay legal under `strict_transfers()`):

  * `FlightRecorder` — an always-on bounded ring of trigger notes plus a
    last-N tail of the `bigdl_tpu` driver log, dumping a postmortem
    bundle (stitched trace JSON + metrics snapshot + log tail +
    config/env fingerprint) when something dies: replica kill, watchdog
    rollback/abort/stall, steady-state recompile alarm, redispatch
    budget exhaustion, SIGTERM (via the PreemptionGuard), or an explicit
    `obs.dump_flight(reason)`.  Triggers are deduplicated per reason
    within `min_interval_s`, so one incident yields ONE bundle, not one
    per bounced request.

  * `build_fleet_trace` / `request_timeline` — stitch the fleet request
    lifecycle out of the span ring.  The in-process fleet shares one
    tracer, so replica separation is reconstructed from the router's
    `fleet.dispatch` instants (cid -> replica at time t): `serve.*` /
    `gen.*` events re-export under a per-replica pid lane with
    `process_name` metadata, the router's `fleet.*` events get their own
    lane, and flow events (`ph: s/t/f`, id = cid) link
    admit -> dispatch -> redispatch -> complete across lanes.  Rings
    from out-of-process replicas (SpanTracer with an explicit `lane`)
    merge in via `extra_tracers`.

Recording costs one lock + one deque append per trigger note; the dump
path (file IO, JSON) only runs on a trigger and is cold by definition.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("bigdl_tpu.obs")

__all__ = ["FlightRecorder", "build_fleet_trace", "request_timeline"]

# pid lanes for the synthesized fleet trace: the un-attributed process
# lane (trainer, submitter threads), the router, then one per replica
_LANE_PROCESS = 0
_LANE_ROUTER = 1
_LANE_REPLICA0 = 2


# ---------------------------------------------------------------------------
# trace stitching
# ---------------------------------------------------------------------------


def _event_cids(args: Optional[Dict[str, Any]]) -> Tuple[str, ...]:
    if not args:
        return ()
    cid = args.get("cid")
    if cid is not None:
        return (cid,)
    cids = args.get("cids")
    if isinstance(cids, (list, tuple)):
        return tuple(cids)
    return ()


def _dispatch_timeline(events: Sequence[tuple]) -> Dict[str, List[Tuple[int, str]]]:
    """cid -> [(ts_ns, replica), ...] from the router's dispatch instants."""
    out: Dict[str, List[Tuple[int, str]]] = {}
    for kind, name, _cat, _tid, _tn, ts_ns, _dur, args in events:
        if name == "fleet.dispatch" and args:
            cid, rep = args.get("cid"), args.get("replica")
            if cid is not None and rep is not None:
                out.setdefault(cid, []).append((ts_ns, rep))
    for seq in out.values():
        seq.sort()
    return out


def _replica_at(seq: List[Tuple[int, str]], ts_ns: int) -> Optional[str]:
    """The replica the cid was dispatched to most recently at `ts_ns`."""
    rep = None
    for t, r in seq:
        if t <= ts_ns:
            rep = r
        else:
            break
    return rep if rep is not None else (seq[0][1] if seq else None)


def build_fleet_trace(tracer, extra_tracers: Sequence = ()) -> Dict[str, Any]:
    """One Chrome-trace doc from the shared ring: router lane on top,
    one process-lane per replica, flow events linking each fleet cid's
    admit -> dispatch -> (redispatch ->) complete chain."""
    events = tracer.events()
    epoch = tracer._epoch_ns
    dispatches = _dispatch_timeline(events)
    replica_lane: Dict[str, int] = {}
    for seq in dispatches.values():
        for _ts, rep in seq:
            if rep not in replica_lane:
                replica_lane[rep] = _LANE_REPLICA0 + len(replica_lane)

    out: List[Dict[str, Any]] = []
    lanes_seen: Dict[Tuple[int, int], str] = {}  # (pid, tid) -> thread name
    chains: Dict[str, List[Tuple[int, int, int]]] = {}  # cid -> (ts, pid, tid)
    for kind, name, cat, tid, tname, ts_ns, dur_ns, args in events:
        cids = _event_cids(args)
        if name.startswith("fleet."):
            pid = _LANE_ROUTER
        elif cids and (name.startswith("serve.") or name.startswith("gen.")):
            pid = _LANE_PROCESS
            for cid in cids:
                seq = dispatches.get(cid)
                if seq:
                    rep = _replica_at(seq, ts_ns)
                    if rep is not None:
                        pid = replica_lane[rep]
                        break
        else:
            pid = _LANE_PROCESS
        lanes_seen.setdefault((pid, tid), tname)
        ev: Dict[str, Any] = {"ph": kind, "name": name, "cat": cat,
                              "pid": pid, "tid": tid,
                              "ts": (ts_ns - epoch) / 1e3}
        if kind == "X":
            ev["dur"] = dur_ns / 1e3
        else:
            ev["s"] = "t"
        if args:
            ev["args"] = dict(args)
        out.append(ev)
        # the request chain only follows lifecycle seams, not every
        # event that happens to mention the cid
        if name in ("fleet.admit", "fleet.dispatch", "fleet.redispatch",
                    "serve.complete", "gen.complete", "fleet.complete"):
            for cid in cids:
                if cid in dispatches:
                    chains.setdefault(cid, []).append((ts_ns, pid, tid))

    flows: List[Dict[str, Any]] = []
    for cid, hops in chains.items():
        if len(hops) < 2:
            continue
        hops.sort()
        for i, (ts_ns, pid, tid) in enumerate(hops):
            ph = "s" if i == 0 else ("f" if i == len(hops) - 1 else "t")
            fl = {"ph": ph, "name": "fleet.request", "cat": "fleet",
                  "id": cid, "pid": pid, "tid": tid,
                  "ts": (ts_ns - epoch) / 1e3}
            if ph == "f":
                fl["bp"] = "e"
            flows.append(fl)

    lane_names = {_LANE_PROCESS: "process", _LANE_ROUTER: "fleet-router"}
    lane_names.update({lane: f"replica:{rep}"
                       for rep, lane in replica_lane.items()})
    meta: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": pname}} for pid, pname in sorted(lane_names.items())]
    meta.extend({"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                 "args": {"name": tname}}
                for (pid, tid), tname in lanes_seen.items())

    dropped = tracer.dropped
    merged = meta + out + flows
    for extra in extra_tracers:
        doc = extra.to_chrome(epoch_ns=epoch)
        merged.extend(doc["traceEvents"])
        dropped += doc["otherData"]["dropped_events"]
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "otherData": {"dropped_events": dropped,
                          # string keys: the doc must round-trip as JSON
                          "replica_lanes": {str(pid): pname for pid, pname
                                            in lane_names.items()}}}


def request_timeline(tracer, cid: str) -> Dict[str, Any]:
    """Hop-by-hop latency reconstruction for one fleet request: every
    lifecycle event carrying the cid, plus the derived breakdown (fleet
    queue wait, redispatch count, batcher wait, device time, settle)."""
    events = tracer.events()
    epoch = tracer._epoch_ns
    hops: List[Dict[str, Any]] = []
    named: Dict[str, List[tuple]] = {}
    for kind, name, _cat, _tid, _tn, ts_ns, dur_ns, args in events:
        if cid not in _event_cids(args):
            continue
        row = {"name": name, "ts_ms": (ts_ns - epoch) / 1e3,
               "dur_ms": dur_ns / 1e3 if kind == "X" else None,
               "args": dict(args) if args else {}}
        hops.append(row)
        named.setdefault(name, []).append((ts_ns, dur_ns, args))
    hops.sort(key=lambda r: r["ts_ms"])

    def first(name):
        seq = named.get(name)
        return min(seq) if seq else None

    def last(name):
        seq = named.get(name)
        return max(seq) if seq else None

    admit = first("fleet.admit")
    disp = first("fleet.dispatch")
    serve_admit = last("serve.admit") or last("gen.admit")
    serve_disp = last("serve.dispatch") or last("gen.prefill")
    complete = last("serve.complete") or last("gen.complete")
    settle = last("fleet.complete")
    out: Dict[str, Any] = {
        "cid": cid, "hops": hops,
        "redispatches": len(named.get("fleet.redispatch", ())),
        "replicas": [a.get("replica") for _t, _d, a in
                     sorted(named.get("fleet.dispatch", ())) if a],
    }

    def ms(a, b):
        return (b[0] - a[0]) / 1e6 if a and b else None

    out["queue_wait_ms"] = ms(admit, disp)
    out["batcher_wait_ms"] = ms(serve_admit, serve_disp)
    out["device_ms"] = serve_disp[1] / 1e6 if serve_disp else None
    out["settle_ms"] = ms(complete, settle)
    if hops:
        out["total_ms"] = hops[-1]["ts_ms"] - hops[0]["ts_ms"]
    return out


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class _LogTail(logging.Handler):
    """Last-N formatted driver log lines, bounded, lock via deque."""

    def __init__(self, n: int):
        super().__init__(level=logging.DEBUG)
        self.ring: deque = deque(maxlen=n)
        self.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.ring.append(self.format(record))
        except Exception:  # noqa: BLE001 — logging must never raise
            pass


class FlightRecorder:
    """Bounded trigger ring + postmortem bundle writer.

    Accessors are injected (same pattern as CompileMonitor) so the
    recorder never imports the obs package it lives under:

      * `registry_fn` -> the active MetricsRegistry
      * `tracer_fn`   -> the active SpanTracer or None
      * `state_fn`    -> the current observability() dict

    `notify(reason)` is the trigger path: cheap note always, bundle dump
    at most once per `min_interval_s` per reason and at most
    `max_bundles` total.  `dump(reason)` is unconditional (the explicit
    `obs.dump_flight()` API).
    """

    def __init__(self, out_dir: Optional[str] = None, capacity: int = 2048,
                 log_lines: int = 256, min_interval_s: float = 30.0,
                 max_bundles: int = 16,
                 registry_fn: Optional[Callable] = None,
                 tracer_fn: Optional[Callable] = None,
                 state_fn: Optional[Callable] = None):
        self.out_dir = out_dir
        self.min_interval_s = float(min_interval_s)
        self.max_bundles = int(max_bundles)
        self._registry_fn = registry_fn
        self._tracer_fn = tracer_fn
        self._state_fn = state_fn
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(capacity))
        self._last_dump: Dict[str, float] = {}
        self._seq = 0
        self.bundles: List[str] = []
        self.triggers = 0
        self._log_tail = _LogTail(int(log_lines))
        logging.getLogger("bigdl_tpu").addHandler(self._log_tail)

    # -- recording (hot enough to stay tiny) -------------------------------

    def note(self, kind: str, **details) -> None:
        """Breadcrumb into the ring without any dump consideration."""
        with self._lock:
            self._ring.append((time.perf_counter_ns(), kind, details))

    def notify(self, reason: str, **details) -> Optional[str]:
        """A trigger fired.  Returns the bundle path if one was written."""
        now = time.monotonic()
        with self._lock:
            self.triggers += 1
            self._ring.append((time.perf_counter_ns(), reason, details))
            last = self._last_dump.get(reason)
            dump = (len(self.bundles) < self.max_bundles
                    and (last is None or now - last >= self.min_interval_s))
            if dump:
                self._last_dump[reason] = now
        reg = self._registry_fn() if self._registry_fn else None
        if reg is not None:
            reg.inc("flight/triggers_total")
            reg.inc(f"flight/triggers_total|reason={reason}")
        if not dump:
            return None
        return self.dump(reason, **details)

    # -- bundle writer (cold path) -----------------------------------------

    def _bundle_dir(self, reason: str) -> str:
        base = self.out_dir
        if base is None:
            import tempfile

            base = tempfile.mkdtemp(prefix="bigdl_tpu_flight_")
            self.out_dir = base
        os.makedirs(base, exist_ok=True)
        with self._lock:
            self._seq += 1
            seq = self._seq
        slug = "".join(c if c.isalnum() else "_" for c in reason)[:48]
        path = os.path.join(base, f"flight_{seq:03d}_{slug}")
        os.makedirs(path, exist_ok=True)
        return path

    def _fingerprint(self) -> Dict[str, Any]:
        fp: Dict[str, Any] = {
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "python": sys.version.split()[0],
            "cwd": os.getcwd(),
            "env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith(("BIGDL_TPU_", "JAX_", "XLA_"))},
        }
        try:
            import jax

            fp["jax"] = jax.__version__
        except Exception:  # noqa: BLE001 — fingerprint must never fail
            pass
        if self._state_fn is not None:
            fp["observability"] = self._state_fn()
        return fp

    def dump(self, reason: str, **details) -> str:
        """Write one postmortem bundle; returns its directory path."""
        path = self._bundle_dir(reason)
        with self._lock:
            ring = [{"ts_ns": t, "kind": k, "details": d}
                    for t, k, d in self._ring]
            log_lines = list(self._log_tail.ring)
        manifest = {
            "reason": reason, "details": details,
            "unix_time": time.time(), "triggers_seen": self.triggers,
            "bundle": os.path.basename(path),
            "contents": ["MANIFEST.json", "fingerprint.json", "events.json",
                         "log_tail.txt", "metrics.json", "trace.json"],
        }
        tr = self._tracer_fn() if self._tracer_fn else None
        reg = self._registry_fn() if self._registry_fn else None
        try:
            with open(os.path.join(path, "MANIFEST.json"), "w") as f:
                json.dump(manifest, f, indent=2)
            with open(os.path.join(path, "fingerprint.json"), "w") as f:
                json.dump(self._fingerprint(), f, indent=2)
            with open(os.path.join(path, "events.json"), "w") as f:
                json.dump(ring, f)
            with open(os.path.join(path, "log_tail.txt"), "w") as f:
                f.write("\n".join(log_lines) + ("\n" if log_lines else ""))
            if reg is not None:
                with open(os.path.join(path, "metrics.json"), "w") as f:
                    json.dump(reg.snapshot(), f, indent=2, default=str)
            # tracing off still yields a bundle whose trace.json simply
            # carries no spans — consumers get one fixed file set either way
            trace_doc = build_fleet_trace(tr) if tr is not None else {
                "traceEvents": [], "displayTimeUnit": "ms",
                "otherData": {"dropped_events": 0, "replica_lanes": {}}}
            with open(os.path.join(path, "trace.json"), "w") as f:
                json.dump(trace_doc, f)
        except OSError:
            logger.exception("flight recorder could not write bundle %s",
                             path)
        with self._lock:
            self.bundles.append(path)
        if reg is not None:
            reg.inc("flight/dumps_total")
        logger.warning("flight recorder: postmortem bundle for %r at %s",
                       reason, path, extra={"reason": reason})
        return path

    def close(self) -> None:
        logging.getLogger("bigdl_tpu").removeHandler(self._log_tail)
