"""Per-tenant SLO objectives, multi-window burn-rate alerting, goodput.

The fleet exports per-tenant latency histograms and rejection counters
(`serving/...{tenant="..."}`) but nothing watches them.  This module is
the watcher, after the SRE-workbook multi-window pattern:

  * an `SLOObjective` names the targets for one tenant — p99 latency,
    deadline-miss rate, TTFT p99 for generation tenants — each with an
    error budget (the tolerated fraction of bad requests; 1% for a p99
    target by construction).
  * `SloMonitor.tick()` snapshots the tenant's counters/histograms and
    evaluates each objective as a burn rate over TWO windows — fast
    (default 60 s: catches a cliff) and slow (default 1800 s: ignores a
    blip) — where burn = observed bad-request rate / budget.  An alert
    fires only when BOTH windows burn past their thresholds (fast 14x /
    slow 6x, the page-worthy tier), increments `slo/alerts_total` (+
    per-tenant label), lands in the trace as an `slo.alert` instant, and
    re-arms once the fast window recovers.
  * goodput — completed-in-deadline requests / everything dispatched —
    exports as `slo/goodput{tenant=...}` per tick; the max burn rate
    across tenants exports as `slo/burn_rate{tenant=...}` and feeds the
    FleetAutoscaler's grow signal.

Windowing is snapshot-delta: the monitor keeps a bounded deque of
(t, counts) rows and differences against the oldest row inside each
window, so cumulative counters work unchanged and nothing here needs a
thread — tick from the autoscaler loop, a test, or any periodic caller.
Everything is host-side arithmetic on already-host counters: zero
device syncs, legal under `strict_transfers()`.

The trainer-side `mfu_estimate` is the same discipline for training:
model FLOPs/step (6 * params * rows for the standard fwd+bwd) over
step time, against `BIGDL_TPU_PEAK_TFLOPS` when the operator declares
the hardware peak.
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("bigdl_tpu.obs")

__all__ = ["SLOObjective", "SloMonitor", "mfu_estimate"]


class SLOObjective:
    """Targets + error budget for one tenant.

    Parameters
    ----------
    tenant : tenant name (matches the fleet's TenantConfig.name).
    p99_ms : end-to-end latency target; a request slower than this is a
        budget-burning "bad" request.  Budget 1% by construction (p99).
    deadline_miss_rate : tolerated fraction of deadline rejections
        (None disables the dimension).
    ttft_p99_ms : time-to-first-token target for generation tenants.
    budget : error budget for the latency dimensions (default 0.01).
    """

    def __init__(self, tenant: str, p99_ms: Optional[float] = None,
                 deadline_miss_rate: Optional[float] = None,
                 ttft_p99_ms: Optional[float] = None,
                 budget: float = 0.01):
        if p99_ms is None and deadline_miss_rate is None \
                and ttft_p99_ms is None:
            raise ValueError(f"objective for {tenant!r} has no targets")
        self.tenant = tenant
        self.p99_ms = p99_ms
        self.deadline_miss_rate = deadline_miss_rate
        self.ttft_p99_ms = ttft_p99_ms
        self.budget = float(budget)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"SLOObjective({self.tenant!r}, p99_ms={self.p99_ms}, "
                f"deadline_miss_rate={self.deadline_miss_rate}, "
                f"ttft_p99_ms={self.ttft_p99_ms})")


def _counts_for(metrics, obj: SLOObjective) -> Dict[str, float]:
    """Cumulative counts the burn-rate math differences.  `metrics` is a
    ServingMetrics or GenerationMetrics (duck-typed: histograms +
    counters both expose the same names)."""
    total_hist = getattr(metrics, "total_ms", None) \
        or getattr(metrics, "e2e_ms", None)
    row: Dict[str, float] = {
        "completed": float(getattr(metrics, "requests_completed", 0)),
        "deadline_rejected": float(getattr(metrics, "rejected_deadline", 0)),
        "dispatched": float(getattr(metrics, "requests_completed", 0)
                            + getattr(metrics, "rejected_deadline", 0)
                            + getattr(metrics, "rejected_shutdown", 0)
                            + getattr(metrics, "rejected_nonfinite", 0)),
    }
    if obj.p99_ms is not None and total_hist is not None:
        row["slow"] = float(total_hist.count_above(obj.p99_ms))
        row["latency_n"] = float(total_hist.count)
    ttft = getattr(metrics, "ttft_ms", None)
    if obj.ttft_p99_ms is not None and ttft is not None:
        row["ttft_slow"] = float(ttft.count_above(obj.ttft_p99_ms))
        row["ttft_n"] = float(ttft.count)
    return row


class SloMonitor:
    """Multi-window burn-rate evaluator over per-tenant fleet metrics.

    `source` maps a tenant name to its live metrics object — pass
    `router.tenant_metrics` for the fleet, or any callable for direct
    ServingMetrics/GenerationMetrics.  Call `tick()` periodically (the
    autoscaler's signal closure is the natural place); pass `now` in
    tests to script time.
    """

    def __init__(self, objectives: List[SLOObjective],
                 source: Callable[[str], Any],
                 fast_window_s: float = 60.0, slow_window_s: float = 1800.0,
                 fast_burn_threshold: float = 14.0,
                 slow_burn_threshold: float = 6.0,
                 registry_fn: Optional[Callable] = None):
        self.objectives = list(objectives)
        self.source = source
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn_threshold = float(fast_burn_threshold)
        self.slow_burn_threshold = float(slow_burn_threshold)
        self._registry_fn = registry_fn
        # (t, {tenant: counts}) rows, bounded by the slow window
        self._rows: deque = deque()
        self._firing: Dict[str, bool] = {}  # "tenant/dimension" -> armed
        self.alerts: List[Dict[str, Any]] = []

    # ------------------------------------------------------------------

    def _burn(self, cur: Dict[str, float], old: Dict[str, float],
              bad_key: str, total_key: str, budget: float) -> float:
        bad = cur.get(bad_key, 0.0) - old.get(bad_key, 0.0)
        total = cur.get(total_key, 0.0) - old.get(total_key, 0.0)
        if total <= 0.0:
            return 0.0
        return (bad / total) / max(budget, 1e-9)

    def _window_rows(self, now: float, window_s: float,
                     tenant: str) -> Optional[Dict[str, float]]:
        """The snapshot closest to (at or before) the window start, so
        the burn delta covers at least `window_s` of history — never a
        stale superset when newer baselines exist.  When every row is
        inside the window (cold start) the oldest row is the best
        available baseline: the slow window means 'all time so far'."""
        chosen = None
        for t, per_tenant in self._rows:
            if tenant not in per_tenant:
                continue
            if chosen is None or t <= now - window_s:
                chosen = per_tenant[tenant]
            if t > now - window_s:
                break
        return chosen

    def tick(self, now: Optional[float] = None) -> Dict[str, Dict[str, Any]]:
        """Evaluate every objective; returns {tenant: verdict}."""
        now = time.monotonic() if now is None else float(now)
        reg = self._registry_fn() if self._registry_fn else None
        cur_row: Dict[str, Dict[str, float]] = {}
        out: Dict[str, Dict[str, Any]] = {}
        for obj in self.objectives:
            metrics = self.source(obj.tenant)
            if metrics is None:
                continue
            cur = _counts_for(metrics, obj)
            cur_row[obj.tenant] = cur
            dims: Dict[str, Dict[str, float]] = {}
            if obj.p99_ms is not None:
                dims["latency"] = {"bad": cur.get("slow", 0.0),
                                   "n": cur.get("latency_n", 0.0),
                                   "budget": obj.budget,
                                   "bad_key": "slow",
                                   "total_key": "latency_n"}
            if obj.deadline_miss_rate is not None:
                dims["deadline"] = {"budget": obj.deadline_miss_rate,
                                    "bad_key": "deadline_rejected",
                                    "total_key": "dispatched"}
            if obj.ttft_p99_ms is not None:
                dims["ttft"] = {"budget": obj.budget,
                                "bad_key": "ttft_slow",
                                "total_key": "ttft_n"}
            verdict: Dict[str, Any] = {"alerts": [], "burn_fast": 0.0,
                                       "burn_slow": 0.0}
            fast_old = self._window_rows(now, self.fast_window_s, obj.tenant)
            slow_old = self._window_rows(now, self.slow_window_s, obj.tenant)
            zero: Dict[str, float] = {}
            for dim, spec in dims.items():
                burn_fast = self._burn(cur, fast_old or zero,
                                       spec["bad_key"], spec["total_key"],
                                       spec["budget"])
                burn_slow = self._burn(cur, slow_old or zero,
                                       spec["bad_key"], spec["total_key"],
                                       spec["budget"])
                verdict["burn_fast"] = max(verdict["burn_fast"], burn_fast)
                verdict["burn_slow"] = max(verdict["burn_slow"], burn_slow)
                key = f"{obj.tenant}/{dim}"
                firing = (burn_fast >= self.fast_burn_threshold
                          and burn_slow >= self.slow_burn_threshold)
                if firing and not self._firing.get(key):
                    self._firing[key] = True
                    alert = {"tenant": obj.tenant, "dimension": dim,
                             "burn_fast": round(burn_fast, 3),
                             "burn_slow": round(burn_slow, 3)}
                    verdict["alerts"].append(alert)
                    self.alerts.append(alert)
                    if reg is not None:
                        reg.inc("slo/alerts_total")
                        reg.inc(f"slo/alerts_total|tenant={obj.tenant}")
                    from bigdl_tpu import obs as _obs

                    _obs.instant("slo.alert", cat="slo", tenant=obj.tenant,
                                 dimension=dim,
                                 burn_fast=round(burn_fast, 3),
                                 burn_slow=round(burn_slow, 3))
                    logger.warning(
                        "SLO burn-rate alert: tenant %r dimension %s "
                        "burning %.1fx fast / %.1fx slow (thresholds "
                        "%gx/%gx)", obj.tenant, dim, burn_fast, burn_slow,
                        self.fast_burn_threshold, self.slow_burn_threshold,
                        extra={"tenant": obj.tenant})
                elif not firing and burn_fast < self.fast_burn_threshold:
                    self._firing[key] = False  # re-arm once fast recovers
            dispatched = cur.get("dispatched", 0.0)
            goodput = (cur.get("completed", 0.0) / dispatched
                       if dispatched else 1.0)
            verdict["goodput"] = goodput
            if reg is not None:
                reg.set_gauge(f"slo/burn_rate|tenant={obj.tenant}",
                              verdict["burn_fast"])
                reg.set_gauge(f"slo/goodput|tenant={obj.tenant}", goodput)
            out[obj.tenant] = verdict
        self._rows.append((now, cur_row))
        while self._rows and self._rows[0][0] < now - self.slow_window_s:
            self._rows.popleft()
        return out

    def max_burn_rate(self) -> float:
        """Latest max fast-window burn across tenants (autoscaler grow
        signal; 0.0 before the first tick)."""
        reg = self._registry_fn() if self._registry_fn else None
        if reg is None:
            return 0.0
        burns = [v for k, v in reg.gauges().items()
                 if k.startswith("slo/burn_rate")]
        return max(burns) if burns else 0.0


def mfu_estimate(n_params: int, rows: float, step_time_s: float,
                 flops_per_row: Optional[float] = None,
                 peak_flops: Optional[float] = None) -> Dict[str, float]:
    """Step-time-derived model-FLOPs utilisation.

    `flops_per_row` defaults to the standard dense fwd+bwd estimate
    (6 * params); `peak_flops` defaults to `BIGDL_TPU_PEAK_TFLOPS` * 1e12
    when set.  Returns {"model_flops_per_s": ..., "mfu": ...} with mfu
    0.0 when no peak is declared (an estimate against an unknown peak is
    noise, not a metric)."""
    if step_time_s <= 0.0:
        return {"model_flops_per_s": 0.0, "mfu": 0.0}
    if flops_per_row is None:
        flops_per_row = 6.0 * float(n_params)
    achieved = flops_per_row * float(rows) / float(step_time_s)
    if peak_flops is None:
        peak_env = os.environ.get("BIGDL_TPU_PEAK_TFLOPS")
        peak_flops = float(peak_env) * 1e12 if peak_env else 0.0
    mfu = achieved / peak_flops if peak_flops else 0.0
    return {"model_flops_per_s": achieved, "mfu": mfu}
