"""Runtime compile-event monitor: attribute XLA compiles, alarm recompiles.

`tpu_lint`'s recompile rules are static — they catch `self.` reads inside
jitted code before it ships.  This module is the *runtime* alarm for
whatever the linter can't see: it listens to `jax.monitoring`'s
`/jax/core/compile/backend_compile_duration` event (fired once per actual
backend compile; jit cache hits fire nothing) and attributes each compile
to the bucket/step signature the caller declared.

Attribution is scope-based because the monitoring event carries no source
info: compiles run synchronously on the thread that triggered them, so a
thread-local stack of `attribute("serving/bucket=8")` scopes names every
compile that fires inside.  Compiles outside any scope land under
"unattributed".

Warmup vs steady-state is decided per signature by *settling*: a
signature's compiles count as warmup until some later `attribute(sig)`
entry completes with zero new compiles — proof the executable set for
that signature is cached.  Every compile after that is a steady-state
RECOMPILE: the executable set grew when it should have been closed
(exactly the condition the lint rules guard against, e.g. a shape leak
past the bucket padding or a `self` read baked into a jitted closure).
`mark_steady()` force-settles (the serving registry calls it after
warmup, so the very first post-warmup compile alarms).

jax.monitoring has no selective unregister (only a global
clear_event_listeners), so ONE process-global listener is registered
lazily and forwards to the swappable active monitor — tests swap
monitors, never the listener.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger("bigdl_tpu.obs")

BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# jax's persistent compilation cache fires this (plain event, no duration)
# INSTEAD of BACKEND_COMPILE_EVENT on a disk hit — backend_compile is
# skipped entirely, so a warm second process compiles nothing.
PERSISTENT_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
UNATTRIBUTED = "unattributed"

_listener_lock = threading.Lock()
_listener_installed = False
_active_monitor: Optional["CompileMonitor"] = None


def _forward(event: str, duration: float, **kwargs) -> None:
    mon = _active_monitor
    if mon is not None and event == BACKEND_COMPILE_EVENT:
        mon.on_compile(duration)


def _forward_event(event: str, **kwargs) -> None:
    mon = _active_monitor
    if mon is not None and event == PERSISTENT_CACHE_HIT_EVENT:
        mon.on_persistent_cache_hit()


def install_monitor(monitor: Optional["CompileMonitor"]) -> None:
    """Make `monitor` the target of the process-global jax.monitoring
    listener (None detaches).  The listener itself is registered once,
    ever — jax.monitoring cannot unregister a single listener."""
    global _listener_installed, _active_monitor
    with _listener_lock:
        _active_monitor = monitor
        if monitor is not None and not _listener_installed:
            from jax import monitoring as _jm
            _jm.register_event_duration_secs_listener(_forward)
            _jm.register_event_listener(_forward_event)
            _listener_installed = True


def active_monitor() -> Optional["CompileMonitor"]:
    return _active_monitor


class _Scope:
    __slots__ = ("_mon", "_sig", "_compiles_at_entry")

    def __init__(self, mon: "CompileMonitor", sig: str):
        self._mon = mon
        self._sig = sig
        self._compiles_at_entry = 0

    def __enter__(self):
        self._compiles_at_entry = self._mon._enter_scope(self._sig)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._mon._exit_scope(self._sig, self._compiles_at_entry)
        return False


class _LoadScope:
    """Attribution scope + thread-local in-cache-load flag: compiles that
    fire while a serialized executable is being deserialized are warmup
    by definition (restart recovery), never steady-state recompiles.
    Unlike `_Scope`, entering/leaving takes NO part in settling — a load
    proves nothing about the signature's executable set being closed."""

    __slots__ = ("_mon", "_sig")

    def __init__(self, mon: "CompileMonitor", sig: str):
        self._mon = mon
        self._sig = sig

    def __enter__(self):
        self._mon._stack().append(self._sig)
        tls = self._mon._tls
        tls.in_cache_load = getattr(tls, "in_cache_load", 0) + 1
        return self

    def __exit__(self, exc_type, exc, tb):
        tls = self._mon._tls
        tls.in_cache_load = max(0, getattr(tls, "in_cache_load", 1) - 1)
        st = self._mon._stack()
        if st and st[-1] == self._sig:
            st.pop()
        return False


class CompileMonitor:
    """Per-signature compile accounting with warmup/steady-state split."""

    def __init__(self, registry_fn: Callable[[], Any] = None,
                 tracer_fn: Callable[[], Any] = None,
                 history: int = 1024):
        self._registry_fn = registry_fn
        self._tracer_fn = tracer_fn
        self._lock = threading.Lock()
        # sig -> {"compiles", "recompiles", "secs", "settled"}
        self._sigs: Dict[str, Dict[str, Any]] = {}
        self.records: deque = deque(maxlen=history)
        self._tls = threading.local()

    # -- attribution scopes (hot-adjacent: two dict ops per entry) ---------

    def attribute(self, signature: str) -> _Scope:
        """Scope naming every compile that fires inside (this thread)."""
        return _Scope(self, signature)

    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _enter_scope(self, sig: str) -> int:
        self._stack().append(sig)
        with self._lock:
            rec = self._sigs.get(sig)
            return rec["compiles"] if rec else 0

    def _exit_scope(self, sig: str, compiles_at_entry: int) -> None:
        st = self._stack()
        if st and st[-1] == sig:
            st.pop()
        with self._lock:
            rec = self._sigs.get(sig)
            # settle: a re-entry that compiled nothing proves the
            # executable set for this signature is closed and cached
            if (rec is not None and not rec["settled"]
                    and compiles_at_entry > 0
                    and rec["compiles"] == compiles_at_entry):
                rec["settled"] = True

    def mark_steady(self, prefix: str = "") -> None:
        """Force-settle signatures under `prefix` (""= all): any further
        compile under them is a steady-state recompile alarm."""
        with self._lock:
            for sig, rec in self._sigs.items():
                if sig.startswith(prefix):
                    rec["settled"] = True

    # -- executable-cache awareness ----------------------------------------

    def cache_load(self, signature: str):
        """Scope for deserializing a cached executable: attributes any
        stray compile inside to `signature` AND classifies it as warmup —
        loading a stored executable after restart is the *opposite* of a
        steady-state recompile, even if the signature already settled."""
        return _LoadScope(self, signature)

    def note_cache_load(self, signature: str, duration_s: float = 0.0) -> None:
        """Record one deserialized-executable load (NOT a compile)."""
        with self._lock:
            rec = self._rec(signature)
            rec["cache_loads"] += 1
            rec["load_secs"] += duration_s

    def on_persistent_cache_hit(self) -> None:
        """jax's persistent compilation cache served a disk hit: the jit
        path warmed without a backend compile.  Counted as a cache load
        for the current scope so warm restarts are visible, never as a
        compile/recompile."""
        st = getattr(self._tls, "stack", None)
        sig = st[-1] if st else UNATTRIBUTED
        with self._lock:
            rec = self._rec(sig)
            rec["cache_loads"] += 1
        reg = self._registry_fn() if self._registry_fn else None
        if reg is not None:
            reg.inc("compile/persistent_cache_hits")

    def _rec(self, sig: str) -> Dict[str, Any]:
        rec = self._sigs.get(sig)
        if rec is None:
            rec = self._sigs[sig] = {
                "compiles": 0, "recompiles": 0, "secs": 0.0,
                "settled": False, "cache_loads": 0, "load_secs": 0.0}
        else:
            # records written by pre-cache code paths lack the load keys
            rec.setdefault("cache_loads", 0)
            rec.setdefault("load_secs", 0.0)
        return rec

    # -- listener target ---------------------------------------------------

    def on_compile(self, duration_s: float) -> None:
        st = getattr(self._tls, "stack", None)
        sig = st[-1] if st else UNATTRIBUTED
        in_load = bool(getattr(self._tls, "in_cache_load", 0))
        with self._lock:
            rec = self._rec(sig)
            steady = rec["settled"] and not in_load
            rec["compiles"] += 1
            rec["secs"] += duration_s
            if steady:
                rec["recompiles"] += 1
            self.records.append((sig, duration_s, steady))
        reg = self._registry_fn() if self._registry_fn else None
        if reg is not None:
            reg.inc("compile/total")
            if steady:
                reg.inc("compile/steady_recompiles")
        tr = self._tracer_fn() if self._tracer_fn else None
        if tr is not None:
            # backdate so the span covers the compile, not its end
            t1 = time.perf_counter_ns()
            dur_ns = int(duration_s * 1e9)
            tr._append("X", "xla_compile", "compile", t1 - dur_ns, dur_ns,
                       {"signature": sig, "steady_recompile": steady})
        if steady:
            logger.warning(
                "steady-state XLA recompile under %r (%.2fs): the "
                "executable set grew after warmup settled — check for "
                "shape drift past the bucket padding or a traced value "
                "baked into the jitted closure", sig, duration_s)
            # flight trigger (lazy import: obs.__init__ imports this
            # module, so the package is only reachable at call time)
            from bigdl_tpu import obs as _obs

            _obs.flight_notify("compile.steady_recompile", signature=sig,
                               duration_s=round(duration_s, 3))

    # -- inspection --------------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {sig: dict(rec) for sig, rec in self._sigs.items()}

    def compiles(self, signature: Optional[str] = None) -> int:
        with self._lock:
            if signature is not None:
                rec = self._sigs.get(signature)
                return rec["compiles"] if rec else 0
            return sum(r["compiles"] for r in self._sigs.values())

    def recompiles(self, prefix: str = "") -> int:
        with self._lock:
            return sum(r["recompiles"] for sig, r in self._sigs.items()
                       if sig.startswith(prefix))

    def compile_secs(self, prefix: str = "") -> float:
        """Total backend-compile seconds under `prefix` — the pre-first-
        step cost a warm executable cache is supposed to eliminate."""
        with self._lock:
            return sum(r["secs"] for sig, r in self._sigs.items()
                       if sig.startswith(prefix))

    def cache_loads(self, prefix: str = "") -> int:
        with self._lock:
            return sum(r.get("cache_loads", 0)
                       for sig, r in self._sigs.items()
                       if sig.startswith(prefix))
