"""Ready-made host input pipelines.

The production ImageNet-train path as ONE reusable builder: C++ TFRecord
prefetcher -> Example parse -> JPEG decode + augmentation in the MT pool
-> stacked (images, labels) batches.  Used by `bench.py --real-data` and
`benchmarks/bench_input_pipeline.py` (the two must measure the SAME
pipeline), and directly usable by trainers.

Reference analogue: dataset/image/MTLabeledBGRImgToBatch.scala over the
SeqFile ImageNet layout (dataset/DataSet.scala:482-560).
"""

from __future__ import annotations

import glob
import io
import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.vision.image import (
    ChannelNormalize,
    Flip,
    ImageFeature,
    MTImageFeatureToBatch,
    RandomCropper,
    RandomResize,
)

# the standard ImageNet channel statistics (reference:
# BGRImgNormalizer defaults, in RGB order here)
IMAGENET_MEAN = (123.68, 116.78, 103.94)
IMAGENET_STD = (58.4, 57.12, 57.38)


class DecodeJPEGFeature:
    """ImageFeature with raw bytes under 'bytes' -> decoded .image, then
    the wrapped augmentation chain — all inside the MT worker pool (PIL
    releases the GIL during decode)."""

    def __init__(self, chain):
        self.chain = chain

    def transform(self, feature: ImageFeature) -> ImageFeature:
        from PIL import Image

        img = Image.open(io.BytesIO(feature.pop("bytes")))
        feature.image = np.asarray(img.convert("RGB"), np.float32)
        return self.chain.transform(feature)


def imagenet_train_chain(image: int = 224):
    """RandomResize(256..480) -> RandomCrop(image) -> HFlip -> Normalize
    (the reference's BGRImg train augmentation, RGB order)."""
    return (RandomResize(256, 480) >> RandomCropper(image, image)
            >> Flip(0.5) >> ChannelNormalize(IMAGENET_MEAN, IMAGENET_STD))


def shard_paths(data_dir: str) -> List[str]:
    paths = sorted(glob.glob(os.path.join(data_dir, "*.tfrecord")))
    if not paths:
        raise FileNotFoundError(
            f"no *.tfrecord shards under {data_dir} "
            f"(tools/gen_imagenet_shards.py writes them)")
    return paths


def imagenet_record_features(paths: Sequence[str], *, loop: bool = False,
                             n_threads: int = 2, capacity: int = 512,
                             label_offset: int = 0) -> Iterator[ImageFeature]:
    """Shards -> undecoded ImageFeatures (bytes + label).

    `label_offset` is ADDED to the stored `image/class/label` value.  The
    default 0 matches the in-repo shards (tools/gen_imagenet_shards.py
    writes 0-based labels).  Standard inception-style ImageNet shards
    store 1-based labels (0 reserved for background); pass
    `label_offset=-1` for those so labels land in [0, 1000) as the
    criterion expects.
    """
    from bigdl_tpu.dataset.tfrecord import PrefetchRecordReader
    from bigdl_tpu.nn.tf_ops import parse_example_proto

    while True:
        for rec in PrefetchRecordReader(list(paths), n_threads=n_threads,
                                        capacity=capacity):
            f = parse_example_proto(rec)
            yield ImageFeature(
                label=int(f["image/class/label"][0]) + label_offset,
                bytes=f["image/encoded"][0])
        if not loop:
            return


def imagenet_train_batches(data_dir: str, batch: int, *, image: int = 224,
                           num_threads: Optional[int] = None,
                           loop: bool = False, label_offset: int = 0
                           ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """The full pipeline: (B, image, image, 3) float32 + (B,) labels.

    `label_offset`: see `imagenet_record_features` (-1 for standard
    1-based inception-style shards; default 0 for the in-repo shards)."""
    mt = MTImageFeatureToBatch(
        image, image, batch, DecodeJPEGFeature(imagenet_train_chain(image)),
        num_threads=num_threads or os.cpu_count() or 2)
    return iter(mt(imagenet_record_features(shard_paths(data_dir),
                                            loop=loop,
                                            label_offset=label_offset)))
