"""ROI-aware detection augmentation: RoiLabel + geometry-preserving
transforms + the SSD random-crop sampler + ROI batching.

Reference: transform/vision/image/label/roi/{RoiLabel, RoiTransformer,
BatchSampler, RandomSampler}.scala + util/BoundingBox.scala — the
transforms that make detection heads TRAINABLE: every geometric image
augmentation (flip/crop/resize/expand) is mirrored on the ground-truth
boxes, and the SSD-style random crop re-samples patches constrained by
gt overlap.

Host-side numpy throughout (augmentation is input-pipeline work); the
batch boundary pads to a static box count so the jitted training step
sees one shape (`RoiImageToBatch`), with class −1 marking padding —
consumed by `MultiBoxCriterion` (nn/detection.py)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from bigdl_tpu.vision.image import FeatureTransformer, ImageFeature

BOUNDING_BOX = "boundingBox"  # reference: ImageFeature.boundingBox


class RoiLabel:
    """Ground-truth record: `classes` (N,) float class ids — or (2, N)
    with difficult flags in the second row — and `bboxes` (N, 4) x1y1x2y2.
    reference: label/roi/RoiLabel.scala."""

    def __init__(self, classes, bboxes):
        self.classes = np.asarray(classes, np.float32)
        self.bboxes = np.asarray(bboxes, np.float32).reshape(-1, 4)
        n = self.bboxes.shape[0]
        if self.classes.ndim == 1:
            if self.classes.shape[0] != n:
                raise ValueError(
                    f"{self.classes.shape[0]} classes vs {n} boxes")
        elif self.classes.size and self.classes.shape[1] != n:
            raise ValueError(f"{self.classes.shape[1]} classes vs {n} boxes")

    def size(self) -> int:
        return 0 if self.bboxes.size < 4 else self.bboxes.shape[0]

    @property
    def class_row(self) -> np.ndarray:
        return self.classes if self.classes.ndim == 1 else self.classes[0]

    @property
    def difficults(self) -> np.ndarray:
        if self.classes.ndim == 2:
            return self.classes[1]
        return np.zeros_like(self.class_row)

    @staticmethod
    def from_tensor(t) -> "RoiLabel":
        """(N, 6) rows [class, difficult, x1, y1, x2, y2] — the layout
        RoiLabel.fromTensor unpacks (RoiLabel.scala:56)."""
        t = np.asarray(t, np.float32)
        return RoiLabel(t[:, :2].T.copy(), t[:, 2:6].copy())

    def __repr__(self):
        return f"RoiLabel(n={self.size()})"


def jaccard_overlap(box: np.ndarray, boxes: np.ndarray) -> np.ndarray:
    """IoU of one (4,) box against (N, 4) boxes
    (BoundingBox.scala:99)."""
    if boxes.size == 0:
        return np.zeros((0,), np.float32)
    w = np.minimum(box[2], boxes[:, 2]) - np.maximum(box[0], boxes[:, 0])
    h = np.minimum(box[3], boxes[:, 3]) - np.maximum(box[1], boxes[:, 1])
    inter = np.where((w < 0) | (h < 0), 0.0, w * h)
    area = (box[2] - box[0]) * (box[3] - box[1])
    areas = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    return (inter / np.maximum(area + areas - inter, 1e-12)).astype(np.float32)


class RoiNormalize(FeatureTransformer):
    """Pixel-space boxes -> [0, 1] (RoiTransformer.scala RoiNormalize)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        h, w = feature.image.shape[:2]
        label: RoiLabel = feature[ImageFeature.LABEL]
        label.bboxes[:, 0::2] /= w
        label.bboxes[:, 1::2] /= h
        return feature


class RoiHFlip(FeatureTransformer):
    """Mirror boxes to match a horizontal image flip
    (RoiTransformer.scala RoiHFlip)."""

    def __init__(self, normalized: bool = True):
        self.normalized = normalized

    def transform(self, feature: ImageFeature) -> ImageFeature:
        label: RoiLabel = feature[ImageFeature.LABEL]
        width = 1.0 if self.normalized else feature.image.shape[1]
        x1 = label.bboxes[:, 0].copy()
        label.bboxes[:, 0] = width - label.bboxes[:, 2]
        label.bboxes[:, 2] = width - x1
        return feature


class RoiResize(FeatureTransformer):
    """Scale pixel-space boxes after an image resize
    (RoiTransformer.scala RoiResize); normalized boxes are unchanged."""

    def __init__(self, normalized: bool = False):
        self.normalized = normalized

    def transform(self, feature: ImageFeature) -> ImageFeature:
        if not self.normalized:
            oh, ow = feature[ImageFeature.ORIGINAL_SIZE][:2]
            h, w = feature.image.shape[:2]
            label: RoiLabel = feature[ImageFeature.LABEL]
            label.bboxes[:, 0::2] *= w / ow
            label.bboxes[:, 1::2] *= h / oh
        return feature


class RoiProject(FeatureTransformer):
    """Re-express normalized gt boxes in the coordinate system of the
    crop window stored under feature['boundingBox'], dropping boxes that
    fall outside (optionally requiring the gt CENTER inside the window).
    (RoiTransformer.scala RoiProject + BoundingBox.projectBbox)."""

    def __init__(self, need_meet_center_constraint: bool = True):
        self.need_center = need_meet_center_constraint

    def transform(self, feature: ImageFeature) -> ImageFeature:
        win = np.asarray(feature[BOUNDING_BOX], np.float32)
        label: RoiLabel = feature[ImageFeature.LABEL]
        boxes, classes, diffs = label.bboxes, label.class_row, \
            label.difficults
        keep_boxes, keep_cls, keep_diff = [], [], []
        ww, wh = win[2] - win[0], win[3] - win[1]
        for i in range(label.size()):
            b = boxes[i]
            cx, cy = (b[0] + b[2]) / 2, (b[1] + b[3]) / 2
            if self.need_center and not (win[0] <= cx <= win[2]
                                         and win[1] <= cy <= win[3]):
                continue
            if b[0] >= win[2] or b[2] <= win[0] \
                    or b[1] >= win[3] or b[3] <= win[1]:
                continue  # no overlap
            proj = np.asarray([(b[0] - win[0]) / ww, (b[1] - win[1]) / wh,
                               (b[2] - win[0]) / ww, (b[3] - win[1]) / wh],
                              np.float32)
            proj = np.clip(proj, 0.0, 1.0)
            if (proj[2] - proj[0]) * (proj[3] - proj[1]) > 0:
                keep_boxes.append(proj)
                keep_cls.append(classes[i])
                keep_diff.append(diffs[i])
        label.bboxes = (np.stack(keep_boxes) if keep_boxes
                        else np.zeros((0, 4), np.float32))
        label.classes = np.stack([np.asarray(keep_cls, np.float32),
                                  np.asarray(keep_diff, np.float32)])
        return feature


class BatchSampler:
    """Sample normalized crop candidates constrained by scale/aspect and
    gt jaccard overlap (label/roi/BatchSampler.scala)."""

    def __init__(self, max_sample: int = 1, max_trials: int = 50,
                 min_scale: float = 1.0, max_scale: float = 1.0,
                 min_aspect_ratio: float = 1.0, max_aspect_ratio: float = 1.0,
                 min_overlap: Optional[float] = None,
                 max_overlap: Optional[float] = None):
        if not (0 < min_scale <= max_scale <= 1):
            raise ValueError("scale range must satisfy 0 < min <= max <= 1")
        self.max_sample = max_sample
        self.max_trials = max_trials
        self.min_scale, self.max_scale = min_scale, max_scale
        self.min_ar, self.max_ar = min_aspect_ratio, max_aspect_ratio
        self.min_overlap, self.max_overlap = min_overlap, max_overlap

    def _sample_box(self, rs: np.random.RandomState) -> np.ndarray:
        scale = rs.uniform(self.min_scale, self.max_scale)
        ratio = rs.uniform(self.min_ar, self.max_ar)
        ratio = min(max(ratio, scale * scale), 1.0 / scale / scale)
        w = scale * np.sqrt(ratio)
        h = scale / np.sqrt(ratio)
        x1 = rs.uniform(0, 1 - w)
        y1 = rs.uniform(0, 1 - h)
        return np.asarray([x1, y1, x1 + w, y1 + h], np.float32)

    def _satisfies(self, box: np.ndarray, label: RoiLabel) -> bool:
        if self.min_overlap is None and self.max_overlap is None:
            return True
        ov = jaccard_overlap(box, label.bboxes)
        ok = np.ones_like(ov, bool)
        if self.min_overlap is not None:
            ok &= ov >= self.min_overlap
        if self.max_overlap is not None:
            ok &= ov <= self.max_overlap
        return bool(ok.any())

    def sample(self, label: RoiLabel, out: List[np.ndarray],
               rs: np.random.RandomState) -> None:
        found = 0
        for _ in range(self.max_trials):
            if found >= self.max_sample:
                return
            box = self._sample_box(rs)
            if self._satisfies(box, label):
                out.append(box)
                found += 1


SSD_SAMPLERS = (
    BatchSampler(max_trials=1),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2,
                 min_overlap=0.1),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2,
                 min_overlap=0.3),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2,
                 min_overlap=0.5),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2,
                 min_overlap=0.7),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2,
                 min_overlap=0.9),
    BatchSampler(min_scale=0.3, min_aspect_ratio=0.5, max_aspect_ratio=2,
                 max_overlap=1.0),
)


class RandomSampler(FeatureTransformer):
    """SSD random-crop: generate candidates from the 7-sampler zoo, pick
    one uniformly, crop the IMAGE to it and record it under
    feature['boundingBox'] for RoiProject (label/roi/RandomSampler.scala;
    `RandomSampler.create()` chains the project step like the reference's
    `RandomSampler() -> RoiProject()`).  Boxes must be normalized."""

    def __init__(self, samplers: Sequence[BatchSampler] = SSD_SAMPLERS,
                 seed: Optional[int] = None):
        self.samplers = list(samplers)
        self._rs = np.random.RandomState(seed)

    @staticmethod
    def create(seed: Optional[int] = None) -> FeatureTransformer:
        return RandomSampler(seed=seed) >> RoiProject()

    def transform(self, feature: ImageFeature) -> ImageFeature:
        label: RoiLabel = feature[ImageFeature.LABEL]
        candidates: List[np.ndarray] = []
        for s in self.samplers:
            s.sample(label, candidates, self._rs)
        if candidates:
            box = candidates[int(self._rs.uniform(0, 1) * len(candidates))]
        else:
            box = np.asarray([0, 0, 1, 1], np.float32)
        h, w = feature.image.shape[:2]
        x1, y1 = int(round(box[0] * w)), int(round(box[1] * h))
        x2, y2 = int(round(box[2] * w)), int(round(box[3] * h))
        feature.image = feature.image[max(y1, 0):max(y2, y1 + 1),
                                      max(x1, 0):max(x2, x1 + 1)].copy()
        feature[BOUNDING_BOX] = box
        return feature


class RoiImageToBatch:
    """Batch ImageFeatures carrying RoiLabels into one MiniBatch with a
    STATIC box count: images stack (B, H, W, C); targets pad to
    (B, n_max, 5) rows [class, x1, y1, x2, y2] with class −1 padding —
    what the jitted step and MultiBoxCriterion consume.  (The reference's
    RoiMiniBatch keeps ragged tables; static shapes are the jit
    requirement here.)"""

    def __init__(self, batch_size: int, n_max_boxes: int = 32):
        self.batch_size = batch_size
        self.n_max = n_max_boxes

    def __call__(self, features):
        from bigdl_tpu.dataset.minibatch import MiniBatch

        buf = []
        for f in features:
            buf.append(f)
            if len(buf) == self.batch_size:
                yield self._batch(buf)
                buf = []

    def _batch(self, feats):
        from bigdl_tpu.dataset.minibatch import MiniBatch

        imgs = np.stack([f.image for f in feats]).astype(np.float32)
        target = np.full((len(feats), self.n_max, 5), -1.0, np.float32)
        for b, f in enumerate(feats):
            label: RoiLabel = f[ImageFeature.LABEL]
            n = min(label.size(), self.n_max)
            if label.size() > self.n_max:
                raise ValueError(
                    f"{label.size()} gt boxes > n_max_boxes={self.n_max}")
            if n:
                target[b, :n, 0] = label.class_row[:n]
                target[b, :n, 1:] = label.bboxes[:n]
        return MiniBatch(imgs, target)
