"""ImageFeature / ImageFrame / FeatureTransformer.

Reference: transform/vision/image/ — `ImageFeature` is a dict-like record
(bytes/mat/label/originalSize...), `ImageFrame` wraps a collection
(Local/Distributed), `FeatureTransformer` is a composable augmentation
applied feature-by-feature (FeatureTransformer.scala), with the
augmentation zoo under transform/vision/image/augmentation/.

TPU-native redesign: the OpenCV Mat becomes a numpy HWC float32 array; the
distributed ImageFrame (Spark RDD) becomes a sharded host pipeline — each
JAX process transforms only its shard, so `LocalImageFrame` is the one
engine.  Augmentation kernels are shared with bigdl_tpu.dataset.image.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.image import (
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    adjust_saturation,
    crop as _crop,
    hflip,
    resize_bilinear,
)
from bigdl_tpu.dataset.sample import Sample


class ImageFeature(dict):
    """Dict-like record. Well-known keys mirror the reference's constants
    (transform/vision/image/ImageFeature.scala)."""

    IMAGE = "image"          # numpy HWC float32
    LABEL = "label"
    ORIGINAL_SIZE = "originalSize"
    URI = "uri"

    def __init__(self, image: Optional[np.ndarray] = None, label: Any = None,
                 uri: Optional[str] = None, **kw):
        super().__init__(**kw)
        if image is not None:
            self[self.IMAGE] = np.asarray(image, np.float32)
            self[self.ORIGINAL_SIZE] = tuple(self[self.IMAGE].shape)
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def image(self) -> np.ndarray:
        return self[self.IMAGE]

    @image.setter
    def image(self, v: np.ndarray) -> None:
        self[self.IMAGE] = v

    @property
    def label(self):
        return self.get(self.LABEL)


class FeatureTransformer:
    """Composable per-feature augmentation
    (reference: transform/vision/image/FeatureTransformer.scala — chains
    with `->`; here with `>>`)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        feature.image = self.transform_image(feature.image)
        return feature

    def transform_image(self, img: np.ndarray) -> np.ndarray:
        raise NotImplementedError(type(self).__name__)

    def __call__(self, feature: ImageFeature) -> ImageFeature:
        return self.transform(feature)

    def __rshift__(self, other: "FeatureTransformer") -> "ChainedFeatureTransformer":
        return ChainedFeatureTransformer([self, other])

    def apply_frame(self, frame: "ImageFrame") -> "ImageFrame":
        return frame.transform(self)


class ChainedFeatureTransformer(FeatureTransformer):
    def __init__(self, stages: List[FeatureTransformer]):
        self.stages = list(stages)

    def transform(self, feature: ImageFeature) -> ImageFeature:
        for s in self.stages:
            feature = s.transform(feature)
        return feature

    def __rshift__(self, other: FeatureTransformer) -> "ChainedFeatureTransformer":
        return ChainedFeatureTransformer(self.stages + [other])


class ImageFrame:
    """Collection of ImageFeatures (reference:
    transform/vision/image/ImageFrame.scala).  `read` builds from arrays;
    the distributed variant is deliberately absent — each host process
    pipelines its own shard (survey §5.8 TPU mapping)."""

    @staticmethod
    def read(images: Iterable[np.ndarray], labels: Optional[Iterable[Any]] = None
             ) -> "LocalImageFrame":
        labels = list(labels) if labels is not None else None
        feats = []
        for i, img in enumerate(images):
            feats.append(ImageFeature(img, None if labels is None else labels[i]))
        return LocalImageFrame(feats)

    def transform(self, t: FeatureTransformer) -> "ImageFrame":
        raise NotImplementedError


class LocalImageFrame(ImageFrame):
    def __init__(self, features: List[ImageFeature]):
        self.features = list(features)

    def transform(self, t: FeatureTransformer) -> "LocalImageFrame":
        return LocalImageFrame([t(f) for f in self.features])

    def __len__(self) -> int:
        return len(self.features)

    def __iter__(self) -> Iterator[ImageFeature]:
        return iter(self.features)


# ---------------------------------------------------------------------------
# Augmentations (reference: transform/vision/image/augmentation/*)
# ---------------------------------------------------------------------------


class PixelsToFeature(FeatureTransformer):
    """Identity marker for pipelines starting from raw arrays."""

    def transform_image(self, img):
        return np.asarray(img, np.float32)


class Brightness(FeatureTransformer):
    """Add a uniform delta (reference: augmentation/Brightness.scala)."""

    def __init__(self, delta_low: float, delta_high: float, seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        return adjust_brightness(img, self.rs.uniform(self.low, self.high))


class Contrast(FeatureTransformer):
    def __init__(self, factor_low: float, factor_high: float, seed: int = 0):
        self.low, self.high = factor_low, factor_high
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        return adjust_contrast(img, self.rs.uniform(self.low, self.high))


class Saturation(FeatureTransformer):
    def __init__(self, factor_low: float, factor_high: float, seed: int = 0):
        self.low, self.high = factor_low, factor_high
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        return adjust_saturation(img, self.rs.uniform(self.low, self.high))


class Hue(FeatureTransformer):
    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        return adjust_hue(img, self.rs.uniform(self.low, self.high))


class ChannelNormalize(FeatureTransformer):
    """(x - mean) / std per channel (reference: augmentation/ChannelNormalize.scala)."""

    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def transform_image(self, img):
        return (img - self.mean) / self.std


class ResizeTo(FeatureTransformer):
    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def transform_image(self, img):
        return resize_bilinear(img, self.h, self.w)


class RandomCropper(FeatureTransformer):
    def __init__(self, height: int, width: int, seed: int = 0):
        self.h, self.w = height, width
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        ih, iw = img.shape[:2]
        y = self.rs.randint(0, ih - self.h + 1)
        x = self.rs.randint(0, iw - self.w + 1)
        return _crop(img, y, x, self.h, self.w)


class CenterCropper(FeatureTransformer):
    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def transform_image(self, img):
        ih, iw = img.shape[:2]
        return _crop(img, (ih - self.h) // 2, (iw - self.w) // 2, self.h, self.w)


class FixedCrop(FeatureTransformer):
    """Crop at explicit (x1, y1, x2, y2), normalized or absolute
    (reference: augmentation/FixedCrop.scala)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = False):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def transform_image(self, img):
        ih, iw = img.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * iw, x2 * iw
            y1, y2 = y1 * ih, y2 * ih
        x1, y1, x2, y2 = (int(round(v)) for v in (x1, y1, x2, y2))
        return img[y1:y2, x1:x2]


class Expand(FeatureTransformer):
    """Zoom-out: place the image on a larger mean-filled canvas
    (reference: augmentation/Expand.scala)."""

    def __init__(self, max_ratio: float = 4.0, means: Sequence[float] = (123, 117, 104),
                 seed: int = 0):
        self.max_ratio = max_ratio
        self.means = np.asarray(means, np.float32)
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        ih, iw, c = img.shape
        ratio = self.rs.uniform(1.0, self.max_ratio)
        oh, ow = int(ih * ratio), int(iw * ratio)
        canvas = np.broadcast_to(self.means, (oh, ow, c)).astype(np.float32).copy()
        y = self.rs.randint(0, oh - ih + 1)
        x = self.rs.randint(0, ow - iw + 1)
        canvas[y:y + ih, x:x + iw] = img
        return canvas


class Flip(FeatureTransformer):
    def __init__(self, p: float = 0.5, seed: int = 0):
        self.p = p
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        return hflip(img) if self.rs.rand() < self.p else img


class ImageFrameToSample(FeatureTransformer):
    """Terminal stage: ImageFeature -> Sample stored under key 'sample'
    (reference: ImageFrameToSample.scala)."""

    SAMPLE = "sample"

    def transform(self, feature: ImageFeature) -> ImageFeature:
        label = feature.label
        feature[self.SAMPLE] = Sample(
            np.ascontiguousarray(feature.image, np.float32),
            None if label is None else np.asarray(label))
        return feature
