"""ImageFeature / ImageFrame / FeatureTransformer.

Reference: transform/vision/image/ — `ImageFeature` is a dict-like record
(bytes/mat/label/originalSize...), `ImageFrame` wraps a collection
(Local/Distributed), `FeatureTransformer` is a composable augmentation
applied feature-by-feature (FeatureTransformer.scala), with the
augmentation zoo under transform/vision/image/augmentation/.

TPU-native redesign: the OpenCV Mat becomes a numpy HWC float32 array; the
distributed ImageFrame (Spark RDD) becomes a sharded host pipeline — each
JAX process transforms only its shard, so `LocalImageFrame` is the one
engine.  Augmentation kernels are shared with bigdl_tpu.dataset.image.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np


_RS_LOCK_GUARD = threading.Lock()


def _locked_sample(transformer, fn):
    """Draw from a transformer's RandomState under a per-instance lock —
    np.random.RandomState is not thread-safe, and MTImageFeatureToBatch runs
    transforms on a thread pool.  Lazy lock creation is itself guarded so
    two first-callers cannot each mint their own lock."""
    lock = getattr(transformer, "_rs_lock", None)
    if lock is None:
        with _RS_LOCK_GUARD:
            lock = getattr(transformer, "_rs_lock", None)
            if lock is None:
                lock = transformer._rs_lock = threading.Lock()
    with lock:
        return fn()

from bigdl_tpu.dataset.image import (
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    adjust_saturation,
    crop as _crop,
    hflip,
    resize_bilinear,
)
from bigdl_tpu.dataset.sample import Sample


class ImageFeature(dict):
    """Dict-like record. Well-known keys mirror the reference's constants
    (transform/vision/image/ImageFeature.scala)."""

    IMAGE = "image"          # numpy HWC float32
    LABEL = "label"
    ORIGINAL_SIZE = "originalSize"
    URI = "uri"

    def __init__(self, image: Optional[np.ndarray] = None, label: Any = None,
                 uri: Optional[str] = None, **kw):
        super().__init__(**kw)
        if image is not None:
            self[self.IMAGE] = np.asarray(image, np.float32)
            self[self.ORIGINAL_SIZE] = tuple(self[self.IMAGE].shape)
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def image(self) -> np.ndarray:
        return self[self.IMAGE]

    @image.setter
    def image(self, v: np.ndarray) -> None:
        self[self.IMAGE] = v

    @property
    def label(self):
        return self.get(self.LABEL)


class FeatureTransformer:
    """Composable per-feature augmentation
    (reference: transform/vision/image/FeatureTransformer.scala — chains
    with `->`; here with `>>`)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        feature.image = self.transform_image(feature.image)
        return feature

    def transform_image(self, img: np.ndarray) -> np.ndarray:
        raise NotImplementedError(type(self).__name__)

    def __call__(self, feature: ImageFeature) -> ImageFeature:
        return self.transform(feature)

    def __rshift__(self, other: "FeatureTransformer") -> "ChainedFeatureTransformer":
        return ChainedFeatureTransformer([self, other])

    def apply_frame(self, frame: "ImageFrame") -> "ImageFrame":
        return frame.transform(self)


class ChainedFeatureTransformer(FeatureTransformer):
    def __init__(self, stages: List[FeatureTransformer]):
        self.stages = list(stages)

    def transform(self, feature: ImageFeature) -> ImageFeature:
        for s in self.stages:
            feature = s.transform(feature)
        return feature

    def __rshift__(self, other: FeatureTransformer) -> "ChainedFeatureTransformer":
        return ChainedFeatureTransformer(self.stages + [other])


class ImageFrame:
    """Collection of ImageFeatures (reference:
    transform/vision/image/ImageFrame.scala).  `read` builds from arrays;
    the distributed variant is deliberately absent — each host process
    pipelines its own shard (survey §5.8 TPU mapping)."""

    @staticmethod
    def read(images: Iterable[np.ndarray], labels: Optional[Iterable[Any]] = None
             ) -> "LocalImageFrame":
        labels = list(labels) if labels is not None else None
        feats = []
        for i, img in enumerate(images):
            feats.append(ImageFeature(img, None if labels is None else labels[i]))
        return LocalImageFrame(feats)

    def transform(self, t: FeatureTransformer) -> "ImageFrame":
        raise NotImplementedError


class LocalImageFrame(ImageFrame):
    def __init__(self, features: List[ImageFeature]):
        self.features = list(features)

    def transform(self, t: FeatureTransformer) -> "LocalImageFrame":
        return LocalImageFrame([t(f) for f in self.features])

    def __len__(self) -> int:
        return len(self.features)

    def __iter__(self) -> Iterator[ImageFeature]:
        return iter(self.features)


# ---------------------------------------------------------------------------
# Augmentations (reference: transform/vision/image/augmentation/*)
# ---------------------------------------------------------------------------


class PixelsToFeature(FeatureTransformer):
    """Identity marker for pipelines starting from raw arrays."""

    def transform_image(self, img):
        return np.asarray(img, np.float32)


class Brightness(FeatureTransformer):
    """Add a uniform delta (reference: augmentation/Brightness.scala)."""

    def __init__(self, delta_low: float, delta_high: float, seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        delta = _locked_sample(self, lambda: self.rs.uniform(self.low, self.high))
        return adjust_brightness(img, delta)


class Contrast(FeatureTransformer):
    def __init__(self, factor_low: float, factor_high: float, seed: int = 0):
        self.low, self.high = factor_low, factor_high
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        f = _locked_sample(self, lambda: self.rs.uniform(self.low, self.high))
        return adjust_contrast(img, f)


class Saturation(FeatureTransformer):
    def __init__(self, factor_low: float, factor_high: float, seed: int = 0):
        self.low, self.high = factor_low, factor_high
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        f = _locked_sample(self, lambda: self.rs.uniform(self.low, self.high))
        return adjust_saturation(img, f)


class Hue(FeatureTransformer):
    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        d = _locked_sample(self, lambda: self.rs.uniform(self.low, self.high))
        return adjust_hue(img, d)


class ChannelNormalize(FeatureTransformer):
    """(x - mean) / std per channel (reference: augmentation/ChannelNormalize.scala)."""

    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def transform_image(self, img):
        return (img - self.mean) / self.std


class ResizeTo(FeatureTransformer):
    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def transform_image(self, img):
        return resize_bilinear(img, self.h, self.w)


class RandomResize(FeatureTransformer):
    """Resize so the SHORTER side equals a random size drawn from
    [min_size, max_size] (aspect preserved).
    reference: transform/vision/image/augmentation/RandomResize.scala."""

    def __init__(self, min_size: int, max_size: int, seed: int = 0):
        self.min_size, self.max_size = min_size, max_size
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        size = _locked_sample(
            self, lambda: self.rs.randint(self.min_size, self.max_size + 1))
        ih, iw = img.shape[:2]
        if ih < iw:
            h, w = size, max(1, round(iw * size / ih))
        else:
            h, w = max(1, round(ih * size / iw)), size
        return resize_bilinear(img, h, w)


class RandomCropper(FeatureTransformer):
    def __init__(self, height: int, width: int, seed: int = 0):
        self.h, self.w = height, width
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        ih, iw = img.shape[:2]
        y, x = _locked_sample(self, lambda: (self.rs.randint(0, ih - self.h + 1),
                                              self.rs.randint(0, iw - self.w + 1)))
        return _crop(img, y, x, self.h, self.w)


class CenterCropper(FeatureTransformer):
    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def transform_image(self, img):
        ih, iw = img.shape[:2]
        return _crop(img, (ih - self.h) // 2, (iw - self.w) // 2, self.h, self.w)


class FixedCrop(FeatureTransformer):
    """Crop at explicit (x1, y1, x2, y2), normalized or absolute
    (reference: augmentation/FixedCrop.scala)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = False):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def transform_image(self, img):
        ih, iw = img.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * iw, x2 * iw
            y1, y2 = y1 * ih, y2 * ih
        x1, y1, x2, y2 = (int(round(v)) for v in (x1, y1, x2, y2))
        return img[y1:y2, x1:x2]


class Expand(FeatureTransformer):
    """Zoom-out: place the image on a larger mean-filled canvas
    (reference: augmentation/Expand.scala)."""

    def __init__(self, max_ratio: float = 4.0, means: Sequence[float] = (123, 117, 104),
                 seed: int = 0):
        self.max_ratio = max_ratio
        self.means = np.asarray(means, np.float32)
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        ih, iw, c = img.shape
        ratio = _locked_sample(self, lambda: self.rs.uniform(1.0, self.max_ratio))
        oh, ow = int(ih * ratio), int(iw * ratio)
        canvas = np.broadcast_to(self.means, (oh, ow, c)).astype(np.float32).copy()
        y, x = _locked_sample(self, lambda: (self.rs.randint(0, oh - ih + 1),
                                              self.rs.randint(0, ow - iw + 1)))
        canvas[y:y + ih, x:x + iw] = img
        return canvas


class Flip(FeatureTransformer):
    def __init__(self, p: float = 0.5, seed: int = 0):
        self.p = p
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        return hflip(img) if _locked_sample(self, self.rs.rand) < self.p else img


class ImageFrameToSample(FeatureTransformer):
    """Terminal stage: ImageFeature -> Sample stored under key 'sample'
    (reference: ImageFrameToSample.scala)."""

    SAMPLE = "sample"

    def transform(self, feature: ImageFeature) -> ImageFeature:
        label = feature.label
        feature[self.SAMPLE] = Sample(
            np.ascontiguousarray(feature.image, np.float32),
            None if label is None else np.asarray(label))
        return feature


class ColorJitter(FeatureTransformer):
    """Random brightness/contrast/saturation in random order.
    reference: augmentation/ColorJitter.scala."""

    def __init__(self, brightness: float = 32.0, contrast: float = 0.5,
                 saturation: float = 0.5, seed: int = 0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        def draw():
            return (self.rs.uniform(-self.brightness, self.brightness),
                    self.rs.uniform(1 - self.contrast, 1 + self.contrast),
                    self.rs.uniform(1 - self.saturation, 1 + self.saturation),
                    self.rs.permutation(3))

        b_delta, c_factor, s_factor, order = _locked_sample(self, draw)
        ops = [lambda im: adjust_brightness(im, b_delta),
               lambda im: adjust_contrast(im, c_factor),
               lambda im: adjust_saturation(im, s_factor)]
        for i in order:
            img = ops[i](img)
        return img


class Lighting(FeatureTransformer):
    """AlexNet-style PCA color noise (reference: augmentation/Lighting.scala;
    eigen basis shared with dataset.image.Lighting)."""

    def __init__(self, alpha_std: float = 0.1, seed: int = 0):
        self.alpha_std = alpha_std
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        from bigdl_tpu.dataset.image import Lighting as _L

        alpha = _locked_sample(
            self, lambda: self.rs.normal(0, self.alpha_std, 3)).astype(np.float32)
        noise = (_L.EIG_VEC * alpha * _L.EIG_VAL).sum(axis=1)
        return img + noise[None, None, :]


class AspectScale(FeatureTransformer):
    """Resize so the short side equals `scale`, capped at `max_size` on the
    long side (reference: augmentation/AspectScale.scala)."""

    def __init__(self, scale: int, max_size: int = 1000,
                 scale_multiple_of: int = 1):
        self.scale = scale
        self.max_size = max_size
        self.multiple = scale_multiple_of

    def _target(self, h, w, scale=None):
        short, long = min(h, w), max(h, w)
        ratio = (self.scale if scale is None else scale) / short
        if ratio * long > self.max_size:
            ratio = self.max_size / long
        th, tw = int(round(h * ratio)), int(round(w * ratio))
        if self.multiple > 1:
            th = -(-th // self.multiple) * self.multiple
            tw = -(-tw // self.multiple) * self.multiple
        return th, tw

    def transform_image(self, img):
        th, tw = self._target(img.shape[0], img.shape[1])
        return resize_bilinear(img, th, tw)


class RandomAspectScale(AspectScale):
    """Pick the short-side scale randomly from `scales`.
    reference: augmentation/RandomAspectScale.scala."""

    def __init__(self, scales: Sequence[int], max_size: int = 1000, seed: int = 0):
        super().__init__(scales[0], max_size)
        self.scales = list(scales)
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        scale = _locked_sample(
            self, lambda: self.scales[self.rs.randint(len(self.scales))])
        th, tw = self._target(img.shape[0], img.shape[1], scale)
        return resize_bilinear(img, th, tw)


class RandomAlterAspect(FeatureTransformer):
    """Random area+aspect-ratio crop resized to a fixed size — the
    Inception-style training crop (reference:
    augmentation/RandomAlterAspect.scala)."""

    def __init__(self, min_area_ratio: float = 0.08, max_area_ratio: float = 1.0,
                 min_aspect: float = 3 / 4, out_h: int = 224, out_w: int = 224,
                 seed: int = 0):
        self.min_area = min_area_ratio
        self.max_area = max_area_ratio
        self.min_aspect = min_aspect
        self.out_h, self.out_w = out_h, out_w
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        h, w = img.shape[:2]
        area = h * w

        def draw():
            for _ in range(10):
                target = self.rs.uniform(self.min_area, self.max_area) * area
                aspect = self.rs.uniform(self.min_aspect, 1.0 / self.min_aspect)
                cw = int(round(np.sqrt(target * aspect)))
                ch = int(round(np.sqrt(target / aspect)))
                if ch <= h and cw <= w:
                    return (self.rs.randint(0, h - ch + 1),
                            self.rs.randint(0, w - cw + 1), ch, cw)
            return None

        box = _locked_sample(self, draw)
        if box is None:
            return resize_bilinear(img, self.out_h, self.out_w)
        y, x, ch, cw = box
        return resize_bilinear(_crop(img, y, x, ch, cw), self.out_h, self.out_w)


class ChannelOrder(FeatureTransformer):
    """Randomly permute the color channels
    (reference: augmentation/ChannelOrder.scala — RGB<->BGR swap)."""

    def __init__(self, seed: int = 0):
        self.rs = np.random.RandomState(seed)

    def transform_image(self, img):
        perm = _locked_sample(self, lambda: self.rs.permutation(img.shape[2]))
        return img[:, :, perm]


class Filler(FeatureTransformer):
    """Fill a normalized-coordinate sub-rectangle with a constant value
    (reference: augmentation/Filler.scala)."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: float = 255.0):
        self.box = (start_x, start_y, end_x, end_y)
        self.value = value

    def transform_image(self, img):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        out = img.copy()
        out[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = self.value
        return out


class PixelNormalizer(FeatureTransformer):
    """Subtract a per-pixel mean array (reference:
    augmentation/PixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform_image(self, img):
        return img - self.means.reshape(img.shape)


class ChannelScaledNormalizer(FeatureTransformer):
    """Per-channel mean subtraction + global scale
    (reference: augmentation/ChannelScaledNormalizer.scala)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float, scale: float):
        self.means = np.asarray([mean_r, mean_g, mean_b], np.float32)
        self.scale = scale

    def transform_image(self, img):
        return (img - self.means[None, None, :]) * self.scale


class RandomTransformer(FeatureTransformer):
    """Apply the inner transformer with probability p
    (reference: augmentation/RandomTransformer.scala)."""

    def __init__(self, inner: FeatureTransformer, p: float, seed: int = 0):
        self.inner = inner
        self.p = p
        self.rs = np.random.RandomState(seed)

    def transform(self, feature: ImageFeature) -> ImageFeature:
        if _locked_sample(self, self.rs.rand) < self.p:
            return self.inner.transform(feature)
        return feature


class MTImageFeatureToBatch:
    """Thread-pooled transform + batch assembly: pulls ImageFeatures, runs
    the transformer across worker threads, emits stacked (images, labels)
    numpy batches.  reference: MTImageFeatureToBatch.scala (its Engine-pool
    parallel transform); numpy releases the GIL on the heavy ops so Python
    threads genuinely overlap.
    """

    def __init__(self, width: int, height: int, batch_size: int,
                 transformer: FeatureTransformer, num_threads: int = 4):
        self.width, self.height = width, height
        self.batch_size = batch_size
        self.transformer = transformer
        self.num_threads = num_threads

    def __call__(self, features: Iterable[ImageFeature]
                 ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        from concurrent.futures import ThreadPoolExecutor

        feats = iter(features)
        with ThreadPoolExecutor(self.num_threads) as pool:
            while True:
                chunk = []
                for _ in range(self.batch_size):
                    try:
                        chunk.append(next(feats))
                    except StopIteration:
                        break
                if not chunk:
                    return
                done = list(pool.map(self.transformer.transform, chunk))
                imgs = np.stack([
                    resize_bilinear(f.image, self.height, self.width)
                    if f.image.shape[:2] != (self.height, self.width)
                    else f.image for f in done])
                labels = np.asarray([f.get(ImageFeature.LABEL, -1) for f in done])
                yield imgs, labels
