"""Vision pipeline: ImageFeature records + composable FeatureTransformers.

Reference: transform/vision/image/ (ImageFrame, ImageFeature,
FeatureTransformer, augmentation/*).
"""

from bigdl_tpu.vision.image import (
    ImageFeature,
    ImageFrame,
    LocalImageFrame,
    FeatureTransformer,
    PixelsToFeature,
    Brightness,
    Contrast,
    Saturation,
    Hue,
    ChannelNormalize,
    RandomCropper,
    CenterCropper,
    FixedCrop,
    Expand,
    Flip,
    ResizeTo,
    RandomResize,
    ImageFrameToSample,
    ColorJitter,
    Lighting,
    AspectScale,
    RandomAspectScale,
    RandomAlterAspect,
    ChannelOrder,
    Filler,
    PixelNormalizer,
    ChannelScaledNormalizer,
    RandomTransformer,
    MTImageFeatureToBatch,
)
from bigdl_tpu.vision import roi  # noqa: F401,E402
