"""TensorBoard event-file writing/reading.

Reference: visualization/ — TrainSummary/ValidationSummary
(TrainSummary.scala:32, ValidationSummary.scala:29) over a from-scratch
FileWriter -> EventWriter -> RecordWriter stack emitting TF Event protobufs
with crc32c framing (EventWriter.scala:26-68, RecordWriter.scala:25,
netty/Crc32c.java).

Here the protobuf subset is hand-encoded (proto.py), the crc32c comes from
the native C++ layer (bigdl_tpu/native), and the record framing is the
shared TFRecord framing — real `events.out.tfevents.*` files TensorBoard
loads directly.
"""

from bigdl_tpu.visualization.writer import (
    FileWriter,
    read_events,
    read_scalar,
    histogram_of,
)
