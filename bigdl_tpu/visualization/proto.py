"""Minimal protobuf wire-format encode/decode for TensorBoard Event files.

Reference: visualization/tensorboard/ writes TF `Event` protobufs via
generated Java classes (EventWriter.scala:26-68, RecordWriter.scala:25).
Here the needed subset of event.proto/summary.proto is encoded by hand —
five message types, no protoc dependency:

  Event       { double wall_time=1; int64 step=2; string file_version=3;
                Summary summary=5; }
  Summary     { repeated Value value=1; }
  Value       { string tag=1; float simple_value=2; HistogramProto histo=5; }
  HistogramProto { double min=1,max=2,num=3,sum=4,sum_squares=5;
                   repeated double bucket_limit=7 [packed];
                   repeated double bucket=8 [packed]; }
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _int64(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _bytes(field: int, v: bytes) -> bytes:
    return _key(field, 2) + _varint(len(v)) + v


def _packed_doubles(field: int, vs) -> bytes:
    body = b"".join(struct.pack("<d", v) for v in vs)
    return _bytes(field, body)


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------


def encode_histogram(min_v: float, max_v: float, num: float, sum_v: float,
                     sum_sq: float, limits, counts) -> bytes:
    return (_double(1, min_v) + _double(2, max_v) + _double(3, num) +
            _double(4, sum_v) + _double(5, sum_sq) +
            _packed_doubles(7, limits) + _packed_doubles(8, counts))


def encode_value_scalar(tag: str, value: float) -> bytes:
    return _bytes(1, tag.encode()) + _float(2, value)


def encode_value_histo(tag: str, histo: bytes) -> bytes:
    return _bytes(1, tag.encode()) + _bytes(5, histo)


def encode_event(wall_time: float, step: Optional[int] = None,
                 file_version: Optional[str] = None,
                 values: Optional[List[bytes]] = None) -> bytes:
    out = _double(1, wall_time)
    if step is not None:
        out += _int64(2, step)
    if file_version is not None:
        out += _bytes(3, file_version.encode())
    if values:
        out += _bytes(5, b"".join(_bytes(1, v) for v in values))
    return out


# ---------------------------------------------------------------------------
# decode (read-back path: TrainSummary.readScalar parity)
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, off: int) -> Tuple[int, int]:
    n = shift = 0
    while True:
        b = buf[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    off = 0
    while off < len(buf):
        key, off = _read_varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, off = _read_varint(buf, off)
        elif wire == 1:
            v = struct.unpack_from("<d", buf, off)[0]
            off += 8
        elif wire == 5:
            v = struct.unpack_from("<f", buf, off)[0]
            off += 4
        elif wire == 2:
            ln, off = _read_varint(buf, off)
            v = buf[off:off + ln]
            off += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, v


def decode_event(buf: bytes) -> Dict[str, Any]:
    ev: Dict[str, Any] = {"values": []}
    for field, wire, v in iter_fields(buf):
        if field == 1 and wire == 1:
            ev["wall_time"] = v
        elif field == 2 and wire == 0:
            ev["step"] = v
        elif field == 3 and wire == 2:
            ev["file_version"] = v.decode()
        elif field == 5 and wire == 2:
            for f2, w2, summary_val in iter_fields(v):
                if f2 == 1 and w2 == 2:
                    val: Dict[str, Any] = {}
                    for f3, w3, x in iter_fields(summary_val):
                        if f3 == 1 and w3 == 2:
                            val["tag"] = x.decode()
                        elif f3 == 2 and w3 == 5:
                            val["simple_value"] = x
                        elif f3 == 5 and w3 == 2:
                            val["histo"] = x
                    ev["values"].append(val)
    return ev
